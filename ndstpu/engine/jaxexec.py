"""JAX/XLA execution backend — the TPU path.

Executes the same logical plans as ndstpu.engine.physical, but on device
arrays with XLA-friendly static shapes (cf. reference execution engine:
Spark SQL + spark-rapids GPU plugin, nds/power_run_gpu.template:23-40).

Design (TPU-first, not a Spark translation):

* **Static capacities + alive mask.** Every table is padded to a
  power-of-two *size class*; a boolean ``alive`` vector marks real rows.
  Filters only AND the mask (no data movement); compaction happens lazily
  at the few points that need it (LIMIT, join sizing).  Data-dependent
  output sizes (join fan-out) sync one scalar to host and pick a size
  class, so XLA recompiles per size class, not per row count.

* **Pure functional operators.** Each operator is a pure function of jnp
  arrays, so any sync-free subtree can be traced under ``jax.jit`` (the
  graft entry point jits a whole query pipeline this way).

* **Sort-based relational kernels.** Group-by = lexicographic sort →
  adjacent-difference dense group ids → ``segment_sum``/min/max (exact
  int64 for decimals).  Equi-join = dense-rank both sides jointly,
  mixed-radix composite key, sort build side, two-sided
  ``searchsorted``, ragged expansion against a host-sized output.

* **Strings never touch the device.**  String columns are int32 codes
  into per-column *sorted* dictionaries; LIKE/substr/upper/… are computed
  once per dictionary entry on host (O(|dict|)) and become code-indexed
  lookup-table gathers on device (O(rows)).  Cross-dictionary equality
  goes through host-built translation tables.

* **Exact decimals.** decimal(p,s) stays scale-shifted int64 on device;
  sums are exact int64 segment sums (validation bar: nds_validate.py
  epsilon semantics).

Nodes/exprs without a device lowering fall back per-subtree to the numpy
reference interpreter (children still run on device; results are pulled
to host once).
"""

from __future__ import annotations

import os
import contextlib
import dataclasses
import math
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from ndstpu import obs  # noqa: E402
# the declarative supported-op registry is the single source of truth
# shared with the static analyzer and scripts/spmd_coverage.py — keep
# capability checks here pointing at it so the two can't drift
from ndstpu.analysis import lowering as lowreg  # noqa: E402
from ndstpu.engine import columnar, expr as ex, physical, plan as lp  # noqa: E402
from ndstpu.engine.columnar import (  # noqa: E402
    BOOL,
    DATE,
    FLOAT64,
    INT32,
    INT64,
    STRING,
    Column,
    DType,
    Table,
    decimal,
)

# Sentinels (int64 key space)
_NULL_KEY = np.int64(-(2 ** 62))      # NULL group/join key
_DEAD_KEY = np.int64(2 ** 62)         # padding / filtered-out rows
# int32 key space (narrow keys: v5e has no native int64 ALU — the x64
# rewrite emulates every s64 op as s32 pairs, so keys whose domain fits
# int32 cut the VPU work of sorts/compares in half or better)
_NULL32 = np.int32(-(2 ** 30))
_DEAD32 = np.int32(2 ** 30)
_ORD_DEAD32 = np.int32(2 ** 30 + 1)   # order keys: dead strictly last
_NARROW_LIM = 2 ** 30                 # |value| bound for int32 keys
_MIN_CAPACITY = 256

# Engine default for NDSTPU_GROUPBY.  Module-level and literal on
# purpose: obs/artifact_lint.py parses it from source (no jax import)
# to cross-check docs/*.json artifacts that pin `engine_defaults`.
GROUPBY_DEFAULT = "pallas"


def size_class(n: int) -> int:
    """Smallest power-of-two capacity >= n (bounded recompilation)."""
    return max(_MIN_CAPACITY, 1 << max(0, (int(n) - 1)).bit_length())


_JNP_DTYPES = {
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float64": jnp.float64,
    "decimal": jnp.int64,
    "date": jnp.int32,
    "string": jnp.int32,
    "bool": jnp.bool_,
}


def jnp_dtype(ct: DType):
    return _JNP_DTYPES[ct.kind]


@dataclasses.dataclass
class _View:
    """Shared row indirection for lazily-gathered columns: ``idx`` maps
    the current capacity into a BASE column's capacity (always one
    level — compositions fold into a single gather), ``mask`` is an
    accumulated validity-kill at the current capacity (or None)."""

    idx: jnp.ndarray
    mask: Optional[jnp.ndarray] = None


class DCol:
    """Device column: padded data + validity (meaningful where alive).

    Either materialized (``data``/``valid`` arrays) or a lazy view over
    a base column (``src_data``/``src_valid`` + shared :class:`_View`).
    Lazy columns materialize on first ``.data``/``.valid`` access with
    ONE gather from the base — a 4M-row gather costs ~30 ms on v5e
    (scripts/prim_bench.py), and eager join expansion re-gathered every
    column of both sides at every join of a multi-join pipeline."""

    __slots__ = ("_data", "_valid", "ctype", "dictionary", "bounds",
                 "src_data", "src_valid", "view")

    def __init__(self, data, valid, ctype: DType,
                 dictionary: Optional[np.ndarray] = None,
                 bounds: Optional[Tuple[int, int]] = None):
        self._data = data
        self._valid = valid
        self.ctype = ctype
        # host-side, sorted dictionary for string columns
        self.dictionary = dictionary
        # host-side static (lo, hi) over the column's valid values, set
        # at upload and preserved by row-subset ops (gather/filter);
        # lets group-by linearize small integer key domains without
        # sorting.  Invalidation rides the same contract as
        # `dictionary`: data changes bump the catalog version, which
        # forces re-upload + re-trace.
        self.bounds = bounds
        self.src_data = None
        self.src_valid = None
        self.view = None

    @classmethod
    def lazy(cls, src_data, src_valid, view: _View, ctype: DType,
             dictionary=None, bounds=None) -> "DCol":
        c = cls(None, None, ctype, dictionary, bounds)
        c.src_data = src_data
        c.src_valid = src_valid
        c.view = view
        return c

    @property
    def data(self):
        if self._data is None:
            self._data = self.src_data[self.view.idx]
        return self._data

    @property
    def valid(self):
        if self._valid is None:
            v = self.view.mask
            if self.src_valid is not None:
                sv = self.src_valid[self.view.idx]
                v = sv if v is None else (sv & v)
            if v is None:
                v = jnp.ones(self.view.idx.shape[0], bool)
            self._valid = v
        return self._valid

    @property
    def capacity(self) -> int:
        if self._data is not None:
            return int(self._data.shape[0])
        return int(self.view.idx.shape[0])


def _select_cols(cols_a: Dict[str, DCol], cols_b: Dict[str, DCol],
                 idx_a: jnp.ndarray, idx_b: jnp.ndarray,
                 pick_a: jnp.ndarray,
                 extra_mask: Optional[jnp.ndarray] = None
                 ) -> Dict[str, DCol]:
    """Two-source row select: out[n][p] = a[n][idx_a[p]] if pick_a[p]
    else b[n][idx_b[p]].  When both columns resolve to the SAME base
    array (a is a lazy view of b's source — the left-join shape), the
    select collapses to ONE combined index and stays lazy; otherwise
    both sides materialize and combine with `where`."""
    memo: Dict[tuple, _View] = {}
    out: Dict[str, DCol] = {}
    ones_a = None
    for n in cols_a:
        a, b = cols_a[n], cols_b[n]
        base_a = a.src_data if a.view is not None else a._data
        base_b = b.src_data if b.view is not None else b._data
        sv_a = a.src_valid if a.view is not None else a._valid
        sv_b = b.src_valid if b.view is not None else b._valid
        # collapsing to one lazy view uses side a's src_valid for rows
        # picked from b — only sound when the VALIDITY bases match too
        # (a shared data buffer with different validity, e.g. a cast
        # built as DCol(c.data, c.valid & ok), must not collapse)
        if base_a is base_b and sv_a is sv_b:
            key = (id(a.view), id(b.view))
            v2 = memo.get(key)
            if v2 is None:
                ia = a.view.idx[idx_a] if a.view is not None else idx_a
                ib = b.view.idx[idx_b] if b.view is not None else idx_b
                nidx = jnp.where(pick_a, ia, ib)
                ma = a.view.mask[idx_a] \
                    if a.view is not None and a.view.mask is not None \
                    else None
                mb = b.view.mask[idx_b] \
                    if b.view is not None and b.view.mask is not None \
                    else None
                if ma is None and mb is None:
                    nmask = None
                else:
                    if ones_a is None:
                        ones_a = jnp.ones(pick_a.shape[0], bool)
                    nmask = jnp.where(pick_a,
                                      ma if ma is not None else ones_a,
                                      mb if mb is not None else ones_a)
                if extra_mask is not None:
                    nmask = extra_mask if nmask is None else \
                        (nmask & extra_mask)
                v2 = memo[key] = _View(nidx, nmask)
            sv = a.src_valid if a.view is not None else a._valid
            out[n] = DCol.lazy(base_a, sv, v2, a.ctype, a.dictionary,
                               _union_bounds(a.bounds, b.bounds))
        else:
            data = jnp.where(pick_a, a.data[idx_a], b.data[idx_b])
            valid = jnp.where(pick_a, a.valid[idx_a], b.valid[idx_b])
            if extra_mask is not None:
                valid = valid & extra_mask
            out[n] = DCol(data, valid, a.ctype, a.dictionary,
                          _union_bounds(a.bounds, b.bounds))
    return out


def _union_bounds(a: Optional[Tuple[int, int]],
                  b: Optional[Tuple[int, int]]):
    if a is None or b is None:
        return None
    return (min(a[0], b[0]), max(a[1], b[1]))


def _gather_cols(cols: Dict[str, DCol], idx: jnp.ndarray,
                 extra_mask: Optional[jnp.ndarray] = None
                 ) -> Dict[str, DCol]:
    """Lazily gather every column by ``idx``: columns sharing a view
    compose index/mask ONCE; materialized sources just wrap.  With
    ``extra_mask`` the gathered validity is additionally ANDed (at the
    output capacity)."""
    ident = _View(idx, extra_mask)
    memo: Dict[int, _View] = {}
    out: Dict[str, DCol] = {}
    for n, c in cols.items():
        if c.view is None:
            out[n] = DCol.lazy(c._data, c._valid, ident, c.ctype,
                               c.dictionary, c.bounds)
            continue
        v2 = memo.get(id(c.view))
        if v2 is None:
            nidx = c.view.idx[idx]
            nmask = c.view.mask[idx] if c.view.mask is not None else None
            if extra_mask is not None:
                nmask = extra_mask if nmask is None else \
                    (nmask & extra_mask)
            v2 = memo[id(c.view)] = _View(nidx, nmask)
        out[n] = DCol.lazy(c.src_data, c.src_valid, v2, c.ctype,
                           c.dictionary, c.bounds)
    return out


@dataclasses.dataclass
class DTable:
    """Device table: named columns + alive mask, all of one capacity."""

    columns: Dict[str, DCol]
    alive: jnp.ndarray

    @property
    def capacity(self) -> int:
        return int(self.alive.shape[0])

    @property
    def column_names(self) -> List[str]:
        return list(self.columns)

    def column(self, name: str) -> DCol:
        return self.columns[name]

    def select(self, names: Sequence[str]) -> "DTable":
        return DTable({n: self.columns[n] for n in names}, self.alive)

    def gather(self, idx: jnp.ndarray, alive: jnp.ndarray) -> "DTable":
        return DTable(_gather_cols(self.columns, idx), alive)


# ---------------------------------------------------------------------------
# host <-> device conversion
# ---------------------------------------------------------------------------


def _pad(arr: np.ndarray, cap: int, fill=0) -> np.ndarray:
    if len(arr) == cap:
        return arr
    out = np.full(cap, fill, dtype=arr.dtype)
    out[:len(arr)] = arr
    return out


import contextlib  # noqa: E402


def host_cpu_device():
    """The host CPU jax device, if one is registered alongside an
    accelerator platform (None when CPU already is the default)."""
    try:
        dev = jax.devices("cpu")[0]
    except Exception:
        return None
    return dev if jax.devices()[0] != dev else None


def host_compute():
    """Context manager pinning uncommitted jax computation to the host
    CPU backend.  The eager/discovery path runs under it — per-primitive
    dispatch to a remote accelerator would cost a round-trip each; only
    compiled replay programs run on the accelerator."""
    dev = host_cpu_device()
    return jax.default_device(dev) if dev is not None else \
        contextlib.nullcontext()


def to_device(t: Table, cap: Optional[int] = None) -> DTable:
    n = t.num_rows
    cap = cap or size_class(n)
    cols: Dict[str, DCol] = {}
    for name, c in t.columns.items():
        host = np.asarray(c.data)
        data = jnp.asarray(_pad(host, cap))
        valid = jnp.asarray(_pad(c.validity(), cap, False))
        bounds = None
        if c.ctype.kind in ("int32", "int64", "date", "decimal") and n > 0:
            hv = host[c.validity()[:n]] if c.valid is not None else host[:n]
            if len(hv):
                bounds = (int(hv.min()), int(hv.max()))
        cols[name] = DCol(data, valid, c.ctype, c.dictionary, bounds)
    alive = jnp.asarray(_pad(np.ones(n, dtype=bool), cap, False))
    return DTable(cols, alive)


def to_host(dt: DTable) -> Table:
    alive = np.asarray(dt.alive)
    cols: Dict[str, Column] = {}
    for name, c in dt.columns.items():
        data = np.asarray(c.data)[alive]
        valid = np.asarray(c.valid)[alive]
        cols[name] = Column(data, c.ctype,
                            None if valid.all() else valid, c.dictionary)
    return Table(cols)


# ---------------------------------------------------------------------------
# streaming H2D prefetch ring (out-of-core chunked execution)
# ---------------------------------------------------------------------------


class ChunkPrefetcher:
    """Double-buffered host->HBM staging ring for the out-of-core
    streaming executor (docs/ARCHITECTURE.md "Streaming out-of-core
    pipeline").

    ``get(i)`` returns chunk ``i``'s staged device arguments; while the
    caller's compiled launch computes on them, a single background
    thread runs ``stage_fn`` (scan-pool read + ``jax.device_put``) for
    chunks ``i+1 .. i+depth``, so the next launch starts without
    waiting on the transfer.  ``depth=0`` is fully synchronous — the
    ring degenerates to the pre-streaming behavior, which is also the
    degraded mode when a background stage fails (the PR-5 ``io.prefetch``
    fault site fires inside the staging path): the stream slows down,
    it never wedges or drops a chunk.

    Counters: ``io.prefetch.hit`` (chunk staged ahead and ready at
    ``get``), ``io.prefetch.miss`` (staged synchronously or still in
    flight), ``engine.h2d.overlap_s`` (wall spent staging in the
    background — transfer time hidden behind compute); ``stage_fn``
    itself accounts ``engine.h2d.bytes``.
    """

    def __init__(self, stage_fn, n_chunks: int, depth: int = 2):
        self._stage = stage_fn
        self._n = int(n_chunks)
        self._depth = max(int(depth), 0)
        self._futs: Dict[int, object] = {}
        self._pool = None
        self._degraded = False
        # eager start: stage chunk 0's window now so whole-query
        # compile time hides the ring warmup
        self._schedule_ahead(-1)

    def reset(self, next_i: int = 0) -> None:
        """Rewind the ring for another pass over the same chunks (the
        repeat-execution path of a cached chunked query), pre-staging
        from chunk ``next_i`` (chunk 0's device args usually persist
        from the first pass)."""
        for fut in self._futs.values():
            fut.cancel()
        self._futs.clear()
        if not self._degraded:
            self._schedule_ahead(next_i - 1)

    def _ensure_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            # one thread: H2D staging is serialized by the transfer
            # engine anyway, and a single writer keeps the host staging
            # buffers single-producer
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ndstpu-h2d")
        return self._pool

    def _stage_bg(self, i: int):
        from ndstpu import faults
        faults.check("io.prefetch", key=str(i))
        t0 = time.monotonic()
        try:
            return self._stage(i)
        finally:
            obs.inc("engine.h2d.overlap_s", time.monotonic() - t0)

    def _schedule_ahead(self, i: int) -> None:
        if self._degraded or self._depth == 0:
            return
        for j in range(i + 1, min(i + 1 + self._depth, self._n)):
            if j not in self._futs:
                self._futs[j] = self._ensure_pool().submit(
                    self._stage_bg, j)

    def get(self, i: int):
        fut = self._futs.pop(i, None)
        if fut is not None:
            done = fut.done()
            obs.inc("io.prefetch.hit" if done else "io.prefetch.miss")
            t0 = time.monotonic()
            try:
                args = fut.result()
                if not done:   # ring behind compute: visible stall
                    obs.inc("engine.h2d.wait_s", time.monotonic() - t0)
                self._schedule_ahead(i)
                return args
            except Exception as e:  # noqa: BLE001 — degrade, don't wedge
                self._degrade(e)
        else:
            obs.inc("io.prefetch.miss")
            self._schedule_ahead(i)
        return self._stage(i)

    def _degrade(self, exc: Exception) -> None:
        if not self._degraded:
            self._degraded = True
            obs.inc("io.prefetch.degraded")
            obs.annotate(
                io_prefetch_degraded=f"{type(exc).__name__}: {exc}")
        for fut in self._futs.values():
            fut.cancel()
        self._futs.clear()

    def close(self) -> None:
        for fut in self._futs.values():
            fut.cancel()
        self._futs.clear()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


# ---------------------------------------------------------------------------
# jnp expression evaluation (device mirror of ex.Evaluator)
# ---------------------------------------------------------------------------


def _table_content_fp(t) -> str:
    """Content hash of a columnar.Table: column names, ctypes,
    dictionaries, and a crc over data+validity bytes.  Process-stable —
    id()-keyed fingerprints made persisted compile records unmatchable
    across processes (and pinned key stability on object lifetime).
    Memoized on the Table (immutable once inlined): _plan_fp runs at
    every memo node, and re-CRCing a large inline table per node would
    turn an O(1) lookup into O(bytes)."""
    # memo token guards against mutation after first fingerprinting: a
    # stale fp would silently key segment reuse and persisted compile
    # records, so the memo is only honored while the table still holds
    # the SAME column objects (identity, with strong refs held — bare
    # id()s could be recycled after GC) and row count
    token = (t.num_rows, tuple(t.columns.values()))
    cached = getattr(t, "_content_fp", None)
    if cached is not None and cached[0][0] == token[0] and \
            len(cached[0][1]) == len(token[1]) and \
            all(a is b for a, b in zip(cached[0][1], token[1])):
        return cached[1]
    import zlib
    parts = []
    for name in t.column_names:
        c = t.columns[name]
        data = np.ascontiguousarray(np.asarray(c.data))
        crc = zlib.crc32(data.tobytes())
        if c.valid is not None:
            crc = zlib.crc32(np.ascontiguousarray(c.valid).tobytes(), crc)
        if c.dictionary is not None:
            # length-prefix each entry: ['ab','c'] must not collide
            # with ['a','bc'] under bare concatenation
            crc = zlib.crc32(str(len(c.dictionary)).encode(), crc)
            for s in c.dictionary:
                b = str(s).encode()
                crc = zlib.crc32(f"{len(b)}:".encode() + b, crc)
        parts.append(f"{name}:{c.ctype!r}:{data.dtype}{data.shape}:{crc}")
    fp = f"T({t.num_rows};" + ";".join(parts) + ")"
    try:
        t._content_fp = (token, fp)
    except (AttributeError, TypeError):
        pass  # slotted/frozen table: recompute next time
    return fp


def _plan_fp(o, out: Optional[list] = None) -> Optional[str]:
    """Structural fingerprint of a plan/expression tree.

    Unlike ``repr``, covers EVERY dataclass field (Scan's repr hides its
    pruned columns and pushed-down predicate; Literal's hides its ctype).
    Every leaf is fingerprinted by CONTENT (inline tables by column
    crc via _table_content_fp), never by id()/default repr — the
    fingerprint must be stable across processes because it keys
    persisted compile records and the replay programs' argument names
    (which feed the XLA persistent-cache key)."""
    top = out is None
    if top:
        out = []
    if isinstance(o, lp.InlineTable):
        out.append(f"IT{_table_content_fp(o.table)}")
    elif dataclasses.is_dataclass(o) and not isinstance(o, type):
        out.append(type(o).__name__)
        out.append("(")
        for f in dataclasses.fields(o):
            _plan_fp(getattr(o, f.name), out)
            out.append(",")
        out.append(")")
    elif isinstance(o, (list, tuple)):
        out.append("[")
        for x in o:
            _plan_fp(x, out)
            out.append(",")
        out.append("]")
    elif isinstance(o, np.ndarray):
        # repr() elides long arrays ("...") — fingerprint the bytes
        import zlib
        out.append(f"ND{o.dtype}{o.shape}{zlib.crc32(o.tobytes())}")
    else:
        r = repr(o)
        # default object repr ("<X object at 0x...>") embeds a
        # process-local address; a fingerprint built from it can never
        # match across processes and would silently disable record
        # reuse.  Anchored to the default-repr shape — a bare
        # " at 0x" substring check would false-positive on ordinary
        # string literals in predicates.
        import re as _re
        if _re.search(r"<[^<>]* at 0x[0-9a-fA-F]+>", r):
            raise TypeError(
                f"_plan_fp: {type(o).__name__} has no content-based "
                f"repr; add an explicit fingerprint branch")
        out.append(r)
    if top:
        return "".join(out)
    return None


class Unsupported(Exception):
    """Raised at build time when an expr/plan has no device lowering.

    ``code`` is the static-analyzer diagnostic (NDS2xx, see
    ndstpu/analysis/diagnostics.py) that predicts this raise site, so a
    runtime fallback can say WHY in the tracer sidecar and run ledger.
    Data-dependent guards the analyzer cannot see statically (rank
    pairing capacity, distinct column type) stay uncoded."""

    def __init__(self, msg: str, code: Optional[str] = None):
        super().__init__(msg)
        self.code = code


def _civil_from_days(days: jnp.ndarray):
    """days since 1970-01-01 -> (year, month, day), integer math only
    (int32 throughout: safe while |days| + 719468 < 2^31, i.e. any
    date the engine can represent)."""
    z = days.astype(jnp.int32) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    year = y + (m <= 2)
    return year.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)


def _dict_lookup_bool(c: DCol, fn) -> jnp.ndarray:
    """Host predicate per dictionary entry -> device bool gather."""
    hits = np.array([bool(fn(str(x))) for x in c.dictionary], dtype=bool)
    table = jnp.asarray(np.concatenate([hits, [False]]))  # -1 -> False
    return table[c.data]


def _dict_remap(c: DCol, fn) -> DCol:
    """Host string->string map per dictionary entry -> new dict + gather."""
    vals = [fn(str(x)) for x in c.dictionary]
    uniq = np.unique(np.asarray(vals, dtype=str)) if vals else \
        np.empty(0, dtype=str)
    remap = (np.searchsorted(uniq, np.asarray(vals, dtype=str))
             .astype(np.int32) if vals else np.empty(0, np.int32))
    table = jnp.asarray(np.concatenate([remap, [-1]]).astype(np.int32))
    return DCol(table[c.data], c.valid, STRING, uniq.astype(object))


def _translate(c: DCol, merged: np.ndarray) -> jnp.ndarray:
    """Device codes of `c` re-expressed in `merged` dictionary order.
    Unmatched/-1 codes become -2 (never equal to a valid code)."""
    if c.dictionary is None or len(c.dictionary) == 0:
        return jnp.full(c.data.shape, -2, jnp.int32)
    pos = np.searchsorted(merged, c.dictionary.astype(str))
    posc = np.clip(pos, 0, max(len(merged) - 1, 0))
    hit = merged[posc] == c.dictionary.astype(str) if len(merged) else \
        np.zeros(len(c.dictionary), dtype=bool)
    mapping = np.where(hit, posc, -2).astype(np.int32)
    table = jnp.asarray(np.concatenate([mapping, [-2]]).astype(np.int32))
    return table[c.data]


def _merged_dict(cols: Sequence[DCol]) -> np.ndarray:
    parts = [c.dictionary.astype(str) for c in cols
             if c.dictionary is not None and len(c.dictionary)]
    if not parts:
        return np.empty(0, dtype=str)
    return np.unique(np.concatenate(parts))


# ---------------------------------------------------------------------------
# runtime parameter binding (canonical plans — analysis/canon.py)
# ---------------------------------------------------------------------------
#
# Canonicalized plans carry ex.Param / ex.InParam where the SQL text had
# literals; the concrete values travel OUTSIDE the plan as an
# ex.ParamBinding, so one traced program serves every rendering of a
# template.  Scalars become broadcast columns (no point bounds — bounds
# would bake the value back into the traced program); string parameters
# become host-computed hit tables over the operand's dictionary, exactly
# like literal string predicates, except the table is a replay ARGUMENT
# instead of a traced constant.  During discovery every table/vector
# materialization is recorded into the program's ``param_spec`` so the
# replay argument subtree can be rebuilt for any later binding; the
# jitted replay pops the spec positionally, mirroring the size-plan
# record discipline.

_ACTIVE_PARAMS = threading.local()


def _active_params() -> Optional["_ParamCtx"]:
    return getattr(_ACTIVE_PARAMS, "ctx", None)


def _param_scalar_np(value, ctype: DType):
    """Host conversion of one bound scalar to its device representation
    (mirrors JEval._lit dtype choices, minus the point bounds)."""
    if ctype.kind == "bool":
        return np.bool_(value)
    if ctype.kind == "decimal":
        v = value * 10 ** ctype.scale if isinstance(value, int) \
            else round(value * 10 ** ctype.scale)
        return np.int64(v)
    if ctype.kind == "float64":
        return np.float64(value)
    if ctype.kind in ("int32", "date"):
        return np.int32(value)
    if ctype.kind == "int64":
        return np.int64(value)
    raise Unsupported(f"parameter scalar {ctype.kind}", code="NDS201")


_PDICT_OPS = {
    "=": lambda a, b: a == b, "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
}


def _pdict_hits(value, op: str, swapped: bool, dictionary) -> np.ndarray:
    """Hit table over a sorted string dictionary for one bound string
    value (or IN tuple): len(dict)+1 bools, last entry False so the -1
    NULL code gathers False (cf. _dict_lookup_bool).  Host python string
    comparison matches np.unique's lexicographic dictionary order, so
    ordered operators agree with the merged-dict literal path."""
    if op == "in":
        vals = set(str(v) for v in value)
        hits = [str(x) in vals for x in dictionary]
    else:
        fn = _PDICT_OPS[op]
        v = str(value)
        hits = [fn(v, str(x)) if swapped else fn(str(x), v)
                for x in dictionary]
    return np.asarray(hits + [False], dtype=bool)


def _gcode_np(value, dictionary) -> np.int32:
    """Bind-time dictionary-code lookup for a scalar string parameter:
    the code of ``value`` in the frozen sorted dictionary, or the miss
    sentinel ``len(dictionary)`` — outside every real code AND distinct
    from the -1/-2 NULL/translate-miss codes that appear in DATA, so
    ``= miss`` is never true and ``<> miss`` holds for every present
    row."""
    from ndstpu import obs
    obs.inc("engine.dict.lookups")
    v = str(value)
    n = len(dictionary)
    if n:
        pos = int(np.searchsorted(
            np.asarray(dictionary).astype(str), v))
        if pos < n and str(dictionary[pos]) == v:
            return np.int32(pos)
    obs.inc("engine.dict.misses")
    return np.int32(n)


def _pvec_np(values, ctype: DType) -> np.ndarray:
    """Coerced device-representation vector for a bound IN-list over a
    numeric/date operand (mirrors JEval._in_list's literal path: decimal
    values arrive scale-shifted from coerce_in_values)."""
    vals, _had_null = ex.coerce_in_values(ctype, values)
    if ctype.kind == "float64":
        return np.array(vals, dtype=np.float64)
    return np.array(vals, dtype=np.int64)


class _ParamCtx:
    """One execution's bound parameters.

    mode ``concrete``: ``values`` holds python literals; hit tables and
    vectors are computed on host directly (and appended to ``spec`` when
    ``record`` is set, i.e. during discovery).  mode ``trace``: inside
    the jitted replay — scalars/tables/vectors are read from the traced
    ``"\\x00params"`` argument subtree; non-scalar entries pop ``spec``
    positionally, exactly like the size-plan record."""

    def __init__(self, values, mode: str, spec: Optional[list] = None,
                 traced: Optional[dict] = None, record: bool = False):
        self.values = values
        self.mode = mode
        self.spec = spec if spec is not None else []
        self.pos = 0
        self.traced = traced if traced is not None else {}
        self.record = record

    def _pop(self, kind: str) -> int:
        j = self.pos
        self.pos += 1
        if j >= len(self.spec) or self.spec[j][0] != kind:
            raise RuntimeError(f"param-spec drift (expected {kind})")
        return j

    def scalar(self, slot: int, ctype: DType, cap: int) -> DCol:
        if self.mode == "trace":
            v = self.traced[f"s{slot}"]
        else:
            v = _param_scalar_np(self.values[slot], ctype)
        data = jnp.broadcast_to(jnp.asarray(v), (cap,))
        return DCol(data, jnp.ones(cap, bool), ctype)

    def str_table(self, slot: int, op: str, swapped: bool,
                  dictionary) -> jnp.ndarray:
        if self.mode == "trace":
            return self.traced[f"d{self._pop('pdict')}"]
        if self.record:
            self.spec.append(("pdict", slot, op, swapped,
                              np.asarray(dictionary, dtype=object)))
        return jnp.asarray(
            _pdict_hits(self.values[slot], op, swapped, dictionary))

    def num_vec(self, slot: int, ctype: DType) -> jnp.ndarray:
        if self.mode == "trace":
            return self.traced[f"v{self._pop('pvec')}"]
        if self.record:
            self.spec.append(("pvec", slot, ctype))
        return jnp.asarray(_pvec_np(self.values[slot], ctype))

    def str_code(self, slot: int, dictionary) -> jnp.ndarray:
        """Scalar dict-code string parameter (=/<> against a frozen
        global dictionary): one traced int32 instead of a len(dict)+1
        hit table per binding."""
        if self.mode == "trace":
            return self.traced[f"g{self._pop('gcode')}"]
        if self.record:
            self.spec.append(("gcode", slot,
                              np.asarray(dictionary, dtype=object)))
        return jnp.asarray(_gcode_np(self.values[slot], dictionary))


@contextlib.contextmanager
def _params_bound(ctx: Optional[_ParamCtx]):
    """Install a parameter context for the device evaluator AND — when
    concrete values are present — the numpy fallback path
    (ex.bound_params) for the dynamic extent."""
    prev = getattr(_ACTIVE_PARAMS, "ctx", None)
    _ACTIVE_PARAMS.ctx = ctx
    try:
        if ctx is not None and ctx.values is not None:
            with ex.bound_params(ctx.values):
                yield
        else:
            yield
    finally:
        _ACTIVE_PARAMS.ctx = prev


def _param_args_np(spec, binding: Optional[ex.ParamBinding]) -> dict:
    """Host argument subtree (the ``"\\x00params"`` replay input) for one
    program under one binding: every bindable scalar slot plus one hit
    table / coerced vector per recorded spec entry."""
    out = {}
    if binding is None:
        return out
    for slot, ctype in binding.scalars:
        out[f"s{slot}"] = _param_scalar_np(binding.values[slot], ctype)
    for j, ent in enumerate(spec or ()):
        if ent[0] == "pdict":
            _tag, slot, op, swapped, dic = ent
            out[f"d{j}"] = _pdict_hits(binding.values[slot], op,
                                       swapped, dic)
        elif ent[0] == "gcode":
            _tag, slot, dic = ent
            out[f"g{j}"] = _gcode_np(binding.values[slot], dic)
        else:
            _tag, slot, ctype = ent
            out[f"v{j}"] = _pvec_np(binding.values[slot], ctype)
    return out


class JEval:
    """Evaluates an Expr over a DTable with jnp ops (traceable)."""

    _CMP = {"=", "<>", "<", "<=", ">", ">="}
    _ARITH = {"+", "-", "*", "/", "%"}

    def __init__(self, table: DTable):
        self.t = table
        self.cap = table.capacity

    # -- helpers -------------------------------------------------------------

    def _lit(self, value, ctype: Optional[DType]) -> DCol:
        cap = self.cap
        if value is None:
            ct = ctype or INT32
            return DCol(jnp.zeros(cap, jnp_dtype(ct)),
                        jnp.zeros(cap, bool), ct,
                        np.empty(0, object) if ct.kind == "string" else None)
        valid = jnp.ones(cap, bool)
        if isinstance(value, bool):
            return DCol(jnp.full(cap, value, jnp.bool_), valid, BOOL)
        if isinstance(value, int):
            # point bounds: every valid row is exactly this value —
            # lets Case-of-literals keys (the fusion pass's bucket id)
            # stay on the small-domain group-by/bitmap paths
            ct = ctype or (INT64 if abs(value) > 2 ** 31 - 1 else INT32)
            if ct.kind == "decimal":
                v = value * 10 ** ct.scale
                return DCol(jnp.full(cap, v, jnp.int64),
                            valid, ct, bounds=(v, v))
            return DCol(jnp.full(cap, value, jnp_dtype(ct)), valid, ct,
                        bounds=(int(value), int(value)))
        if isinstance(value, float):
            if ctype and ctype.kind == "decimal":
                v = round(value * 10 ** ctype.scale)
                return DCol(jnp.full(cap, v, jnp.int64),
                            valid, ctype, bounds=(v, v))
            return DCol(jnp.full(cap, value, jnp.float64), valid, FLOAT64)
        if isinstance(value, str):
            d = np.array([value], dtype=object)
            return DCol(jnp.zeros(cap, jnp.int32), valid, STRING, d)
        raise Unsupported(f"literal {value!r}", code="NDS201")

    def cast(self, c: DCol, target: DType) -> DCol:
        k, tk = c.ctype.kind, target.kind
        if k == tk and (tk != "decimal" or c.ctype.scale == target.scale):
            if tk != "decimal":
                return c
            if target.precision < c.ctype.precision:
                # Spark non-ANSI overflow: out-of-precision values -> NULL
                limit = 10 ** target.precision
                ok = jnp.abs(c.data) < limit
                b = (-(limit - 1), limit - 1)
                if c.bounds is not None:
                    b = (max(b[0], c.bounds[0]), min(b[1], c.bounds[1]))
                return DCol(c.data, c.valid & ok, target, c.dictionary,
                            bounds=b if b[0] <= b[1] else None)
            return DCol(c.data, c.valid, target, c.dictionary,
                        bounds=c.bounds)
        if tk == "float64":
            if k == "decimal":
                data = c.data.astype(jnp.float64) / (10 ** c.ctype.scale)
            elif k == "string":
                data, valid = self._string_parse_float(c)
                return DCol(data, valid, FLOAT64)
            else:
                data = c.data.astype(jnp.float64)
            return DCol(data, c.valid, FLOAT64)
        if tk == "decimal":
            scale = 10 ** target.scale
            bounds = None
            if k == "decimal":
                shift = target.scale - c.ctype.scale
                if shift >= 0:
                    data = c.data * (10 ** shift)
                    if c.bounds is not None:
                        m = 10 ** shift
                        bounds = (c.bounds[0] * m, c.bounds[1] * m)
                else:
                    d = 10 ** (-shift)
                    sign = jnp.sign(c.data)
                    data = sign * ((jnp.abs(c.data) + d // 2) // d)
                    if c.bounds is not None:
                        # round-half-away-from-zero is monotonic
                        def _rd(v: int) -> int:
                            s = -1 if v < 0 else 1
                            return s * ((abs(v) + d // 2) // d)
                        bounds = (_rd(c.bounds[0]), _rd(c.bounds[1]))
            elif k == "float64":
                x = c.data * scale
                data = (jnp.floor(jnp.abs(x) + 0.5) *
                        jnp.sign(x)).astype(jnp.int64)
            elif k == "string":
                f, valid = self._string_parse_float(c)
                x = f * scale
                data = (jnp.floor(jnp.abs(x) + 0.5) *
                        jnp.sign(x)).astype(jnp.int64)
                return DCol(data, valid, target)
            else:
                data = c.data.astype(jnp.int64) * scale
                if k in ("int32", "int64") and c.bounds is not None:
                    bounds = (c.bounds[0] * scale, c.bounds[1] * scale)
                elif k == "bool":
                    bounds = (0, scale)
            return DCol(data.astype(jnp.int64), c.valid, target,
                        bounds=bounds)
        if tk in ("int32", "int64"):
            dt = jnp.int64 if tk == "int64" else jnp.int32
            bounds = None
            if k == "decimal":
                data = jnp.trunc(
                    c.data / (10 ** c.ctype.scale)).astype(dt)
                if c.bounds is not None and \
                        max(abs(c.bounds[0]), abs(c.bounds[1])) < (1 << 53):
                    # the data path divides in float64; below 2^53 the
                    # scaled value is exact and trunc(fl(v/s)) == v//s
                    # (an up-crossing needs s-r <= hi*2^-53 < 1, and
                    # exact multiples divide exactly), so exact-integer
                    # bounds match the computed values.  At or above
                    # 2^53 they can disagree -> no bounds (sort path).
                    s = 10 ** c.ctype.scale
                    # trunc-toward-zero is monotonic
                    def _tr(v: int) -> int:
                        return -((-v) // s) if v < 0 else v // s
                    bounds = (_tr(c.bounds[0]), _tr(c.bounds[1]))
            elif k == "string":
                f, valid = self._string_parse_float(c)
                return DCol(f.astype(dt), valid, target)
            else:
                data = c.data.astype(dt)
                if k in ("int32", "int64") and c.bounds is not None:
                    bounds = c.bounds
                elif k == "bool":
                    bounds = (0, 1)
            if bounds is not None and tk == "int32" and not (
                    -(1 << 31) <= bounds[0] and bounds[1] < (1 << 31)):
                # narrowing may wrap valid values; no safe bounds
                bounds = None
            return DCol(data, c.valid, target, bounds=bounds)
        if tk == "date":
            if k == "string":
                return self._string_parse_date(c)
            return DCol(c.data.astype(jnp.int32), c.valid, DATE)
        if tk == "bool":
            return DCol(c.data.astype(jnp.bool_), c.valid, BOOL)
        raise Unsupported(f"cast {c.ctype} -> {target}", code="NDS204")

    def _string_parse_float(self, c: DCol):
        vals = np.zeros(len(c.dictionary) + 1, dtype=np.float64)
        ok = np.zeros(len(c.dictionary) + 1, dtype=bool)
        for i, s in enumerate(c.dictionary):
            try:
                vals[i] = float(str(s))
                ok[i] = True
            except ValueError:
                pass
        data = jnp.asarray(vals)[c.data]
        valid = c.valid & jnp.asarray(ok)[c.data]
        return data, valid

    def _string_parse_date(self, c: DCol) -> DCol:
        base = np.datetime64("1970-01-01")
        vals = np.zeros(len(c.dictionary) + 1, dtype=np.int32)
        ok = np.zeros(len(c.dictionary) + 1, dtype=bool)
        for i, s in enumerate(c.dictionary):
            try:
                vals[i] = columnar.parse_date_days(str(s))
                ok[i] = True
            except ValueError:
                pass
        data = jnp.asarray(vals)[c.data]
        valid = c.valid & jnp.asarray(ok)[c.data]
        return DCol(data, valid, DATE)

    # -- entry ---------------------------------------------------------------

    def eval(self, e: ex.Expr) -> DCol:
        if isinstance(e, ex.ColumnRef):
            return self.t.column(e.name)
        if isinstance(e, ex.Literal):
            return self._lit(e.value, e.ctype)
        if isinstance(e, ex.Cast):
            return self.cast(self.eval(e.operand), e.target)
        if isinstance(e, ex.BinOp):
            return self._binop(e)
        if isinstance(e, ex.UnaryOp):
            return self._unary(e)
        if isinstance(e, ex.Case):
            return self._case(e)
        if isinstance(e, ex.Func):
            return self._func(e)
        if isinstance(e, ex.InList):
            return self._in_list(e)
        if isinstance(e, ex.Param):
            return self._param(e)
        if isinstance(e, ex.InParam):
            return self._in_param(e)
        raise Unsupported(f"expr {type(e).__name__}", code="NDS201")

    # -- operators -----------------------------------------------------------

    def _binop(self, e: ex.BinOp) -> DCol:
        op = e.op
        if op in ("and", "or"):
            lc, rc = self.eval(e.left), self.eval(e.right)
            ld = lc.data.astype(bool) & lc.valid
            rd = rc.data.astype(bool) & rc.valid
            if op == "and":
                data = ld & rd
                definite_false = (~lc.data.astype(bool) & lc.valid) | \
                                 (~rc.data.astype(bool) & rc.valid)
                valid = (lc.valid & rc.valid) | definite_false
            else:
                data = ld | rd
                valid = (lc.valid & rc.valid) | ld | rd
            return DCol(data, valid, BOOL)
        if op in self._CMP:
            pc = self._param_compare(e, op)
            if pc is not None:
                return pc
        lc, rc = self.eval(e.left), self.eval(e.right)
        if op in self._CMP:
            return self._compare(op, lc, rc)
        if op in self._ARITH:
            return self._arith(op, lc, rc)
        if op == "||":
            return self._concat_pair(lc, rc)
        raise Unsupported(f"binop {op}", code="NDS202")

    def _align_compare(self, lc: DCol, rc: DCol):
        lk, rk = lc.ctype.kind, rc.ctype.kind
        if lk == "string" and rk == "string":
            if lc.dictionary is not None and rc.dictionary is not None and \
                    len(lc.dictionary) == len(rc.dictionary) and \
                    np.array_equal(lc.dictionary, rc.dictionary):
                return lc.data, rc.data
            merged = _merged_dict([lc, rc])
            return _translate(lc, merged), _translate(rc, merged)
        if lk == "decimal" or rk == "decimal":
            if "float64" in (lk, rk):
                return (self.cast(lc, FLOAT64).data,
                        self.cast(rc, FLOAT64).data)
            s = max(lc.ctype.scale if lk == "decimal" else 0,
                    rc.ctype.scale if rk == "decimal" else 0)
            tgt = decimal(38, s)
            return self.cast(lc, tgt).data, self.cast(rc, tgt).data
        if lk == "float64" or rk == "float64":
            return (self.cast(lc, FLOAT64).data,
                    self.cast(rc, FLOAT64).data)
        return lc.data, rc.data

    def _compare(self, op: str, lc: DCol, rc: DCol) -> DCol:
        # implicit string->date coercion (Spark semantics), mirroring
        # ex.Evaluator._compare so both backends stay bit-identical:
        # without it a bare `d_date >= '2002-4-01'` compared date days
        # against the literal's dictionary code
        if lc.ctype.kind == "date" and rc.ctype.kind == "string":
            rc = self._string_to_date(rc)
        elif rc.ctype.kind == "date" and lc.ctype.kind == "string":
            lc = self._string_to_date(lc)
        ld, rd = self._align_compare(lc, rc)
        data = {"=": lambda: ld == rd, "<>": lambda: ld != rd,
                "<": lambda: ld < rd, "<=": lambda: ld <= rd,
                ">": lambda: ld > rd, ">=": lambda: ld >= rd}[op]()
        return DCol(data, lc.valid & rc.valid, BOOL)

    def _string_to_date(self, c: DCol) -> DCol:
        """Parse string codes as dates through a host-parsed dictionary
        table; unparseable entries and negative codes become NULL
        (same table as ex.string_to_date_column)."""
        days, ok = ex.parse_dictionary_days(c.dictionary)
        if not len(days):
            return DCol(jnp.zeros(self.cap, jnp.int32),
                        jnp.zeros(self.cap, bool), DATE)
        codes_ok = c.data >= 0
        idx = jnp.clip(c.data, 0, len(days) - 1)
        out = jnp.where(codes_ok, jnp.asarray(days)[idx], jnp.int32(0))
        valid = c.valid & codes_ok & jnp.asarray(ok)[idx]
        return DCol(out, valid, DATE)

    def _arith(self, op: str, lc: DCol, rc: DCol) -> DCol:
        lk, rk = lc.ctype.kind, rc.ctype.kind
        valid = lc.valid & rc.valid
        if lk == "date" and rk in ("int32", "int64"):
            delta = rc.data.astype(jnp.int32)
            data = lc.data + (delta if op == "+" else -delta)
            return DCol(data, valid, DATE)
        if op == "/":
            ld = self.cast(lc, FLOAT64).data
            rd = self.cast(rc, FLOAT64).data
            safe = jnp.where(rd == 0, 1.0, rd)
            return DCol(ld / safe, valid & (rd != 0), FLOAT64)
        if lk == "decimal" or rk == "decimal":
            if "float64" in (lk, rk):
                ld = self.cast(lc, FLOAT64).data
                rd = self.cast(rc, FLOAT64).data
                data = {"+": ld + rd, "-": ld - rd, "*": ld * rd,
                        "%": jnp.mod(ld, jnp.where(rd == 0, 1, rd))}[op]
                return DCol(data, valid, FLOAT64)
            ls = lc.ctype.scale if lk == "decimal" else 0
            rs = rc.ctype.scale if rk == "decimal" else 0
            if op == "*":
                data = lc.data.astype(jnp.int64) * rc.data.astype(jnp.int64)
                return DCol(data, valid, decimal(38, ls + rs))
            s = max(ls, rs)
            ld = lc.data.astype(jnp.int64) * (10 ** (s - ls))
            rd = rc.data.astype(jnp.int64) * (10 ** (s - rs))
            if op == "%":
                safe = jnp.where(rd == 0, 1, rd)
                return DCol(jnp.mod(ld, safe), valid & (rd != 0),
                            decimal(38, s))
            data = ld + rd if op == "+" else ld - rd
            return DCol(data, valid, decimal(38, s))
        tgt = ex.common_type(lc.ctype, rc.ctype)
        ld = self.cast(lc, tgt).data
        rd = self.cast(rc, tgt).data
        if op == "%":
            safe = jnp.where(rd == 0, 1, rd)
            return DCol(jnp.mod(ld, safe), valid & (rd != 0), tgt)
        data = {"+": ld + rd, "-": ld - rd, "*": ld * rd}[op]
        return DCol(data, valid, tgt)

    def _unary(self, e: ex.UnaryOp) -> DCol:
        c = self.eval(e.operand)
        if e.op == "not":
            return DCol(~c.data.astype(bool), c.valid, BOOL)
        if e.op == "neg":
            return DCol(-c.data, c.valid, c.ctype)
        if e.op == "isnull":
            return DCol(~c.valid, jnp.ones(self.cap, bool), BOOL)
        if e.op == "isnotnull":
            return DCol(c.valid, jnp.ones(self.cap, bool), BOOL)
        raise Unsupported(f"unary {e.op}", code="NDS203")

    def _case(self, e: ex.Case) -> DCol:
        conds, vals = [], []
        for cond, val in e.whens:
            cc = self.eval(cond)
            conds.append(cc.data.astype(bool) & cc.valid)
            vals.append(self.eval(val))
        default = self.eval(e.default) if e.default is not None else None
        cands = vals + ([default] if default is not None else [])
        tgt = cands[0].ctype
        for c in cands[1:]:
            if ex.is_numeric(c.ctype) and ex.is_numeric(tgt):
                tgt = ex.common_type(tgt, c.ctype)
            elif c.ctype.kind != tgt.kind:
                tgt = c.ctype if tgt.kind == "int32" else tgt
        if tgt.kind == "string":
            # all-branch merged dictionary, then code selection on device
            scols = [self.cast(v, STRING) for v in vals]
            sdef = self.cast(default, STRING) if default is not None else None
            allc = scols + ([sdef] if sdef is not None else [])
            merged = _merged_dict(allc)
            data = jnp.full(self.cap, -2, jnp.int32)
            valid = jnp.zeros(self.cap, bool)
            taken = jnp.zeros(self.cap, bool)
            for cond, vc in zip(conds, scols):
                sel = cond & ~taken
                data = jnp.where(sel, _translate(vc, merged), data)
                valid = jnp.where(sel, vc.valid, valid)
                taken = taken | cond
            if sdef is not None:
                data = jnp.where(taken, data, _translate(sdef, merged))
                valid = jnp.where(taken, valid, sdef.valid)
            data = jnp.where(valid, data, -1)
            return DCol(data, valid, STRING, merged.astype(object))
        data = jnp.zeros(self.cap, jnp_dtype(tgt))
        valid = jnp.zeros(self.cap, bool)
        taken = jnp.zeros(self.cap, bool)
        branch_bounds = []
        for cond, val in zip(conds, vals):
            vc = self.cast(val, tgt)
            sel = cond & ~taken
            data = jnp.where(sel, vc.data, data)
            valid = jnp.where(sel, vc.valid, valid)
            taken = taken | cond
            branch_bounds.append(vc.bounds)
        if default is not None:
            dc = self.cast(default, tgt)
            data = jnp.where(taken, data, dc.data)
            valid = jnp.where(taken, valid, dc.valid)
            # a NULL-literal default contributes no VALID rows, so it
            # cannot widen the bounds of the output's valid values
            if not (isinstance(e.default, ex.Literal)
                    and e.default.value is None):
                branch_bounds.append(dc.bounds)
        bounds = None
        if tgt.kind in ("int32", "int64", "decimal") and branch_bounds \
                and all(b is not None for b in branch_bounds):
            # every valid output row carries some branch's valid value,
            # so the union of branch bounds bounds the output
            bounds = (min(b[0] for b in branch_bounds),
                      max(b[1] for b in branch_bounds))
        return DCol(data.astype(jnp_dtype(tgt)), valid, tgt,
                    bounds=bounds)

    def _in_list(self, e: ex.InList) -> DCol:
        c = self.eval(e.operand)
        had_null = False
        if c.ctype.kind == "string":
            vals = set(str(v) for v in e.values)
            data = _dict_lookup_bool(c, lambda s: s in vals)
        elif c.ctype.kind == "decimal":
            vals, had_null = ex.coerce_in_values(c.ctype, e.values)
            data = jnp.isin(c.data, jnp.asarray(
                np.array(vals, dtype=np.int64))) if vals else \
                jnp.zeros(c.capacity, bool)
        else:
            vals, had_null = ex.coerce_in_values(c.ctype, e.values)
            if not vals:
                data = jnp.zeros(c.capacity, bool)
            else:
                arr = np.asarray(vals)
                if arr.dtype == object or arr.dtype.kind in "US":
                    raise Unsupported(f"IN-list literals {arr.dtype} for "
                                      f"{c.ctype.kind} column",
                                      code="NDS212")
                data = jnp.isin(c.data, jnp.asarray(arr))
        if e.negated:
            # x NOT IN (..., NULL) is never TRUE (NULL semantics)
            data = jnp.zeros_like(data) if had_null else ~data
        return DCol(data, c.valid, BOOL)

    # -- bound parameters (canonical plans) ----------------------------------

    def _param(self, e: ex.Param) -> DCol:
        ctx = _active_params()
        if ctx is None or e.shape:
            raise Unsupported(f"unbound parameter S{e.slot}",
                              code="NDS201")
        if e.ctype.kind == "string":
            # string scalars only bind through the dictionary-compare /
            # IN intercepts; reaching generic eval means the
            # canonicalizer lifted a string the device cannot broadcast
            raise Unsupported("string parameter outside dictionary "
                              "context", code="NDS206")
        return ctx.scalar(e.slot, e.ctype, self.cap)

    def _param_compare(self, e: ex.BinOp, op: str) -> Optional[DCol]:
        """String-parameter comparison: host hit table over the other
        side's dictionary (the parametric twin of the literal-string
        merged-dict path)."""
        ctx = _active_params()
        if ctx is None:
            return None
        for par, other, swapped in ((e.right, e.left, False),
                                    (e.left, e.right, True)):
            if isinstance(par, ex.Param) and not par.shape and \
                    par.ctype.kind == "string":
                oc = self.eval(other)
                if oc.ctype.kind != "string" or oc.dictionary is None:
                    raise Unsupported("string parameter vs non-dictionary"
                                      " operand", code="NDS206")
                from ndstpu.io import gdict
                if op in ("=", "<>") and gdict.enabled():
                    # scalar dict-code param: the bound value resolves
                    # to one frozen-dictionary code on the host (miss ->
                    # len(dict) sentinel), so equality runs on raw codes
                    # and every binding replays one traced scalar
                    code = ctx.str_code(par.slot, oc.dictionary)
                    eq = oc.data == code
                    return DCol(eq if op == "=" else ~eq, oc.valid, BOOL)
                table = ctx.str_table(par.slot, op, swapped,
                                      oc.dictionary)
                return DCol(table[oc.data], oc.valid, BOOL)
        return None

    def _in_param(self, e: ex.InParam) -> DCol:
        ctx = _active_params()
        if ctx is None:
            raise Unsupported(f"unbound parameter P{e.slot}",
                              code="NDS201")
        c = self.eval(e.operand)
        if c.ctype.kind == "string":
            if c.dictionary is None:
                raise Unsupported("IN parameter on non-dictionary "
                                  "string", code="NDS206")
            table = ctx.str_table(e.slot, "in", False, c.dictionary)
            data = table[c.data]
        else:
            data = jnp.isin(c.data, ctx.num_vec(e.slot, c.ctype))
        if e.negated:
            # the canonicalizer only lifts NULL-free IN-lists, so plain
            # complement is exact (no three-valued NOT IN hazard)
            data = ~data
        return DCol(data, c.valid, BOOL)

    def _concat_pair(self, a: DCol, b: DCol) -> DCol:
        """String concatenation on dictionary codes.  One-sided literal:
        host remap of the other side's dictionary.  Dict x dict: host
        cross-product dictionary (guarded against blowup) + device pair
        codes.  NULL || x is NULL (SQL semantics)."""
        if a.ctype.kind != "string" or b.ctype.kind != "string":
            raise Unsupported("|| on non-string operands", code="NDS206")
        da = a.dictionary if a.dictionary is not None else np.empty(0, object)
        db = b.dictionary if b.dictionary is not None else np.empty(0, object)
        na, nb = len(da), len(db)
        valid = a.valid & b.valid & (a.data >= 0) & (b.data >= 0)
        if na == 0 or nb == 0:  # one side all-NULL
            return DCol(jnp.full(self.cap, -1, jnp.int32),
                        jnp.zeros(self.cap, bool), STRING,
                        np.empty(0, object))
        def encode(vals: np.ndarray):
            uniq, remap = np.unique(vals, return_inverse=True)
            table = jnp.asarray(np.concatenate(
                [remap.astype(np.int64), [-1]]).astype(np.int32))
            return uniq.astype(object), table

        if na == 1 or nb == 1:
            if nb == 1:
                base, vals = a, np.char.add(da.astype(str),
                                            str(db[0]))
            else:
                base, vals = b, np.char.add(str(da[0]),
                                            db.astype(str))
            uniq, table = encode(vals)
            data = jnp.where(valid, table[base.data], -1)
            return DCol(data, valid, STRING, uniq)
        if na * nb > (1 << 20):
            raise Unsupported("|| dictionary cross-product too large",
                              code="NDS213")
        uniq, table = encode(np.char.add(np.repeat(da.astype(str), nb),
                                         np.tile(db.astype(str), na)))
        pair = jnp.where(valid, a.data * nb + b.data, na * nb)
        return DCol(table[pair], valid, STRING, uniq)

    # -- functions -----------------------------------------------------------

    def _func(self, e: ex.Func) -> DCol:
        name = e.name
        if name == "concat":
            cols = [self.eval(a) for a in e.args]
            out = cols[0]
            for c in cols[1:]:
                out = self._concat_pair(out, c)
            return out
        if name == "coalesce":
            cols = [self.eval(a) for a in e.args]
            tgt = ex.coalesce_common_type(e.args,
                                          [c.ctype for c in cols])
            if tgt.kind == "string":
                scols = [self.cast(c, STRING) for c in cols]
                merged = _merged_dict(scols)
                data = jnp.full(self.cap, -1, jnp.int32)
                valid = jnp.zeros(self.cap, bool)
                for c in scols:
                    take = ~valid & c.valid
                    data = jnp.where(take, _translate(c, merged), data)
                    valid = valid | c.valid
                return DCol(data, valid, STRING, merged.astype(object))
            data = jnp.zeros(self.cap, jnp_dtype(tgt))
            valid = jnp.zeros(self.cap, bool)
            for c in cols:
                cc = self.cast(c, tgt)
                take = ~valid & cc.valid
                data = jnp.where(take, cc.data, data)
                valid = valid | cc.valid
            return DCol(data.astype(jnp_dtype(tgt)), valid, tgt)
        if name == "like":
            c = self.eval(e.args[0])
            rx = re.compile(_like_to_regex(e.args[1].value), re.S)
            data = _dict_lookup_bool(
                c, lambda s: rx.fullmatch(s) is not None)
            return DCol(data, c.valid, BOOL)
        if name in ("substr", "substring"):
            c = self.eval(e.args[0])
            start = int(e.args[1].value)
            length = int(e.args[2].value) if len(e.args) > 2 else None

            def sub(s: str) -> str:
                i = start - 1 if start > 0 else len(s) + start
                return s[i:i + length] if length is not None else s[i:]
            out = _dict_remap(self.cast(c, STRING) if c.ctype.kind != "string"
                              else c, sub)
            return DCol(out.data, c.valid, STRING, out.dictionary)
        if name == "upper":
            c = self._as_string(e.args[0])
            out = _dict_remap(c, str.upper)
            return DCol(out.data, c.valid, STRING, out.dictionary)
        if name == "lower":
            c = self._as_string(e.args[0])
            out = _dict_remap(c, str.lower)
            return DCol(out.data, c.valid, STRING, out.dictionary)
        if name == "trim":
            c = self._as_string(e.args[0])
            out = _dict_remap(c, str.strip)
            return DCol(out.data, c.valid, STRING, out.dictionary)
        if name == "length":
            c = self._as_string(e.args[0])
            lens = np.array([len(str(x)) for x in c.dictionary] + [0],
                            dtype=np.int32)
            return DCol(jnp.asarray(lens)[c.data], c.valid, INT32)
        if name == "abs":
            c = self.eval(e.args[0])
            return DCol(jnp.abs(c.data), c.valid, c.ctype)
        if name == "round":
            c = self.eval(e.args[0])
            nd = int(e.args[1].value) if len(e.args) > 1 else 0
            if c.ctype.kind == "decimal":
                if nd >= c.ctype.scale:
                    return c
                return self.cast(c, decimal(c.ctype.precision, nd))
            m = 10.0 ** nd
            data = jnp.floor(jnp.abs(c.data) * m + 0.5) / m * \
                jnp.sign(c.data)
            return DCol(data, c.valid, FLOAT64)
        if name == "floor":
            c = self.cast(self.eval(e.args[0]), FLOAT64)
            return DCol(jnp.floor(c.data), c.valid, FLOAT64)
        if name == "ceil":
            c = self.cast(self.eval(e.args[0]), FLOAT64)
            return DCol(jnp.ceil(c.data), c.valid, FLOAT64)
        if name == "sqrt":
            c = self.cast(self.eval(e.args[0]), FLOAT64)
            return DCol(jnp.sqrt(jnp.maximum(c.data, 0)), c.valid, FLOAT64)
        if name in ("year", "month", "day"):
            c = self.eval(e.args[0])
            y, m, d = _civil_from_days(c.data)
            return DCol({"year": y, "month": m, "day": d}[name],
                        c.valid, INT32)
        if name == "nullif":
            a = self.eval(e.args[0])
            b = self.eval(e.args[1])
            eqc = self._compare("=", a, b)
            eq = eqc.data & eqc.valid
            return DCol(a.data, a.valid & ~eq, a.ctype, a.dictionary)
        raise Unsupported(f"function {name}", code="NDS205")

    def _as_string(self, arg: ex.Expr) -> DCol:
        c = self.eval(arg)
        if c.ctype.kind != "string":
            raise Unsupported("cast-to-string on device", code="NDS206")
        return c

    def predicate(self, e: ex.Expr) -> jnp.ndarray:
        c = self.eval(e)
        return c.data.astype(bool) & c.valid & self.t.alive


# ---------------------------------------------------------------------------
# relational kernels (pure jnp, traceable)
# ---------------------------------------------------------------------------


def _minmax_vals(data: jnp.ndarray, valid: jnp.ndarray, kind: str,
                 is_min: bool) -> jnp.ndarray:
    """Reduction input for min/max in the data's NATIVE dtype: invalid
    rows filled with the dtype's own extremum (the reduction identity).
    Bool widens to int32 (no iinfo for bool)."""
    if kind == "bool":
        data = data.astype(jnp.int32)
    info = jnp.iinfo(data.dtype)
    sent = data.dtype.type(info.max if is_min else info.min)
    return jnp.where(valid, data, sent)


def _sum_input(data: jnp.ndarray, valid: jnp.ndarray, kind: str):
    """Summation input under the TPU precision rule: decimal/int sums
    stay exact int64 (s64 is exactly emulated on TPU via s32 pairs);
    float sums are float64 (which TPU hardware computes at f32
    precision — acceptable only for genuinely-float data)."""
    if kind in ("decimal", "int32", "int64"):
        return jnp.where(valid, data.astype(jnp.int64), jnp.int64(0))
    return jnp.where(valid, data.astype(jnp.float64), 0.0)


def _key_i64(c: DCol, alive: jnp.ndarray,
             peer: Optional[DCol] = None) -> jnp.ndarray:
    """Column -> int64 key with NULL/dead sentinels (grouping/join space).
    For strings, translates into a dictionary merged with `peer` when
    dictionaries differ."""
    if c.ctype.kind == "string":
        if peer is not None and peer.ctype.kind == "string" and not (
                c.dictionary is not None and peer.dictionary is not None and
                len(c.dictionary) == len(peer.dictionary) and
                np.array_equal(c.dictionary, peer.dictionary)):
            merged = _merged_dict([c, peer])
            data = _translate(c, merged).astype(jnp.int64)
        else:
            data = c.data.astype(jnp.int64)
    elif c.ctype.kind == "float64":
        # float64 keys STAY float64: consumers only sort and compare, and
        # the TPU X64-rewrite pass has no lowering for f64<->s64
        # bitcast-convert (a bit-pattern encoding crashes the TPU
        # compiler outright).  IEEE gives SQL semantics for free
        # (-0.0 == 0.0); NaNs fold to +inf so they group/join as one
        # value; the sentinel magnitudes (2^62) are exactly representable
        # and far outside any decimal-derived data domain.
        data = c.data.astype(jnp.float64)
        # NaNs fold to DBL_MAX (one NaN group, +inf stays distinct;
        # only a literal DBL_MAX in the data could collide)
        data = jnp.where(jnp.isnan(data),
                         jnp.finfo(jnp.float64).max, data)
        data = jnp.where(c.valid, data, jnp.float64(_NULL_KEY))
        return jnp.where(alive, data, jnp.float64(_DEAD_KEY))
    else:
        data = c.data.astype(jnp.int64)
    data = jnp.where(c.valid, data, _NULL_KEY)
    return jnp.where(alive, data, _DEAD_KEY)


def _lexsort_order(keys: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable argsort by multiple keys; keys[0] is the primary.

    ONE variadic ``lax.sort`` (num_keys=len(keys)) with an int32 iota
    payload — not a chain of per-key argsorts: a single sort HLO on TPU
    costs roughly one sort regardless of key count, and the int32
    permutation avoids x64's default int64 index arrays."""
    n = keys[0].shape[0]
    iota = jax.lax.iota(jnp.int32, n)
    return jax.lax.sort(tuple(keys) + (iota,), num_keys=len(keys),
                        is_stable=True)[-1]


def _inv_permute(order: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """out[order[i]] = vals[i] for a permutation `order`: a pair-sort
    keyed by the permutation (~9 ms at 4M on v5e) instead of a scatter
    (~29 ms) — scripts/prim_bench.py."""
    return jax.lax.sort((order, vals), num_keys=1, is_stable=True)[1]


def _group_ids(keys: List[jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                 jnp.ndarray]:
    """Dense group ids via ONE variadic sort: (gid int32, order int32,
    newgrp).  Sorted key columns come straight out of the sort — no
    per-key re-gather."""
    n = keys[0].shape[0]
    iota = jax.lax.iota(jnp.int32, n)
    res = jax.lax.sort(tuple(keys) + (iota,), num_keys=len(keys),
                       is_stable=True)
    order = res[-1]
    diff = jnp.zeros(n, bool).at[0].set(True)
    for ks in res[:-1]:
        diff = diff.at[1:].set(diff[1:] | (ks[1:] != ks[:-1]))
    gid_sorted = jnp.cumsum(diff.astype(jnp.int32)) - 1
    gid = _inv_permute(order, gid_sorted)
    return gid, order, diff


def _dense_rank_pair(a: jnp.ndarray, b: jnp.ndarray):
    """Joint dense rank of two arrays (values aligned across both).
    Ranks are int32 (row counts are always < 2^31)."""
    both = jnp.concatenate([a, b])
    n = both.shape[0]
    iota = jax.lax.iota(jnp.int32, n)
    s, order = jax.lax.sort((both, iota), num_keys=1, is_stable=True)
    diff = jnp.zeros(n, jnp.int32).at[1:].set(
        (s[1:] != s[:-1]).astype(jnp.int32))
    rank_sorted = jnp.cumsum(diff)
    ranks = _inv_permute(order, rank_sorted)
    return ranks[:a.shape[0]], ranks[a.shape[0]:]


def _narrow_span(c: DCol) -> Optional[Tuple[int, int]]:
    """(lo, hi) when every valid value of ``c`` fits the int32 key
    space (|v| < 2^30), else None.  Strings qualify via dictionary
    size (codes are 0..len-1); int-like kinds need static bounds."""
    if c.ctype.kind == "string":
        nd = 0 if c.dictionary is None else len(c.dictionary)
        return (0, max(nd - 1, 0)) if nd < _NARROW_LIM else None
    if c.ctype.kind in ("int32", "int64", "date", "decimal") and \
            c.bounds is not None:
        lo, hi = c.bounds
        if -_NARROW_LIM < lo and hi < _NARROW_LIM:
            return (int(lo), int(hi))
    return None


def _key_col(c: DCol, alive: jnp.ndarray) -> jnp.ndarray:
    """Single-table grouping/sort key in the narrowest dtype: int32
    with int32 sentinels when the value domain fits, else the int64
    (or float64) encoding of :func:`_key_i64`."""
    if c.ctype.kind == "float64":
        return _key_i64(c, alive)
    if _narrow_span(c) is not None:
        data = c.data.astype(jnp.int32)
        data = jnp.where(c.valid, data, _NULL32)
        return jnp.where(alive, data, _DEAD32)
    return _key_i64(c, alive)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


class JaxExecutor:
    """Plan executor on the JAX backend, with per-subtree numpy fallback.

    Three modes share one operator implementation:

    * ``eager``    — ops dispatch immediately (correctness path).
    * ``discover`` — like eager, but records every data-dependent decision
      (output capacities at join/compact sync points, null-aware branch
      bools, resolved subquery literals) into a *size plan*.
    * ``replay``   — re-runs the plan under ``jax.jit`` tracing: recorded
      capacities become static shapes, recorded branches drive control
      flow, and each decision contributes a traced ``ok`` guard; the
      whole query becomes ONE XLA program (critical on real TPUs, where
      eager dispatch costs a host round-trip per primitive).

    If the guards fail at runtime (data changed enough to overflow a
    size class), the caller re-discovers and recompiles.
    """

    def __init__(self, catalog):
        self.catalog = catalog
        self.np_exec = physical.Executor(catalog)
        self._device_cache: Dict[str, Tuple[int, DTable]] = {}
        self._accel_cache: Dict[str, Tuple[int, object]] = {}
        self._subq_cache: Dict[int, ex.Expr] = {}
        self.mode = "eager"
        self._rec: Optional[list] = None   # size plan being written/read
        self._pos = 0
        self._oks: Optional[list] = None   # traced guard bools (replay)
        self._trace_tables: Optional[Dict[str, DTable]] = None
        self._used_fallback = False
        self._fallback_codes: List[str] = []
        # compiled-query cache: plan identity -> _CompiledPlan
        self._compiled: Dict[int, "_CompiledPlan"] = {}
        # segmented compilation: fingerprint -> segment _CompiledPlan,
        # shared across queries; eager segment results for the plan
        # currently being discovered / eager-executed
        self._seg_compiled: Dict[str, "_CompiledPlan"] = {}
        self._seg_tables: Dict[str, DTable] = {}
        # group-by strategy: "sort" = lexsort dense-rank only; "auto" =
        # linearized gid when the key domain is small (skips the sort);
        # "pallas" = auto + one-hot MXU segment sums for exact
        # decimal/int aggregates (ndstpu.ops.segsum).  Read once per
        # executor: the choice is baked into traced programs.
        # Default is pallas since the r5 Mosaic fix: XLA's int64
        # scatter emulation costs 247 ms at 4M rows x 1024 segments
        # where the limb kernel takes 3.6 ms (69x; 5.8x at 18k
        # segments) — scripts/pallas_bench.py, measured on chip.
        # The kernel only engages where it would COMPILE (TPU replay);
        # interpret-mode execution (CPU platforms, eager/discovery
        # passes) keeps the scatter path unless NDSTPU_GROUPBY=pallas
        # is set explicitly (tests use that for interpreter coverage).
        import os as _os
        self.groupby_mode = _os.environ.get("NDSTPU_GROUPBY",
                                            GROUPBY_DEFAULT)
        self._groupby_explicit = "NDSTPU_GROUPBY" in _os.environ
        self.groupby_domain_cap = int(
            _os.environ.get("NDSTPU_GROUPBY_DOMAIN", str(1 << 21)))
        # 1<<16 left q2's pivoted (d_week_seq x d_day_name) composite
        # key (~83k slots) — and q59's (week x store x day, ~1.17M) —
        # on the SORT path: a full multi-key sort of the 2-3M-row fact
        # spine that costs more than the masked scatters the pivot
        # removed.  Slot buffers are ngseg-sized (1<<21 x 8B = 16 MB
        # per reduction, freed per aggregate), trivial next to the row
        # data; sparse scatter output stays cheaper than sorting
        # millions of rows.
        # LUT-join domain cap: counts/starts tables of `bound` slots live
        # in HBM (2 x 4B x bound; 1<<25 -> 256 MB peak, freed per join)
        self.join_lut_cap = int(
            _os.environ.get("NDSTPU_JOIN_LUT_CAP", str(1 << 25)))
        # compile+run the jitted replay at the end of discovery so
        # steady-state executions never pay a trace/compile (opt out
        # with NDSTPU_WARM_REPLAY=0)
        self.warm_replay = _os.environ.get(
            "NDSTPU_WARM_REPLAY", "1") != "0"
        # introspection counters: tests assert steady-state executions
        # re-run NO discovery and build NO new jitted programs
        self.n_discoveries = 0
        self.n_jit_builds = 0
        # Thread-safety (inproc throughput scheduler): the executor
        # keeps per-query mutable state (mode/_rec/_pos, subquery
        # memos, eager segment tables), so query execution is
        # serialized under _exec_lock (RLock: replay of a demoted
        # segment re-enters execute_to_host).  _key_latch adds per-key
        # "discover once, others wait": a second stream arriving for a
        # text mid-compile blocks on the key, then hits _compiled.
        from ndstpu.engine.latch import KeyedLatch
        self._exec_lock = threading.RLock()
        self._key_latch = KeyedLatch()
        # eager bounds diagnostic: plain (non-compiling) executors keep
        # it always on — they have no discovery phase to front-load the
        # check into; CompilingExecutor narrows it to discovery
        self._in_discovery = True

    # -- public --------------------------------------------------------------

    def execute_to_host(self, p: lp.Plan) -> Table:
        with self._exec_lock:
            # per-query subquery memo: expr ids are only stable within
            # one plan
            self._subq_cache = {}
            self._tree_cache = {}
            self.np_exec = physical.Executor(self.catalog)
            self.mode = "eager"
            with host_compute():
                return to_host(self.execute(p))

    # -- sync-point abstraction ----------------------------------------------

    def _capacity_for(self, count) -> Tuple[int, jnp.ndarray]:
        """Size-class a data-dependent output count.

        eager/discover: host-sync the count, compute the size class
        (discover records it).  replay: pop the recorded capacity (static)
        and guard ``count <= cap``; the traced count still drives alive
        masks, so results stay exact as long as the guard holds."""
        if self.mode == "replay":
            tag, cap = self._rec[self._pos]
            self._pos += 1
            if tag != "cap":
                raise RuntimeError("size-plan drift (expected cap)")
            self._oks.append(count <= cap)
            return cap, count
        n = int(count)
        cap = size_class(max(n, 1))
        if self.mode == "discover":
            self._rec.append(("cap", cap))
        return cap, count

    def _branch_bool(self, flag) -> bool:
        """Host-sync a branch decision (replay: recorded + guarded)."""
        if self.mode == "replay":
            tag, val = self._rec[self._pos]
            self._pos += 1
            if tag != "bool":
                raise RuntimeError("size-plan drift (expected bool)")
            self._oks.append(jnp.asarray(flag) == val)
            return val
        b = bool(flag)
        if self.mode == "discover":
            self._rec.append(("bool", b))
        return b

    # expensive nodes worth structural-dedup: repeated CTE instances are
    # deep-copied by the planner (copy_plan) so identity can't match, but
    # instances the optimizer left identical (same pushed-down filters /
    # pruned columns) fingerprint equal and execute ONCE per query.
    # Deterministic given the plan tree, so discover and replay hit the
    # memo at the same points and the size-plan record stays aligned.
    _MEMO_NODES = (lp.Join, lp.Aggregate, lp.SetOp, lp.Window,
                   lp.Distinct, lp.Sort)

    def execute(self, p: lp.Plan) -> DTable:
        if isinstance(p, self._MEMO_NODES):
            try:
                key = _plan_fp(p)
            except TypeError:
                # un-fingerprintable leaf (no content-based repr):
                # skip memoization rather than fail the query
                return self._execute_node(p)
            cache = getattr(self, "_tree_cache", None)
            if cache is None:
                cache = self._tree_cache = {}
            hit = cache.get(key)
            if hit is not None:
                return hit
            out = self._execute_node(p)
            cache[key] = out
            return out
        return self._execute_node(p)

    def _execute_node(self, p: lp.Plan) -> DTable:
        name = "_exec_" + type(p).__name__.lower()
        m = getattr(self, name, None)
        if m is None:
            return self._fallback(p)
        try:
            return m(p)
        except Unsupported as u:
            return self._fallback(p, code=u.code)

    # -- fallback ------------------------------------------------------------

    def _fallback(self, p: lp.Plan, code: Optional[str] = None) -> DTable:
        """Run this node on the numpy interpreter; children still execute on
        the device path and are pulled to host once.  ``code`` is the
        NDS2xx diagnostic of the Unsupported that sent us here; it is
        counted and annotated onto the enclosing query span so sidecar
        and ledger rows record why the query fell back."""
        if self.mode == "replay":
            raise RuntimeError(
                f"fallback for {type(p).__name__} during replay — "
                "discovery should have marked this plan non-compilable")
        self._used_fallback = True
        tag = f"{code or 'uncoded'}:{type(p).__name__}"
        if tag not in self._fallback_codes:
            self._fallback_codes.append(tag)
        obs.inc(f"engine.fallback.{code or 'uncoded'}")
        obs.annotate(fallback_codes=",".join(sorted(self._fallback_codes)))
        repl = self._replace_children_with_host(p)
        host = self.np_exec.execute(repl)
        return to_device(host)

    def _replace_children_with_host(self, p: lp.Plan) -> lp.Plan:
        def host_child(c: lp.Plan) -> lp.Plan:
            return lp.InlineTable(to_host(self.execute(c)))

        if isinstance(p, (lp.Filter, lp.Project, lp.Limit, lp.Distinct,
                          lp.Window, lp.Sort, lp.Aggregate,
                          lp.SubqueryAlias)):
            q = lp.copy_plan(p)
            q.child = host_child(p.child)
            return q
        if isinstance(p, lp.Join):
            q = lp.copy_plan(p)
            q.left = host_child(p.left)
            q.right = host_child(p.right)
            return q
        if isinstance(p, lp.SetOp):
            q = lp.copy_plan(p)
            q.left = host_child(p.left)
            q.right = host_child(p.right)
            return q
        return p

    # -- subqueries ----------------------------------------------------------

    def _resolve_subqueries(self, e: ex.Expr) -> ex.Expr:
        if isinstance(e, ex.SubqueryExpr):
            if id(e) in self._subq_cache:
                return self._subq_cache[id(e)]
            if self.mode == "replay":
                # subquery results were resolved during discovery and are
                # part of the size plan (guarded by catalog versions)
                tag, out = self._rec[self._pos]
                self._pos += 1
                if tag != "subq":
                    raise RuntimeError("size-plan drift (expected subq)")
                self._subq_cache[id(e)] = out
                return out
            # the sub-plan executes eagerly even during discovery so its
            # own sync points never leak into the main plan's size plan
            # (replay skips the sub-plan entirely — a fallback inside it
            # doesn't make the main plan non-compilable either)
            outer = self.mode
            outer_fallback = self._used_fallback
            # isolate the subtree memo: a main-plan subtree must never
            # hit a DTable cached during subquery resolution — replay
            # skips subqueries entirely, so such a hit would desync the
            # size-plan record positions between discover and replay
            outer_tree = getattr(self, "_tree_cache", None)
            self._tree_cache = {}
            self.mode = "eager"
            try:
                t = to_host(self.execute(e.plan))
                col = t.columns[t.column_names[0]]
                if e.kind == "scalar":
                    if t.num_rows == 0:
                        out = ex.Literal(None, col.ctype)
                    else:
                        vals = col.to_pylist()
                        if len(vals) > 1:
                            raise RuntimeError(
                                "scalar subquery returned >1 row")
                        out = ex.Literal(vals[0], col.ctype)
                elif e.kind == "in":
                    pyvals = col.to_pylist()
                    has_null = any(v is None for v in pyvals)
                    vals = tuple(v for v in pyvals if v is not None)
                    if e.negated and has_null:
                        out = ex.Literal(False)
                    else:
                        out = ex.InList(
                            self._resolve_subqueries(e.operand), vals,
                            e.negated)
                else:
                    raise Unsupported(f"subquery kind {e.kind}", code="NDS211")
            finally:
                self.mode = outer
                self._used_fallback = outer_fallback
                self._tree_cache = outer_tree if outer_tree is not None \
                    else {}
            if self.mode == "discover":
                self._rec.append(("subq", out))
            self._subq_cache[id(e)] = out
            return out
        if isinstance(e, ex.BinOp):
            return ex.BinOp(e.op, self._resolve_subqueries(e.left),
                            self._resolve_subqueries(e.right))
        if isinstance(e, ex.UnaryOp):
            return ex.UnaryOp(e.op, self._resolve_subqueries(e.operand))
        if isinstance(e, ex.Cast):
            return ex.Cast(self._resolve_subqueries(e.operand), e.target)
        if isinstance(e, ex.Func):
            return ex.Func(e.name, tuple(self._resolve_subqueries(a)
                                         for a in e.args))
        if isinstance(e, ex.Case):
            return ex.Case(
                tuple((self._resolve_subqueries(c),
                       self._resolve_subqueries(v)) for c, v in e.whens),
                self._resolve_subqueries(e.default)
                if e.default is not None else None)
        if isinstance(e, ex.InList):
            return ex.InList(self._resolve_subqueries(e.operand), e.values,
                             e.negated)
        if isinstance(e, ex.InParam):
            return ex.InParam(self._resolve_subqueries(e.operand), e.slot,
                              e.n, e.negated)
        return e

    # -- leaves --------------------------------------------------------------

    def _table_device(self, name: str) -> DTable:
        host = self.catalog.get(name)
        version = getattr(self.catalog, "versions", {}).get(name)
        cached = self._device_cache.get(name)
        if cached is not None and cached[0] == version and \
                version is not None:
            obs.inc("engine.cache.device.hit")
            return cached[1]
        obs.inc("engine.cache.device.miss")
        # always materialize on the HOST backend: this cache feeds
        # eager/discovery and replay metadata; pinning a second full
        # copy of every table in accelerator HBM (alongside the
        # per-column replay buffers) starved the device at SF1
        with host_compute():
            dt = to_device(host)
        self._device_cache[name] = (version, dt)
        return dt

    def _exec_scan(self, p: lp.Scan) -> DTable:
        if self.mode == "replay":
            dt = self._trace_tables[p.table]
        else:
            dt = self._table_device(p.table)
        if p.columns is not None:
            cols = list(p.columns) or dt.column_names[:1]
            dt = dt.select(cols)
        if p.predicate is not None:
            pred = self._resolve_subqueries(p.predicate)
            mask = JEval(dt).predicate(pred)
            dt = DTable(dt.columns, dt.alive & mask)
        return dt

    def _exec_inlinetable(self, p: lp.InlineTable) -> DTable:
        return to_device(p.table)

    def _exec_deviceresult(self, p: lp.DeviceResult) -> DTable:
        """Separately-compiled segment result (segmented compilation):
        replay reads the parent program's argument; eager/discover read
        the eager segment tables staged by the orchestrator."""
        if self.mode == "replay":
            return self._trace_tables[_seg_argname(p.key)]
        return self._seg_tables[p.key]

    def _exec_subqueryalias(self, p: lp.SubqueryAlias) -> DTable:
        dt = self.execute(p.child)
        if p.column_aliases:
            dt = DTable(dict(zip(p.column_aliases, dt.columns.values())),
                        dt.alive)
        return dt

    # -- row ops -------------------------------------------------------------

    def _exec_filter(self, p: lp.Filter) -> DTable:
        dt = self.execute(p.child)
        cond = self._resolve_subqueries(p.condition)
        mask = JEval(dt).predicate(cond)
        return DTable(dt.columns, dt.alive & mask)

    def _exec_project(self, p: lp.Project) -> DTable:
        dt = self.execute(p.child)
        evl = JEval(dt)
        cols = {}
        for name, e in p.exprs:
            cols[name] = evl.eval(self._resolve_subqueries(e))
        return DTable(cols, dt.alive)

    def _exec_limit(self, p: lp.Limit) -> DTable:
        dt = self.compact(self.execute(p.child))
        cap = dt.capacity
        keep = jax.lax.iota(jnp.int32, cap) < min(p.n, cap)
        return DTable(dt.columns, dt.alive & keep)

    def compact(self, dt: DTable) -> DTable:
        """Scatter alive rows to the front (order-preserving); one
        sync point for the new capacity."""
        cap, n_alive = self._capacity_for(jnp.sum(dt.alive))
        idx_src = jnp.nonzero(dt.alive, size=cap,
                              fill_value=0)[0].astype(jnp.int32)
        alive = jax.lax.iota(jnp.int32, cap) < \
            jnp.asarray(n_alive).astype(jnp.int32)
        return DTable(_gather_cols(dt.columns, idx_src, alive), alive)

    # -- sort ----------------------------------------------------------------

    def _order_key(self, evl: JEval, c: DCol, asc: bool,
                   nulls_first: Optional[bool]) -> jnp.ndarray:
        if nulls_first is None:
            nulls_first = asc
        alive = evl.t.alive
        if c.ctype.kind == "float64":
            data = c.data.astype(jnp.float64)
            key = data if asc else -data
            key = jnp.where(c.valid, key,
                            -jnp.inf if nulls_first else jnp.inf)
            # dead rows strictly last
            return jnp.where(alive, key, jnp.inf)
        if _narrow_span(c) is not None:
            # int32 order key (dictionary codes already collate — the
            # dictionaries are sorted)
            data = c.data.astype(jnp.int32)
            key = data if asc else -data
            key = jnp.where(c.valid, key,
                            _NULL32 if nulls_first else -_NULL32)
            return jnp.where(alive, key, _ORD_DEAD32)
        data = c.data.astype(jnp.int64)
        key = data if asc else -data
        key = jnp.where(c.valid, key,
                        _NULL_KEY if nulls_first else -_NULL_KEY)
        return jnp.where(alive, key, _DEAD_KEY)

    def _exec_sort(self, p: lp.Sort) -> DTable:
        dt = self.execute(p.child)
        evl = JEval(dt)
        keys = []
        for entry in p.keys:
            e, asc = entry[0], entry[1]
            nf = entry[2] if len(entry) > 2 else None
            keys.append(self._order_key(
                evl, evl.eval(self._resolve_subqueries(e)), asc, nf))
        order = _lexsort_order(keys)
        return dt.gather(order, dt.alive[order])

    # -- aggregate -----------------------------------------------------------

    def _exec_aggregate(self, p: lp.Aggregate) -> DTable:
        for _, e in p.aggs:
            self._check_agg_supported(e)
        dt = self.execute(p.child)
        if p.grouping_sets is None:
            return self._aggregate_once(dt, p, None)
        parts = self._grouping_sets_partials(dt, p)
        if parts is None:
            # non-decomposable aggregates (distinct, stddev, ...):
            # per-set full passes over the child
            parts = [self._aggregate_once(dt, p, subset)
                     for subset in p.grouping_sets]
        cols: Dict[str, DCol] = {}
        for n in parts[0].column_names:
            cs = [t.columns[n] for t in parts]
            bounds = None
            if all(c.bounds is not None for c in cs):
                bounds = (min(c.bounds[0] for c in cs),
                          max(c.bounds[1] for c in cs))
            cols[n] = DCol(jnp.concatenate([c.data for c in cs]),
                           jnp.concatenate([c.valid for c in cs]),
                           cs[0].ctype, cs[0].dictionary, bounds)
        return DTable(cols, jnp.concatenate([t.alive for t in parts]))

    _GS_COMBINABLE = lowreg.GS_COMBINABLE_AGGS

    def _grouping_sets_partials(self, dt: DTable,
                                p: lp.Aggregate) -> Optional[list]:
        """Grouping sets via decomposable partials.

        ONE finest-grain aggregation over the (large) child, then
        per-set re-aggregation of the tiny compacted partial table —
        the single-chip analog of dplan's distributed partial
        recombine (dplan.py _agg_partials/_combine_partials).  Before
        this, q22's 5-set ROLLUP paid 5 full-capacity sort+segment
        passes over inventory; now it pays one, plus 5 passes over
        ~#items rows.  Returns None when an aggregate is not
        decomposable (distinct, stddev) or an agg expression contains
        nodes the rewrite can't walk — the caller falls back to
        per-set full passes.
        """
        # dedup key is _plan_fp, NOT repr: AggExpr.__repr__ delegates to
        # arg reprs and Literal's repr hides its ctype, so two agg
        # expressions differing only in literal type would collide and
        # share one partial column.  NOTE the two-stage sum reorders
        # float64 summation vs the per-set direct path; the differential
        # harness epsilon (1e-5 relative) covers that drift.
        leaves: Dict[str, ex.AggExpr] = {}
        for _name, e in p.aggs:
            for node in e.walk():
                if isinstance(node, ex.AggExpr):
                    if node.distinct or \
                            node.func not in self._GS_COMBINABLE:
                        return None
                    leaves.setdefault(_plan_fp(node), node)
        # finest-grain partials: sum+count for sum/avg, the func itself
        # for count/min/max (counts recombine by sum, min/max by
        # min/max; sum-of-sums preserves NULL-iff-no-valid-rows because
        # a cnt=0 finest partial is itself NULL)
        fine_aggs: List[tuple] = []
        combine: Dict[str, ex.Expr] = {}
        for i, (rkey, a) in enumerate(leaves.items()):
            if a.func in ("sum", "avg"):
                sname = f"__gs{i}s"
                fine_aggs.append((sname, ex.AggExpr("sum", a.arg)))
                if a.func == "sum":
                    combine[rkey] = ex.AggExpr(
                        "sum", ex.ColumnRef(sname))
                else:
                    cname = f"__gs{i}c"
                    fine_aggs.append(
                        (cname, ex.AggExpr("count", a.arg)))
                    # avg = total sum / total count; Cast(decimal ->
                    # float64) descales exactly like _agg_column's avg
                    combine[rkey] = ex.BinOp(
                        "/",
                        ex.Cast(ex.AggExpr("sum", ex.ColumnRef(sname)),
                                FLOAT64),
                        ex.Cast(ex.AggExpr("sum", ex.ColumnRef(cname)),
                                FLOAT64))
            elif a.func == "count":
                cname = f"__gs{i}c"
                fine_aggs.append((cname, ex.AggExpr("count", a.arg)))
                combine[rkey] = ex.AggExpr("sum", ex.ColumnRef(cname))
            else:  # min / max
                mname = f"__gs{i}m"
                fine_aggs.append((mname, ex.AggExpr(a.func, a.arg)))
                combine[rkey] = ex.AggExpr(a.func, ex.ColumnRef(mname))

        def rebuild(node: ex.Expr) -> ex.Expr:
            if isinstance(node, ex.AggExpr):
                return combine[_plan_fp(node)]
            if isinstance(node, ex.BinOp):
                return ex.BinOp(node.op, rebuild(node.left),
                                rebuild(node.right))
            if isinstance(node, ex.Cast):
                return ex.Cast(rebuild(node.operand), node.target)
            if isinstance(node, ex.Func):
                if node.name == "grouping":
                    return node  # static per set; _grouping_ctx resolves
                return ex.Func(node.name,
                               tuple(rebuild(x) for x in node.args))
            if isinstance(node, ex.Case):
                return ex.Case(
                    tuple((rebuild(c), rebuild(v))
                          for c, v in node.whens),
                    rebuild(node.default)
                    if node.default is not None else None)
            if isinstance(node, (ex.Literal, ex.Param)):
                return node
            raise Unsupported(
                f"grouping-sets rewrite: {type(node).__name__}")

        try:
            set_aggs = [(name, rebuild(e)) for name, e in p.aggs]
        except Unsupported:
            return None
        p_fine = lp.Aggregate(p.child, p.group_by, fine_aggs, None)
        ft = self.compact(self._aggregate_once(dt, p_fine, None))
        set_group_by = [(n, ex.ColumnRef(n)) for n, _ in p.group_by]
        p_set = lp.Aggregate(p.child, set_group_by, set_aggs, None)
        return [self._aggregate_once(ft, p_set, subset)
                for subset in p.grouping_sets]

    def _aggregate_once(self, dt: DTable, p: lp.Aggregate,
                        subset: Optional[List[int]]) -> DTable:
        evl = JEval(dt)
        cap = dt.capacity
        key_cols = []
        for i, (name, e) in enumerate(p.group_by):
            c = evl.eval(self._resolve_subqueries(e))
            if subset is not None and i not in subset:
                # excluded key in this grouping set -> all NULL (rollup)
                c = DCol(jnp.zeros_like(c.data), jnp.zeros(cap, bool),
                         c.ctype, c.dictionary)
            key_cols.append((name, c))
        self._grouping_ctx = ([n for n, _ in p.group_by], subset)
        use_pallas = False
        direct = None
        if key_cols and self.groupby_mode in ("auto", "pallas"):
            direct = self._direct_group_ids(key_cols, dt.alive)
        if direct is not None:
            gid, ngseg, out_alive, out_cols, order = direct
            use_pallas = self.groupby_mode == "pallas"
        elif key_cols:
            keys = [_key_col(c, dt.alive) for _, c in key_cols]
            gid, order, newgrp = _group_ids(keys)
            ngseg = cap
            # representative (first-in-sorted-order) row per group
            first_pos = jnp.full(cap, cap, jnp.int32).at[
                (jnp.cumsum(newgrp.astype(jnp.int32)) - 1)].min(
                jax.lax.iota(jnp.int32, cap))
            rep = order[jnp.clip(first_pos, 0, cap - 1)]
            galive = jax.ops.segment_sum(
                dt.alive.astype(jnp.int32), gid, num_segments=ngseg) > 0
            # group table alive mask: one slot per distinct gid
            n_groups_mask = jnp.zeros(cap, bool).at[gid].set(True)
            out_alive = n_groups_mask & galive
            # lazy: the final output compaction composes these rep
            # gathers down to the compacted capacity (8 string group
            # keys at 4M cost ~0.5 s in eager gathers otherwise)
            out_cols = _gather_cols(dict(key_cols), rep, out_alive)
        else:
            # keyless (scalar) aggregate: TWO segments (alive row 0,
            # dead row 1).  The old path used ngseg=cap — a cap-sized
            # scatter target per aggregate — and eagerly lexsorted the
            # whole capacity by a 0/1 key; q28's six scalar-agg
            # branches paid six full sorts for nothing.  The sort is
            # now lazy (only a float df64 sum needs gid-contiguous
            # order) and reductions land in 2 slots.
            gid = jnp.where(dt.alive, 0, 1).astype(jnp.int32)
            ngseg = 2
            out_alive = jnp.asarray([True, False])
            out_cols = {}
            memo_o: Dict[str, object] = {}

            def order(memo=memo_o, g=gid):
                if "o" not in memo:
                    memo["o"] = _lexsort_order([g])
                return memo["o"]
        # gid-sorted row order rides alongside gid: float sums use the
        # compensated segmented scan (ndstpu.engine.df64).  Passed as a
        # parameter, NOT instance state — _resolve_subqueries may run a
        # nested aggregate mid-loop and would clobber it.
        for name, e in p.aggs:
            out_cols[name] = self._eval_agg(
                dt, evl, self._resolve_subqueries(e), gid, ngseg, out_alive,
                order, use_pallas)
        return DTable(out_cols, out_alive)

    def _direct_group_ids(self, key_cols, alive):
        """Linearized group ids for small host-known key domains.

        When every group key is dictionary-coded or carries static
        bounds, the (keys) tuple maps bijectively to a mixed-radix index
        over ``domain = prod(span_i + 1)`` slots (+1 = a NULL slot per
        key), so dense group ids need NO sort, segment reductions run
        over ``domain`` slots instead of the row capacity, and the one-
        hot MXU kernels apply.  Returns None when ineligible; then the
        sort-based path runs.  (Sort path analog of Spark's hash vs
        sort aggregate choice; reference picks per-plan the same way.)
        """
        parts = []
        domain = 1
        for _name, c in key_cols:
            if c.dictionary is not None and c.ctype.kind == "string":
                lo, span = 0, len(c.dictionary)
            elif c.bounds is not None and c.ctype.kind in (
                    "int32", "int64", "date", "decimal"):
                lo, hi = c.bounds
                span = hi - lo + 1
            else:
                return None
            if span <= 0:
                return None
            domain *= span + 1
            if domain > self.groupby_domain_cap or domain >= 2 ** 31 - 1:
                return None
            parts.append((c, lo, span))
        cap = int(alive.shape[0])
        # the domain cap keeps the mixed-radix gid well inside int32
        gid = jnp.zeros(cap, jnp.int32)
        # bounds-invariant guard: a valid value outside its static
        # bounds means a DCol constructor copied bounds across a
        # value-changing transform — route the row to the trash slot
        # (visibly dropped) instead of silently merging it into the
        # boundary group
        row_ok = jnp.ones(cap, bool)
        for c, lo, span in parts:
            if -(2 ** 31) < lo and lo + span - 1 < 2 ** 31 and \
                    c.data.dtype == jnp.int32:
                raw = c.data - np.int32(lo)
                row_ok = row_ok & (~c.valid | ((raw >= 0) & (raw < span)))
                idx = jnp.clip(raw, 0, span - 1)
            else:
                raw64 = c.data.astype(jnp.int64) - lo
                row_ok = row_ok & (~c.valid |
                                   ((raw64 >= 0) & (raw64 < span)))
                idx = jnp.clip(raw64, 0, span - 1).astype(jnp.int32)
            idx = jnp.where(c.valid, idx, span)     # NULL slot per key
            gid = gid * (span + 1) + idx
        # dead / bounds-violating rows -> trash slot
        bad = alive & ~row_ok
        if self.mode == "replay":
            # a violation means upstream bounds propagation broke: fail
            # the replay guard so the query rediscovers (and the eager
            # pass below warns) instead of silently dropping rows
            self._oks.append(~jnp.any(bad))
        elif self._in_discovery or \
                os.environ.get("NDSTPU_DEBUG_BOUNDS", "0") not in ("", "0"):
            # the bool() forces a blocking device sync — pay it during
            # discovery (which covers demoted-to-eager subtrees too:
            # every query's FIRST execution passes through
            # _discover_plan, so bugs surface then), not on every
            # steady-state demoted eager aggregate.  NDSTPU_DEBUG_BOUNDS
            # restores the per-execution check.
            if bool(jnp.any(bad)):
                import warnings
                warnings.warn(
                    f"group-by bounds invariant violated: "
                    f"{int(jnp.sum(bad))} valid rows fell outside static "
                    f"key bounds and were dropped (upstream bounds-"
                    f"propagation bug)", stacklevel=2)
        gid = jnp.where(alive & row_ok, gid, domain)
        ngseg = domain + 1
        counts = jax.ops.segment_sum(alive.astype(jnp.int32), gid,
                                     num_segments=ngseg)
        out_alive = (counts > 0).at[domain].set(False)
        # reconstruct key values from the slot index (bijective mapping)
        rem = jnp.arange(ngseg)
        idxs = []
        for c, lo, span in reversed(parts):
            idxs.append(rem % (span + 1))
            rem = rem // (span + 1)
        idxs.reverse()
        out_cols: Dict[str, DCol] = {}
        for (name, c), (c2, lo, span), idx in zip(key_cols, parts, idxs):
            vout = (idx != span) & out_alive
            data = (lo + jnp.clip(idx, 0, span - 1)).astype(c.data.dtype)
            out_cols[name] = DCol(data, vout, c.ctype, c.dictionary,
                                  (lo, lo + span - 1))
        # float sums need a gid-contiguous row order (df64 compensated
        # scan); computed lazily — the common decimal/int case skips it
        memo = {}

        def order_thunk():
            if "o" not in memo:
                memo["o"] = _lexsort_order([gid])
            return memo["o"]

        return gid, ngseg, out_alive, out_cols, order_thunk

    def _check_agg_supported(self, e: ex.Expr):
        for node in e.walk():
            if isinstance(node, ex.AggExpr):
                if node.distinct and \
                        node.func not in lowreg.DISTINCT_AGG_FUNCS:
                    raise Unsupported(
                        f"distinct aggregate {node.func} on device",
                        code="NDS207")
                if node.func not in lowreg.SUPPORTED_AGG_FUNCS:
                    raise Unsupported(f"aggregate {node.func}",
                                      code="NDS207")

    def _eval_agg(self, dt: DTable, evl: JEval, e: ex.Expr, gid, ngseg,
                  out_alive, order, use_pallas: bool = False) -> DCol:
        if isinstance(e, ex.AggExpr):
            return self._agg_column(dt, evl, e, gid, ngseg, out_alive,
                                    order, use_pallas)
        if isinstance(e, ex.Func) and e.name == "grouping":
            # grouping(key) = 0 when the key participates in this grouping
            # set, 1 when rolled up (Spark semantics)
            names, subset = self._grouping_ctx
            arg = e.args[0]
            idx = names.index(arg.name) if isinstance(
                arg, ex.ColumnRef) and arg.name in names else -1
            active = subset is None or idx in subset
            return DCol(jnp.full(ngseg, 0 if active else 1, jnp.int32),
                        jnp.ones(ngseg, bool), INT32)
        if isinstance(e, (ex.BinOp, ex.Cast, ex.Func, ex.Case, ex.Literal,
                          ex.Param)):
            # expression over aggregates: evaluate leaves then combine on
            # the group-capacity table
            sub_cols: Dict[str, DCol] = {}
            counter = [0]

            def lower(node: ex.Expr) -> ex.Expr:
                if isinstance(node, ex.AggExpr):
                    name = f"__agg{counter[0]}"
                    counter[0] += 1
                    sub_cols[name] = self._agg_column(
                        dt, evl, node, gid, ngseg, out_alive, order,
                        use_pallas)
                    return ex.ColumnRef(name)
                if isinstance(node, ex.Func) and node.name == "grouping":
                    name = f"__agg{counter[0]}"
                    counter[0] += 1
                    sub_cols[name] = self._eval_agg(
                        dt, evl, node, gid, ngseg, out_alive, order,
                        use_pallas)
                    return ex.ColumnRef(name)
                if isinstance(node, ex.BinOp):
                    return ex.BinOp(node.op, lower(node.left),
                                    lower(node.right))
                if isinstance(node, ex.Cast):
                    return ex.Cast(lower(node.operand), node.target)
                if isinstance(node, ex.Func):
                    return ex.Func(node.name,
                                   tuple(lower(a) for a in node.args))
                if isinstance(node, ex.Case):
                    return ex.Case(
                        tuple((lower(c), lower(v)) for c, v in node.whens),
                        lower(node.default)
                        if node.default is not None else None)
                return node

            lowered = lower(e)
            gtable = DTable(sub_cols, out_alive) if sub_cols else DTable(
                {"__x": DCol(jnp.zeros(ngseg, jnp.int32),
                             jnp.ones(ngseg, bool), INT32)}, out_alive)
            return JEval(gtable).eval(lowered)
        raise Unsupported(f"aggregate output {type(e).__name__}",
                          code="NDS208")

    def _scan_levels(self, gid, order) -> int:
        """Recorded bound on the compensated scan's doubling steps: the
        longest same-gid run (in sorted order), size-classed through
        ``_capacity_for`` so replay gets a STATIC level count plus a
        data-changed guard.  Typical group-bys need 8 levels, not the
        log2(capacity)=22+ an unconditional full scan pays."""
        gs = gid[order]
        n = int(gs.shape[0])
        pos = jax.lax.iota(jnp.int32, n)
        newrun = jnp.ones(n, bool).at[1:].set(gs[1:] != gs[:-1])
        runstart = jax.lax.cummax(jnp.where(newrun, pos, 0))
        cap, _ = self._capacity_for(jnp.max(pos - runstart) + 1)
        return max(0, int(cap).bit_length() - 1)

    def _segment_sum_typed(self, vals, gid, ngseg, kind: str, order):
        """int/decimal sums stay exact s64 segment_sum; float sums use
        the compensated segmented scan (TPU computes f64 at f32
        precision — ndstpu.engine.df64).  `order` may be a lazy thunk
        (direct group-id path computes the sort only when floats need
        it)."""
        if kind in ("decimal", "int32", "int64"):
            return jax.ops.segment_sum(vals, gid, num_segments=ngseg)
        from ndstpu.engine import df64
        if callable(order):
            order = order()
        levels = self._scan_levels(gid, order)
        return df64.segment_sum_compensated(vals, gid, ngseg, order,
                                            levels)

    def _segment_sum_float_pair(self, x1, x2, gid, ngseg, order):
        """Two compensated float segment sums sharing ONE scan (one
        sort-order gather, one doubled-carry scan — half the HLO of two
        independent scans; q39's stddev moments are the hot caller)."""
        from ndstpu.engine import df64
        if callable(order):
            order = order()
        levels = self._scan_levels(gid, order)
        return df64.segment_sum_compensated2(x1, x2, gid, ngseg, order,
                                             levels)

    def _pallas_interpret(self) -> bool:
        """Mosaic lowering only exists on real TPU backends; everywhere
        else (CPU tests, host-pinned discovery) run the interpreter."""
        if self.mode != "replay":
            return True
        return jax.devices()[0].platform == "cpu"

    # one-hot MXU segment sums stay exact while every |value| < 2^41
    # (ndstpu.ops.segsum bias bound) and rows fit the int32 accumulator
    _PALLAS_ROWS_MAX = (2 ** 31 - 1) // 255
    # measured win margins: 69x at 1k segs, 5.8x at 18k, 1.85x at 65k
    # (one-hot work grows with rows x segs); 32k keeps the whole
    # SF1 item domain on the kernel with a comfortable margin
    _PALLAS_SEGS_MAX = 32768

    def _pallas_sum_ok(self, c: DCol, ngseg: int) -> bool:
        if ngseg > self._PALLAS_SEGS_MAX or \
                c.data.shape[0] > self._PALLAS_ROWS_MAX:
            return False
        if c.ctype.kind == "int32":
            return True
        if c.ctype.kind == "decimal":
            return c.ctype.precision <= 12      # |v| < 10^12 < 2^41
        if c.ctype.kind == "int64":
            return c.bounds is not None and \
                max(abs(c.bounds[0]), abs(c.bounds[1])) < (1 << 41)
        return False

    def _agg_column(self, dt: DTable, evl: JEval, a: ex.AggExpr, gid, ngseg,
                    out_alive, order, use_pallas: bool = False) -> DCol:
        func = a.func
        alive = dt.alive
        if a.distinct and func in ("count", "sum", "avg") and \
                not isinstance(a.arg, ex.Star):
            # distinct is a no-op for min/max; for count/sum/avg dedup
            # (group, value) pairs sort-side first
            return self._agg_distinct(dt, evl, a, gid, ngseg)
        if isinstance(a.arg, ex.Star):
            # count in int32 (row capacities are < 2^31); widen only the
            # group-capacity output to the INT64 result contract
            counts = jax.ops.segment_sum(alive.astype(jnp.int32), gid,
                                         num_segments=ngseg)
            return DCol(counts.astype(jnp.int64), jnp.ones(ngseg, bool),
                        INT64)
        c = evl.eval(a.arg)
        valid = c.valid & alive
        if use_pallas and func in ("sum", "avg") and \
                self._pallas_sum_ok(c, ngseg) and \
                (not self._pallas_interpret() or self._groupby_explicit):
            # exact int64 sums + counts in one one-hot MXU kernel pass.
            # Interpret-mode execution (eager/discovery, CPU platforms)
            # keeps the scatter path unless pallas was requested
            # explicitly: the Pallas INTERPRETER over a power-run-sized
            # grid is drastically slower than XLA's scatter, and the
            # path choice adds no size-plan sync points, so discovery-
            # on-scatter + replay-on-kernel stays record-consistent.
            from ndstpu.ops import segsum
            sums, cnts = segsum.segment_sum_decimal(
                c.data.astype(jnp.int64), gid, valid, ngseg,
                interpret=self._pallas_interpret())
            if func == "sum":
                if c.ctype.kind == "decimal":
                    return DCol(sums, cnts > 0, decimal(38, c.ctype.scale))
                return DCol(sums, cnts > 0, INT64)
            data = sums.astype(jnp.float64) / jnp.maximum(cnts, 1)
            if c.ctype.kind == "decimal":
                data = data / (10 ** c.ctype.scale)
            return DCol(data, cnts > 0, FLOAT64)
        if func == "count":
            counts = jax.ops.segment_sum(valid.astype(jnp.int32), gid,
                                         num_segments=ngseg)
            return DCol(counts.astype(jnp.int64), jnp.ones(ngseg, bool),
                        INT64)
        got = jax.ops.segment_sum(valid.astype(jnp.int32), gid,
                                  num_segments=ngseg) > 0
        if func == "sum":
            sums = self._segment_sum_typed(
                _sum_input(c.data, valid, c.ctype.kind), gid, ngseg,
                c.ctype.kind, order)
            if c.ctype.kind == "decimal":
                return DCol(sums, got, decimal(38, c.ctype.scale))
            if c.ctype.kind in ("int32", "int64"):
                return DCol(sums, got, INT64)
            return DCol(sums, got, FLOAT64)
        if func == "avg":
            cnts = jax.ops.segment_sum(valid.astype(jnp.int32), gid,
                                       num_segments=ngseg)
            sums = self._segment_sum_typed(
                _sum_input(c.data, valid, c.ctype.kind), gid, ngseg,
                c.ctype.kind, order)
            data = sums.astype(jnp.float64) / jnp.maximum(cnts, 1)
            if c.ctype.kind == "decimal":
                data = data / (10 ** c.ctype.scale)
            return DCol(data, cnts > 0, FLOAT64)
        if func in ("min", "max"):
            if c.ctype.kind == "float64":
                init = jnp.inf if func == "min" else -jnp.inf
                vals = jnp.where(valid, c.data, init)
                seg = (jax.ops.segment_min if func == "min"
                       else jax.ops.segment_max)
                out = seg(vals, gid, num_segments=ngseg)
                return DCol(out, got, c.ctype)
            vals = _minmax_vals(c.data, valid, c.ctype.kind,
                                func == "min")
            seg = (jax.ops.segment_min if func == "min"
                   else jax.ops.segment_max)
            out = seg(vals, gid, num_segments=ngseg)
            return DCol(out.astype(c.data.dtype), got, c.ctype,
                        c.dictionary, c.bounds)
        if func in ("stddev_samp", "var_samp", "stddev", "variance"):
            # shifted two-pass moments (see physical.py analog): center
            # by the group mean so E[x^2]-E[x]^2 cancellation cannot eat
            # the variance when mean >> stddev; the (sum d)^2/n term
            # corrects the mean's own rounding.  d1/d2 ride ONE
            # compensated scan (df64 pair carry) instead of two.
            x = evl.cast(c, FLOAT64).data
            xv = jnp.where(valid, x, 0.0)
            cnt = jax.ops.segment_sum(valid.astype(jnp.int32), gid,
                                      num_segments=ngseg)
            s1 = self._segment_sum_typed(xv, gid, ngseg, "float64", order)
            mean = s1 / jnp.maximum(cnt, 1)
            d = jnp.where(valid, x - mean[gid], 0.0)
            d1, d2 = self._segment_sum_float_pair(d, d * d, gid, ngseg,
                                                  order)
            ok = cnt > 1
            denom = jnp.where(ok, cnt - 1, 1)
            var = jnp.maximum(
                d2 - jnp.where(cnt > 0, d1 * d1 / jnp.maximum(cnt, 1), 0.0),
                0.0) / denom
            data = var if func in ("var_samp", "variance") else jnp.sqrt(var)
            return DCol(data, ok, FLOAT64)
        raise Unsupported(f"aggregate {func}", code="NDS207")

    # presence-bitmap distinct: ngseg x domain slots; 1<<22 int32 slots
    # = 16 MB peak, freed per aggregate
    _DISTINCT_BITMAP_SLOTS = 1 << 22

    def _agg_distinct(self, dt: DTable, evl: JEval, a: ex.AggExpr,
                      gid, ngseg) -> DCol:
        """count/sum/avg(DISTINCT x).

        Small-domain int/decimal columns (static bounds) use a
        presence BITMAP: scatter 1s into (segment, value-lo) slots and
        reduce rows of the dense (ngseg, domain) array — no sort.
        q28's six count(distinct ss_list_price) branches each paid a
        full-capacity 2-key sort over store_sales this replaces.  The
        branch choice derives ONLY from static metadata (ctype, bounds,
        ngseg), so discovery and replay always agree; replay guards
        values escaping the recorded bounds via the ok-mask like the
        group-by linearizer.  Everything else keeps the sort path:
        sort (group, value), keep the first row of each distinct pair,
        segment-combine as usual."""
        func = a.func
        c = evl.eval(a.arg)
        valid = c.valid & dt.alive
        if c.ctype.kind in ("decimal", "int32", "int64") and \
                c.bounds is not None:
            lo, hi = c.bounds
            domain = int(hi - lo + 1)
            if 0 < domain and ngseg * domain <= self._DISTINCT_BITMAP_SLOTS:
                return self._agg_distinct_bitmap(
                    c, valid, gid, ngseg, lo, domain, func)
        vkey = _key_col(c, dt.alive)
        order = _lexsort_order([gid, vkey])
        gid_s = gid[order]
        vkey_s = vkey[order]
        cap = dt.capacity
        first = jnp.ones(cap, bool).at[1:].set(
            (gid_s[1:] != gid_s[:-1]) | (vkey_s[1:] != vkey_s[:-1]))
        uniq = first & valid[order]
        cnts = jax.ops.segment_sum(uniq.astype(jnp.int32), gid_s,
                                   num_segments=ngseg)
        if func == "count":
            return DCol(cnts.astype(jnp.int64), jnp.ones(ngseg, bool),
                        INT64)
        got = cnts > 0
        data_s = c.data[order]
        if c.ctype.kind in ("decimal", "int32", "int64"):
            vals = jnp.where(uniq, data_s.astype(jnp.int64), 0)
            sums = jax.ops.segment_sum(vals, gid_s, num_segments=ngseg)
            if func == "sum":
                if c.ctype.kind == "decimal":
                    return DCol(sums, got, decimal(38, c.ctype.scale))
                return DCol(sums, got, INT64)
            mean = sums.astype(jnp.float64) / jnp.maximum(cnts, 1)
            if c.ctype.kind == "decimal":
                mean = mean / (10 ** c.ctype.scale)
            return DCol(mean, got, FLOAT64)
        vals = jnp.where(uniq, data_s.astype(jnp.float64), 0.0)
        sums = jax.ops.segment_sum(vals, gid_s, num_segments=ngseg)
        if func == "sum":
            return DCol(sums, got, FLOAT64)
        return DCol(sums / jnp.maximum(cnts, 1), got, FLOAT64)

    def _agg_distinct_bitmap(self, c: DCol, valid, gid, ngseg: int,
                             lo: int, domain: int, func: str) -> DCol:
        raw = c.data.astype(jnp.int64) - lo
        in_dom = (raw >= 0) & (raw < domain)
        use = valid & in_dom
        if self.mode == "replay":
            # a valid value outside the recorded bounds means the data
            # changed under this size class: fail the guard, rediscover
            self._oks.append(~jnp.any(valid & ~in_dom))
        idx = gid.astype(jnp.int64) * domain + jnp.clip(raw, 0, domain - 1)
        idx = jnp.where(use, idx, ngseg * domain)  # trash slot
        seen = jnp.zeros(ngseg * domain + 1, jnp.int32).at[idx].max(
            use.astype(jnp.int32))
        seen2 = seen[:-1].reshape(ngseg, domain)
        cnts = seen2.sum(axis=1).astype(jnp.int64)
        if func == "count":
            return DCol(cnts, jnp.ones(ngseg, bool), INT64)
        got = cnts > 0
        slot_vals = lo + jnp.arange(domain, dtype=jnp.int64)
        sums = (seen2.astype(jnp.int64) * slot_vals[None, :]).sum(axis=1)
        if func == "sum":
            if c.ctype.kind == "decimal":
                return DCol(sums, got, decimal(38, c.ctype.scale))
            return DCol(sums, got, INT64)
        mean = sums.astype(jnp.float64) / jnp.maximum(cnts, 1)
        if c.ctype.kind == "decimal":
            mean = mean / (10 ** c.ctype.scale)
        return DCol(mean, got, FLOAT64)

    # -- window --------------------------------------------------------------

    def _exec_window(self, p: lp.Window) -> DTable:
        dt = self.execute(p.child)
        out = dict(dt.columns)
        for name, e in p.exprs:
            if not isinstance(e, ex.WindowExpr):
                raise Unsupported("non-window expr in Window node",
                                  code="NDS209")
            out[name] = self._window_column(dt, e)
        return DTable(out, dt.alive)

    def _window_column(self, dt: DTable, w: ex.WindowExpr) -> DCol:
        cap = dt.capacity
        evl = JEval(dt)
        if w.partition_by:
            pcols = [evl.eval(self._resolve_subqueries(e))
                     for e in w.partition_by]
            pkeys = [_key_col(c, dt.alive) for c in pcols]
        else:
            pkeys = [jnp.where(dt.alive, 0, 1).astype(jnp.int32)]
        pid, _, _ = _group_ids(pkeys)
        okeys = []
        for e, asc in w.order_by:
            c = evl.eval(self._resolve_subqueries(e))
            okeys.append(self._order_key(evl, c, asc, None))
        if w.func in ("row_number", "rank", "dense_rank"):
            order = _lexsort_order([pid] + okeys)
            idx = jax.lax.iota(jnp.int32, cap)
            pid_s = pid[order]
            newpart = jnp.ones(cap, bool)
            if cap > 1:
                newpart = newpart.at[1:].set(pid_s[1:] != pid_s[:-1])
            part_start = jax.lax.cummax(jnp.where(newpart, idx, 0))
            pos_in_part = idx - part_start
            inv = jnp.zeros(cap, jnp.int32).at[order].set(idx)
            if w.func == "row_number":
                return DCol((pos_in_part + 1)[inv].astype(jnp.int64),
                            jnp.ones(cap, bool), INT64)
            tie = jnp.zeros(cap, bool)
            if cap > 1:
                t = jnp.ones(cap - 1, bool)
                for k in okeys:
                    ks = k[order]
                    t = t & (ks[1:] == ks[:-1])
                tie = tie.at[1:].set(t & ~newpart[1:])
            if w.func == "rank":
                last_nontie = jax.lax.cummax(jnp.where(~tie, idx, 0))
                ranks = pos_in_part[last_nontie] + 1
            else:
                incr = jnp.where(newpart, 0, (~tie).astype(jnp.int32))
                csum = jnp.cumsum(incr)
                base = jax.lax.cummax(jnp.where(newpart, csum, 0))
                ranks = csum - base + 1
            return DCol(ranks[inv].astype(jnp.int64),
                        jnp.ones(cap, bool), INT64)
        # aggregate window: whole partition without ORDER BY; with ORDER BY
        # a running UNBOUNDED PRECEDING..CURRENT ROW frame (Spark default
        # RANGE — peers share the run value; explicit ROWS = per-row)
        if w.order_by:
            return self._running_window(dt, evl, w, pid, okeys)
        gid = pid
        if w.func == "count" and (w.arg is None or
                                  isinstance(w.arg, ex.Star)):
            cnt = jax.ops.segment_sum(dt.alive.astype(jnp.int32), gid,
                                      num_segments=cap)
            return DCol(cnt[gid].astype(jnp.int64), jnp.ones(cap, bool),
                        INT64)
        arg = evl.eval(self._resolve_subqueries(w.arg))
        valid = arg.valid & dt.alive
        cnts = jax.ops.segment_sum(valid.astype(jnp.int32), gid,
                                   num_segments=cap)
        got = (cnts > 0)[gid]
        if w.func == "count":
            return DCol(cnts[gid].astype(jnp.int64),
                        jnp.ones(cap, bool), INT64)
        if w.func == "sum":
            tot = jax.ops.segment_sum(
                _sum_input(arg.data, valid, arg.ctype.kind), gid,
                num_segments=cap)
            if arg.ctype.kind == "decimal":
                return DCol(tot[gid], got, decimal(38, arg.ctype.scale))
            if arg.ctype.kind in ("int32", "int64"):
                return DCol(tot[gid], got, INT64)
            return DCol(tot[gid], got, FLOAT64)
        if w.func == "avg":
            tot = jax.ops.segment_sum(
                _sum_input(arg.data, valid, arg.ctype.kind), gid,
                num_segments=cap)
            mean = tot.astype(jnp.float64) / jnp.maximum(cnts, 1)
            if arg.ctype.kind == "decimal":
                mean = mean / (10 ** arg.ctype.scale)
            return DCol(mean[gid], got, FLOAT64)
        if w.func in ("min", "max"):
            if arg.ctype.kind == "float64":
                init = jnp.inf if w.func == "min" else -jnp.inf
                vals = jnp.where(valid, arg.data, init)
                seg = (jax.ops.segment_min if w.func == "min"
                       else jax.ops.segment_max)
                return DCol(seg(vals, gid, num_segments=cap)[gid], got,
                            arg.ctype)
            vals = _minmax_vals(arg.data, valid, arg.ctype.kind,
                                w.func == "min")
            seg = (jax.ops.segment_min if w.func == "min"
                   else jax.ops.segment_max)
            out = seg(vals, gid, num_segments=cap)[gid]
            return DCol(out.astype(arg.data.dtype), got, arg.ctype,
                        arg.dictionary)
        raise Unsupported(f"window {w.func}", code="NDS209")

    def _running_window(self, dt: DTable, evl: JEval, w: ex.WindowExpr,
                        pid, okeys: List[jnp.ndarray]) -> DCol:
        """UNBOUNDED PRECEDING..CURRENT ROW running aggregate on device
        (q51 shape; numpy analog: physical.Executor._running_window).
        Sort by (partition, order keys), segmented cumulative combine,
        peers share the end-of-tie-run value under RANGE frames."""
        cap = dt.capacity
        idx = jax.lax.iota(jnp.int32, cap)
        order = _lexsort_order([pid] + okeys)
        inv = jnp.zeros(cap, jnp.int32).at[order].set(idx)
        pid_s = pid[order]
        newpart = jnp.ones(cap, bool).at[1:].set(pid_s[1:] != pid_s[:-1])
        pstart = jax.lax.cummax(jnp.where(newpart, idx, 0))
        if w.frame != "rows":
            t = jnp.ones(cap - 1, bool)
            for k in okeys:
                ks = k[order]
                t = t & (ks[1:] == ks[:-1])
            tie = jnp.zeros(cap, bool).at[1:].set(t & ~newpart[1:])
            end_marker = jnp.ones(cap, bool).at[:-1].set(~tie[1:])
            run_end = jax.lax.cummin(jnp.where(end_marker, idx, cap),
                                     reverse=True)
        else:
            run_end = idx

        def seg_cumsum(x):
            cs = jnp.cumsum(x)
            base = jnp.where(pstart > 0, cs[jnp.maximum(pstart - 1, 0)], 0)
            return cs - base

        alive_s = dt.alive[order]
        if w.arg is None or isinstance(w.arg, ex.Star):  # count(*)
            run = seg_cumsum(alive_s.astype(jnp.int32))[run_end]
            return DCol(run[inv].astype(jnp.int64),
                        jnp.ones(cap, bool), INT64)
        arg = evl.eval(self._resolve_subqueries(w.arg))
        valid_s = (arg.valid & dt.alive)[order]
        data_s = arg.data[order]
        rcnt = seg_cumsum(valid_s.astype(jnp.int32))[run_end]
        got = (rcnt > 0)[inv]
        if w.func == "count":
            return DCol(rcnt[inv].astype(jnp.int64),
                        jnp.ones(cap, bool), INT64)
        if w.func in ("sum", "avg"):
            run = seg_cumsum(
                _sum_input(data_s, valid_s, arg.ctype.kind))[run_end]
            if w.func == "sum":
                if arg.ctype.kind == "decimal":
                    return DCol(run[inv], got,
                                decimal(38, arg.ctype.scale))
                if arg.ctype.kind in ("int32", "int64"):
                    return DCol(run[inv], got, INT64)
                return DCol(run[inv], got, FLOAT64)
            mean = run.astype(jnp.float64)
            if arg.ctype.kind == "decimal":
                mean = mean / (10 ** arg.ctype.scale)
            return DCol((mean / jnp.maximum(rcnt, 1))[inv], got, FLOAT64)
        if w.func in ("min", "max"):
            is_min = w.func == "min"
            opfn = jnp.minimum if is_min else jnp.maximum
            if arg.ctype.kind == "float64":
                sent = jnp.inf if is_min else -jnp.inf
                x = jnp.where(valid_s, data_s, sent)
            else:
                x = _minmax_vals(data_s, valid_s, arg.ctype.kind, is_min)
                sent = x.dtype.type(
                    jnp.iinfo(x.dtype).max if is_min
                    else jnp.iinfo(x.dtype).min)
            # doubling prefix scan clipped at partition starts
            out = x
            shift = 1
            while shift < cap:
                cand = jnp.concatenate(
                    [jnp.full(shift, sent, out.dtype), out[:-shift]])
                take = (idx - shift) >= pstart
                out = jnp.where(take, opfn(out, cand), out)
                shift *= 2
            out = out[run_end][inv]
            if arg.ctype.kind != "float64":
                out = out.astype(arg.data.dtype)
            return DCol(out, got, arg.ctype, arg.dictionary)
        raise Unsupported(f"running window {w.func}", code="NDS209")

    # -- distinct ------------------------------------------------------------

    def _exec_distinct(self, p: lp.Distinct) -> DTable:
        return self._distinct_of(self.execute(p.child))

    def _distinct_of(self, dt: DTable) -> DTable:
        for c in dt.columns.values():
            if c.ctype.kind not in ("int32", "int64", "decimal", "date",
                                    "string", "bool", "float64"):
                raise Unsupported("distinct column type")
        cap = dt.capacity
        keys = [_key_col(c, dt.alive) for c in dt.columns.values()]
        gid, order, newgrp = _group_ids(keys)
        first_pos = jnp.full(cap, cap, jnp.int32).at[
            (jnp.cumsum(newgrp.astype(jnp.int32)) - 1)].min(
            jax.lax.iota(jnp.int32, cap))
        rep = order[jnp.clip(first_pos, 0, cap - 1)]
        slot_used = jnp.zeros(cap, bool).at[gid].set(True)
        galive = jax.ops.segment_sum(dt.alive.astype(jnp.int32), gid,
                                     num_segments=cap) > 0
        out_alive = slot_used & galive
        return DTable(_gather_cols(dt.columns, rep, out_alive), out_alive)

    # -- set ops -------------------------------------------------------------

    def _exec_setop(self, p: lp.SetOp) -> DTable:
        lt = self.execute(p.left)
        rt = self.execute(p.right)
        rt = DTable(dict(zip(lt.column_names, rt.columns.values())),
                    rt.alive)
        both = self._vconcat(lt, rt)
        if p.kind == "union":
            return both if p.all else self._distinct_of(both)
        # intersect / except, distinct semantics (Spark): keep the first
        # left occurrence of each qualifying row-value group
        cap = both.capacity
        nl = lt.capacity
        keys = [_key_col(c, both.alive) for c in both.columns.values()]
        gid, order, newgrp = _group_ids(keys)
        pos = jax.lax.iota(jnp.int32, cap)
        is_left = pos < nl
        in_left = jax.ops.segment_sum(
            (both.alive & is_left).astype(jnp.int32), gid,
            num_segments=cap) > 0
        in_right = jax.ops.segment_sum(
            (both.alive & ~is_left).astype(jnp.int32), gid,
            num_segments=cap) > 0
        keepg = (in_left & in_right) if p.kind == "intersect" else \
            (in_left & ~in_right)
        lidx = jnp.where(both.alive & is_left, pos, cap)
        firstl = jnp.full(cap, cap, jnp.int32).at[gid].min(lidx)
        keep = (firstl[gid] == pos) & keepg[gid] & both.alive & is_left
        return DTable(both.columns, keep)

    def _vconcat(self, lt: DTable, rt: DTable) -> DTable:
        """Vertical concat with dictionary merge / numeric unification."""
        cols: Dict[str, DCol] = {}
        for n in lt.column_names:
            lc, rc = lt.column(n), rt.column(n)
            if lc.ctype.kind == "string":
                merged = _merged_dict([lc, rc])
                ld = _translate(lc, merged)
                rd = _translate(rc, merged)
                ld = jnp.where(ld == -2, -1, ld)
                rd = jnp.where(rd == -2, -1, rd)
                cols[n] = DCol(jnp.concatenate([ld, rd]),
                               jnp.concatenate([lc.valid, rc.valid]),
                               STRING, merged.astype(object))
            else:
                tgt = lc.ctype
                if rc.ctype.kind != tgt.kind or \
                        (tgt.kind == "decimal" and
                         rc.ctype.scale != tgt.scale):
                    tgt = ex.common_type(lc.ctype, rc.ctype)
                    lc = JEval(lt).cast(lc, tgt)
                    rc = JEval(rt).cast(rc, tgt)
                bounds = None
                if lc.bounds is not None and rc.bounds is not None:
                    bounds = (min(lc.bounds[0], rc.bounds[0]),
                              max(lc.bounds[1], rc.bounds[1]))
                cols[n] = DCol(
                    jnp.concatenate([lc.data, rc.data]),
                    jnp.concatenate([lc.valid, rc.valid]), tgt, None,
                    bounds)
        alive = jnp.concatenate([lt.alive, rt.alive])
        return DTable(cols, alive)

    # -- join ----------------------------------------------------------------

    @staticmethod
    def _direct_join_spec(lc: DCol, rc: DCol):
        """Static (lo, span, lmult, rmult) when this key pair can be
        encoded directly from values (no rank-pairing sort): int-like
        kinds on both sides with known bounds, scales aligned by exact
        host-side multipliers.  None -> rank-pair fallback."""
        int_kinds = ("int32", "int64", "date", "decimal")
        if lc.ctype.kind not in int_kinds or rc.ctype.kind not in int_kinds:
            return None
        if lc.bounds is None or rc.bounds is None:
            return None
        ls = lc.ctype.scale if lc.ctype.kind == "decimal" else 0
        rs = rc.ctype.scale if rc.ctype.kind == "decimal" else 0
        s = max(ls, rs)
        lmult, rmult = 10 ** (s - ls), 10 ** (s - rs)
        blo = min(lc.bounds[0] * lmult, rc.bounds[0] * rmult)
        bhi = max(lc.bounds[1] * lmult, rc.bounds[1] * rmult)
        span = bhi - blo + 1
        if span >= 2 ** 62:
            return None
        return (blo, span, lmult, rmult)

    @staticmethod
    def _string_join_spec(lc: DCol, rc: DCol):
        """Static (merged_dict_or_None, span) for a string key pair.
        merged is None when both sides share one dictionary (codes used
        as-is)."""
        if lc.ctype.kind != "string" or rc.ctype.kind != "string":
            return None
        if lc.dictionary is not None and rc.dictionary is not None and \
                len(lc.dictionary) == len(rc.dictionary) and \
                np.array_equal(lc.dictionary, rc.dictionary):
            return (None, max(len(lc.dictionary), 1))
        merged = _merged_dict([lc, rc])
        return (merged, max(len(merged), 1))

    def _join_keys(self, lt: DTable, rt: DTable,
                   keys: List[Tuple[ex.Expr, ex.Expr]]):
        """Composite join keys on both sides (mixed-radix).

        Key pairs whose value domain is statically known (int-like with
        bounds, dictionary-coded strings) are encoded DIRECTLY from
        values — no joint dense-rank, which costs a full sort over the
        combined capacities per key.  Only unbounded pairs (raw float64,
        computed columns without bounds) pay the rank-pairing sort.
        When the final composite bound fits int32 the whole key build
        runs in int32 (native on v5e; int64 is emulated as s32 pairs)."""
        levl, revl = JEval(lt), JEval(rt)
        lcols = [levl.eval(self._resolve_subqueries(le)) for le, _ in keys]
        rcols = [revl.eval(self._resolve_subqueries(re_)) for _, re_ in keys]
        capl, capr = lt.capacity, rt.capacity
        rank_radix = capl + capr + 3
        specs = []
        for lc, rc in zip(lcols, rcols):
            spec = self._direct_join_spec(lc, rc)
            if spec is None and lc.ctype.kind == "string":
                sspec = self._string_join_spec(lc, rc)
                if sspec is not None:
                    spec = ("str",) + sspec
            specs.append(spec)
        # simulate the radix accumulation host-side to pick the key dtype
        bound = 1
        redensified = False
        for spec in specs:
            if spec is None:
                radix = rank_radix
            elif spec[0] == "str":
                radix = spec[2]
            else:
                radix = spec[1]
            if bound * radix >= 2 ** 62:
                redensified = True
                bound = rank_radix
            bound *= radix
        use32 = (not redensified) and bound < 2 ** 31
        kdt = jnp.int32 if use32 else jnp.int64
        lkey = jnp.zeros(capl, kdt)
        rkey = jnp.zeros(capr, kdt)
        lvalid = jnp.ones(capl, bool)
        rvalid = jnp.ones(capr, bool)
        bound = 1  # exclusive upper bound on current composite key values
        for (lc, rc), spec in zip(zip(lcols, rcols), specs):
            if spec is not None and spec[0] == "str":
                _, merged, span = spec
                la = _translate(lc, merged) if merged is not None \
                    else lc.data
                ra = _translate(rc, merged) if merged is not None \
                    else rc.data
                # invalid codes (<0) clip into range; those rows are
                # overridden by the validity sentinels downstream
                la = jnp.clip(la, 0, span - 1).astype(kdt)
                ra = jnp.clip(ra, 0, span - 1).astype(kdt)
                radix = span
            elif spec is not None:
                blo, span, lmult, rmult = spec
                radix = span
                # build in int32 only when the aligned value range fits;
                # garbage (dead/invalid) rows may wrap — they are
                # sentinel-overridden downstream
                if use32 and -(2 ** 31) < blo and \
                        blo + span - 1 < 2 ** 31:
                    la = jnp.clip(lc.data.astype(jnp.int32) * lmult - blo,
                                  0, span - 1)
                    ra = jnp.clip(rc.data.astype(jnp.int32) * rmult - blo,
                                  0, span - 1)
                else:
                    la = jnp.clip(lc.data.astype(jnp.int64) * lmult - blo,
                                  0, span - 1).astype(kdt)
                    ra = jnp.clip(rc.data.astype(jnp.int64) * rmult - blo,
                                  0, span - 1).astype(kdt)
            else:
                if capl * capr > 2 ** 48:
                    raise Unsupported("join too large for rank pairing")
                la64 = _key_i64(lc, lt.alive, peer=rc)
                ra64 = _key_i64(rc, rt.alive, peer=lc)
                # decimal/int alignment (rank path only; direct path
                # aligns via host multipliers)
                if lc.ctype.kind == "decimal" or rc.ctype.kind == "decimal":
                    ls = lc.ctype.scale if lc.ctype.kind == "decimal" else 0
                    rs = rc.ctype.scale if rc.ctype.kind == "decimal" else 0
                    s = max(ls, rs)
                    la64 = jnp.where(jnp.abs(la64) < _DEAD_KEY,
                                     la64 * (10 ** (s - ls)), la64)
                    ra64 = jnp.where(jnp.abs(ra64) < _DEAD_KEY,
                                     ra64 * (10 ** (s - rs)), ra64)
                lr, rr = _dense_rank_pair(la64, ra64)
                la, ra = lr.astype(kdt), rr.astype(kdt)
                radix = rank_radix
            if bound * radix >= 2 ** 62:
                # re-densify the accumulated composite so mixed-radix
                # never overflows int64, however many join keys there are
                lkey, rkey = _dense_rank_pair(lkey, rkey)
                lkey, rkey = lkey.astype(kdt), rkey.astype(kdt)
                bound = rank_radix
            lkey = lkey * radix + la
            rkey = rkey * radix + ra
            bound = bound * radix
            lvalid = lvalid & lc.valid
            rvalid = rvalid & rc.valid
        return lkey, rkey, lvalid, rvalid, bound

    def _probe_counts(self, pkey: jnp.ndarray, bkey: jnp.ndarray,
                      bound: int, need_order: bool = True):
        """Per-probe-row (lo, counts) against the build side, plus the
        build-side stable key order: ``order[lo[i] .. lo[i]+counts[i]-1]``
        are the build rows matching probe row ``i``.

        NO ``searchsorted``: on TPU its binary-search lowering costs one
        4M-index gather per iteration (~0.5-0.7 s per call measured on
        v5e at SF1 — scripts/prim_bench.py).  Instead:

        * ``bound <= _LUT_CAP``: direct-addressed lookup tables.  Build
          counts via one scatter-add over the key domain, starts via one
          cumsum, probe via two gathers.  (The composite join key bound
          is statically known — _join_keys tracks it — so this is the
          common case: surrogate-key joins are dense small domains.)
        * otherwise: ONE variadic sort of concat(build, probe) tagged by
          side; in sorted order, builds-before = prefix count, the run
          start carries lo, and unique-destination scatters route
          lo/counts back to probe positions and build ranks to `order`.

        Probe rows with key < 0 (sentinels) never match; build rows with
        key < 0 never enter the tables but DO occupy `order` slots (they
        sort first), matching the old sort+searchsorted layout.
        """
        m = int(bkey.shape[0])
        n = int(pkey.shape[0])
        iota_m = jax.lax.iota(jnp.int32, m)
        # LUT only when the domain is within both the absolute cap and a
        # small multiple of the table sizes: its cumsum/memset run over
        # `bound` slots, so a near-cap domain against tiny tables would
        # cost far more than the sort path over m+n rows
        if bound is not None and 0 < bound <= min(
                self.join_lut_cap, max(8 * (m + n), 1 << 20)):
            span = int(bound)
            bidx = jnp.where(bkey >= 0, bkey, span).astype(jnp.int32)
            cnt_t = jnp.zeros(span + 1, jnp.int32).at[bidx].add(1)
            cnt = cnt_t[:span]
            ccnt = jnp.cumsum(cnt)
            # valid build keys sort AFTER the (<0) sentinel rows in the
            # stable key order, so starts are offset by the dead count
            n_dead = jnp.sum((bkey < 0).astype(jnp.int32))
            starts = ccnt - cnt + n_dead
            pk = jnp.clip(pkey, 0, span - 1).astype(jnp.int32)
            hit = pkey >= 0
            counts = jnp.where(hit, cnt[pk], 0)
            lo = starts[pk].astype(jnp.int32)
            order = None
            if need_order:
                # dead build rows (key < 0) sort FIRST, matching the
                # `starts` offset by n_dead above
                okey = jnp.where(bkey >= 0, bkey, -1).astype(jnp.int32)
                order = jax.lax.sort((okey, iota_m), num_keys=1,
                                     is_stable=True)[1]
            return lo, counts, order
        key = jnp.concatenate([bkey, pkey])
        tag = (jax.lax.iota(jnp.int32, m + n) >= m).astype(jnp.int32)
        idx = jax.lax.iota(jnp.int32, m + n)
        skey, stag, sidx = jax.lax.sort((key, tag, idx), num_keys=2,
                                        is_stable=True)
        isb = (stag == 0).astype(jnp.int32)
        builds_le = jnp.cumsum(isb)               # builds at pos <= s
        before = builds_le - isb                  # builds strictly before s
        newrun = jnp.ones(m + n, bool).at[1:].set(skey[1:] != skey[:-1])
        # `before` is non-decreasing, so cummax propagates each run
        # start's value (builds with key < run key) across the run
        lo_sorted = jax.lax.cummax(jnp.where(newrun, before, 0))
        cnt_sorted = builds_le - lo_sorted        # builds in run up to s
        dest = jnp.where(stag == 1, sidx - m, n)  # build rows -> trash slot
        lo = jnp.zeros(n + 1, jnp.int32).at[dest].set(lo_sorted)[:n]
        counts = jnp.zeros(n + 1, jnp.int32).at[dest].set(cnt_sorted)[:n]
        counts = jnp.where(pkey >= 0, counts, 0)
        order = None
        if need_order:
            bdest = jnp.where(isb == 1, builds_le - 1, m)
            order = jnp.zeros(m + 1, jnp.int32).at[bdest].set(sidx)[:m]
        return lo, counts, order

    @staticmethod
    def _expand_li(counts: jnp.ndarray, starts: jnp.ndarray,
                   out_cap: int) -> jnp.ndarray:
        """Left-row index feeding each expansion output position.

        Replaces ``searchsorted(cumsum(counts), pos)``: scatter each
        emitting row's id at its start position, cummax fills the run.
        Starts of emitting rows are strictly increasing, so destinations
        are unique."""
        cap = int(counts.shape[0])
        emit = counts > 0
        sdest = jnp.where(emit, starts, out_cap)
        rid = jnp.where(emit, jax.lax.iota(jnp.int32, cap) + 1, 0)
        tmp = jnp.zeros(out_cap + 1, jnp.int32).at[sdest].max(rid)
        li = jax.lax.cummax(tmp[:out_cap]) - 1
        return jnp.clip(li, 0, cap - 1)

    def _exec_join(self, p: lp.Join) -> DTable:
        kind = p.kind
        lt = self.execute(p.left)
        rt = self.execute(p.right)
        extra = self._resolve_subqueries(p.extra) \
            if p.extra is not None else None
        if kind == "cross" or not p.keys:
            if kind not in ("cross", "inner"):
                raise Unsupported(f"non-equi {kind} join", code="NDS210")
            return self._cross_join(lt, rt, extra)
        if kind == "right":
            out = self._equi_join(rt, lt,
                                  [(r, l) for l, r in p.keys], "left",
                                  extra)
            return out.select(list(lt.columns) + list(rt.columns))
        if kind == "full":
            return self._full_join(lt, rt, p.keys, extra)
        if kind == "mark":
            return self._equi_join(lt, rt, p.keys, kind, extra,
                                   mark=p.mark)
        return self._equi_join(lt, rt, p.keys, kind, extra)

    def _cross_join(self, lt: DTable, rt: DTable, extra) -> DTable:
        ltc = self.compact(lt)
        rtc = self.compact(rt)
        nl = jnp.sum(ltc.alive)
        nr = jnp.sum(rtc.alive)
        out_cap, total = self._capacity_for(nl * nr)
        pos = jax.lax.iota(jnp.int32, out_cap)
        nr_safe = jnp.maximum(nr, 1).astype(jnp.int32)
        li = jnp.minimum(pos // nr_safe, ltc.capacity - 1)
        ri = jnp.minimum(pos % nr_safe, rtc.capacity - 1)
        alive = pos < jnp.asarray(total).astype(jnp.int32)
        lcols = _gather_cols(ltc.columns, li, alive)
        rcols = _gather_cols(rtc.columns, ri, alive)
        out = DTable({**lcols, **rcols}, alive)
        if extra is not None:
            mask = JEval(out).predicate(extra)
            out = DTable(out.columns, out.alive & mask)
        return out

    def _full_join(self, lt: DTable, rt: DTable, keys, extra) -> DTable:
        left_part = self._equi_join(lt, rt, keys, "left", extra)
        # right rows with no key match (residual predicate excluded, as in
        # the reference interpreter's full-join path)
        lkey, rkey, lvalid, rvalid, bound = self._join_keys(lt, rt, keys)
        lkey = jnp.where(lvalid & lt.alive, lkey, -1)
        rkey = jnp.where(rvalid & rt.alive, rkey, -2)
        _, rcounts, _ = self._probe_counts(rkey, lkey, bound,
                                           need_order=False)
        runmatched = rt.alive & ~(rcounts > 0)
        # bottom block: null left columns + unmatched right rows
        bottom_cols: Dict[str, DCol] = {}
        for n, c in lt.columns.items():
            # null left columns sized to the bottom block's (right)
            # capacity; bounds stay sound (filler rows are all invalid)
            bottom_cols[n] = DCol(jnp.zeros(rt.capacity, c.data.dtype),
                                  jnp.zeros(rt.capacity, bool), c.ctype,
                                  c.dictionary, c.bounds)
        for n, c in rt.columns.items():
            bottom_cols[n] = DCol(c.data, c.valid & runmatched, c.ctype,
                                  c.dictionary, c.bounds)
        bottom = DTable(bottom_cols, runmatched)
        return self._vconcat(left_part, bottom)

    def _residual_hits(self, lt: DTable, rt: DTable, order, lo, counts,
                       extra) -> jnp.ndarray:
        """Per-left-row mask: does any key match survive the residual
        predicate?  (shared by semi / anti / mark joins)"""
        out_cap, total = self._capacity_for(
            jnp.sum(counts, dtype=jnp.int64))
        inner = self._expand(lt, rt, order, lo, counts, total, out_cap)
        keep = JEval(inner).predicate(extra)
        starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)
        li_all = self._expand_li(counts, starts, out_cap)
        return jax.ops.segment_sum(keep.astype(jnp.int32), li_all,
                                   num_segments=lt.capacity) > 0

    def _equi_join(self, lt: DTable, rt: DTable, keys, kind,
                   extra, mark: Optional[str] = None) -> DTable:
        lkey, rkey, lvalid, rvalid, bound = self._join_keys(lt, rt, keys)

        if kind == "nullaware_anti":
            rt_has_null = self._branch_bool(jnp.any(~rvalid & rt.alive))
            rt_nonempty = self._branch_bool(jnp.any(rt.alive))
            if rt_has_null:
                return DTable(lt.columns, jnp.zeros(lt.capacity, bool))
            kind = "anti"
            if rt_nonempty:
                lt = DTable(lt.columns, lt.alive & lvalid)

        # null keys never match; dead rows already sentineled apart
        lkey = jnp.where(lvalid & lt.alive, lkey, -1)
        rkey = jnp.where(rvalid & rt.alive, rkey, -2)

        need_order = kind in ("inner", "left") or extra is not None
        lo, counts, order = self._probe_counts(lkey, rkey, bound,
                                               need_order=need_order)
        counts = jnp.where(lt.alive, counts, 0)
        matched = counts > 0

        if kind == "mark":
            # EXISTS under OR: left table + boolean mark column
            # (numpy analog: physical.py mark-join path)
            if extra is not None:
                matched = self._residual_hits(lt, rt, order, lo, counts,
                                              extra)
            cols = dict(lt.columns)
            cols[mark] = DCol(matched & lt.alive,
                              jnp.ones(lt.capacity, bool), BOOL)
            return DTable(cols, lt.alive)

        if kind in ("semi", "anti"):
            if extra is not None:
                hits = self._residual_hits(lt, rt, order, lo, counts,
                                           extra)
                mask = hits if kind == "semi" else ~hits
                return DTable(lt.columns, lt.alive & mask)
            mask = matched if kind == "semi" else \
                (~matched & lt.alive)
            return DTable(lt.columns, lt.alive & mask)

        # inner/left expansion: one sync point for output capacity
        if kind == "inner":
            out_cap, total = self._capacity_for(
                jnp.sum(counts, dtype=jnp.int64))
            out = self._expand(lt, rt, order, lo, counts, total, out_cap)
            if extra is not None:
                mask = JEval(out).predicate(extra)
                out = DTable(out.columns, out.alive & mask)
            return out
        if kind == "left":
            return self._left_join(lt, rt, order, lo, counts, extra)
        raise Unsupported(f"join kind {kind}", code="NDS210")

    def _expand(self, lt: DTable, rt: DTable, order, lo, counts,
                total, out_cap: int) -> DTable:
        starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)
        pos = jax.lax.iota(jnp.int32, out_cap)
        li = self._expand_li(counts, starts, out_cap)
        within = (pos - starts[li]).astype(lo.dtype)
        rpos = jnp.clip(lo[li] + within, 0, rt.capacity - 1)
        ri = order[rpos]
        alive = pos < jnp.asarray(total).astype(jnp.int32)
        lcols = _gather_cols(lt.columns, li, alive)
        rcols = _gather_cols(rt.columns, ri, alive)
        return DTable({**lcols, **rcols}, alive)

    def _left_join(self, lt: DTable, rt: DTable, order, lo, counts,
                   extra) -> DTable:
        matched_cap, total = self._capacity_for(
            jnp.sum(counts, dtype=jnp.int64))
        inner = self._expand(lt, rt, order, lo, counts, total, matched_cap)
        # left-row index feeding each inner output position
        starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)
        li_all = self._expand_li(counts, starts, matched_cap)
        if extra is not None:
            keep = JEval(inner).predicate(extra)
            inner = DTable(inner.columns, keep)
        # left rows that kept >=1 match after the residual predicate
        hits = jax.ops.segment_sum(inner.alive.astype(jnp.int32), li_all,
                                   num_segments=lt.capacity)
        unmatched_mask = lt.alive & (hits == 0)
        inner_c = self.compact(inner)
        n_matched = jnp.sum(inner_c.alive, dtype=jnp.int32)
        n_unmatched = jnp.sum(unmatched_mask, dtype=jnp.int32)
        out_cap, _ = self._capacity_for(n_matched + n_unmatched)
        # out[pos] = matched[pos] for pos < n_matched,
        #            unmatched-left[pos - n_matched] after (null right side)
        pos = jax.lax.iota(jnp.int32, out_cap)
        is_m = pos < n_matched
        mi = jnp.clip(pos, 0, inner_c.capacity - 1)
        um_idx = jnp.nonzero(unmatched_mask, size=out_cap,
                             fill_value=0)[0].astype(jnp.int32)
        um_rows = um_idx[jnp.clip(pos - n_matched, 0, out_cap - 1)]
        out_alive = pos < (n_matched + n_unmatched)
        cols = _select_cols(
            {n: inner_c.column(n) for n in lt.column_names},
            {n: lt.column(n) for n in lt.column_names},
            mi, um_rows, is_m, out_alive)
        cols.update(_gather_cols(
            {n: inner_c.column(n) for n in rt.column_names},
            mi, is_m & out_alive))
        return DTable(cols, out_alive)


@dataclasses.dataclass
class _CompiledPlan:
    plan: lp.Plan
    compilable: bool
    record: list
    versions: tuple
    # per-table column subset actually scanned (None = all columns)
    table_cols: Dict[str, Optional[List[str]]] = None
    fn: object = None                    # jitted replay function
    out_meta: List[tuple] = None         # (name, ctype, dictionary, bounds)
    # loaded from disk and not yet validated by a successful replay —
    # the first execution self-heals (rediscovers) on any failure
    preloaded: bool = False
    # fn has executed successfully at least once: later backend errors
    # are real device failures and propagate instead of falling back
    fn_validated: bool = False
    # segmented compilation (parent programs only): fingerprints of the
    # separately-compiled subtrees this plan consumes via DeviceResult
    seg_fps: Optional[List[str]] = None
    # output capacity after the final compact (segment replays feed the
    # parent at exactly this padded size)
    out_capacity: int = 0
    # "NDSxxx:NodeName" tags for every fallback hit during discovery
    # (empty when compilable) — the static analyzer's prediction target
    fallback_codes: tuple = ()
    # parameter materializations recorded during discovery (pdict hit
    # tables / pvec IN vectors, in traversal order) — drives the
    # "\x00params" replay-argument subtree for any later binding
    param_spec: list = None
    # representative SQL text for persisted records: canonical cache
    # keys are not re-plannable, so save/load round-trips through SQL
    source_sql: Optional[str] = None


def _scan_columns(p: lp.Plan) -> Dict[str, Optional[List[str]]]:
    """Union of scanned columns per table (None = full table)."""
    out: Dict[str, Optional[List[str]]] = {}
    for node in p.walk():
        if isinstance(node, lp.Scan):
            if node.columns is None:
                out[node.table] = None
            elif node.table not in out:
                out[node.table] = list(node.columns)
            elif out[node.table] is not None:
                for c in node.columns:
                    if c not in out[node.table]:
                        out[node.table].append(c)
    return out


def _seg_argname(fp: str) -> str:
    """Replay-argument key for a segment result (cannot collide with a
    table name: NUL is not legal in identifiers)."""
    return "\x00seg:" + fp


# segmented compilation thresholds: one whole-query XLA program wedges
# the TPU compiler somewhere past ~5k HLO ops (q4 traces to 10k and
# hangs the remote-compile RPC; q1/q3/q6 at 1-2k compile in seconds), so
# plans above _SEG_MIN_TOTAL nodes compile their big aggregate subtrees
# as separate programs whose results stay device-resident.
_SEG_CUT_TYPES = (lp.Aggregate, lp.Window, lp.Distinct)
_SEG_MIN_NODES = 5       # minimum subtree size worth its own program
_SEG_MIN_TOTAL = 14      # plans smaller than this stay single-program


def _cut_segments(p: lp.Plan):
    """Split a plan for segmented compilation.

    Returns ``(parent_plan, segments)`` where segments is an ordered
    {fingerprint: subplan} of maximal Aggregate/Window/Distinct subtrees
    and parent_plan has each occurrence replaced by lp.DeviceResult.
    Identical subtrees (multi-part CTE instantiation) share one segment.
    Deterministic for a given plan tree — discovery, replay, and record
    reload all cut identically."""
    segs: Dict[str, lp.Plan] = {}
    if sum(1 for _ in p.walk()) < _SEG_MIN_TOTAL:
        return p, segs

    import copy as _copy

    def rebuild(node: lp.Plan, is_root: bool) -> lp.Plan:
        if not is_root and isinstance(node, _SEG_CUT_TYPES) and \
                sum(1 for _ in node.walk()) >= _SEG_MIN_NODES:
            try:
                fp = _plan_fp(node)
            except TypeError:
                fp = None  # un-fingerprintable: keep the subtree inline
            if fp is not None:
                segs.setdefault(fp, node)
                return lp.DeviceResult(fp)
        kids = node.children()
        if not kids:
            return node
        new_kids = [rebuild(k, False) for k in kids]
        if all(nk is k for nk, k in zip(new_kids, kids)):
            return node
        q = _copy.copy(node)
        if hasattr(q, "child"):
            q.child = new_kids[0]
        elif hasattr(q, "left"):
            q.left, q.right = new_kids
        else:
            raise RuntimeError(
                f"unknown child layout on {type(node).__name__}")
        return q

    parent = rebuild(p, True)
    return parent, segs


class CompilingExecutor(JaxExecutor):
    """JaxExecutor + whole-query compile cache keyed by SQL text.

    First execution of a query discovers its size plan eagerly; later
    executions run a FIXED set of jitted XLA programs per query — one
    parent program plus one per cut segment (_cut_segments); results of
    segments stay on the device and feed the parent as arguments.
    Segmentation bounds program size (the TPU compiler wedges on ~10k-op
    whole-query programs), shares identical CTE subtrees across query
    parts, and isolates numpy fallbacks to the segment that needs them.
    Guard failure (size-class overflow after data changes) or catalog
    version changes trigger rediscovery.
    """

    def __init__(self, catalog):
        super().__init__(catalog)
        # the eager bounds diagnostic syncs the device; pay it only
        # inside discovery (every query's first execution), not on
        # steady-state demoted eager aggregates
        self._in_discovery = False
        # opt-in per-query attribution (NDSTPU_ATTRIB=1): splits a
        # replay into host-arg-build / device-compute / result-fetch
        # spans and records fetched bytes + XLA cost-analysis flops so
        # a query can be classified dispatch-, transfer-, or
        # compute-bound (the wall clock alone cannot say which —
        # SURVEY §5: the reference has only wall-clock).  Off by
        # default: the extra block_until_ready serializes the device
        # pipeline.
        self.attrib_enabled = os.environ.get(
            "NDSTPU_ATTRIB", "0") not in ("", "0")
        self.last_attribution: Optional[dict] = None

    def execute_cached(self, p: lp.Plan, key: str,
                       params: Optional[ex.ParamBinding] = None,
                       sql: Optional[str] = None) -> Table:
        # compile-once across concurrent streams: the key latch makes
        # the first arrival for a key pay discovery while later
        # arrivals block, then take the cache-hit replay path; the
        # exec lock serializes the actual device execution (see
        # JaxExecutor.__init__).  A failed discovery caches nothing
        # and releases the latch, so it cannot poison other streams.
        # Under canonical keying (analysis/canon.py) `key` is the plan's
        # structural fingerprint, `p` the parameterized exec plan, and
        # `params` the binding for THIS rendering — streams rendering
        # different literals for one template share the compiled entry.
        with self._key_latch.holding(key):
            with self._exec_lock:
                ctx = _ParamCtx(params.values, "concrete") \
                    if params is not None else None
                with _params_bound(ctx):
                    return self._execute_cached_locked(p, key, params,
                                                       sql)

    def _execute_cached_locked(self, p: lp.Plan, key: str,
                               params: Optional[ex.ParamBinding] = None,
                               sql: Optional[str] = None) -> Table:
        versions = tuple(sorted(
            getattr(self.catalog, "versions", {}).items()))
        cp = self._compiled.get(key)
        if cp is not None and cp.versions != versions:
            cp = None
        if cp is None:
            from ndstpu import faults
            faults.check("compile", key=key)
            obs.inc("engine.cache.compiled.miss")
            return self._discover_query(p, key, versions, params, sql)
        obs.inc("engine.cache.compiled.hit")
        if not cp.compilable:
            result = self._eager_with_segments(cp, params)
            if result is None:   # a shared segment was evicted: rebuild
                return self._forget_and_rediscover(p, key, versions,
                                                   params, sql)
            return result
        if cp.fn is None:
            # size-plan record preloaded from disk (see
            # save/load_compile_records): build the jitted replay now
            try:
                cp.fn = self._build_jit(cp)
            except Exception:
                return self._forget_and_rediscover(p, key, versions,
                                                   params, sql)
        if cp.preloaded:
            # first execution of a disk-loaded record: ANY failure —
            # arg build, compile, execution, or result assembly against
            # stale out_meta — means the record drifted; rediscover
            try:
                result = self._replay_query(cp, binding=params)
            except Exception:
                result = None
            if result is None:
                return self._forget_and_rediscover(p, key, versions,
                                                   params, sql)
            cp.preloaded = False
            cp.fn_validated = True
            return result
        try:
            result = self._replay_query(cp, binding=params)
        except jax.errors.JaxRuntimeError as first_err:
            if cp.fn_validated:
                raise  # a real device failure, not a compile rejection
            # could be a compile rejection OR a transient device fault
            # (preemption/OOM): retry once before permanently demoting
            # this query to the eager per-op path — slower, correct
            try:
                result = self._replay_query(cp, binding=params)
            except jax.errors.JaxRuntimeError:
                import warnings
                # warnings.warn (not print): the harness report layer
                # collects warnings into CompletedWithTaskFailures —
                # the reference's task-failure listener analog
                # (PysparkBenchReport.py:89-92); a run that silently
                # fell off the compiled path must say so
                warnings.warn(
                    f"whole-query compile failed twice, demoted to "
                    f"eager per-op execution: {first_err}",
                    stacklevel=2)
                cp.compilable = False
                cp.fn = None
                return self._eager_with_segments(cp, params)
        if result is None:  # size-class guard failed: data changed
            return self._forget_and_rediscover(p, key, versions,
                                               params, sql)
        cp.fn_validated = True
        return result

    def _forget_and_rediscover(self, p, key, versions,
                               params=None, sql=None) -> Table:
        import warnings
        warnings.warn(
            f"compiled plan invalidated (size-class guard failed or "
            f"preloaded record drifted); rediscovering "
            f"{key.split('|', 1)[-1][:80]!r}", stacklevel=2)
        cp = self._compiled.pop(key, None)
        if cp is not None:
            for fp in (cp.seg_fps or ()):
                self._seg_compiled.pop(fp, None)
        return self._discover_query(p, key, versions, params, sql)

    # -- replay ---------------------------------------------------------------

    def _replay_query(self, cp: _CompiledPlan, bucket: str = "execute_s",
                      binding: Optional[ex.ParamBinding] = None,
                      ) -> Optional[Table]:
        """Dispatch segment programs then the parent; ONE batched
        device->host fetch at the end (a fetch costs a tunnel round
        trip).  None = some size guard failed (data changed).

        The whole replay runs under a tracer span attributed to
        ``bucket`` — ``execute_s`` normally, ``compile_s`` for the
        discovery-time warm-up call that pays the XLA compile — so the
        harness's per-query cost split is self-labeling.  The finer
        host-prep/device/fetch sub-split (NDSTPU_ATTRIB=1) keeps its
        opt-in: it needs a block_until_ready that serializes the
        device pipeline."""
        with obs.span("replay", cat="plan-node", bucket=bucket,
                      n_programs=1 + len(cp.seg_fps or ())) as sp:
            result = self._replay_query_timed(cp, sp, binding)
        return result

    def _replay_query_timed(self, cp: _CompiledPlan, sp,
                            binding: Optional[ex.ParamBinding] = None,
                            ) -> Optional[Table]:
        attrib = self.attrib_enabled
        t_start = time.perf_counter()
        seg_args = {}
        seg_oks = []
        seg_flop_args: list = []
        for fp in (cp.seg_fps or ()):
            scp = self._seg_compiled.get(fp)
            if scp is None or scp.versions != cp.versions:
                obs.inc("engine.cache.seg_compiled.miss")
                return None
            obs.inc("engine.cache.seg_compiled.hit")
            if scp.compilable:
                if scp.fn is None:
                    scp.fn = self._build_jit(scp)
                args = {t: self._accel_args(t, c)
                        for t, c in scp.table_cols.items()}
                args["\x00params"] = _param_args_np(scp.param_spec,
                                                    binding)
                if attrib:
                    seg_flop_args.append((scp, args))
                (out, alive), ok = scp.fn(args)
                seg_args[_seg_argname(fp)] = (out, alive)
                seg_oks.append(ok)
            else:
                # fallback-isolated segment: host numpy result, shipped
                # to the device at the recorded output capacity (the
                # ambient concrete _ParamCtx supplies bound values)
                host = self.execute_to_host(scp.plan)
                seg_args[_seg_argname(fp)] = self._seg_host_args(
                    scp, host)
        args = {t: self._accel_args(t, cols)
                for t, cols in cp.table_cols.items()}
        args["\x00params"] = _param_args_np(cp.param_spec, binding)
        args.update(seg_args)
        t_dispatch = time.perf_counter()
        (out, alive), ok = cp.fn(args)
        if attrib:
            # serialize: device span ends when every output is ready,
            # fetch span is then the pure device->host transfer
            jax.block_until_ready(((out, alive), ok))
            t_ready = time.perf_counter()
        (out, alive_np), okv, seg_okv = jax.device_get(
            ((out, alive), ok, seg_oks))
        t_fetched = time.perf_counter()
        fetched = int(alive_np.nbytes) + sum(
            d.nbytes + v.nbytes for d, v in out.values())
        obs.inc("engine.fetched_bytes", fetched)
        sp.set(host_prep_s=round(t_dispatch - t_start, 5),
               fetched_bytes=fetched)
        if attrib:
            attribution = {
                "host_prep_s": round(t_dispatch - t_start, 5),
                "device_s": round(t_ready - t_dispatch, 5),
                "fetch_s": round(t_fetched - t_ready, 5),
                "fetched_bytes": fetched,
                "n_programs": 1 + len(cp.seg_fps or ()),
                "flops": self._cost_flops(cp, args, seg_flop_args),
            }
            self.last_attribution = attribution
            sp.set(device_s=attribution["device_s"],
                   fetch_s=attribution["fetch_s"],
                   flops=attribution["flops"])
        if not (bool(okv) and all(bool(o) for o in seg_okv)):
            return None
        for fp in (cp.seg_fps or ()):
            scp = self._seg_compiled.get(fp)
            if scp is not None:
                scp.preloaded = False
                scp.fn_validated = True
        return self._assemble_host(cp, out, alive_np)

    def _cost_flops(self, cp: _CompiledPlan, args,
                    seg_flop_args) -> Optional[float]:
        """XLA cost-analysis flops of the parent + compiled segment
        programs (drives MFU = flops / device_s / peak_flops).  Each
        program is re-lowered ONCE to reach cost_analysis (tracing can
        take seconds on CTE-heavy queries), then cached on its
        _CompiledPlan.  None when the backend offers no analysis."""

        def one(plan_cp, plan_args) -> float:
            cached = getattr(plan_cp, "cost_flops", None)
            if cached is not None:
                return cached
            an = plan_cp.fn.lower(plan_args).compile().cost_analysis()
            if isinstance(an, (list, tuple)):
                flops = sum(float(d.get("flops", 0.0)) for d in an if d)
            else:
                flops = float(an.get("flops", 0.0))
            plan_cp.cost_flops = flops
            return flops

        try:
            total = one(cp, args)
            for scp, sargs in seg_flop_args:
                total += one(scp, sargs)
            return total
        except Exception:
            return None

    @staticmethod
    def _assemble_host(cp: _CompiledPlan, out, alive_np) -> Table:
        cols = {}
        for name, ctype, dictionary, _bounds in cp.out_meta:
            data, valid = out[name]
            data = data[alive_np]
            valid = valid[alive_np]
            cols[name] = Column(data, ctype,
                                None if valid.all() else valid, dictionary)
        return Table(cols)

    def _replay_one(self, scp: _CompiledPlan,
                    binding: Optional[ex.ParamBinding] = None,
                    ) -> Optional[Table]:
        """Replay a single segment program to a host Table (reuse path:
        a second query part sharing an already-compiled segment).  Under
        canonical keying the segment's parameter slots are bound from
        the CURRENT query's binding — fingerprint-identical subtrees
        share the compiled program even when their literals differ."""
        if not scp.compilable:
            return self.execute_to_host(scp.plan)
        if scp.fn is None:
            scp.fn = self._build_jit(scp)
        args = {t: self._accel_args(t, c)
                for t, c in scp.table_cols.items()}
        args["\x00params"] = _param_args_np(scp.param_spec, binding)
        (out, alive), ok = scp.fn(args)
        (out, alive_np), okv = jax.device_get(((out, alive), ok))
        if not bool(okv):
            return None
        return self._assemble_host(scp, out, alive_np)

    def _seg_host_args(self, scp: _CompiledPlan, host: Table):
        """(cols, alive) replay-argument structure for a host-computed
        segment result, padded to the segment's recorded capacity."""
        cap = max(scp.out_capacity, size_class(max(host.num_rows, 1)))
        n = host.num_rows
        alive = np.zeros(cap, bool)
        alive[:n] = True
        cols = {}
        for name, ctype, dictionary, _bounds in scp.out_meta:
            col = host.columns[name]
            data = _pad(np.asarray(col.data), cap)
            valid = _pad(col.validity(), cap, fill=False)
            cols[name] = (jnp.asarray(data), jnp.asarray(valid))
        return (cols, jnp.asarray(alive))

    def _dt_from_host(self, scp: _CompiledPlan, host: Table) -> DTable:
        """Eager DTable view of a segment's host result carrying EXACTLY
        the segment's out_meta (ctype/dictionary/bounds): parent
        discovery must see the same static metadata replay will, or the
        traced parent program diverges from the discovered record."""
        (cols, alive) = self._seg_host_args(scp, host)
        dcols = {}
        for name, ctype, dictionary, bounds in scp.out_meta:
            d, v = cols[name]
            dcols[name] = DCol(d, v, ctype, dictionary, bounds)
        return DTable(dcols, alive)

    # -- discovery ------------------------------------------------------------

    def _discover_query(self, p: lp.Plan, key: str, versions,
                        params: Optional[ex.ParamBinding] = None,
                        sql: Optional[str] = None) -> Table:
        # the whole first-ever pass — eager discovery, jit builds, and
        # the warm-up replay that pays the XLA compile — is cold-path
        # cost a steady-state run never pays: bucket it as compile_s so
        # headline numbers are self-labeling (round-5 verdict: a cold
        # run was committed as warm because nothing could tell)
        with obs.span("discover_query", cat="plan-node",
                      bucket="compile_s", n_segments=0) as sp:
            obs.inc("engine.discoveries")
            return self._discover_query_traced(p, key, versions, sp,
                                               params, sql)

    def _discover_query_traced(self, p: lp.Plan, key: str, versions, sp,
                               params: Optional[ex.ParamBinding] = None,
                               sql: Optional[str] = None) -> Table:
        parent, segs = _cut_segments(p)
        sp.set(n_segments=len(segs))
        self._seg_tables = {}
        for fp, sub in segs.items():
            dt = None
            scp = self._seg_compiled.get(fp)
            if scp is not None and scp.versions == versions:
                obs.inc("engine.cache.seg_compiled.hit")
            else:
                obs.inc("engine.cache.seg_compiled.miss")
            if scp is not None and scp.versions == versions:
                # already compiled for another query (part): replay it
                # for values instead of re-running eager discovery
                try:
                    host = self._replay_one(scp, params)
                except Exception:
                    host = None
                if host is not None:
                    with host_compute():
                        dt = self._dt_from_host(scp, host)
                    scp.preloaded = False
                    scp.fn_validated = True
            if dt is None:
                scp, dt = self._discover_plan(sub, versions,
                                              params=params)
                self._seg_compiled[fp] = scp
            self._seg_tables[fp] = dt
        # the parent's jit closure captures segment metas, so seg_fps
        # MUST be set before the fn is built (build_fn=False + build
        # here), or replay KeyErrors on the segment argument names
        cp, dtp = self._discover_plan(parent, versions, build_fn=False,
                                      params=params)
        cp.seg_fps = list(segs.keys())
        cp.source_sql = sql
        if cp.compilable:
            try:
                cp.fn = self._build_jit(cp)
            except Exception:
                cp.compilable = False
        self._compiled[key] = cp
        if cp.compilable and self.warm_replay:
            # trace+compile+execute the replay NOW (jit is lazy: the
            # first fn call pays the whole compile).  Without this the
            # "steady-state" second run of every query paid its compile
            # — r03's query1 took 59.4 s on run 2 vs 5.9 s discovery.
            # A warm failure is not fatal: the next execute_cached
            # replays (or demotes) through the normal path.
            try:
                # the warm-up call pays the XLA compile inside fn():
                # bucket it compile_s, not execute_s
                if self._replay_query(cp, bucket="compile_s",
                                      binding=params) is not None:
                    cp.fn_validated = True
            except Exception as e:  # noqa: BLE001
                import warnings
                warnings.warn(
                    f"replay warm-up failed ({type(e).__name__}: {e}); "
                    f"first replay will retry", stacklevel=2)
        try:
            with host_compute():
                return to_host(dtp)
        finally:
            # the eager segment DTables are device-resident padded
            # buffers; keeping them past the query holds HBM for nothing
            self._seg_tables = {}

    def _discover_plan(self, p: lp.Plan, versions, build_fn=True,
                       params: Optional[ex.ParamBinding] = None):
        """Discover ONE program (parent or segment): eager host
        execution recording every data-dependent decision; returns
        (cp, compacted eager DTable)."""
        self.n_discoveries += 1
        self._subq_cache = {}
        self._tree_cache = {}
        self.np_exec = physical.Executor(self.catalog)
        self.mode = "discover"
        self._in_discovery = True
        self._rec = []
        self._used_fallback = False
        self._fallback_codes = []
        # record parameter materializations (pdict/pvec) alongside the
        # size plan so replay can rebuild the argument subtree for any
        # later binding of the same canonical fingerprint
        pspec: list = []
        pctx = _ParamCtx(params.values, "concrete", spec=pspec,
                         record=True) if params is not None else None
        try:
            with _params_bound(pctx) if pctx is not None \
                    else contextlib.nullcontext():
                with host_compute():
                    dt = self.execute(p)
                    # compact to the result's own size class BEFORE
                    # output: replay fetches (or hands the parent) every
                    # output column at padded capacity, and results are
                    # usually far smaller than the fact capacity they
                    # ride in on.  The compaction capacity is one more
                    # recorded sync point, so replay stays static.
                    dt = self.compact(dt)
        finally:
            self.mode = "eager"
            self._in_discovery = False
        cp = _CompiledPlan(p, not self._used_fallback, self._rec, versions)
        cp.param_spec = pspec
        cp.fallback_codes = tuple(sorted(self._fallback_codes))
        cp.table_cols = _scan_columns(p)
        cp.out_capacity = dt.capacity
        cp.out_meta = [(name, c.ctype, c.dictionary, c.bounds)
                       for name, c in dt.columns.items()]
        if cp.compilable and build_fn:
            try:
                cp.fn = self._build_jit(cp)
            except Exception:
                cp.compilable = False
        return cp, dt

    def _eager_with_segments(self, cp: _CompiledPlan,
                             params: Optional[ex.ParamBinding] = None):
        """Non-compilable parent: numpy-interpreter execution over
        segment results (still compiled where possible).  None when a
        shared segment is missing or its guard failed — the caller
        rediscovers.  The ambient concrete _ParamCtx (installed by
        execute_cached) binds any parameter slots the interpreter hits."""
        self._seg_tables = {}
        for fp in (cp.seg_fps or ()):
            scp = self._seg_compiled.get(fp)
            if scp is None:
                return None
            try:
                host = self._replay_one(scp, params)
            except Exception:
                host = None
            if host is None:
                return None
            with host_compute():
                self._seg_tables[fp] = self._dt_from_host(scp, host)
        try:
            return self.execute_to_host(cp.plan)
        finally:
            self._seg_tables = {}

    # -- persisted size-plan records ------------------------------------------

    def _table_fingerprint(self, name: str) -> tuple:
        """Cheap content identity for a catalog table: row count + a
        prefix checksum over integer-backed columns.  Guards persisted
        size-plan records against a *different dataset* at the same
        paths — per-process version counters cannot (they restart at 1)."""
        t = self.catalog.get(name)
        chk = 0
        for cname in t.column_names[:3]:
            col = t.column(cname)
            if col.data.dtype.kind in "iu":
                chk ^= int(np.asarray(col.data[:4096], dtype=np.int64)
                           .sum()) & (2 ** 61 - 1)
        return (name, t.num_rows, chk)

    _REC_FORMAT = 4   # bump when the pickle schema changes
                      # (4: + per-program param_spec; keys round-trip
                      # through representative SQL so canonical cache
                      # keys can be rebuilt by re-canonicalizing)

    def save_compile_records(self, path: str) -> int:
        """Persist discovery size-plan records (NOT compiled code — XLA
        has its own persistent cache) so a fresh process can skip the
        eager discovery pass per query.  Keys are stored as bare SQL
        text (the in-memory views-epoch prefix is process-local).
        Returns the record count."""
        import pickle
        with self._exec_lock:
            return self._save_compile_records_locked(path, pickle)

    def _save_compile_records_locked(self, path: str, pickle) -> int:
        data = {"\x00fmt": self._REC_FORMAT, "\x00segments": {}}
        segstore = data["\x00segments"]
        for key, cp in self._compiled.items():
            if not (cp.compilable and cp.record is not None):
                continue
            # canonical keys are not re-plannable text: prefer the
            # representative SQL captured at discovery
            sql = cp.source_sql or (
                key.split("|", 1)[1] if "|" in key else key)
            try:
                fps = tuple(self._table_fingerprint(t)
                            for t in sorted(cp.table_cols or ()))
            except KeyError:
                continue  # references a since-dropped table
            ok = True
            for fp in (cp.seg_fps or ()):
                scp = self._seg_compiled.get(fp)
                if scp is None or scp.record is None:
                    ok = False
                    break
                if fp not in segstore:
                    try:
                        sfps = tuple(self._table_fingerprint(t)
                                     for t in sorted(scp.table_cols or ()))
                    except KeyError:
                        ok = False
                        break
                    segstore[fp] = (scp.record, sfps, scp.table_cols,
                                    scp.out_meta, scp.out_capacity,
                                    scp.compilable, scp.param_spec)
            if ok:
                data[sql] = (cp.record, fps, cp.table_cols, cp.out_meta,
                             cp.seg_fps, cp.out_capacity, cp.param_spec)
        # MERGE with what's already on disk, then publish atomically:
        # a subset run (e.g. a 12-query validation pass) must never
        # truncate a full-corpus record file another process spent
        # hours warming, and concurrent throughput streams saving to
        # one path must never interleave writes into a corrupt pickle
        # (last atomic writer wins with a valid superset).
        try:
            with open(path, "rb") as f:
                prev = pickle.load(f)
            if isinstance(prev, dict) and \
                    prev.get("\x00fmt") == self._REC_FORMAT:
                for k, v in prev.items():
                    if k == "\x00segments":
                        for fp, sv in v.items():
                            segstore.setdefault(fp, sv)
                    else:
                        data.setdefault(k, v)
        except Exception:  # noqa: BLE001 — absent or corrupt prior file
            pass
        import os as _os
        import uuid as _uuid
        tmp = f"{path}.tmp.{_uuid.uuid4().hex}"
        with open(tmp, "wb") as f:
            pickle.dump(data, f)
        _os.replace(tmp, path)
        return len(data) - 2

    def load_compile_records(self, path: str, plan_for_key,
                             key_prefix: str = "0") -> int:
        """Preload size-plan records saved by save_compile_records.
        `plan_for_key(sql)` must return the optimized plan for the SQL
        text — or, under canonical keying, an ``(exec_plan, cache_key)``
        pair so the record registers under the same canonical key a
        fresh rendering will probe (or None to skip).  Records whose
        table fingerprints no longer match the catalog are dropped;
        drifted records self-heal at first execution (the replay guard
        rediscovers).  Returns the count loaded."""
        import pickle
        with open(path, "rb") as f:
            data = pickle.load(f)
        if not isinstance(data, dict) or \
                data.get("\x00fmt") != self._REC_FORMAT:
            return 0
        with self._exec_lock:
            return self._load_compile_records_locked(
                data, plan_for_key, key_prefix)

    def _load_compile_records_locked(self, data, plan_for_key,
                                     key_prefix: str) -> int:
        segstore = data.get("\x00segments", {})
        versions_now = tuple(sorted(
            getattr(self.catalog, "versions", {}).items()))

        def fingerprints_ok(fps):
            try:
                return all(self._table_fingerprint(fp[0]) == fp
                           for fp in fps)
            except KeyError:
                return False

        from ndstpu.engine.sql import normalize_sql_key
        n = 0
        for sql, ent in data.items():
            if sql.startswith("\x00"):
                continue
            (record, fps, table_cols, out_meta, seg_fps, out_cap,
             pspec) = ent
            if not fingerprints_ok(fps):
                continue
            res = plan_for_key(sql)
            if res is None:
                continue
            if isinstance(res, tuple):
                plan, ckey = res   # canonical keying
            else:
                plan, ckey = res, normalize_sql_key(sql)
            parent, segs = _cut_segments(plan)
            if sorted(segs.keys()) != sorted(seg_fps or ()):
                continue  # cut heuristic or plan changed: rediscover
            seg_ok = True
            for fp in (seg_fps or ()):
                if fp in self._seg_compiled and \
                        self._seg_compiled[fp].versions == versions_now:
                    continue
                sent = segstore.get(fp)
                if sent is None or not fingerprints_ok(sent[1]):
                    seg_ok = False
                    break
                (srec, _sfps, stc, som, socap, scomp, spspec) = sent
                scp = _CompiledPlan(segs[fp], scomp, srec, versions_now,
                                    stc, None, som, preloaded=True)
                scp.out_capacity = socap
                scp.param_spec = spspec
                self._seg_compiled[fp] = scp
            if not seg_ok:
                continue
            cp = _CompiledPlan(parent, True, record, versions_now,
                               table_cols, None, out_meta, preloaded=True)
            cp.seg_fps = list(seg_fps or ())
            cp.out_capacity = out_cap
            cp.param_spec = pspec
            cp.source_sql = sql
            self._compiled[f"{key_prefix}|{ckey}"] = cp
            n += 1
        return n

    # -- replay argument assembly --------------------------------------------

    def _table_args(self, name: str, cols: Optional[List[str]] = None):
        dt = self._table_device(name)
        names = dt.column_names if cols is None else cols
        return ({n: (dt.columns[n].data, dt.columns[n].valid)
                 for n in names}, dt.alive)

    def _accel_args(self, name: str, cols: Optional[List[str]] = None):
        """Replay inputs, resident on the accelerator.  Cached per
        (table version, COLUMN) — different queries scan overlapping
        column subsets, and caching whole subsets pinned duplicate
        copies of every shared column in HBM (at SF1 the accumulation
        crashed the TPU worker under the big rollup programs).  Args
        are assembled from the shared per-column buffers; the structure
        the jitted replay sees is unchanged."""
        version = getattr(self.catalog, "versions", {}).get(name)
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            return self._table_args(name, cols)
        dt = self._table_device(name)
        names = dt.column_names if cols is None else cols
        akey = (name, None)     # None can never be a column name
        ent = self._accel_cache.get(akey)
        if ent is None or ent[0] != version or version is None:
            # version changed: drop every stale buffer of this table
            for k in [k for k in self._accel_cache if k[0] == name]:
                del self._accel_cache[k]
            self._accel_cache[akey] = (
                version, jax.device_put(dt.alive, dev))
        alive = self._accel_cache[akey][1]
        missing = [n for n in names
                   if self._accel_cache.get((name, n)) is None or
                   self._accel_cache[(name, n)][0] != version or
                   version is None]
        if missing:
            # one batched transfer for every missing column (per-column
            # device_put would pay the tunnel round-trip per call)
            up = jax.device_put(
                {n: (dt.columns[n].data, dt.columns[n].valid)
                 for n in missing}, dev)
            for n in missing:
                self._accel_cache[(name, n)] = (version, up[n])
        return ({n: self._accel_cache[(name, n)][1] for n in names},
                alive)

    def _build_jit(self, cp: _CompiledPlan):
        self.n_jit_builds += 1
        obs.inc("engine.jit_builds")
        with obs.span("build_jit", cat="plan-node", bucket="compile_s"):
            return self._build_jit_traced(cp)

    def _build_jit_traced(self, cp: _CompiledPlan):
        metas = {}
        for name in cp.table_cols:
            dt = self._table_device(name)
            metas[name] = {n: (c.ctype, c.dictionary, c.bounds)
                           for n, c in dt.columns.items()}
        for fp in (cp.seg_fps or ()):
            scp = self._seg_compiled[fp]
            metas[_seg_argname(fp)] = {
                n: (ct, d, b) for n, ct, d, b in scp.out_meta}

        def replay(tables):
            self._subq_cache = {}
            self._tree_cache = {}
            self.mode = "replay"
            self._pos = 0
            self._oks = []
            self._rec = cp.record
            self._trace_tables = {}
            for name, entry in tables.items():
                if name == "\x00params":
                    continue   # parameter subtree, not a table
                cols, alive = entry
                # iterate in META order, not arg order: jax pytrees sort
                # dict keys, and column ORDER must match what discovery
                # saw (SubqueryAlias zips aliases positionally)
                dcols = {n: DCol(*cols[n], *metas[name][n])
                         for n in metas[name] if n in cols}
                self._trace_tables[name] = DTable(dcols, alive)
            pctx = _ParamCtx(None, "trace", spec=cp.param_spec or [],
                             traced=tables.get("\x00params") or {})
            try:
                with _params_bound(pctx):
                    dt = self.execute(cp.plan)
                    dt = self.compact(dt)   # mirror of _discover_plan
                if pctx.pos != len(pctx.spec):
                    raise RuntimeError(
                        "param-spec drift (unconsumed entries)")
                # output-type guard: engine typing changes (e.g. the
                # r04 coalesce decimal-literal fix) can retype a
                # column without changing the PLAN tree, so a
                # preloaded record's out_meta goes stale while its
                # size plan still matches.  Assembling scaled-decimal
                # data under a recorded float64 meta silently wrote
                # x100 values — raise at trace time instead (ctypes
                # are static here); callers rediscover.
                rec_meta = {name: ct for name, ct, _d, _b in cp.out_meta}
                for name, c in dt.columns.items():
                    if rec_meta.get(name) != c.ctype:
                        raise RuntimeError(
                            f"size-plan drift: output column {name} "
                            f"traced as {c.ctype}, recorded "
                            f"{rec_meta.get(name)}")
                ok = jnp.asarray(True)
                for o in self._oks:
                    ok = ok & o
            finally:
                self.mode = "eager"
                self._trace_tables = None
            out = {name: (c.data, c.valid) for name, c in dt.columns.items()}
            return (out, dt.alive), ok

        return jax.jit(replay)


def execute(plan: lp.Plan, catalog) -> Table:
    """Execute a plan on the JAX backend, returning a host Table."""
    return JaxExecutor(catalog).execute_to_host(plan)
