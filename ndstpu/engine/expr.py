"""Expression IR and null-aware columnar evaluation (numpy reference path).

Implements Spark-SQL-compatible semantics the validator depends on
(cf. reference nds_validate.py equality rules):

* three-valued logic for comparisons and AND/OR over NULLs
* decimal arithmetic on scale-shifted int64 (add/sub align scales,
  multiply adds scales, divide produces float64)
* string predicates (LIKE, substr, ||) evaluated once per dictionary entry,
  then gathered by code — O(|dict|) instead of O(rows)
* date arithmetic as int32 day counts (+ INTERVAL n DAYS)

The same IR is compiled to jax expressions by ndstpu.engine.kernels for the
TPU path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import re
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ndstpu.engine import columnar
from ndstpu.engine.columnar import (
    BOOL,
    DATE,
    FLOAT64,
    INT32,
    INT64,
    STRING,
    Column,
    DType,
    Table,
    decimal,
)

# ---------------------------------------------------------------------------
# IR nodes
# ---------------------------------------------------------------------------


class Expr:
    def children(self) -> Sequence["Expr"]:
        return ()

    def walk(self):
        yield self
        for c in self.children():
            yield from c.walk()


@dataclasses.dataclass(frozen=True)
class ColumnRef(Expr):
    name: str

    def __repr__(self):
        return f"col({self.name})"


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    value: object  # python int/float/str/bool/None
    ctype: Optional[DType] = None

    def __repr__(self):
        return f"lit({self.value!r})"


@dataclasses.dataclass(frozen=True)
class Star(Expr):
    """COUNT(*) argument."""


@dataclasses.dataclass(frozen=True)
class BinOp(Expr):
    op: str  # + - * / = <> < <= > >= and or
    left: Expr
    right: Expr

    def children(self):
        return (self.left, self.right)

    def __repr__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclasses.dataclass(frozen=True)
class UnaryOp(Expr):
    op: str  # not, neg, isnull, isnotnull
    operand: Expr

    def children(self):
        return (self.operand,)


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    operand: Expr
    target: DType

    def children(self):
        return (self.operand,)


@dataclasses.dataclass(frozen=True)
class Case(Expr):
    whens: Tuple[Tuple[Expr, Expr], ...]
    default: Optional[Expr]

    def children(self):
        out = []
        for c, v in self.whens:
            out += [c, v]
        if self.default is not None:
            out.append(self.default)
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class Func(Expr):
    name: str  # substr, coalesce, like, upper, lower, abs, round, extract...
    args: Tuple[Expr, ...]

    def children(self):
        return self.args


@dataclasses.dataclass(frozen=True)
class InList(Expr):
    operand: Expr
    values: Tuple[object, ...]
    negated: bool = False

    def children(self):
        return (self.operand,)


@dataclasses.dataclass(frozen=True)
class Param(Expr):
    """A literal lifted into a typed runtime-parameter slot by
    ndstpu.analysis.canon.  The slot's value travels outside the plan (in
    the canonical binding) so structurally identical queries share one
    compiled program.  `shape=True` marks slots whose value participates
    in static shape planning; those are substituted back to concrete
    literals before execution and exist only for fingerprinting."""

    slot: int
    ctype: DType  # always resolved by the canonicalizer, never None
    shape: bool = False

    def __repr__(self):
        k = "S" if self.shape else "P"
        return f"param({k}{self.slot}:{self.ctype!r})"


@dataclasses.dataclass(frozen=True)
class InParam(Expr):
    """An IN-list whose value tuple is lifted into one parameter slot.
    The arity is static (part of the compiled program's shape); only the
    member values are bound at execution time."""

    operand: Expr
    slot: int
    n: int
    negated: bool = False

    def children(self):
        return (self.operand,)

    def __repr__(self):
        neg = "not " if self.negated else ""
        return f"inparam({self.operand} {neg}in P{self.slot}[{self.n}])"


@dataclasses.dataclass(frozen=True)
class ParamBinding:
    """Slot values for one execution of a canonical plan.

    ``values`` is indexed by slot id (IN-list slots hold the value tuple,
    shape slots hold the substituted-back literal).  ``scalars`` lists the
    runtime-bindable scalar slots with their resolved types — the compiled
    program declares one traced argument per entry, so the set must be a
    pure function of the canonical fingerprint (it is: both derive from
    the same canonicalization)."""

    values: Tuple[object, ...]
    scalars: Tuple[Tuple[int, DType], ...] = ()


# Active parameter binding for the numpy evaluator (and any fallback path
# that re-evaluates canonical subtrees host-side).  Thread-local because
# harness streams share one Session from worker threads.
_PARAMS = threading.local()


def active_params() -> Optional[Tuple[object, ...]]:
    return getattr(_PARAMS, "values", None)


@contextlib.contextmanager
def bound_params(values: Optional[Sequence[object]]):
    prev = getattr(_PARAMS, "values", None)
    _PARAMS.values = tuple(values) if values is not None else None
    try:
        yield
    finally:
        _PARAMS.values = prev


@dataclasses.dataclass(frozen=True)
class AggExpr(Expr):
    func: str  # sum, avg, count, min, max, stddev_samp, count_distinct
    arg: Expr  # Star() for count(*)
    distinct: bool = False

    def children(self):
        return (self.arg,) if not isinstance(self.arg, Star) else ()

    def __repr__(self):
        d = "distinct " if self.distinct else ""
        return f"{self.func}({d}{self.arg})"


@dataclasses.dataclass(frozen=True)
class WindowExpr(Expr):
    func: str  # rank, dense_rank, row_number, sum, avg, min, max, count
    arg: Optional[Expr]
    partition_by: Tuple[Expr, ...]
    order_by: Tuple[Tuple[Expr, bool], ...]  # (expr, ascending)
    # "rows" | "range" running frame (UNBOUNDED PRECEDING..CURRENT ROW);
    # None = no explicit frame (aggregates with order_by still run as a
    # RANGE running frame, Spark's default)
    frame: Optional[str] = None

    def children(self):
        out = list(self.partition_by) + [e for e, _ in self.order_by]
        if self.arg is not None and not isinstance(self.arg, Star):
            out.append(self.arg)
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class SubqueryExpr(Expr):
    """Scalar / IN / EXISTS subquery — replaced by the planner (decorrelation
    or pre-execution) before evaluation; evaluating one directly is an error
    unless `resolved` has been filled with a literal/column."""

    kind: str  # scalar, in, exists
    plan: object  # logical plan node
    operand: Optional[Expr] = None  # for IN
    negated: bool = False
    correlated_predicates: Tuple = ()

    def children(self):
        return (self.operand,) if self.operand is not None else ()


# ---------------------------------------------------------------------------
# Type utilities
# ---------------------------------------------------------------------------


def is_numeric(ct: DType) -> bool:
    return ct.kind in ("int32", "int64", "float64", "decimal")


def literal_decimal_type(e) -> Optional[DType]:
    """Spark literal typing for fractional literals: `0.0` parses as
    DECIMAL(1,1), `1.25` as DECIMAL(3,2) (scientific notation stays
    double).  Returns None when `e` is not an exactly-decimal float
    literal.  Reference behavior: Spark's Literal(BigDecimal)."""
    if not isinstance(e, Literal) or not isinstance(e.value, float):
        return None
    if e.ctype is not None and e.ctype.kind != "float64":
        return None
    from decimal import Decimal
    d = Decimal(str(e.value))
    exp = -d.as_tuple().exponent
    if exp < 0 or exp > 12 or float(d) != e.value:
        return None
    # BigDecimal("0.0") is precision 1 scale 1 (digits (0,) count as
    # one digit, all fractional)
    prec = max(len(d.as_tuple().digits), exp, 1)
    return decimal(prec, exp)


def coalesce_common_type(arg_exprs, arg_ctypes) -> DType:
    """COALESCE result type with Spark-faithful literal typing: an
    exact fractional literal (`0.0`) counts as DECIMAL, so
    coalesce(decimal_col, 0.0) stays DECIMAL instead of promoting to
    float.  Exactness matters beyond fidelity: TPU f64 is emulated at
    reduced precision, and a float-promoted money column made q75's
    UNION-distinct collapse different duplicate sets on TPU vs the
    numpy interpreter (6 of 100 groups drifted by a few counts).
    Shared by both evaluators so the backends agree."""
    eff = []
    for a, ct in zip(arg_exprs, arg_ctypes):
        if ct.kind == "float64":
            dt = literal_decimal_type(a)
            if dt is not None:
                ct = dt
        eff.append(ct)
    tgt = eff[0]
    for ct in eff[1:]:
        if is_numeric(ct) and is_numeric(tgt):
            tgt = common_type(tgt, ct)
    return tgt


def common_type(a: DType, b: DType) -> DType:
    """Numeric type unification (Spark-ish)."""
    if a.kind == b.kind == "decimal":
        s = max(a.scale, b.scale)
        return decimal(max(a.precision - a.scale, b.precision - b.scale) + s, s)
    if "float64" in (a.kind, b.kind):
        return FLOAT64
    if "decimal" in (a.kind, b.kind):
        d = a if a.kind == "decimal" else b
        return d
    if "int64" in (a.kind, b.kind):
        return INT64
    if a.kind == "date" or b.kind == "date":
        return DATE
    return INT32


def coerce_in_values(ctype: DType, values) -> Tuple[list, bool]:
    """Coerce untyped string IN-list literals to a non-string operand
    column's domain (SQL implicit cast: `d_date in ('2000-06-30', ...)`).
    A literal that fails the cast is NULL in SQL: dropped from the match
    set (it can never compare equal), but reported via the second return
    so NOT IN can apply NULL semantics (never TRUE).  For decimal
    columns the returned values are scale-shifted int64.  Shared by both
    the numpy and JAX evaluators so the backends agree."""
    out, had_null = [], False
    for v in values:
        try:
            if ctype.kind == "decimal":
                if isinstance(v, str):
                    try:
                        v = int(v)  # exact for integral literals > 2^53
                    except ValueError:
                        v = float(v)
                if isinstance(v, int):
                    v = v * 10 ** ctype.scale
                else:
                    v = round(float(v) * 10 ** ctype.scale)
            elif isinstance(v, str):
                if ctype.kind == "date":
                    v = columnar.parse_date_days(v)
                else:
                    try:
                        v = int(v)  # int first: float would lose >2^53
                    except ValueError:
                        v = float(v)
        except ValueError:
            had_null = True
            continue
        out.append(v)
    return out, had_null


def cast_column(c: Column, target: DType) -> Column:
    k, tk = c.ctype.kind, target.kind
    if k == tk and (tk != "decimal" or c.ctype.scale == target.scale):
        if tk == "decimal" and c.ctype.precision != target.precision:
            # same scale -> same representation; retag the precision, but
            # values that overflow the narrower precision become NULL
            # (Spark non-ANSI overflow semantics)
            if target.precision < c.ctype.precision:
                limit = 10 ** target.precision
                ok = np.abs(c.data) < limit
                valid = ok if c.valid is None else (c.valid & ok)
                return Column(c.data, target,
                              None if valid.all() else valid, c.dictionary)
            return Column(c.data, target, c.valid, c.dictionary)
        return c
    v = c.valid

    def _strings_to_floats():
        """Per-value parse; unparseable -> NULL (Spark cast semantics)."""
        out = np.zeros(len(c.data), dtype=np.float64)
        valid = c.validity().copy()
        for i, x in enumerate(c.to_pylist()):
            if x is None:
                valid[i] = False
                continue
            try:
                out[i] = float(x)
            except ValueError:
                valid[i] = False
        return out, (None if valid.all() else valid)

    def _half_up(x: np.ndarray) -> np.ndarray:
        return np.floor(np.abs(x) + 0.5) * np.sign(x)

    if tk == "float64":
        if k == "decimal":
            data = c.data.astype(np.float64) / (10 ** c.ctype.scale)
        elif k == "string":
            data, v = _strings_to_floats()
        else:
            data = c.data.astype(np.float64)
        return Column(data, FLOAT64, v)
    if tk == "decimal":
        scale = 10 ** target.scale
        if k == "decimal":
            shift = target.scale - c.ctype.scale
            data = (c.data * (10 ** shift) if shift >= 0
                    else _div_round_half_up(c.data, 10 ** (-shift)))
        elif k == "float64":
            data = _half_up(c.data * scale)  # Spark HALF_UP, not banker's
        elif k == "string":
            floats, v = _strings_to_floats()
            data = _half_up(floats * scale)
        else:
            data = c.data.astype(np.int64) * scale
        return Column(data.astype(np.int64), target, v)
    if tk in ("int32", "int64"):
        dt = np.int64 if tk == "int64" else np.int32
        if k == "decimal":
            data = _div_trunc(c.data, 10 ** c.ctype.scale).astype(dt)
        elif k == "float64":
            data = c.data.astype(dt)
        elif k == "string":
            out = np.zeros(len(c.data), dtype=dt)
            valid = c.validity().copy()
            for i, x in enumerate(c.to_pylist()):
                if x is None:
                    valid[i] = False
                    continue
                try:
                    out[i] = int(float(x))
                except ValueError:
                    valid[i] = False
            return Column(out, target, valid)
        else:
            data = c.data.astype(dt)
        return Column(data, target, v)
    if tk == "date":
        if k == "string":
            out = np.zeros(len(c.data), dtype=np.int32)
            valid = c.validity().copy()
            for i, x in enumerate(c.to_pylist()):
                if x is None:
                    valid[i] = False
                    continue
                out[i] = columnar.parse_date_days(x)
            return Column(out, DATE, valid)
        return Column(c.data.astype(np.int32), DATE, v)
    if tk == "string":
        vals = c.to_pylist()
        strs = [None if x is None else _to_str(x, c.ctype) for x in vals]
        return Column.from_strings(strs)
    if tk == "bool":
        return Column(c.data.astype(bool), BOOL, v)
    raise NotImplementedError(f"cast {c.ctype} -> {target}")


def parse_dictionary_days(dictionary) -> Tuple[np.ndarray, np.ndarray]:
    """Parse a string dictionary's entries as dates: (days, parse_ok)
    per entry.  Shared by both backends' implicit string->date compare
    coercion so their NULL semantics for unparseable values agree."""
    n = len(dictionary) if dictionary is not None else 0
    days = np.zeros(n, dtype=np.int32)
    ok = np.ones(n, dtype=bool)
    for i in range(n):
        try:
            days[i] = columnar.parse_date_days(str(dictionary[i]))
        except ValueError:
            ok[i] = False
    return days, ok


def string_to_date_column(c: Column) -> Column:
    """Implicit string->date coercion for compares: decode via the
    (small) dictionary, unparseable entries and negative codes become
    NULL."""
    days, ok = parse_dictionary_days(c.dictionary)
    codes_ok = c.data >= 0
    if len(days):
        idx = np.clip(c.data, 0, len(days) - 1)
        out = np.where(codes_ok, days[idx], np.int32(0))
        valid = c.validity() & codes_ok & ok[idx]
    else:
        out = np.zeros(len(c.data), dtype=np.int32)
        valid = np.zeros(len(c.data), dtype=bool)
    return Column(out.astype(np.int32), DATE, valid)


def _to_str(x, ct: DType) -> str:
    if ct.kind == "decimal":
        return f"{x:.{ct.scale}f}"
    if isinstance(x, float) and x.is_integer():
        return str(int(x))
    return str(x)


def _div_round_half_up(a: np.ndarray, d: int) -> np.ndarray:
    sign = np.sign(a)
    return sign * ((np.abs(a) + d // 2) // d)


def _div_trunc(a: np.ndarray, d: int) -> np.ndarray:
    return np.trunc(a / d).astype(np.int64)


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}
_ARITH_OPS = {"+", "-", "*", "/", "%"}


def literal_column(value, n: int, ctype: Optional[DType] = None) -> Column:
    if value is None:
        ct = ctype or INT32
        data = np.zeros(n, dtype=columnar.numpy_dtype(ct))
        return Column(data, ct, np.zeros(n, dtype=bool))
    if isinstance(value, bool):
        return Column(np.full(n, value, dtype=bool), BOOL)
    if isinstance(value, int):
        ct = ctype or (INT64 if abs(value) > 2**31 - 1 else INT32)
        if ct.kind == "decimal":
            return Column(np.full(n, value * 10 ** ct.scale, np.int64), ct)
        return Column(np.full(n, value, columnar.numpy_dtype(ct)), ct)
    if isinstance(value, float):
        if ctype and ctype.kind == "decimal":
            return Column(
                np.full(n, round(value * 10 ** ctype.scale), np.int64), ctype)
        return Column(np.full(n, value, np.float64), FLOAT64)
    if isinstance(value, str):
        d = np.array([value], dtype=object)
        return Column(np.zeros(n, dtype=np.int32), STRING, None, d)
    raise NotImplementedError(f"literal {value!r}")


class Evaluator:
    """Evaluates an Expr against a Table (numpy backend)."""

    def __init__(self, table: Table):
        self.table = table
        self.n = table.num_rows

    def eval(self, e: Expr) -> Column:
        if isinstance(e, ColumnRef):
            return self.table.column(e.name)
        if isinstance(e, Literal):
            return literal_column(e.value, self.n, e.ctype)
        if isinstance(e, Cast):
            return cast_column(self.eval(e.operand), e.target)
        if isinstance(e, BinOp):
            return self._binop(e)
        if isinstance(e, UnaryOp):
            return self._unary(e)
        if isinstance(e, Case):
            return self._case(e)
        if isinstance(e, Func):
            return self._func(e)
        if isinstance(e, InList):
            return self._in_list(e)
        if isinstance(e, Param):
            vals = active_params()
            if vals is None or e.shape:
                raise RuntimeError(
                    f"unbound parameter slot {e.slot} reached evaluation")
            return literal_column(vals[e.slot], self.n, e.ctype)
        if isinstance(e, InParam):
            vals = active_params()
            if vals is None:
                raise RuntimeError(
                    f"unbound parameter slot {e.slot} reached evaluation")
            return self._in_list(
                InList(e.operand, tuple(vals[e.slot]), e.negated))
        if isinstance(e, SubqueryExpr):
            raise RuntimeError(
                "unresolved subquery reached evaluation — planner bug")
        raise NotImplementedError(f"eval {type(e).__name__}")

    # -- operators -----------------------------------------------------------

    def _binop(self, e: BinOp) -> Column:
        op = e.op
        if op in ("and", "or"):
            return self._logical(op, self.eval(e.left), self.eval(e.right))
        lc = self.eval(e.left)
        rc = self.eval(e.right)
        if op in _CMP_OPS:
            return self._compare(op, lc, rc)
        if op in _ARITH_OPS:
            return self._arith(op, lc, rc)
        if op == "||":
            return self._concat(lc, rc)
        raise NotImplementedError(f"binop {op}")

    def _logical(self, op: str, lc: Column, rc: Column) -> Column:
        lv, rv = lc.validity(), rc.validity()
        ld = lc.data.astype(bool) & lv
        rd = rc.data.astype(bool) & rv
        if op == "and":
            data = ld & rd
            # null unless (false anywhere) or (both valid)
            definite_false = (~lc.data.astype(bool) & lv) | \
                             (~rc.data.astype(bool) & rv)
            valid = (lv & rv) | definite_false
        else:
            data = ld | rd
            definite_true = ld | rd
            valid = (lv & rv) | definite_true
        return Column(data, BOOL, None if valid.all() else valid)

    def _align_for_compare(self, lc: Column, rc: Column):
        """Return comparable numpy arrays for the two sides."""
        lk, rk = lc.ctype.kind, rc.ctype.kind
        if lk == "string" and rk == "string":
            if lc.dictionary is not None and rc.dictionary is not None:
                if len(rc.dictionary) and len(lc.dictionary) and \
                        np.array_equal(lc.dictionary, rc.dictionary):
                    return lc.data, rc.data
                # translate right codes into left's dictionary ordering via
                # string rank comparison: compare decoded order keys
                merged = columnar.merge_dictionaries([lc, rc])
                ltr = columnar.translate_codes(lc, merged)
                rtr = columnar.translate_codes(rc, merged)
                return ltr, rtr
        if lk == "decimal" or rk == "decimal":
            s = max(lc.ctype.scale if lk == "decimal" else 0,
                    rc.ctype.scale if rk == "decimal" else 0)
            tgt = decimal(38, s)
            if "float64" in (lk, rk):
                return (cast_column(lc, FLOAT64).data,
                        cast_column(rc, FLOAT64).data)
            return cast_column(lc, tgt).data, cast_column(rc, tgt).data
        if lk == "float64" or rk == "float64":
            return (cast_column(lc, FLOAT64).data,
                    cast_column(rc, FLOAT64).data)
        return lc.data, rc.data

    def _compare(self, op: str, lc: Column, rc: Column) -> Column:
        # implicit string->date coercion (Spark semantics): a string
        # compared against a date parses as a date, unparseable -> NULL.
        # Without it both backends fell through to comparing date days
        # against raw dictionary codes — `d_date >= '2002-4-01'` matched
        # every date since 1970 (the string's code is 0).
        if lc.ctype.kind == "date" and rc.ctype.kind == "string":
            rc = string_to_date_column(rc)
        elif rc.ctype.kind == "date" and lc.ctype.kind == "string":
            lc = string_to_date_column(lc)
        ld, rd = self._align_for_compare(lc, rc)
        if op == "=":
            data = ld == rd
        elif op == "<>":
            data = ld != rd
        elif op == "<":
            data = ld < rd
        elif op == "<=":
            data = ld <= rd
        elif op == ">":
            data = ld > rd
        else:
            data = ld >= rd
        valid = lc.validity() & rc.validity()
        return Column(np.asarray(data, dtype=bool), BOOL,
                      None if valid.all() else valid)

    def _arith(self, op: str, lc: Column, rc: Column) -> Column:
        lk, rk = lc.ctype.kind, rc.ctype.kind
        valid = lc.validity() & rc.validity()
        vopt = None if valid.all() else valid
        # date +/- interval days (int)
        if lk == "date" and rk in ("int32", "int64"):
            data = (lc.data.astype(np.int64) +
                    (rc.data if op == "+" else -rc.data)).astype(np.int32)
            return Column(data, DATE, vopt)
        if op == "/":
            ld = cast_column(lc, FLOAT64).data
            rd = cast_column(rc, FLOAT64).data
            safe = np.where(rd == 0, 1.0, rd)
            data = ld / safe
            valid = valid & (rd != 0)  # Spark: x/0 -> NULL
            return Column(data, FLOAT64,
                          None if valid.all() else valid)
        if lk == "decimal" or rk == "decimal":
            if "float64" in (lk, rk):
                ld = cast_column(lc, FLOAT64).data
                rd = cast_column(rc, FLOAT64).data
                data = {"+": ld + rd, "-": ld - rd, "*": ld * rd,
                        "%": np.mod(ld, np.where(rd == 0, 1, rd))}[op]
                return Column(data, FLOAT64, vopt)
            ls = lc.ctype.scale if lk == "decimal" else 0
            rs = rc.ctype.scale if rk == "decimal" else 0
            if op == "*":
                data = lc.data.astype(np.int64) * rc.data.astype(np.int64)
                return Column(data, decimal(38, ls + rs), vopt)
            s = max(ls, rs)
            ld = lc.data.astype(np.int64) * (10 ** (s - ls))
            rd = rc.data.astype(np.int64) * (10 ** (s - rs))
            if op == "%":
                safe = np.where(rd == 0, 1, rd)
                data = np.mod(ld, safe)
                valid = valid & (rd != 0)
                return Column(data, decimal(38, s),
                              None if valid.all() else valid)
            data = ld + rd if op == "+" else ld - rd
            return Column(data, decimal(38, s), vopt)
        tgt = common_type(lc.ctype, rc.ctype)
        ld = cast_column(lc, tgt).data
        rd = cast_column(rc, tgt).data
        if op == "%":
            safe = np.where(rd == 0, 1, rd)
            data = np.mod(ld, safe)
            valid = valid & (rd != 0)
            return Column(data, tgt, None if valid.all() else valid)
        data = {"+": ld + rd, "-": ld - rd, "*": ld * rd}[op]
        return Column(data, tgt, vopt)

    def _concat(self, lc: Column, rc: Column) -> Column:
        ls = cast_column(lc, STRING)
        rs = cast_column(rc, STRING)
        lv, rv = ls.to_pylist(), rs.to_pylist()
        return Column.from_strings(
            [None if a is None or b is None else a + b
             for a, b in zip(lv, rv)])

    def _unary(self, e: UnaryOp) -> Column:
        c = self.eval(e.operand)
        if e.op == "not":
            v = c.validity()
            return Column(~c.data.astype(bool), BOOL,
                          None if v.all() else v)
        if e.op == "neg":
            return Column(-c.data, c.ctype, c.valid)
        if e.op == "isnull":
            return Column(~c.validity(), BOOL)
        if e.op == "isnotnull":
            return Column(c.validity().copy(), BOOL)
        raise NotImplementedError(f"unary {e.op}")

    def _case(self, e: Case) -> Column:
        n = self.n
        conds = []
        vals = []
        for cond, val in e.whens:
            cc = self.eval(cond)
            conds.append(cc.data.astype(bool) & cc.validity())
            vals.append(self.eval(val))
        default = (self.eval(e.default) if e.default is not None
                   else None)
        # unify result type
        cands = vals + ([default] if default is not None else [])
        tgt = cands[0].ctype
        for c in cands[1:]:
            if is_numeric(c.ctype) and is_numeric(tgt):
                tgt = common_type(tgt, c.ctype)
            elif c.ctype.kind != tgt.kind:
                tgt = c.ctype if tgt.kind == "int32" else tgt
        if tgt.kind == "string":
            out: List = [None] * n
            taken = np.zeros(n, dtype=bool)
            for cond, val in zip(conds, vals):
                sv = cast_column(val, STRING).to_pylist()
                idx = np.nonzero(cond & ~taken)[0]
                for i in idx:
                    out[i] = sv[i]
                taken |= cond
            if default is not None:
                dv = cast_column(default, STRING).to_pylist()
                for i in np.nonzero(~taken)[0]:
                    out[i] = dv[i]
            return Column.from_strings(out)
        data = np.zeros(n, dtype=columnar.numpy_dtype(tgt))
        valid = np.zeros(n, dtype=bool)
        taken = np.zeros(n, dtype=bool)
        for cond, val in zip(conds, vals):
            vc = cast_column(val, tgt)
            sel = cond & ~taken
            data = np.where(sel, vc.data, data)
            valid = np.where(sel, vc.validity(), valid)
            taken |= cond
        if default is not None:
            dc = cast_column(default, tgt)
            data = np.where(taken, data, dc.data)
            valid = np.where(taken, valid, dc.validity())
        return Column(data.astype(columnar.numpy_dtype(tgt)), tgt,
                      None if valid.all() else valid)

    def _in_list(self, e: InList) -> Column:
        c = self.eval(e.operand)
        had_null = False
        if c.ctype.kind == "string":
            vals = set(str(v) for v in e.values)
            hit_codes = np.array(
                [i for i, d in enumerate(c.dictionary) if str(d) in vals],
                dtype=np.int32)
            data = np.isin(c.data, hit_codes)
        elif c.ctype.kind == "decimal":
            vals, had_null = coerce_in_values(c.ctype, e.values)
            data = np.isin(c.data, np.array(vals, dtype=np.int64)) \
                if vals else np.zeros(len(c.data), dtype=bool)
        else:
            vals, had_null = coerce_in_values(c.ctype, e.values)
            data = np.isin(c.data, np.array(vals)) if vals else \
                np.zeros(len(c.data), dtype=bool)
        if e.negated:
            # x NOT IN (..., NULL) is never TRUE (NULL semantics)
            data = np.zeros_like(data) if had_null else ~data
        v = c.validity()
        return Column(data, BOOL, None if v.all() else v)

    # -- functions -----------------------------------------------------------

    def _dict_map(self, c: Column, fn) -> Column:
        """Apply a python string function per dictionary entry, re-encode."""
        if c.ctype.kind != "string":
            c = cast_column(c, STRING)
        new_vals = [fn(str(x)) for x in c.dictionary]
        uniq = np.unique(np.asarray(new_vals, dtype=str)) if new_vals else \
            np.empty(0, dtype=object)
        remap = np.searchsorted(uniq, np.asarray(new_vals, dtype=str)).astype(
            np.int32) if new_vals else np.empty(0, np.int32)
        out = np.full(len(c.data), -1, dtype=np.int32)
        ok = c.data >= 0
        out[ok] = remap[c.data[ok]]
        return Column(out, STRING, c.valid, uniq.astype(object))

    def _dict_pred(self, c: Column, fn) -> Column:
        """Apply a python predicate per dictionary entry -> bool column."""
        if c.ctype.kind != "string":
            c = cast_column(c, STRING)
        hits = np.array([bool(fn(str(x))) for x in c.dictionary], dtype=bool)
        data = np.zeros(len(c.data), dtype=bool)
        ok = c.data >= 0
        data[ok] = hits[c.data[ok]]
        v = c.validity()
        return Column(data, BOOL, None if v.all() else v)

    def _func(self, e: Func) -> Column:
        name = e.name
        if name == "coalesce":
            cols = [self.eval(a) for a in e.args]
            tgt = coalesce_common_type(e.args, [c.ctype for c in cols])
            if tgt.kind == "string":
                lists = [cast_column(c, STRING).to_pylist() for c in cols]
                out = [next((x for x in row if x is not None), None)
                       for row in zip(*lists)]
                return Column.from_strings(out)
            data = np.zeros(self.n, dtype=columnar.numpy_dtype(tgt))
            valid = np.zeros(self.n, dtype=bool)
            for c in cols:
                cc = cast_column(c, tgt)
                take = ~valid & cc.validity()
                data = np.where(take, cc.data, data)
                valid |= cc.validity()
            return Column(data.astype(columnar.numpy_dtype(tgt)), tgt,
                          None if valid.all() else valid)
        if name == "like":
            c = self.eval(e.args[0])
            pattern = e.args[1].value  # literal
            rx = re.compile(_like_to_regex(pattern), re.S)
            return self._dict_pred(c, lambda s: rx.fullmatch(s) is not None)
        if name in ("substr", "substring"):
            c = self.eval(e.args[0])
            start = int(e.args[1].value)
            length = int(e.args[2].value) if len(e.args) > 2 else None

            def sub(s: str) -> str:
                i = start - 1 if start > 0 else len(s) + start
                return s[i:i + length] if length is not None else s[i:]
            return self._dict_map(c, sub)
        if name == "upper":
            return self._dict_map(self.eval(e.args[0]), str.upper)
        if name == "lower":
            return self._dict_map(self.eval(e.args[0]), str.lower)
        if name == "trim":
            return self._dict_map(self.eval(e.args[0]), str.strip)
        if name == "length":
            c = self.eval(e.args[0])
            if c.ctype.kind != "string":
                c = cast_column(c, STRING)
            lens = np.array([len(str(x)) for x in c.dictionary],
                            dtype=np.int32)
            data = np.zeros(len(c.data), dtype=np.int32)
            ok = c.data >= 0
            data[ok] = lens[c.data[ok]]
            return Column(data, INT32, c.valid)
        if name == "abs":
            c = self.eval(e.args[0])
            return Column(np.abs(c.data), c.ctype, c.valid)
        if name == "round":
            c = self.eval(e.args[0])
            nd = int(e.args[1].value) if len(e.args) > 1 else 0
            if c.ctype.kind == "decimal":
                if nd >= c.ctype.scale:
                    return c
                return cast_column(c, decimal(c.ctype.precision, nd))
            # float round-half-up (Spark semantics), not banker's rounding
            m = 10.0 ** nd
            data = np.floor(np.abs(c.data) * m + 0.5) / m * np.sign(c.data)
            return Column(data, FLOAT64, c.valid)
        if name == "floor":
            c = cast_column(self.eval(e.args[0]), FLOAT64)
            return Column(np.floor(c.data), FLOAT64, c.valid)
        if name == "ceil":
            c = cast_column(self.eval(e.args[0]), FLOAT64)
            return Column(np.ceil(c.data), FLOAT64, c.valid)
        if name == "sqrt":
            c = cast_column(self.eval(e.args[0]), FLOAT64)
            with np.errstate(invalid="ignore"):
                return Column(np.sqrt(np.maximum(c.data, 0)), FLOAT64, c.valid)
        if name == "year":
            c = self.eval(e.args[0])
            days = c.data.astype("datetime64[D]")
            years = days.astype("datetime64[Y]").astype(int) + 1970
            return Column(years.astype(np.int32), INT32, c.valid)
        if name == "month":
            c = self.eval(e.args[0])
            days = c.data.astype("datetime64[D]")
            months = days.astype("datetime64[M]").astype(int) % 12 + 1
            return Column(months.astype(np.int32), INT32, c.valid)
        if name == "day":
            c = self.eval(e.args[0])
            days = c.data.astype("datetime64[D]")
            dom = (days - days.astype("datetime64[M]")).astype(int) + 1
            return Column(dom.astype(np.int32), INT32, c.valid)
        if name == "concat":
            cols = [cast_column(self.eval(a), STRING).to_pylist()
                    for a in e.args]
            out = [None if any(x is None for x in row) else "".join(row)
                   for row in zip(*cols)]
            return Column.from_strings(out)
        if name == "nullif":
            a = self.eval(e.args[0])
            b = self.eval(e.args[1])
            eqc = self._compare("=", a, b)
            eq = eqc.data & eqc.validity()
            valid = a.validity() & ~eq
            return Column(a.data, a.ctype, None if valid.all() else valid,
                          a.dictionary)
        raise NotImplementedError(f"function {name}")


def _like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)


def eval_predicate(table: Table, e: Expr) -> np.ndarray:
    """Evaluate a predicate to a keep-mask (NULL -> False, SQL WHERE)."""
    c = Evaluator(table).eval(e)
    return np.asarray(c.data, dtype=bool) & c.validity()
