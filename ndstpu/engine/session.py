"""Engine session: the `SparkSession.sql()` analog.

Holds the table catalog + temp views, parses/plans/executes SQL, and
dispatches DM statements (CREATE TEMP VIEW / CTAS / INSERT / DELETE / DROP)
— the surface the harness layers (power run, maintenance, validation) drive,
replacing the reference's SparkSession usage (nds_power.py:221-245,
nds_maintenance.py:107-116).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ndstpu.engine import columnar, physical, planner as pl, plan as lp
from ndstpu.engine.sql import ast, parse_statement, parse_statements


class _NullCM:
    """No-op lock stand-in for Session-like objects that predate the
    __post_init__ lock set (e.g. unpickled from an old snapshot)."""

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CM = _NullCM()


class ChunkFallthroughError(RuntimeError):
    """NDS311 under NDSTPU_SPMD_STRICT: configured chunked streaming
    silently degraded to the single-chip whole-fact path."""


@dataclass
class SnapshotPin:
    """An immutable catalog epoch captured at query admission
    (docs/ARCHITECTURE.md "Snapshot-pinned reads").

    Catalog entries are REPLACED, never mutated, by DML and register
    (io/loader.Catalog), so shallow-copied dicts are a frozen,
    consistent view: a query planned and executed against this pin
    sees exactly the snapshot that existed when the pin was taken,
    however many refresh functions commit meanwhile.  ``epoch`` is the
    durable data-version identity (io/lake.warehouse_epoch when a
    warehouse is attached) the ingest differential keys results on."""

    catalog: object
    views: Dict[str, lp.Plan]
    views_epoch: int
    versions: tuple  # sorted (table, version) catalog-version vector
    epoch: Optional[str] = None

    @property
    def state(self):
        """Cache-state tuple, same shape the live caches key on."""
        return (self.views_epoch, self.versions)


@dataclass
class Session:
    catalog: object  # ndstpu.io.loader.Catalog
    views: Dict[str, lp.Plan] = field(default_factory=dict)
    # ndslake warehouse root for ACID INSERT/DELETE passthrough (maintenance)
    warehouse: Optional[str] = None
    # cpu | tpu | tpu-spmd (tpu falls back per-plan when needed; tpu-spmd
    # runs the distributed SPMD executor over the device mesh and falls
    # back to the single-chip tpu path on unsupported plan shapes)
    backend: str = "cpu"
    # tpu-spmd: minimum table rows to shard (None = dplan default)
    spmd_threshold: Optional[int] = None
    # out-of-core streaming (tpu AND tpu-spmd): stream facts larger
    # than this through the mesh shard-major in chunks of this many
    # rows — each device scans only its own shard's chunks.  "auto"
    # lets the spill-aware planner (engine/memplan.py) size chunks and
    # prefetch depth from device memory stats; None = whole-fact
    # HBM-resident.  On a multi-device mesh a plan shape the chunked
    # executor cannot run falls back to the whole-fact single-chip
    # path, defeating out-of-core — that fall-through is surfaced as
    # diagnostic NDS311 (warning + counter; NDSTPU_SPMD_STRICT raises)
    spmd_chunk_rows: Optional[object] = None
    # chunks staged ahead of compute by the H2D prefetch ring
    # (0 = synchronous streaming; None = planner/executor default)
    spmd_prefetch_depth: Optional[int] = None
    # cross-query spine-materialization cache (engine/spine.SpineCache);
    # None = no sharing.  Installed by the inproc scheduler when its
    # streams share flagged spines; NDSTPU_SPINES=0 kills splicing even
    # when installed
    spine_cache: Optional[object] = None
    # bumped on view create/drop — part of the compiled-query cache key
    # (same SQL text over a redefined view must not reuse a stale plan)
    _views_epoch: int = 0

    def __post_init__(self):
        # Thread-safety contract (inproc throughput scheduler,
        # ndstpu/harness/scheduler.py): N stream threads share one
        # Session.  Three pieces make that sound:
        #   _cache_lock — guards _plan_cache get/put and lazy
        #       sub-object init (executor, spmd caches);
        #   _plan_latch — per-query-text "plan once, others wait"
        #       (ndstpu.engine.latch.KeyedLatch), so concurrent streams
        #       never duplicate planning work and cache-hit counters
        #       stay an honest compile-once proof;
        #   _exec_lock  — serializes statement EXECUTION (and all
        #       DDL/DML).  The executor keeps per-query mutable state
        #       (discovery recorder, subquery memos) and the physical
        #       device runs programs serially anyway, so statement-
        #       granularity serialization loses no real parallelism;
        #       cross-statement overlap happens at the admission gate.
        # RLocks: CTAS/INSERT recurse into _run on the same thread.
        import threading

        from ndstpu.engine.latch import KeyedLatch
        if self.spmd_chunk_rows is not None and not (
                self.spmd_chunk_rows == "auto"
                or (isinstance(self.spmd_chunk_rows, int)
                    and not isinstance(self.spmd_chunk_rows, bool)
                    and self.spmd_chunk_rows > 0)):
            raise ValueError(
                f"spmd_chunk_rows must be a positive int, 'auto', or "
                f"None, got {self.spmd_chunk_rows!r}")
        if self.spmd_prefetch_depth is not None and (
                not isinstance(self.spmd_prefetch_depth, int)
                or self.spmd_prefetch_depth < 0):
            raise ValueError(
                f"spmd_prefetch_depth must be a non-negative int or "
                f"None, got {self.spmd_prefetch_depth!r}")
        self._cache_lock = threading.RLock()
        self._exec_lock = threading.RLock()
        self._plan_latch = KeyedLatch()
        self._plan_cache: Dict[str, tuple] = {}

    def sql(self, text: str,
            pin: Optional[SnapshotPin] = None) -> Optional[columnar.Table]:
        """Execute one statement; returns a Table for queries, None for
        DDL.  With ``pin`` (from :meth:`pin_snapshot`), a query runs
        against that frozen catalog epoch regardless of concurrent
        ingest commits — DML/DDL under a pin is an error."""
        from ndstpu.engine.sql import normalize_sql_key
        stmt = parse_statement(text)
        return self._run(stmt, key=normalize_sql_key(text), pin=pin)

    def sql_script(self, text: str) -> List[Optional[columnar.Table]]:
        return [self._run(s) for s in parse_statements(text)]

    def pin_snapshot(self) -> SnapshotPin:
        """Resolve and freeze the current catalog epoch for a query's
        lifetime.  Taken under the execution lock — the micro-batch
        ingestor (harness/ingest.py) holds the same lock across each
        whole refresh function, so a pin can only ever observe batch
        boundaries, never half a refresh function."""
        from ndstpu import obs
        with self._exec_lock:
            from ndstpu.io.loader import Catalog
            cat = Catalog(tables=dict(self.catalog.tables),
                          meta=dict(getattr(self.catalog, "meta", {})),
                          versions=dict(
                              getattr(self.catalog, "versions", {})))
            pin = SnapshotPin(
                catalog=cat, views=dict(self.views),
                views_epoch=self._views_epoch,
                versions=tuple(sorted(cat.versions.items())),
                epoch=self.snapshot_epoch())
        obs.inc("engine.snapshot.pinned")
        return pin

    def snapshot_epoch(self) -> Optional[str]:
        """Durable data-version identity of this session's data: the
        lake warehouse epoch (io/lake.py) when a warehouse is attached,
        else a local tag over the in-memory catalog-version vector."""
        if self.warehouse is not None:
            from ndstpu.io import lake
            ep = lake.warehouse_epoch(self.warehouse)
            if ep is not None:
                return ep
        import hashlib
        versions = tuple(sorted(
            getattr(self.catalog, "versions", {}).items()))
        blob = repr((self._views_epoch, versions)).encode()
        return "mem" + hashlib.sha256(blob).hexdigest()[:12]

    def plan(self, text: str):
        stmt = parse_statement(text)
        if not isinstance(stmt, ast.Query):
            raise ValueError("plan() expects a query")
        planner = pl.Planner(self.catalog, dict(self.views))
        plan, cols = planner.plan_query(stmt)
        from ndstpu.engine.optimizer import optimize
        return optimize(plan, self.catalog), cols

    def _run(self, stmt: ast.Node, key: Optional[str] = None,
             pin: Optional[SnapshotPin] = None
             ) -> Optional[columnar.Table]:
        # the whole statement is execute_s; cold-path work nested inside
        # (discovery, jit builds) carries its own compile_s bucket and
        # is subtracted by the tracer's self-time accounting, so the
        # per-query compile/execute split needs no bookkeeping here
        from ndstpu import obs
        with obs.span("statement", cat="plan-node", bucket="execute_s",
                      kind=type(stmt).__name__, backend=self.backend):
            return self._run_traced(stmt, key, pin)

    def _run_traced(self, stmt: ast.Node,
                    key: Optional[str] = None,
                    pin: Optional[SnapshotPin] = None
                    ) -> Optional[columnar.Table]:
        if isinstance(stmt, ast.Query):
            plan, disp, canon = self._plan_cached(stmt, key, pin)
            if canon is not None:
                # canonical identity on the query span: sidecars and the
                # run ledger can group renderings by structure
                from ndstpu import obs
                obs.annotate(canon_fp=canon.fingerprint,
                             canon_key=canon.cache_key)
                codes = sorted({d.code for d in canon.diagnostics})
                if codes:
                    obs.annotate(canon_codes=",".join(codes))
            # execution serialized (see __post_init__): the executor's
            # per-query mutable state is not safe under concurrent
            # statements, and one device runs programs serially anyway
            with self._exec_lock:
                if getattr(self, "spine_cache", None) is not None:
                    plan, canon = self._splice_spines(plan, canon, key,
                                                      pin)
                out = self._execute(plan, key=key, canon=canon, pin=pin)
            return columnar.Table(dict(zip(disp, out.columns.values())))
        if pin is not None:
            raise ValueError(
                "DDL/DML cannot run against a snapshot pin — pins are "
                "read-only views of a committed epoch")
        with self._exec_lock:
            return self._run_ddl(stmt)

    def _plan_cached(self, stmt: "ast.Query", key: Optional[str],
                     pin: Optional[SnapshotPin] = None):
        """Plan + optimize + canonicalize with the text-keyed plan
        cache; returns ``(plan, display_names, CanonResult-or-None)``.

        A steady-state replay of a compiled query must not re-plan +
        re-optimize the SQL every call (50-150 ms of pure host overhead
        per execution on complex plans).  The key is the TEXT alone —
        one slot per query, with views epoch + catalog versions stored
        in the value and replace-on-mismatch (like _spmd_cache): DML or
        view churn must invalidate without stranding old-epoch entries
        forever.  Under the per-key latch, concurrent streams plan each
        distinct text exactly once: later arrivals block, then hit.
        Planning itself is host-pure (reads catalog/views), so distinct
        texts plan concurrently while the device executes.

        A pinned query plans against the pin's frozen catalog/views and
        keys the cache on the pin's state — a pin that fell behind the
        live epoch replaces the entry and vice versa (thrash, never a
        wrong plan), while a pin still AT the live epoch (the common
        case between refresh batches) shares the live entry.
        """
        from ndstpu import faults, obs
        faults.check("plan", key=key)
        pc = getattr(self, "_plan_cache", None)
        if pc is None:
            with getattr(self, "_cache_lock", _NULL_CM):
                pc = getattr(self, "_plan_cache", None)
                if pc is None:
                    pc = self._plan_cache = {}
        if key is None:
            with obs.span("plan", cat="plan-node"):
                plan, disp = self._plan_fresh(stmt, pin)
            return plan, disp, None
        latch = getattr(self, "_plan_latch", None)
        with (latch.holding(key) if latch is not None else _NULL_CM):
            if pin is not None:
                state = pin.state
            else:
                versions = tuple(sorted(
                    getattr(self.catalog, "versions", {}).items()))
                state = (self._views_epoch, versions)
            with getattr(self, "_cache_lock", _NULL_CM):
                ent = pc.get(key)
            if ent is not None and ent[0] != state:
                ent = None
            obs.inc("engine.cache.plan.hit" if ent is not None
                    else "engine.cache.plan.miss")
            if ent is not None:
                _s, plan, disp, canon = ent
                return plan, disp, canon
            with obs.span("plan", cat="plan-node"):
                plan, disp = self._plan_fresh(stmt, pin)
            canon = self._canonicalize(plan, key)
            # store only on success: a planner exception propagates
            # with nothing cached (no poisoning), the latch releases
            # in its finally, and the next arrival retries
            with getattr(self, "_cache_lock", _NULL_CM):
                pc[key] = (state, plan, disp, canon)
            return plan, disp, canon

    def _canonicalize(self, plan: lp.Plan, key: str):
        """Parameter-lift an optimized plan (analysis/canon.py) for
        shape-keyed compile caching.  None (→ text keying) on any
        canonicalization failure or with NDSTPU_CANON=0 — the safety
        valve keeps queries running when the analyzer is wrong."""
        import os
        if os.environ.get("NDSTPU_CANON", "1") in ("", "0"):
            return None
        from ndstpu import obs
        try:
            from ndstpu.analysis import canon as _canon
            with obs.span("canonicalize", cat="plan-node"):
                return _canon.canonicalize(plan, query=key)
        except Exception as e:  # noqa: BLE001
            obs.inc("engine.canon.errors")
            obs.annotate(canon_error=f"{type(e).__name__}: {e}")
            return None

    # -- cross-query spine sharing (engine/spine.py + analysis/spines.py) ----

    def _spine_sites_for(self, plan: lp.Plan, key: str):
        """Eligible spine sites for one cached plan: the outermost
        non-overlapping shareable subtrees the analyzer flags
        (analysis/spines.py — shared rule set with the MQO audit).
        Memoized per query text; invalidated with the plan cache's
        state so site node references always point into the plan
        object `_plan_cached` currently serves."""
        from ndstpu.analysis import spines as sp
        memo = getattr(self, "_spine_sites_cache", None)
        if memo is None:
            with getattr(self, "_cache_lock", _NULL_CM):
                memo = getattr(self, "_spine_sites_cache", None)
                if memo is None:
                    memo = self._spine_sites_cache = {}
        ent = memo.get(key)
        if ent is not None and ent[0] == id(plan):
            return ent[1]
        sites = sp.eligible_sites(sp.subtree_sites(plan, query=key))
        with getattr(self, "_cache_lock", _NULL_CM):
            memo[key] = (id(plan), sites)
        return sites

    def spine_candidate_keys(self, text: str) -> set:
        """Value keys of the eligible spine sites in one query text —
        what the scheduler counts across streams to decide which spines
        are worth publishing (>= 2 occurrences)."""
        from ndstpu.engine.sql import normalize_sql_key
        try:
            stmt = parse_statement(text)
            if not isinstance(stmt, ast.Query):
                return set()
            key = normalize_sql_key(text)
            plan, _disp, canon = self._plan_cached(stmt, key)
            if canon is None:
                return set()   # canonicalization off/failed: no splicing
            return {s.value_key for s in self._spine_sites_for(plan, key)}
        except Exception:  # noqa: BLE001 — unplannable text
            return set()

    def _splice_spines(self, plan: lp.Plan, canon, key: Optional[str],
                       pin: Optional[SnapshotPin] = None):
        """Replace this plan's flagged spine subtrees with their
        materialized tables (InlineTable), publishing on first use.

        Requires a successful canonicalization and a text key: the
        spliced plan re-canonicalizes before execution, and the
        InlineTable content hash folds into that fingerprint, so the
        spliced and unspliced programs get distinct compile-cache
        entries by construction.  Runs under `_exec_lock` — the per-key
        latch in the cache only adds materialize-once semantics for
        callers outside it.  A materialization failure propagates like
        any query failure (the harness retry/fault taxonomy owns it);
        analysis failures just skip splicing."""
        import os
        if os.environ.get("NDSTPU_SPINES", "1") in ("", "0"):
            return plan, canon
        cache = self.spine_cache
        if cache is None or canon is None or key is None:
            return plan, canon
        from ndstpu import obs
        try:
            sites = [s for s in self._spine_sites_for(plan, key)
                     if cache.eligible(s.value_key)]
        except Exception:  # noqa: BLE001 — analyzer defect: run unspliced
            obs.inc("engine.spine.errors")
            return plan, canon
        if not sites:
            return plan, canon
        if pin is not None:
            # spine entries are keyed to the PIN's epoch: a query
            # pinned before an ingest commit neither serves nor is
            # served a post-commit spine (the cache's state check
            # drops the mismatch and ticks engine.snapshot.stale_drops)
            state = pin.state
        else:
            versions = tuple(sorted(
                getattr(self.catalog, "versions", {}).items()))
            state = (self._views_epoch, versions)
        memo = getattr(self, "_spine_splice_memo", None)
        if memo is None:
            memo = self._spine_splice_memo = {}
        from ndstpu.engine import spine as spine_mod
        hits = 0
        saved = 0
        replacements = {}
        spliced_keys = []
        for site in sites:
            vk = site.value_key
            with cache.holding(vk):
                t = cache.get(vk, state)
                if t is None:
                    obs.inc("engine.spine.miss")
                    cache.misses += 1
                    # materialize the subtree standalone; exceptions
                    # propagate as this query's failure
                    t = self._execute(site.node, pin=pin)
                    cache.put(vk, state, t)
                else:
                    hits += 1
                    cache.hits += 1
                    nbytes = spine_mod.table_bytes(t)
                    saved += nbytes
                    obs.inc("engine.spine.hit")
                    obs.inc("engine.spine.bytes", nbytes)
            replacements[id(site.node)] = lp.InlineTable(
                t, name=f"spine:{vk[:16]}")
            spliced_keys.append(vk)
        if hits:
            obs.annotate(spine_hits=hits, spine_bytes_saved=saved)
        # memo the spliced plan + its canon: same text + same spine
        # tables + same state = same splice (tables are replaced, not
        # mutated, so identity-keying on them is sound).  Host-memory
        # pin until the memo entry rotates out (capped) — accepted.
        mk = (key, tuple(spliced_keys), state,
              tuple(id(r.table) for r in replacements.values()))
        ent = memo.get(mk)
        if ent is not None:
            return ent
        new_plan = spine_mod.replace_nodes(plan, replacements)
        canon2 = self._canonicalize(new_plan, key)
        if canon2 is None:
            # without a canonical key the spliced plan would collide
            # with the unspliced program under the text key — run
            # unspliced instead (correct, just unshared)
            return plan, canon
        if len(memo) >= 256:
            memo.pop(next(iter(memo)))
        memo[mk] = (new_plan, canon2)
        return new_plan, canon2

    def _plan_fresh(self, stmt: "ast.Query",
                    pin: Optional[SnapshotPin] = None):
        cat = self.catalog if pin is None else pin.catalog
        views = self.views if pin is None else pin.views
        planner = pl.Planner(cat, dict(views))
        plan, cols = planner.plan_query(stmt)
        from ndstpu.engine.optimizer import optimize
        plan = optimize(plan, cat)
        # display names: strip alias qualifiers
        disp = self._dedupe(planner._display_names(cols))
        return plan, disp

    def _run_ddl(self, stmt: ast.Node) -> Optional[columnar.Table]:
        if isinstance(stmt, ast.CreateView):
            planner = pl.Planner(self.catalog, dict(self.views))
            plan, cols = planner.plan_query(stmt.query)
            disp = planner._display_names(cols)
            from ndstpu.engine import expr as ex
            self.views[stmt.name] = lp.Project(
                plan, [(d, ex.ColumnRef(c)) for d, c in zip(
                    self._dedupe(disp), cols)])
            self._views_epoch += 1
            return None
        if isinstance(stmt, ast.CreateTableAs):
            t = self._run(stmt.query)
            self.catalog.register(stmt.name, t)
            return None
        if isinstance(stmt, ast.InsertInto):
            return self._insert(stmt)
        if isinstance(stmt, ast.DeleteFrom):
            return self._delete(stmt)
        if isinstance(stmt, ast.DropRel):
            self.views.pop(stmt.name, None)
            self._views_epoch += 1
            if stmt.kind == "table":
                self.catalog.unregister(stmt.name)
            return None
        raise NotImplementedError(f"statement {type(stmt).__name__}")

    @staticmethod
    def _dedupe(names: List[str]) -> List[str]:
        seen: Dict[str, int] = {}
        out = []
        for n in names:
            if n in seen:
                seen[n] += 1
                out.append(f"{n}_{seen[n]}")
            else:
                seen[n] = 0
                out.append(n)
        return out

    def _execute(self, plan: lp.Plan, key: Optional[str] = None,
                 canon=None,
                 pin: Optional[SnapshotPin] = None) -> columnar.Table:
        from ndstpu import faults
        faults.check("execute", key=key)
        if pin is not None and not self._pin_matches_live(pin):
            # the catalog advanced past this pin (ingest committed
            # between admission and execution): run against the pinned
            # snapshot directly on the host engine.  Device-side caches
            # are keyed to live state, so a stale pin trades device
            # speed for snapshot isolation — the robustness-over-perf
            # choice; the common case (pin == live epoch) stays on the
            # normal backend path below.
            return physical.execute(plan, pin.catalog)
        # single-chip out-of-core: when chunk_rows is set, the `tpu`
        # backend streams facts through the SAME chunked executor as
        # tpu-spmd, just over a 1-device mesh (SF >> HBM on one chip;
        # host partial combine).  Unsupported shapes fall through to
        # the whole-fact-resident jaxexec path below.
        if self.backend == "tpu-spmd" or (
                self.backend == "tpu" and self.spmd_chunk_rows is not None):
            from ndstpu.engine import jaxexec
            from ndstpu.parallel import dplan
            versions = tuple(sorted(
                getattr(self.catalog, "versions", {}).items()))
            cache = getattr(self, "_spmd_cache", None)
            if cache is None:
                cache = self._spmd_cache = {}
                self._spmd_dev_cache = {}
            # shape-keyed SPMD cache: a canonical plan with an empty
            # shape residual is keyed on fingerprint + bound-value hash
            # (the values substitute back into literals before tracing,
            # so distinct bindings are distinct compiled programs) and
            # the parameterized exec plan rides with its binding;
            # renderings differing only in text share one entry
            spmd_plan, spmd_params = plan, None
            if canon is not None and not canon.residual:
                import hashlib
                vh = hashlib.sha256(
                    repr(canon.binding.values).encode()).hexdigest()[:16]
                ck = f"{self._views_epoch}|{canon.cache_key}|v{vh}"
                spmd_plan, spmd_params = canon.exec_plan, canon.binding
            else:
                ck = f"{self._views_epoch}|{key}" if key is not None \
                    else None
            ent = cache.get(ck) if ck else None
            if ent is not None and ent[0] != versions:
                # data changed: drop the stale executor (its pinned
                # device args go with it) and rebuild below
                del cache[ck]
                ent = None
            from ndstpu import obs
            obs.inc("engine.cache.spmd.hit" if ent is not None
                    else "engine.cache.spmd.miss")
            if ent is not None:
                try:
                    out = ent[1].execute_again()
                    self._spmd_used = True
                    return out
                except Exception as e:  # noqa: BLE001
                    # degrade a cached re-execution defect the same way
                    # as a first-run one: drop the executor, fall back
                    del cache[ck]
                    self._record_spmd_error(e)
                    ent = None
            try:
                kw = {"dev_cache": self._spmd_dev_cache}
                if self.spmd_threshold is not None:
                    kw["shard_threshold_rows"] = self.spmd_threshold
                if self.spmd_chunk_rows is not None:
                    kw["chunk_rows"] = self.spmd_chunk_rows
                if self.spmd_prefetch_depth is not None:
                    kw["prefetch_depth"] = self.spmd_prefetch_depth
                kw["cost_advisor"] = self._cost_advisor()
                exe = dplan.DistributedPlanExecutor(
                    self.catalog, self._mesh(), **kw)
                out = exe.execute_plan(spmd_plan, params=spmd_params)
                if ck:
                    cache[ck] = (versions, exe)
                self._spmd_used = True
                return out
            except (dplan.DistUnsupported, jaxexec.Unsupported) as u:
                # plan shape or an expression outside the distributed
                # subset: the single-chip path below has per-plan fallback
                obs.inc("engine.spmd.unsupported_fallbacks")
                code = getattr(u, "code", None)
                obs.annotate(spmd_fallback=f"{code or 'uncoded'}: {u}")
                if code:
                    obs.inc(f"engine.spmd.fallback.{code}")
                self._note_chunk_fallthrough(u)
            except Exception as e:  # noqa: BLE001
                # a distributed-executor defect must degrade to the
                # single-chip path, not fail the query; strict mode
                # (tests/CI) re-raises instead, and the first defect
                # warns — see _record_spmd_error
                self._record_spmd_error(e)
        if self.backend in ("tpu", "tpu-spmd"):
            exe = self._jax_executor()
            if key is not None:
                if canon is not None:
                    # shape-keyed compile cache: the key is the plan's
                    # canonical fingerprint (+ shape residual), the plan
                    # is the parameterized exec plan, and this
                    # rendering's literals travel as the binding —
                    # every rendering of a template shares one compile
                    return exe.execute_cached(
                        canon.exec_plan,
                        f"{self._views_epoch}|{canon.cache_key}",
                        params=canon.binding, sql=key)
                return exe.execute_cached(
                    plan, f"{self._views_epoch}|{key}")
            return exe.execute_to_host(plan)
        return physical.execute(plan, self.catalog)

    def _pin_matches_live(self, pin: SnapshotPin) -> bool:
        versions = tuple(sorted(
            getattr(self.catalog, "versions", {}).items()))
        return pin.views_epoch == self._views_epoch \
            and pin.versions == versions

    def _note_chunk_fallthrough(self, u: Exception) -> None:
        """NDS311: out-of-core streaming was configured on a multi-device
        mesh but this plan fell back to the single-chip whole-fact path,
        where `spmd_chunk_rows` is ignored and the fact must fit HBM
        resident.  Silent before this diagnostic — a run configured for
        SF100 streaming could quietly become a whole-fact load.  Warns
        + counts (`engine.spmd.fallback.NDS311`); NDSTPU_SPMD_STRICT
        turns it into an error."""
        import os
        import warnings

        from ndstpu import obs
        if self.spmd_chunk_rows is None or self.backend != "tpu-spmd" \
                or self._mesh().devices.size <= 1:
            return
        code = getattr(u, "code", None)
        msg = (f"NDS311: chunked streaming configured "
               f"(spmd_chunk_rows={self.spmd_chunk_rows!r}) but this "
               f"plan fell back to the single-chip whole-fact path "
               f"({code or 'uncoded'}: {u}); the fact must fit HBM "
               f"resident there")
        obs.inc("engine.spmd.fallback.NDS311")
        obs.annotate(chunk_fallthrough=f"{code or 'uncoded'}")
        if os.environ.get("NDSTPU_SPMD_STRICT"):
            raise ChunkFallthroughError(msg) from u
        warnings.warn(msg, stacklevel=3)

    def _record_spmd_error(self, e: Exception) -> None:
        """A non-DistUnsupported distributed failure is a defect, not a
        capability gap: NDSTPU_SPMD_STRICT re-raises it (tests/CI), and
        the first one warns on stderr so a distributed-correctness
        regression cannot hide as an invisible perf cliff."""
        import os
        import sys
        import warnings

        from ndstpu import obs
        obs.inc("engine.spmd.error_fallbacks")
        if os.environ.get("NDSTPU_SPMD_STRICT"):
            raise e
        errs = getattr(self, "_spmd_errors", None)
        if errs is None:
            errs = self._spmd_errors = []
        if not errs:
            print(f"WARNING: distributed executor failed "
                  f"({type(e).__name__}: {e}); falling back to the "
                  f"single-chip path (further fallbacks collected in "
                  f"Session._spmd_errors)", file=sys.stderr)
        # surfaces in the BenchReport as CompletedWithTaskFailures —
        # the reference's task-failure listener analog (report.py)
        warnings.warn(f"distributed executor fell back to single-chip: "
                      f"{type(e).__name__}: {e}", stacklevel=2)
        errs.append(repr(e))

    def _mesh(self):
        m = getattr(self, "_mesh_cache", None)
        if m is None:
            from ndstpu.parallel import mesh as pmesh
            # tpu = single-chip out-of-core (1-device mesh); tpu-spmd =
            # every visible device
            m = pmesh.make_mesh(1) if self.backend == "tpu" \
                else pmesh.default_mesh()
            self._mesh_cache = m
        return m

    def _cost_advisor(self):
        """Session-cached exchange-placement advisor (analysis/cost.py)
        for the distributed executors; re-checks the NDSTPU_COST kill
        switch per query so tests may flip it around one session, but
        probes the device budget only once."""
        from ndstpu.analysis import cost
        if not cost.enabled():
            return None
        adv = getattr(self, "_cost_advisor_cache", None)
        if adv is None:
            from ndstpu.analysis import lowering as lowreg
            adv = cost.default_advisor(lowreg.SPMD_BROADCAST_LIMIT_ROWS)
            self._cost_advisor_cache = adv
        return adv

    def canonical_key(self, text: str) -> str:
        """Structure-first dedup key for a query text: the canonical
        plan fingerprint + shape residual (analysis/canon.py) when
        canonicalization succeeds, the normalized text otherwise.  Two
        renderings of a template that differ only in runtime-bindable
        literals map to the SAME key — in-flight dedup and compile
        caches keyed on this collapse per-stream permutations."""
        from ndstpu.engine.sql import normalize_sql_key
        norm = normalize_sql_key(text)
        try:
            stmt = parse_statement(text)
            if not isinstance(stmt, ast.Query):
                return norm
            _plan, _disp, canon = self._plan_cached(stmt, norm)
        except Exception:  # noqa: BLE001 — unparseable/unplannable text
            return norm
        return canon.cache_key if canon is not None else norm

    def compiled_plan(self, text: str):
        """The cached whole-query compile record for a SQL text (or None).
        Test/introspection hook — mirrors the key used by `_execute`:
        canonical fingerprint first, normalized text as fallback."""
        from ndstpu.engine.sql import normalize_sql_key
        exe = getattr(self, "_jax_exec_cache", None)
        if exe is None:
            return None
        cp = exe._compiled.get(
            f"{self._views_epoch}|{self.canonical_key(text)}")
        if cp is None:
            cp = exe._compiled.get(
                f"{self._views_epoch}|{normalize_sql_key(text)}")
        return cp

    def compiled_count(self) -> int:
        """Number of whole-query compile records this session holds.
        The serve layer polls this after each request to persist compile
        records incrementally — a SIGKILL'd server must still warm-start
        from everything compiled before the kill, so it cannot wait for
        a clean drain to call :meth:`save_compiled`."""
        exe = getattr(self, "_jax_exec_cache", None)
        return len(exe._compiled) if exe is not None else 0

    def save_compiled(self, path: str) -> int:
        """Persist whole-query size-plan records for the jax backend."""
        return self._jax_executor().save_compile_records(path)

    def preload_compiled(self, path: str) -> int:
        """Preload size-plan records: later sql() calls skip discovery
        and go straight to the jitted replay (warm XLA cache makes the
        first execution ~compile-free too).  Records re-canonicalize on
        load so they register under the same canonical key a fresh
        rendering will probe — a discover-process and a preload-process
        agree on cache identity by construction."""
        def plan_for_sql(sql):
            from ndstpu.engine.sql import normalize_sql_key
            try:
                stmt = parse_statement(sql)
                if not isinstance(stmt, ast.Query):
                    return None
                plan, _disp, canon = self._plan_cached(
                    stmt, normalize_sql_key(sql))
            except Exception:  # noqa: BLE001
                return None
            if canon is not None:
                return canon.exec_plan, canon.cache_key
            return plan

        import os
        if not os.path.exists(path):
            return 0
        return self._jax_executor().load_compile_records(
            path, plan_for_sql, key_prefix=str(self._views_epoch))

    def _jax_executor(self):
        """One executor per session: keeps uploaded tables cached in HBM
        and whole-query compiled programs cached by SQL text (analog of
        Spark's cached TempViews + codegen cache).  Per-table invalidation
        happens inside the executor via catalog versions."""
        from ndstpu.engine import jaxexec
        with getattr(self, "_cache_lock", _NULL_CM):
            exe = getattr(self, "_jax_exec_cache", None)
            if exe is None or exe.catalog is not self.catalog:
                exe = jaxexec.CompilingExecutor(self.catalog)
                self._jax_exec_cache = exe
            return exe

    # -- DML against the warehouse (ACID ndslake tables) ---------------------

    def _insert(self, stmt: ast.InsertInto):
        from ndstpu.engine import expr as ex
        rows = self._run(stmt.query)
        target = self.catalog.get(stmt.table)
        if len(rows.column_names) != len(target.column_names):
            raise ValueError(
                f"INSERT INTO {stmt.table}: {len(rows.column_names)} values "
                f"for {len(target.column_names)} columns")
        # positional mapping + cast to the target's exact column types
        rows = columnar.Table({
            name: ex.cast_column(col, target.column(name).ctype)
            for name, col in zip(target.column_names,
                                 rows.columns.values())})
        if self.warehouse is not None:
            import os

            from ndstpu.io import lake
            root = os.path.join(self.warehouse, stmt.table)
            if lake.is_lake(root):
                lake.append(root, columnar.to_arrow(rows))
        merged = columnar.Table.concat([target, rows])
        self.catalog.register(stmt.table, merged)
        return None

    def _delete(self, stmt: ast.DeleteFrom):
        import numpy as np

        from ndstpu.engine import expr as ex
        target = self.catalog.get(stmt.table)
        if stmt.where is None:
            mask = np.ones(target.num_rows, dtype=bool)
        else:
            planner = pl.Planner(self.catalog, dict(self.views))
            scope = pl.Scope()
            scope.add(pl.Source(stmt.table, target.column_names))
            bound = planner._bind(stmt.where, scope)
            bound = physical.Executor(self.catalog)._resolve_subqueries(bound)
            # bound refs are internal "table.col" names; rename view
            renamed = columnar.Table({f"{stmt.table}.{n}": c
                                      for n, c in target.columns.items()})
            mask = ex.eval_predicate(renamed, bound)
        if self.warehouse is not None:
            import os

            from ndstpu.io import lake
            root = os.path.join(self.warehouse, stmt.table)
            if lake.is_lake(root):
                # re-evaluate the WHERE per data file — never assume the
                # in-memory row order matches file iteration order
                if stmt.where is None:
                    lake.delete_rows(
                        root, lambda at: np.ones(at.num_rows, dtype=bool))
                else:
                    from ndstpu import schema as nds_schema
                    try:
                        sch = nds_schema.get_schema(stmt.table)
                    except KeyError:
                        sch = None

                    def pred(at):
                        t = columnar.from_arrow(at, sch)
                        rn = columnar.Table(
                            {f"{stmt.table}.{n}": c
                             for n, c in t.columns.items()})
                        return ex.eval_predicate(rn, bound)
                    lake.delete_rows(root, pred)
        self.catalog.register(stmt.table, target.filter(~mask))
        return None
