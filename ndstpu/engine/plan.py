"""Logical plan nodes.

Produced by the planner from SQL ASTs, rewritten by the optimizer, executed
by ndstpu.engine.physical (numpy interpreter) or compiled by
ndstpu.engine.kernels (jax/TPU path).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ndstpu.engine.expr import Expr


class Plan:
    def children(self) -> Sequence["Plan"]:
        return ()

    def walk(self):
        yield self
        for c in self.children():
            yield from c.walk()


@dataclasses.dataclass
class Scan(Plan):
    table: str
    alias: str
    # column projection filled by the optimizer (None = all)
    columns: Optional[List[str]] = None
    # pushed-down predicate (in terms of output names)
    predicate: Optional[Expr] = None

    def __repr__(self):
        return f"Scan({self.table} as {self.alias})"


@dataclasses.dataclass
class InlineTable(Plan):
    """Literal rows (VALUES) or a pre-materialized engine table."""
    table: object  # columnar.Table
    name: str = "values"


@dataclasses.dataclass
class Filter(Plan):
    child: Plan
    condition: Expr

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Project(Plan):
    child: Plan
    exprs: List[Tuple[str, Expr]]  # (output name, expr)

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Join(Plan):
    left: Plan
    right: Plan
    kind: str  # inner, left, right, full, semi, anti, cross
    # equi-join key pairs (left expr, right expr); non-equi residual in extra
    keys: List[Tuple[Expr, Expr]]
    extra: Optional[Expr] = None

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass
class Aggregate(Plan):
    child: Plan
    group_by: List[Tuple[str, Expr]]  # output name, key expr
    aggs: List[Tuple[str, Expr]]      # output name, AggExpr (or expr of aggs)
    # None = plain group-by; otherwise list of index-subsets of group_by
    # (grouping sets / rollup). Each set produces rows with the excluded
    # keys NULL, Spark ROLLUP semantics.
    grouping_sets: Optional[List[List[int]]] = None

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Window(Plan):
    child: Plan
    exprs: List[Tuple[str, Expr]]  # output name, WindowExpr

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Sort(Plan):
    child: Plan
    keys: List[Tuple[Expr, bool]]  # (expr, ascending)

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Limit(Plan):
    child: Plan
    n: int

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Distinct(Plan):
    child: Plan

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class SetOp(Plan):
    kind: str  # union, intersect, except
    left: Plan
    right: Plan
    all: bool = False

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass
class SubqueryAlias(Plan):
    """Named derived table / CTE reference."""
    child: Plan
    alias: str
    column_aliases: Optional[List[str]] = None

    def children(self):
        return (self.child,)


def plan_string(p: Plan, indent: int = 0) -> str:
    pad = "  " * indent
    label = type(p).__name__
    detail = ""
    if isinstance(p, Scan):
        detail = f" {p.table} as {p.alias}" + (
            f" pred={p.predicate}" if p.predicate is not None else "")
    elif isinstance(p, Filter):
        detail = f" {p.condition}"
    elif isinstance(p, Join):
        detail = f" {p.kind} on {p.keys}" + (
            f" extra={p.extra}" if p.extra is not None else "")
    elif isinstance(p, Aggregate):
        detail = f" by {[n for n, _ in p.group_by]}"
        if p.grouping_sets is not None:
            detail += f" sets={p.grouping_sets}"
    elif isinstance(p, Project):
        detail = f" {[n for n, _ in p.exprs]}"
    elif isinstance(p, Sort):
        detail = f" {[(str(e), a) for e, a in p.keys]}"
    elif isinstance(p, Limit):
        detail = f" {p.n}"
    elif isinstance(p, SetOp):
        detail = f" {p.kind}{' all' if p.all else ''}"
    elif isinstance(p, SubqueryAlias):
        detail = f" {p.alias}"
    lines = [f"{pad}{label}{detail}"]
    for c in p.children():
        lines.append(plan_string(c, indent + 1))
    return "\n".join(lines)
