"""Logical plan nodes.

Produced by the planner from SQL ASTs, rewritten by the optimizer, executed
by ndstpu.engine.physical (numpy interpreter) or compiled by
ndstpu.engine.kernels (jax/TPU path).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ndstpu.engine.expr import Expr


class Plan:
    def children(self) -> Sequence["Plan"]:
        return ()

    def walk(self):
        yield self
        for c in self.children():
            yield from c.walk()


@dataclasses.dataclass
class Scan(Plan):
    table: str
    alias: str
    # column projection filled by the optimizer (None = all)
    columns: Optional[List[str]] = None
    # pushed-down predicate (in terms of output names)
    predicate: Optional[Expr] = None

    def __repr__(self):
        return f"Scan({self.table} as {self.alias})"


@dataclasses.dataclass
class InlineTable(Plan):
    """Literal rows (VALUES) or a pre-materialized engine table."""
    table: object  # columnar.Table
    name: str = "values"


@dataclasses.dataclass
class Filter(Plan):
    child: Plan
    condition: Expr

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Project(Plan):
    child: Plan
    exprs: List[Tuple[str, Expr]]  # (output name, expr)

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Join(Plan):
    left: Plan
    right: Plan
    kind: str  # inner, left, right, full, semi, anti, cross, mark
    # equi-join key pairs (left expr, right expr); non-equi residual in extra
    keys: List[Tuple[Expr, Expr]]
    extra: Optional[Expr] = None
    # "mark" joins: output = left columns + a boolean column named `mark`
    # that is True where the row has a match (EXISTS under OR/CASE)
    mark: Optional[str] = None

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass
class Aggregate(Plan):
    child: Plan
    group_by: List[Tuple[str, Expr]]  # output name, key expr
    aggs: List[Tuple[str, Expr]]      # output name, AggExpr (or expr of aggs)
    # None = plain group-by; otherwise list of index-subsets of group_by
    # (grouping sets / rollup). Each set produces rows with the excluded
    # keys NULL, Spark ROLLUP semantics.
    grouping_sets: Optional[List[List[int]]] = None

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Window(Plan):
    child: Plan
    exprs: List[Tuple[str, Expr]]  # output name, WindowExpr

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Sort(Plan):
    child: Plan
    keys: List[Tuple[Expr, bool]]  # (expr, ascending)

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Limit(Plan):
    child: Plan
    n: int

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Distinct(Plan):
    child: Plan

    def children(self):
        return (self.child,)


@dataclasses.dataclass
class SetOp(Plan):
    kind: str  # union, intersect, except
    left: Plan
    right: Plan
    all: bool = False

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass
class DeviceResult(Plan):
    """Leaf standing in for a separately-compiled plan segment whose
    result is already resident on the device (jaxexec segmented
    compilation: one whole-query program per SQL text wedges the TPU
    compiler past ~5k ops, so big aggregate subtrees compile as their
    own programs and feed the parent as arguments)."""
    key: str  # segment fingerprint (jaxexec._plan_fp of the subtree)


@dataclasses.dataclass
class SubqueryAlias(Plan):
    """Named derived table / CTE reference."""
    child: Plan
    alias: str
    column_aliases: Optional[List[str]] = None

    def children(self):
        return (self.child,)


def copy_plan(p: Plan) -> Plan:
    """Deep-copy the plan tree (expressions are immutable and shared).

    Required wherever one stored plan (view/CTE) is instantiated more than
    once: the optimizer mutates nodes in place (Scan.predicate/columns,
    Project.exprs, Aggregate lists), so each reference needs its own nodes."""
    if isinstance(p, Scan):
        return Scan(p.table, p.alias,
                    None if p.columns is None else list(p.columns),
                    p.predicate)
    if isinstance(p, InlineTable):
        return InlineTable(p.table, p.name)
    if isinstance(p, Filter):
        return Filter(copy_plan(p.child), p.condition)
    if isinstance(p, Project):
        return Project(copy_plan(p.child), list(p.exprs))
    if isinstance(p, Join):
        return Join(copy_plan(p.left), copy_plan(p.right), p.kind,
                    list(p.keys), p.extra, p.mark)
    if isinstance(p, Aggregate):
        return Aggregate(copy_plan(p.child), list(p.group_by), list(p.aggs),
                         None if p.grouping_sets is None
                         else [list(s) for s in p.grouping_sets])
    if isinstance(p, Window):
        return Window(copy_plan(p.child), list(p.exprs))
    if isinstance(p, Sort):
        return Sort(copy_plan(p.child), list(p.keys))
    if isinstance(p, Limit):
        return Limit(copy_plan(p.child), p.n)
    if isinstance(p, Distinct):
        return Distinct(copy_plan(p.child))
    if isinstance(p, SetOp):
        return SetOp(p.kind, copy_plan(p.left), copy_plan(p.right), p.all)
    if isinstance(p, SubqueryAlias):
        return SubqueryAlias(copy_plan(p.child), p.alias,
                             None if p.column_aliases is None
                             else list(p.column_aliases))
    raise TypeError(f"copy_plan: {type(p).__name__}")


def plan_string(p: Plan, indent: int = 0) -> str:
    pad = "  " * indent
    label = type(p).__name__
    detail = ""
    if isinstance(p, Scan):
        detail = f" {p.table} as {p.alias}" + (
            f" pred={p.predicate}" if p.predicate is not None else "")
    elif isinstance(p, Filter):
        detail = f" {p.condition}"
    elif isinstance(p, Join):
        detail = f" {p.kind} on {p.keys}" + (
            f" extra={p.extra}" if p.extra is not None else "")
    elif isinstance(p, Aggregate):
        detail = f" by {[n for n, _ in p.group_by]}"
        if p.grouping_sets is not None:
            detail += f" sets={p.grouping_sets}"
    elif isinstance(p, Project):
        detail = f" {[n for n, _ in p.exprs]}"
    elif isinstance(p, Sort):
        detail = f" {[(str(k[0]), k[1]) for k in p.keys]}"
    elif isinstance(p, Limit):
        detail = f" {p.n}"
    elif isinstance(p, SetOp):
        detail = f" {p.kind}{' all' if p.all else ''}"
    elif isinstance(p, SubqueryAlias):
        detail = f" {p.alias}"
    lines = [f"{pad}{label}{detail}"]
    for c in p.children():
        lines.append(plan_string(c, indent + 1))
    return "\n".join(lines)
