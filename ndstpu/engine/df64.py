"""Compensated ("double-single") float accumulation for TPU.

TPU hardware has no f64 ALU: under the x64 rewrite, ``jnp.float64``
arithmetic lands at f32 precision (verified on chip — docs/STATUS.md).
Money math in this engine is exact scaled-int64 and unaffected; the
exposure is genuinely-float aggregation (``--floats`` mode, stddev
moments), where a naive f32 segment-sum accumulates drift that grows
with the row count and can breach the validator's 1e-5 epsilon
(nds/nds_validate.py:48-114 semantics) at large scale factors.

This module accumulates in an unevaluated pair of f32s (hi + lo, ~48-bit
effective mantissa) using error-free transforms:

* Knuth TwoSum — exact error of one f32 addition (no branch, VPU-friendly)
* a pair-add (Dekker add2) used as the combiner of a segmented
  ``lax.associative_scan`` — a log-depth, fully parallel reduction tree
  whose every node re-captures the rounding error, so the final hi+lo
  carries the sum to ~2^-48 relative instead of f32's 2^-24 drift.

The segmented-scan trick: carry = (segment id, hi, lo); the combiner
restarts the accumulator when segment ids differ.  Flag/segment scans
are associative, so XLA is free to tree-schedule them.  Inputs must be
pre-sorted by segment id — the aggregation paths already sort to build
group ids, so this is free at the call sites.

On CPU (tests / numpy mesh) every op here is IEEE f32 too, so behavior
is bit-identical across backends by construction.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

jax.config.update("jax_enable_x64", True)  # keep f64 carriers real on host

import jax.numpy as jnp  # noqa: E402
from jax import lax


def two_sum(a: jnp.ndarray, b: jnp.ndarray
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Error-free f32 addition: s + e == a + b exactly (Knuth)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def ds_add(ah, al, bh, bl) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Add two double-single numbers, renormalized."""
    s, e = two_sum(ah, bh)
    e = e + (al + bl)
    hi = s + e
    lo = e - (hi - s)
    return hi, lo


def ds_from_f64(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Split a float64 array into a (hi, lo) f32 pair.

    On the host (real f64) this is an exact split; on TPU the value is
    already f32-precision so lo comes out ~0 — harmless either way."""
    hi = x.astype(jnp.float32)
    lo = (x - hi.astype(x.dtype)).astype(jnp.float32)
    return hi, lo


def ds_to_f64(hi: jnp.ndarray, lo: jnp.ndarray) -> jnp.ndarray:
    """Recombine; exact on host f64, 2^-24 relative on TPU (the final
    single rounding — the accumulated drift is what the pair removed)."""
    return hi.astype(jnp.float64) + lo.astype(jnp.float64)


def segment_sum_ds_multi(xs, gid_sorted: jnp.ndarray, num_segments: int,
                         levels: Optional[int] = None):
    """Compensated per-segment sums of N value streams over ONE shared
    Hillis-Steele segmented scan.

    Each ``xs[i]`` holds float64 values in sorted-segment order (invalid
    rows must be zeroed); ``gid_sorted`` the matching non-decreasing
    segment ids.  Returns a list of per-segment (hi, lo) f32 pairs;
    combine with :func:`ds_to_f64` (host-side for full effect).

    ``levels`` bounds the longest segment run: after ``levels`` doubling
    steps every position's prefix covers ``2**levels`` rows, so segments
    no longer than that are complete.  Callers with a recorded run-length
    bound (jaxexec discovery) pass it to emit ~15 full-width ops per
    level — ``lax.associative_scan`` at fact capacities emitted a
    program the TPU compiler never returned from (the q39 wedge), and
    scanned ALL log2(n) levels regardless of segment sizes.
    """
    n = int(xs[0].shape[0])
    k = len(xs)
    if n == 0:
        z = jnp.zeros(num_segments, jnp.float32)
        return [(z, z)] * k
    if levels is None:
        levels = max(0, (n - 1).bit_length())
    pairs = [ds_from_f64(x) for x in xs]
    his = [p[0] for p in pairs]
    los = [p[1] for p in pairs]
    g = gid_sorted.astype(jnp.int32)
    shift = 1
    for _ in range(levels):
        if shift >= n:
            break
        # x[i] (+)= x[i - shift] when both sit in the same segment:
        # inclusive segmented prefix-scan, compensated at every add
        same = jnp.zeros(n, bool).at[shift:].set(g[shift:] == g[:-shift])
        for i in range(k):
            sh = jnp.where(same, jnp.roll(his[i], shift), 0.0)
            sl = jnp.where(same, jnp.roll(los[i], shift), 0.0)
            his[i], los[i] = ds_add(sh, sl, his[i], los[i])
        shift *= 2
    # segment totals sit at each segment's last row; scatter-add so the
    # non-last rows (adding 0.0) can never clobber a total the way a
    # duplicate-index scatter-set could
    last = jnp.ones(n, bool).at[:-1].set(g[:-1] != g[1:])
    seg = jnp.clip(g, 0, num_segments - 1)
    zero = jnp.zeros(num_segments, jnp.float32)
    out = []
    for i in range(k):
        out.append((zero.at[seg].add(jnp.where(last, his[i], 0.0)),
                    zero.at[seg].add(jnp.where(last, los[i], 0.0))))
    return out


def segment_sum_ds(x: jnp.ndarray, gid_sorted: jnp.ndarray,
                   num_segments: int, levels: Optional[int] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compensated per-segment sum over rows pre-sorted by segment id
    (single-stream wrapper over :func:`segment_sum_ds_multi`)."""
    return segment_sum_ds_multi([x], gid_sorted, num_segments, levels)[0]


def segment_sum_compensated(x: jnp.ndarray, gid: jnp.ndarray,
                            num_segments: int, order: jnp.ndarray,
                            levels: Optional[int] = None) -> jnp.ndarray:
    """Drop-in for ``jax.ops.segment_sum`` on float64 data with an
    available sort order (``gid[order]`` non-decreasing).  Returns f64
    per-segment sums accumulated at ~2^-48 instead of f32 drift."""
    hi, lo = segment_sum_ds(x[order], gid[order], num_segments, levels)
    return ds_to_f64(hi, lo)


def segment_sum_compensated2(x1: jnp.ndarray, x2: jnp.ndarray,
                             gid: jnp.ndarray, num_segments: int,
                             order: jnp.ndarray,
                             levels: Optional[int] = None):
    """Two compensated segment sums over the SAME segmentation in ONE
    scan (doubled (hi, lo) carry): half the HLO of two independent
    scans for callers needing paired moments (stddev's d and d^2)."""
    gs = gid[order]
    (h1, l1), (h2, l2) = segment_sum_ds_multi(
        [x1[order], x2[order]], gs, num_segments, levels)
    return ds_to_f64(h1, l1), ds_to_f64(h2, l2)
