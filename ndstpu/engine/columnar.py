"""Columnar data model for the NDS-TPU SQL engine.

Design (TPU-first):

* A ``Column`` is a flat numpy (host) or jax (device) array plus an optional
  validity mask.  All engine kernels see only fixed-dtype numeric arrays —
  the forms XLA can tile:

  - int32 / int64           integers and surrogate keys
  - float64                 doubles (``--floats`` mode)
  - decimal(p,s)            scale-shifted int64 (exact money arithmetic)
  - date                    int32 days since 1970-01-01
  - string                  int32 codes into a per-column *sorted* dictionary
  - bool                    bool

* String dictionaries are sorted, so ``<``, ``>``, ORDER BY and range
  predicates operate directly on codes.  Cross-table string equality
  (joins) goes through a host-side code translation of the two small
  dictionaries (`translate_codes`) — unless both sides carry the SAME
  frozen warehouse-wide dictionary (``Column.gdict``, ndstpu/io/gdict.py),
  in which case codes compare directly with no translation at all.
  Columns loaded from a transcoded warehouse encode against the table's
  global dictionary sidecar, so codes are stable across chunks, shards
  and snapshots.

* NULL is carried as a validity mask (True = present).  String NULLs are
  additionally code ``-1``.

Replaces the reference's reliance on Spark's InternalRow/ColumnarBatch; the
schema layer above is ndstpu.schema (cf. reference nds/nds_schema.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ndstpu.schema import (  # noqa: F401  (re-exported engine type aliases)
    BOOL,
    DATE,
    FLOAT64,
    INT32,
    INT64,
    STRING,
    DType,
    TableSchema,
    decimal,
)


_NUMPY_DTYPES = {
    "int32": np.int32,
    "int64": np.int64,
    "float64": np.float64,
    "decimal": np.int64,
    "date": np.int32,
    "string": np.int32,  # dictionary codes
    "bool": np.bool_,
}


_DATE_RE = None


def parse_date_days(s: str) -> int:
    """Days since 1970-01-01 for a date string; tolerates non-padded
    month/day ('2002-4-01', Spark-compatible) unlike raw np.datetime64."""
    global _DATE_RE
    if _DATE_RE is None:
        import re
        _DATE_RE = re.compile(r"^(\d{4})-(\d{1,2})-(\d{1,2})$")
    s = s.strip()
    m = _DATE_RE.match(s)
    if m:
        y, mo, d = m.groups()
        s = f"{y}-{int(mo):02d}-{int(d):02d}"
    return int((np.datetime64(s, "D") -
                np.datetime64("1970-01-01")).astype(int))


def numpy_dtype(ctype: DType):
    return _NUMPY_DTYPES[ctype.kind]


@dataclasses.dataclass
class Column:
    """One column: data array (+ validity mask, + dictionary for strings)."""

    data: np.ndarray
    ctype: DType
    valid: Optional[np.ndarray] = None  # bool mask, None == all valid
    dictionary: Optional[np.ndarray] = None  # object array, sorted, for string
    # frozen warehouse-wide dictionary this column's codes live in
    # (io.gdict.GlobalDict); None for ad-hoc per-call dictionaries
    gdict: Optional[object] = None

    def __post_init__(self):
        if self.ctype.kind == "string" and self.dictionary is None:
            self.dictionary = np.empty(0, dtype=object)

    def __len__(self) -> int:
        return len(self.data)

    @property
    def has_nulls(self) -> bool:
        return self.valid is not None and not bool(self.valid.all())

    def validity(self) -> np.ndarray:
        """Materialized validity mask."""
        if self.valid is None:
            return np.ones(len(self.data), dtype=bool)
        return self.valid

    # -- construction helpers ------------------------------------------------

    @staticmethod
    def from_numpy(data: np.ndarray, ctype: DType,
                   valid: Optional[np.ndarray] = None,
                   dictionary: Optional[np.ndarray] = None) -> "Column":
        return Column(np.asarray(data, dtype=numpy_dtype(ctype)), ctype,
                      valid, dictionary)

    @staticmethod
    def from_strings(values: Sequence[Optional[str]]) -> "Column":
        """Dictionary-encode python strings (sorted dictionary)."""
        arr = np.asarray(values, dtype=object)
        valid = np.array([v is not None for v in arr], dtype=bool)
        present = arr[valid]
        uniq = np.unique(present.astype(str)) if len(present) else \
            np.empty(0, dtype=object)
        codes = np.full(len(arr), -1, dtype=np.int32)
        if len(present):
            codes[valid] = np.searchsorted(uniq, present.astype(str)).astype(
                np.int32)
        return Column(codes, STRING, None if valid.all() else valid,
                      uniq.astype(object))

    # -- value materialization ----------------------------------------------

    def to_pylist(self) -> List:
        """Decode to python values (None for nulls) — used by validation,
        output writing and the result materialization that power-run
        timing wraps (the `collect()` analog), so it is numpy-vectorized:
        the old per-element loop cost 1-2 s on a 100k-row result."""
        v = self.validity()
        k = self.ctype.kind
        data = self.data
        if k == "string":
            d = self.dictionary
            good = v & (data >= 0)
            if d is None or not len(d):
                obj = np.full(len(data), None, dtype=object)
            else:
                # dictionary entries are python str by construction
                obj = d[np.clip(data, 0, len(d) - 1)].astype(object)
        elif k == "decimal":
            scale = 10 ** self.ctype.scale
            obj = (data.astype(np.float64) / scale).astype(object)
            # f64 can't hold >=2^53 unscaled values exactly; match the
            # exact int/int division semantics for those rare rows
            big = np.abs(data) >= (1 << 53)
            if big.any():
                for i in np.nonzero(big)[0]:
                    obj[i] = int(data[i]) / scale
            good = v
        elif k == "date":
            days = data.astype("timedelta64[D]") + \
                np.datetime64("1970-01-01")
            obj = days.astype("datetime64[D]").astype(str).astype(object)
            good = v
        elif k == "bool":
            obj = data.astype(bool).astype(object)
            good = v
        elif k in ("int32", "int64"):
            obj = data.astype(np.int64).astype(object)
            good = v
        else:
            obj = data.astype(np.float64).astype(object)
            good = v
        if not good.all():
            obj = obj.copy() if obj.base is not None else obj
            obj[~good] = None
        return obj.tolist()

    def gather(self, indices: np.ndarray,
               extra_valid: Optional[np.ndarray] = None) -> "Column":
        """Take rows by index; `extra_valid` marks gathered rows that are
        actually invalid (e.g. failed joins)."""
        data = self.data[indices]
        valid = self.valid[indices] if self.valid is not None else None
        if extra_valid is not None:
            valid = extra_valid if valid is None else (valid & extra_valid)
        return Column(data, self.ctype, valid, self.dictionary, self.gdict)

    def filter(self, mask: np.ndarray) -> "Column":
        valid = self.valid[mask] if self.valid is not None else None
        return Column(self.data[mask], self.ctype, valid, self.dictionary,
                      self.gdict)


def translate_codes(src: Column, dst_dictionary: np.ndarray) -> np.ndarray:
    """Map `src` string codes into another sorted dictionary's code space.
    Codes with no match become -2 (never equal to any valid code)."""
    if len(src.dictionary) == 0:
        return np.full(len(src.data), -2, dtype=np.int32)
    pos = np.searchsorted(dst_dictionary, src.dictionary)
    pos_clipped = np.clip(pos, 0, max(len(dst_dictionary) - 1, 0))
    hit = (
        dst_dictionary[pos_clipped] == src.dictionary
    ) if len(dst_dictionary) else np.zeros(len(src.dictionary), dtype=bool)
    mapping = np.where(hit, pos_clipped, -2).astype(np.int32)
    out = np.full(len(src.data), -2, dtype=np.int32)
    ok = src.data >= 0
    out[ok] = mapping[src.data[ok]]
    return out


def merge_dictionaries(cols: Sequence[Column]) -> np.ndarray:
    """Union of several sorted dictionaries (for UNION/concat of tables)."""
    parts = [c.dictionary for c in cols if c.dictionary is not None
             and len(c.dictionary)]
    if not parts:
        return np.empty(0, dtype=object)
    return np.unique(np.concatenate([p.astype(str) for p in parts])).astype(
        object)


@dataclasses.dataclass
class Table:
    """Ordered set of equal-length named columns."""

    columns: Dict[str, Column]

    def __post_init__(self):
        lens = {len(c) for c in self.columns.values()}
        if len(lens) > 1:
            raise ValueError(f"ragged table: column lengths {lens}")

    @property
    def num_rows(self) -> int:
        for c in self.columns.values():
            return len(c)
        return 0

    @property
    def column_names(self) -> List[str]:
        return list(self.columns)

    def column(self, name: str) -> Column:
        return self.columns[name]

    def select(self, names: Sequence[str]) -> "Table":
        return Table({n: self.columns[n] for n in names})

    def rename(self, mapping: Dict[str, str]) -> "Table":
        return Table({mapping.get(n, n): c for n, c in self.columns.items()})

    def filter(self, mask: np.ndarray) -> "Table":
        return Table({n: c.filter(mask) for n, c in self.columns.items()})

    def gather(self, indices: np.ndarray,
               extra_valid: Optional[np.ndarray] = None) -> "Table":
        return Table({n: c.gather(indices, extra_valid)
                      for n, c in self.columns.items()})

    def head(self, n: int) -> "Table":
        return Table({name: Column(c.data[:n], c.ctype,
                                   None if c.valid is None else c.valid[:n],
                                   c.dictionary, c.gdict)
                      for name, c in self.columns.items()})

    def to_pydict(self) -> Dict[str, List]:
        return {n: c.to_pylist() for n, c in self.columns.items()}

    def to_rows(self) -> List[tuple]:
        cols = [c.to_pylist() for c in self.columns.values()]
        return list(zip(*cols)) if cols else []

    @staticmethod
    def concat(tables: Sequence["Table"]) -> "Table":
        """Vertical concat; re-encodes string columns into a merged
        dictionary."""
        if not tables:
            raise ValueError("concat of zero tables")
        names = tables[0].column_names
        out: Dict[str, Column] = {}
        for n in names:
            cols = [t.column(n) for t in tables]
            ct = cols[0].ctype
            if ct.kind == "string" and len(cols) > 1 and all(
                    len(c.dictionary) == len(cols[0].dictionary)
                    and np.array_equal(c.dictionary, cols[0].dictionary)
                    for c in cols[1:]):
                # shared code space (same frozen global dictionary, or
                # simply identical dictionaries): concat codes directly
                valid = np.concatenate([c.validity() for c in cols])
                out[n] = Column(np.concatenate([c.data for c in cols]), ct,
                                None if valid.all() else valid,
                                cols[0].dictionary,
                                cols[0].gdict if all(
                                    c.gdict is cols[0].gdict
                                    for c in cols) else None)
            elif ct.kind == "string":
                merged = merge_dictionaries(cols)
                datas, valids = [], []
                for c in cols:
                    codes = translate_codes(c, merged)
                    codes[codes == -2] = -1
                    datas.append(codes)
                    valids.append(c.validity())
                data = np.concatenate(datas)
                valid = np.concatenate(valids)
                out[n] = Column(data, ct, None if valid.all() else valid,
                                merged)
            else:
                data = np.concatenate([c.data for c in cols])
                valid = np.concatenate([c.validity() for c in cols])
                out[n] = Column(data, ct,
                                None if valid.all() else valid)
        return Table(out)


# ---------------------------------------------------------------------------
# Arrow interop (loader / writer boundary)
# ---------------------------------------------------------------------------


def _coerce_to_spec(arr, spec_dtype: DType):
    """Cast an arrow array toward the declared schema type, so warehouses in
    lossy formats (csv/json) still load with exact engine types."""
    import pyarrow as pa

    typ = arr.type
    k = spec_dtype.kind
    try:
        if k == "decimal" and not pa.types.is_decimal(typ):
            return arr.cast(pa.decimal128(
                max(spec_dtype.precision, spec_dtype.scale + 1),
                spec_dtype.scale))
        if k == "date" and not pa.types.is_date(typ):
            if pa.types.is_timestamp(typ):
                return arr.cast(pa.date32())
            if pa.types.is_string(typ) or pa.types.is_large_string(typ):
                return arr.cast(pa.timestamp("ms")).cast(pa.date32())
            if pa.types.is_integer(typ) or pa.types.is_floating(typ):
                # numeric dates from lossy formats: epoch-ms vs epoch-days by
                # magnitude (days fit well under 1e7; ms are > 1e10)
                import pyarrow.compute as pc
                vals = arr.cast(pa.int64())
                if len(vals) and pc.max(pc.abs(vals)).as_py() > 10**7:
                    vals = pc.divide(vals, 86_400_000)
                return vals.cast(pa.int32()).cast(pa.date32())
        if k == "float64" and not pa.types.is_floating(typ):
            return arr.cast(pa.float64())
        if k in ("int32", "int64") and typ != (
                pa.int64() if k == "int64" else pa.int32()):
            return arr.cast(pa.int64() if k == "int64" else pa.int32())
    except pa.ArrowInvalid as exc:
        import warnings
        warnings.warn(f"schema coercion to {spec_dtype} failed: {exc}; "
                      "keeping source type", RuntimeWarning)
        return arr
    return arr


def _encode_strings_arrow(arr, global_dict=None) -> Column:
    """Dictionary-encode an arrow string array with a *sorted* dictionary,
    all in arrow/numpy (no per-row python).

    With ``global_dict`` (an io.gdict.GlobalDict), codes are emitted
    against the frozen warehouse-wide dictionary instead of the values
    this call happens to see, so every chunk/shard/snapshot of the table
    shares one code space.  A value absent from the global dictionary
    (stale sidecar) falls back to a local per-call dictionary — callers
    that REQUIRE the shared code space (chunk sources) check
    ``Column.gdict`` after the fact.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    denc = pc.dictionary_encode(arr)
    if isinstance(denc, pa.ChunkedArray):
        denc = denc.combine_chunks()
    dict_vals = np.asarray(denc.dictionary.to_pylist(), dtype=object)
    codes = np.asarray(denc.indices.to_numpy(zero_copy_only=False))
    null_mask = np.asarray(arr.is_null())
    valid = ~null_mask if null_mask.any() else None
    if len(dict_vals) == 0:
        gdv = None if global_dict is None else global_dict.values
        return Column(np.full(len(codes), -1, np.int32), STRING, valid,
                      np.empty(0, dtype=object) if gdv is None else gdv,
                      global_dict)
    order = np.argsort(dict_vals.astype(str), kind="stable")
    sorted_dict = dict_vals[order]
    remap = np.empty(len(order), dtype=np.int32)
    remap[order] = np.arange(len(order), dtype=np.int32)
    if global_dict is not None:
        # remap local sorted positions into the frozen global code space
        gvals = global_dict.values.astype(str)
        pos = np.searchsorted(gvals, sorted_dict.astype(str))
        posc = np.clip(pos, 0, max(len(gvals) - 1, 0))
        hit = (gvals[posc] == sorted_dict.astype(str)) if len(gvals) else \
            np.zeros(len(sorted_dict), dtype=bool)
        if bool(hit.all()):
            remap = posc.astype(np.int32)[remap]
            sorted_dict = global_dict.values
        else:
            from ndstpu import obs
            obs.inc("engine.dict.misses", int((~hit).sum()))
            global_dict = None  # value outside the sidecar: local encode
    out = np.full(len(codes), -1, dtype=np.int32)
    ok = ~np.isnan(codes) if codes.dtype.kind == "f" else np.ones(
        len(codes), dtype=bool)
    if valid is not None:
        ok &= valid
    out[ok] = remap[codes[ok].astype(np.int64)]
    return Column(out, STRING, valid, sorted_dict, global_dict)


def from_arrow(at, schema: Optional[TableSchema] = None,
               gdicts: Optional[Dict[str, object]] = None) -> Table:
    """pyarrow.Table -> engine Table.

    Numeric/date columns map directly; decimals become scaled int64 using the
    schema's (p,s) (or the arrow type's scale); strings are dictionary-encoded
    with a sorted dictionary.  When a TableSchema is given, arrow columns are
    first coerced toward the declared types (csv/json round-trips).  When
    ``gdicts`` maps column names to io.gdict.GlobalDict, string columns are
    encoded against those frozen warehouse-wide dictionaries.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    cols: Dict[str, Column] = {}
    for i, name in enumerate(at.column_names):
        arr = at.column(i)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        spec = schema.column(name) if schema is not None else None
        if spec is not None:
            arr = _coerce_to_spec(arr, spec.dtype)
        typ = arr.type
        if pa.types.is_dictionary(typ) and not pa.types.is_string(
                typ.value_type):
            arr = arr.cast(typ.value_type)
            typ = arr.type
        null_mask = np.asarray(arr.is_null())
        valid = ~null_mask if null_mask.any() else None
        if pa.types.is_decimal(typ):
            scale = typ.scale
            ints = pc.multiply(arr.cast(pa.float64()),
                               float(10 ** scale))
            data = np.nan_to_num(
                np.asarray(ints.to_numpy(zero_copy_only=False))).round()
            ctype = decimal(typ.precision, scale)
            cols[name] = Column(data.astype(np.int64), ctype, valid)
        elif pa.types.is_date(typ):
            data = np.nan_to_num(
                arr.cast(pa.int32()).to_numpy(zero_copy_only=False))
            cols[name] = Column(data.astype(np.int32), DATE, valid)
        elif pa.types.is_floating(typ):
            data = np.nan_to_num(arr.to_numpy(zero_copy_only=False))
            cols[name] = Column(data.astype(np.float64), FLOAT64, valid)
        elif pa.types.is_integer(typ):
            want = INT64 if (spec and spec.dtype.kind == "int64") or \
                pa.types.is_int64(typ) else INT32
            data = arr.to_numpy(zero_copy_only=False)
            data = np.where(null_mask, 0, data) if null_mask.any() else data
            cols[name] = Column(
                np.asarray(data, dtype=numpy_dtype(want)), want, valid)
        elif pa.types.is_boolean(typ):
            data = np.asarray(arr.to_numpy(zero_copy_only=False))
            data = np.where(null_mask, False, data) if null_mask.any() else data
            cols[name] = Column(data.astype(np.bool_), BOOL, valid)
        else:  # strings (incl. dictionary<string>)
            if pa.types.is_dictionary(typ):
                arr = arr.cast(typ.value_type)
            cols[name] = _encode_strings_arrow(
                arr, gdicts.get(name) if gdicts else None)
    return Table(cols)


def to_arrow(t: Table):
    """engine Table -> pyarrow.Table (for Parquet output / validation)."""
    import pyarrow as pa

    arrays, names = [], []
    for name, c in t.columns.items():
        v = c.validity()
        k = c.ctype.kind
        if k == "string":
            d = c.dictionary
            vals = [str(d[code]) if v[i] and code >= 0 else None
                    for i, code in enumerate(c.data)]
            arrays.append(pa.array(vals, type=pa.string()))
        elif k == "decimal":
            import decimal as pydec
            q = pydec.Decimal(1).scaleb(-c.ctype.scale)
            vals = [
                (pydec.Decimal(int(x)).scaleb(-c.ctype.scale)).quantize(q)
                if v[i] else None for i, x in enumerate(c.data)]
            arrays.append(pa.array(
                vals, type=pa.decimal128(max(c.ctype.precision, 1),
                                         c.ctype.scale)))
        elif k == "date":
            vals = [int(x) if v[i] else None for i, x in enumerate(c.data)]
            arrays.append(pa.array(vals, type=pa.date32()))
        else:
            vals = [c.data[i].item() if v[i] else None
                    for i in range(len(c.data))]
            pa_type = {"int32": pa.int32(), "int64": pa.int64(),
                       "float64": pa.float64(), "bool": pa.bool_()}[k]
            arrays.append(pa.array(vals, type=pa_type))
        names.append(name)
    return pa.table(arrays, names=names)
