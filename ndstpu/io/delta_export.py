"""Export an `ndslake`/`ndsdelta` table as a STANDARD Delta Lake table.

The framework's two ACID formats are functionally equivalent to
Iceberg/Delta (snapshots, deletes, RESTORE) but private; the reference's
maintenance phase targets catalogs any engine can read
(/root/reference/nds/nds_power.py:107-121,
convert_submit_cpu_delta.template:24-27).  This module closes that gap
with a snapshot export: the table's CURRENT state becomes a minimal but
protocol-correct Delta table — `_delta_log/...0.json` carrying
`protocol` (reader 1 / writer 2), `metaData` (Spark-JSON schemaString
derived from the parquet schema), and one `add` per data file with
size, modificationTime and partitionValues — which delta-rs, Spark
Delta, DuckDB delta, Trino etc. read directly.  Data files are linked
(hard link, falling back to copy), not rewritten.

CLI:
    python -m ndstpu.io.delta_export TABLE_DIR OUT_DIR
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import time
import uuid
from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as pq


def _spark_type(t: pa.DataType) -> object:
    if pa.types.is_boolean(t):
        return "boolean"
    if pa.types.is_int8(t) or pa.types.is_int16(t):
        return "short"
    if pa.types.is_int32(t):
        return "integer"
    if pa.types.is_int64(t):
        return "long"
    if pa.types.is_float32(t):
        return "float"
    if pa.types.is_float64(t):
        return "double"
    if pa.types.is_decimal(t):
        return f"decimal({t.precision},{t.scale})"
    if pa.types.is_date(t):
        return "date"
    if pa.types.is_timestamp(t):
        return "timestamp"
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return "string"
    if pa.types.is_binary(t):
        return "binary"
    if pa.types.is_dictionary(t):
        return _spark_type(t.value_type)
    raise ValueError(f"no Spark type mapping for arrow type {t}")


def schema_string(schema: pa.Schema) -> str:
    """Arrow schema -> Spark StructType JSON (the Delta metaData
    schemaString contract)."""
    fields = [{"name": f.name, "type": _spark_type(f.type),
               "nullable": True, "metadata": {}} for f in schema]
    return json.dumps({"type": "struct", "fields": fields})


def _snapshot_files(table_dir: str) -> List[str]:
    """Absolute paths of the data files making up the CURRENT state."""
    from ndstpu.io import acid, deltalog
    if deltalog.is_ndsdelta(table_dir):
        st = deltalog._replay(table_dir)
        return [os.path.join(table_dir, p) for p in st.files]
    if acid.is_ndslake(table_dir):
        snap = acid.load_snapshot(table_dir)
        return [os.path.join(table_dir, f["path"]) for f in snap.files]
    # plain parquet dir exports too (trivial snapshot)
    parts = sorted(
        os.path.join(table_dir, n) for n in os.listdir(table_dir)
        if n.endswith(".parquet"))
    if not parts:
        raise FileNotFoundError(f"no exportable table at {table_dir}")
    return parts


def _materialized_residual(table_dir: str) -> Optional[pa.Table]:
    """ndslake deletion vectors are merge-on-read: files with pending
    deletes cannot be linked as-is.  Returns the fully-materialized
    table when residual deletes exist, else None (zero-copy path)."""
    from ndstpu.io import acid
    if acid.is_ndslake(table_dir):
        snap = acid.load_snapshot(table_dir)
        if any(f.get("deletes") for f in snap.files):
            return acid.read(table_dir)
    return None


def export(table_dir: str, out_dir: str) -> dict:
    """Write OUT_DIR as a standard Delta table of TABLE_DIR's current
    snapshot; returns a manifest summary."""
    os.makedirs(os.path.join(out_dir, "_delta_log"), exist_ok=True)
    adds = []
    ts_ms = int(time.time() * 1000)
    residual = _materialized_residual(table_dir)
    if residual is not None:
        rel = f"part-00000-{uuid.uuid4().hex}-c000.snappy.parquet"
        pq.write_table(residual, os.path.join(out_dir, rel),
                       compression="snappy")
        files = [os.path.join(out_dir, rel)]
        linked = False
    else:
        files = _snapshot_files(table_dir)
        linked = True
    schema = None
    total_rows = 0
    for src in files:
        if linked:
            rel = f"part-{uuid.uuid4().hex}-c000.snappy.parquet"
            dst = os.path.join(out_dir, rel)
            try:
                os.link(src, dst)
            except OSError:
                shutil.copy2(src, dst)
        else:
            rel = os.path.basename(src)
            dst = src
        md = pq.read_metadata(dst)
        total_rows += md.num_rows
        if schema is None:
            schema = pq.read_schema(dst)
        adds.append({"add": {
            "path": rel,
            "partitionValues": {},
            "size": os.path.getsize(dst),
            "modificationTime": ts_ms,
            "dataChange": True,
        }})
    if schema is None:
        raise FileNotFoundError(f"no data files in {table_dir}")
    actions = [
        {"commitInfo": {"timestamp": ts_ms,
                        "operation": "WRITE",
                        "operationParameters": {"mode": "ErrorIfExists"},
                        "engineInfo": "ndstpu-delta-export"}},
        {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
        {"metaData": {
            "id": str(uuid.uuid4()),
            "format": {"provider": "parquet", "options": {}},
            "schemaString": schema_string(schema),
            "partitionColumns": [],
            "configuration": {},
            "createdTime": ts_ms,
        }},
    ] + adds
    log_path = os.path.join(out_dir, "_delta_log", f"{0:020d}.json")
    tmp = log_path + f".tmp.{uuid.uuid4().hex}"
    with open(tmp, "w") as f:
        f.write("\n".join(json.dumps(a) for a in actions) + "\n")
    os.replace(tmp, log_path)
    return {"files": len(adds), "rows": total_rows, "log": log_path}


def main() -> int:
    ap = argparse.ArgumentParser(
        description="export an ndslake/ndsdelta table as standard Delta")
    ap.add_argument("table_dir")
    ap.add_argument("out_dir")
    args = ap.parse_args()
    info = export(args.table_dir, args.out_dir)
    print(json.dumps(info))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
