"""IO layer: pipe-CSV ingest, Parquet/ORC/JSON transcode with date
partitioning, warehouse loading into engine tables, and the ACID
(`ndslake`, `ndsdelta`) table formats used by data maintenance.
"""
