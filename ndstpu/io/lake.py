"""ACID table-format dispatch: ndslake (Iceberg analog) | ndsdelta
(Delta analog).

The reference registers Iceberg and Delta tables through distinct
catalog/extension paths but drives both through one SQL surface
(nds/nds_power.py:107-121, nds/nds_maintenance.py:43); here both formats
share one function-level API and callers detect the format from the
table directory's metadata marker (`_ndslake/` vs `_delta_log/`).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional

from ndstpu.io import acid, deltalog
from ndstpu.io.commit import CommitConflict  # noqa: F401  (re-export)

FORMATS = ("ndslake", "ndsdelta")


def detect(table_dir: str):
    """The format module owning `table_dir`, or None."""
    if acid.is_ndslake(table_dir):
        return acid
    if deltalog.is_ndsdelta(table_dir):
        return deltalog
    return None


def is_lake(table_dir: str) -> bool:
    return detect(table_dir) is not None


def module_for(fmt: str):
    if fmt == "ndslake":
        return acid
    if fmt == "ndsdelta":
        return deltalog
    raise ValueError(f"unknown ACID format {fmt!r}")


def create_table(fmt: str, table_dir: str, at,
                 partition_col: Optional[str] = None) -> None:
    module_for(fmt).create_table(table_dir, at, partition_col)


def read(table_dir: str, version: Optional[int] = None, columns=None):
    return detect(table_dir).read(table_dir, version, columns=columns)


def append(table_dir: str, at,
           expected_version: Optional[int] = None) -> None:
    detect(table_dir).append(table_dir, at,
                             expected_version=expected_version)


def delete_rows(table_dir: str, predicate,
                expected_version: Optional[int] = None) -> int:
    return detect(table_dir).delete_rows(
        table_dir, predicate, expected_version=expected_version)


def current_version(table_dir: str) -> int:
    return detect(table_dir).current_version(table_dir)


def rollback_to_timestamp(table_dir: str, ts: float) -> int:
    return detect(table_dir).rollback_to_timestamp(table_dir, ts)


def rollback_to_version(table_dir: str, version: int) -> int:
    return detect(table_dir).rollback_to_version(table_dir, version)


def abort_to_version(table_dir: str, version: int) -> int:
    """Crash-recovery retraction (history-REWRITING, unlike
    rollback_to_version) — see the format modules for the safety
    contract.  Used only by the ingest restore path."""
    return detect(table_dir).abort_to_version(table_dir, version)


def gc_orphan_manifests(table_dir: str) -> list:
    return detect(table_dir).gc_orphan_manifests(table_dir)


def gc_orphans(warehouse: str) -> Dict[str, list]:
    """GC unpublished commit leftovers in every ACID table (a crash or
    injected fault between manifest write and pointer publish).  The
    ingest restore/resume path runs this so a retried run's version
    numbering matches a clean run's (harness/ingest.py)."""
    out: Dict[str, list] = {}
    for name in lake_tables(warehouse):
        removed = gc_orphan_manifests(os.path.join(warehouse, name))
        if removed:
            out[name] = removed
    return out


def lake_tables(warehouse: str) -> List[str]:
    """Names of the ACID-format table directories under a warehouse."""
    try:
        names = sorted(os.listdir(warehouse))
    except OSError:
        return []
    return [n for n in names if is_lake(os.path.join(warehouse, n))]


def versions_vector(warehouse: str) -> Dict[str, int]:
    """Per-table CURRENT versions for every ACID table in a warehouse
    — the durable half of a snapshot pin (engine/session.py)."""
    out: Dict[str, int] = {}
    for name in lake_tables(warehouse):
        try:
            out[name] = current_version(os.path.join(warehouse, name))
        except (OSError, ValueError):
            # table mid-create (metadata dir exists, no commit yet)
            continue
    return out


def warehouse_epoch(warehouse: str) -> Optional[str]:
    """Durable data-version identity of a warehouse: a stable hash over
    every ACID table's CURRENT version.  Two processes observing the
    same committed state compute the same epoch, whatever their
    in-memory catalogs look like — this is what ledger rows are stamped
    with (obs/ledger.py extra.snapshot_epoch) and what the ingest
    differential keys its per-epoch result map on
    (scripts/ingest_smoke.py).  None when the warehouse has no ACID
    tables (nothing versioned to pin)."""
    vec = versions_vector(warehouse)
    if not vec:
        return None
    blob = json.dumps(sorted(vec.items()))
    return "e" + hashlib.sha256(blob.encode()).hexdigest()[:12]
