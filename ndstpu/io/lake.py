"""ACID table-format dispatch: ndslake (Iceberg analog) | ndsdelta
(Delta analog).

The reference registers Iceberg and Delta tables through distinct
catalog/extension paths but drives both through one SQL surface
(nds/nds_power.py:107-121, nds/nds_maintenance.py:43); here both formats
share one function-level API and callers detect the format from the
table directory's metadata marker (`_ndslake/` vs `_delta_log/`).
"""

from __future__ import annotations

from typing import Optional

from ndstpu.io import acid, deltalog

FORMATS = ("ndslake", "ndsdelta")


def detect(table_dir: str):
    """The format module owning `table_dir`, or None."""
    if acid.is_ndslake(table_dir):
        return acid
    if deltalog.is_ndsdelta(table_dir):
        return deltalog
    return None


def is_lake(table_dir: str) -> bool:
    return detect(table_dir) is not None


def module_for(fmt: str):
    if fmt == "ndslake":
        return acid
    if fmt == "ndsdelta":
        return deltalog
    raise ValueError(f"unknown ACID format {fmt!r}")


def create_table(fmt: str, table_dir: str, at,
                 partition_col: Optional[str] = None) -> None:
    module_for(fmt).create_table(table_dir, at, partition_col)


def read(table_dir: str, version: Optional[int] = None, columns=None):
    return detect(table_dir).read(table_dir, version, columns=columns)


def append(table_dir: str, at) -> None:
    detect(table_dir).append(table_dir, at)


def delete_rows(table_dir: str, predicate) -> int:
    return detect(table_dir).delete_rows(table_dir, predicate)


def rollback_to_timestamp(table_dir: str, ts: float) -> int:
    return detect(table_dir).rollback_to_timestamp(table_dir, ts)


def rollback_to_version(table_dir: str, version: int) -> int:
    return detect(table_dir).rollback_to_version(table_dir, version)
