"""Warehouse loader: transcode output -> engine Tables (host or device).

Loads per-table warehouse directories (hive-partitioned parquet datasets,
single parquet/orc files, or ndslake ACID tables) into
:class:`ndstpu.engine.columnar.Table`, recording per-table key metadata the
engine exploits:

* dense surrogate keys — every dimension's primary key is `1..N` (or
  offset-dense like date_dim's Julian day sk), so FK->PK joins lower to a
  bounds-checked gather instead of a hash table (TPU-friendly).

This is the analog of the reference's table registration step
(nds_power.py:78-121 setup_tables / register_delta_tables), with Spark
TempViews replaced by an in-process catalog.
"""

from __future__ import annotations

import glob
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads

from ndstpu import schema as nds_schema
from ndstpu.engine import columnar
from ndstpu.io import gdict, lake


@dataclass
class TableMeta:
    name: str
    num_rows: int
    # primary key column with dense values pk_min..pk_min+N-1, if detected
    dense_key: Optional[str] = None
    dense_min: int = 0


@dataclass
class Catalog:
    """Named engine tables + metadata, the engine's table registry."""

    tables: Dict[str, columnar.Table] = field(default_factory=dict)
    meta: Dict[str, TableMeta] = field(default_factory=dict)
    # per-table monotonic version, bumped on every (re)register — the
    # invalidation key for device-resident caches (id() reuse is not sound)
    versions: Dict[str, int] = field(default_factory=dict)
    # out-of-core scan sources (table -> ChunkSource): the distributed
    # chunked executor streams these tables' rows through the scan/decode
    # pool instead of slicing the resident copy (docs/ARCHITECTURE.md
    # "Streaming out-of-core pipeline")
    streams: Dict[str, "ChunkSource"] = field(default_factory=dict)

    def register(self, name: str, table: columnar.Table) -> None:
        self.tables[name] = table
        self.meta[name] = TableMeta(name, table.num_rows)
        self.versions[name] = self.versions.get(name, 0) + 1
        # re-registration replaces the data: a chunk source built over
        # the old rows must not keep serving them
        self.streams.pop(name, None)
        key = _primary_key_column(name, table)
        if key is not None:
            col = table.column(key)
            if col.valid is None and len(col.data):
                data = col.data
                lo = int(data.min())
                hi = int(data.max())
                if hi - lo + 1 == len(data) and _is_permutation(data, lo, hi):
                    self.meta[name].dense_key = key
                    self.meta[name].dense_min = lo

    def unregister(self, name: str) -> None:
        self.tables.pop(name, None)
        self.meta.pop(name, None)
        self.streams.pop(name, None)
        self.versions[name] = self.versions.get(name, 0) + 1

    def get(self, name: str) -> columnar.Table:
        return self.tables[name]

    def __contains__(self, name: str) -> bool:
        return name in self.tables


def _is_permutation(data: np.ndarray, lo: int, hi: int) -> bool:
    seen = np.zeros(hi - lo + 1, dtype=bool)
    seen[data - lo] = True
    return bool(seen.all())


_PK_OVERRIDES = {
    "date_dim": "d_date_sk",
    "time_dim": "t_time_sk",
}


def _primary_key_column(name: str, table: columnar.Table) -> Optional[str]:
    if name in _PK_OVERRIDES:
        return _PK_OVERRIDES[name]
    # convention: first column ending in _sk is the surrogate PK
    first = table.column_names[0] if table.column_names else None
    if first and first.endswith("_sk"):
        return first
    return None


def read_warehouse_table(warehouse: str, table: str,
                         columns: Optional[List[str]] = None) -> pa.Table:
    """Read one table from a transcoded warehouse, any supported layout."""
    root = os.path.join(warehouse, table)
    if lake.is_lake(root):
        return lake.read(root, columns=columns)
    singles = sorted(glob.glob(os.path.join(root, f"{table}*.parquet")))
    if singles:
        import pyarrow.parquet as pq
        parts = [pq.read_table(p, columns=columns) for p in singles]
        return pa.concat_tables(parts) if len(parts) > 1 else parts[0]
    for ext, fmt in (("orc", "orc"), ("avro", "avro"), ("csv", "csv"),
                     ("json", "json")):
        paths = sorted(glob.glob(os.path.join(root, f"{table}*.{ext}")))
        if paths:
            parts = []
            for p in paths:
                if fmt == "orc":
                    import pyarrow.orc as paorc
                    parts.append(paorc.read_table(p))
                elif fmt == "avro":
                    from ndstpu.io import avroio
                    parts.append(avroio.read_table(p))
                elif fmt == "csv":
                    import pyarrow.csv as pacsv
                    parts.append(pacsv.read_csv(
                        p, convert_options=pacsv.ConvertOptions(
                            strings_can_be_null=True)))
                else:
                    import pandas as pd
                    parts.append(
                        pa.Table.from_pandas(pd.read_json(p, lines=True)))
            t = pa.concat_tables(parts) if len(parts) > 1 else parts[0]
            return t.select(columns) if columns else t
    if os.path.isdir(root):
        # hive-partitioned parquet dataset
        dset = pads.dataset(root, format="parquet", partitioning="hive")
        at = dset.to_table(columns=columns)
        return at
    raise FileNotFoundError(f"table {table} not found under {warehouse}")


def _postprocess_partition_dtypes(table: str, at: pa.Table) -> pa.Table:
    """Hive partition keys come back as inferred ints; restore int32 for the
    *_date_sk partition columns so schemas round-trip."""
    part_col = nds_schema.TABLE_PARTITIONING.get(table)
    if part_col and part_col in at.column_names:
        idx = at.column_names.index(part_col)
        col = at.column(idx)
        if not pa.types.is_int32(col.type):
            at = at.set_column(idx, part_col, col.cast(pa.int32()))
    return at


def load_catalog(warehouse: str, tables: Optional[List[str]] = None,
                 use_decimal: bool = True,
                 max_workers: Optional[int] = None) -> Catalog:
    """Load a transcoded warehouse into an engine catalog.

    Per-table scan (pyarrow file reads) and decode (``from_arrow``
    dictionary encoding / decimal scaling) run on a bounded worker
    pool — both release the GIL, so tables load concurrently.
    ``max_workers`` defaults to ``NDSTPU_IO_WORKERS`` or 4; 1 restores
    the serial path.  Registration order stays the caller's table
    order regardless of completion order.
    """
    from ndstpu import obs
    if tables is None:
        tables = [t for t in nds_schema.SOURCE_TABLE_NAMES
                  if os.path.isdir(os.path.join(warehouse, t))]
    schemas = {**nds_schema.get_schemas(use_decimal),
               **nds_schema.get_maintenance_schemas(use_decimal)}

    def load_one(t: str) -> columnar.Table:
        at = read_warehouse_table(warehouse, t)
        at = _postprocess_partition_dtypes(t, at)
        sch = schemas.get(t)
        if sch is not None:
            # restore declared column order (partitioned reads reorder)
            order = [c.name for c in sch.columns
                     if c.name in at.column_names]
            at = at.select(order)
        # encode strings against the table's frozen global dictionary
        # sidecar (if present), so resident codes match what chunk
        # sources and other processes emit for the same warehouse
        gds = gdict.table_dicts(os.path.join(warehouse, t), t)
        return columnar.from_arrow(at, sch, gdicts=gds or None)

    if max_workers is None:
        max_workers = int(os.environ.get("NDSTPU_IO_WORKERS", "4"))
    cat = Catalog()
    with obs.span("load_catalog", cat="io", n_tables=len(tables),
                  workers=max_workers):
        if max_workers <= 1 or len(tables) <= 1:
            for t in tables:
                cat.register(t, load_one(t))
            return cat
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(
                max_workers=min(max_workers, len(tables)),
                thread_name_prefix="ndstpu-io") as pool:
            futs = {t: pool.submit(load_one, t) for t in tables}
            for t in tables:
                t0 = time.monotonic()
                done = futs[t].done()
                table = futs[t].result()
                if not done:
                    obs.inc("io.scan.wait_s", time.monotonic() - t0)
                cat.register(t, table)
    return cat


# ---------------------------------------------------------------------------
# Streaming out-of-core scan: chunk sources + read-ahead decode pool
# ---------------------------------------------------------------------------


class StreamUnsupported(RuntimeError):
    """A table/column shape the streaming scan cannot serve (the caller
    falls back to the resident path, never wedges)."""


def _string_stream_reject(table: str, col: str) -> StreamUnsupported:
    """Why a string column cannot stream, naming the knob that changes
    the answer: streaming strings requires the table's frozen global
    dictionary (ndstpu/io/gdict.py) so every chunk emits codes in one
    shared code space."""
    if not gdict.enabled():
        why = ("global dictionaries are disabled "
               "(NDSTPU_GLOBAL_DICTS=0)")
    else:
        why = (f"the table has no {gdict.GDICT_FILE} sidecar covering "
               f"it — re-transcode the warehouse to build one; "
               f"scripts/dict_audit.py (DICT_AUDIT.md) reports "
               f"per-column coverage")
    return StreamUnsupported(
        f"string column {col} of {table}: per-chunk dictionaries do not "
        f"share a code space, and {why}")


def _check_gdict_decode(t: columnar.Table, table: str) -> columnar.Table:
    """A decoded chunk must carry its strings in the frozen global code
    space; local-dictionary fallback (a value missing from the sidecar)
    would silently emit codes other chunks disagree with."""
    for n, c in t.columns.items():
        if c.ctype.kind == "string" and c.gdict is None:
            raise StreamUnsupported(
                f"string column {n} of {table}: chunk holds values "
                f"outside the frozen global dictionary (stale "
                f"{gdict.GDICT_FILE} sidecar — re-transcode the table "
                f"or check DICT_AUDIT.md coverage; "
                f"NDSTPU_GLOBAL_DICTS=0 disables string streaming "
                f"entirely)")
    return t


#: one decoded chunk: column name -> (data, validity) numpy arrays,
#: exactly ``count`` rows each
ChunkPayload = Dict[str, Tuple[np.ndarray, np.ndarray]]


class ChunkSource:
    """Row-range reads of a table's column subset, decoded to the
    engine's numpy layout.  Implementations must be thread-safe for
    concurrent ``read`` calls (the scan pool issues them from worker
    threads)."""

    num_rows: int = 0
    table: str = ""
    columns: Sequence[str] = ()

    def column_meta(self) -> Dict[str, tuple]:
        """name -> (ctype, numpy dtype, dictionary-or-None), the static
        metadata the traced spine needs without touching row data."""
        raise NotImplementedError

    def read(self, start: int, count: int) -> ChunkPayload:
        raise NotImplementedError


class TableChunkSource(ChunkSource):
    """Scan source over a resident :class:`columnar.Table` — decode is
    a numpy slice.  The default source when no out-of-core stream is
    registered: the same pipeline (scan pool -> staging ring -> device)
    runs over it, so the streaming path has ONE shape regardless of
    where rows physically live."""

    def __init__(self, table: columnar.Table, name: str,
                 columns: Sequence[str]):
        self._t = table
        self.table = name
        self._cols = self.columns = list(columns)
        self.num_rows = table.num_rows

    def column_meta(self) -> Dict[str, tuple]:
        return {n: (self._t.column(n).ctype, self._t.column(n).data.dtype,
                    self._t.column(n).dictionary) for n in self._cols}

    def read(self, start: int, count: int) -> ChunkPayload:
        from ndstpu import faults
        faults.check("io.read", key=f"{self.table}@{start}")
        out: ChunkPayload = {}
        for n in self._cols:
            c = self._t.column(n)
            out[n] = (c.data[start:start + count],
                      c.validity()[start:start + count])
        return out


class ParquetChunkSource(ChunkSource):
    """True out-of-core scan source: row-range reads over a transcoded
    warehouse table's parquet files, row-group-aligned, decoded with
    the same ``from_arrow`` rules the resident loader uses.

    String columns stream when the table carries a global dictionary
    sidecar (ndstpu/io/gdict.py): every chunk decodes its strings
    against the frozen table-wide dictionary, so codes agree with the
    resident load and the traced spine's compile-time dictionary.
    Without a sidecar (or with ``NDSTPU_GLOBAL_DICTS=0``) they are
    rejected (``StreamUnsupported``): per-chunk dictionary encodings
    would not share a code space.  Hive partition-key columns live in
    directory names, not the files, and are likewise rejected.
    """

    def __init__(self, warehouse: str, table: str,
                 columns: Optional[Sequence[str]] = None,
                 use_decimal: bool = True):
        import pyarrow.parquet as pq
        self._pq = pq
        self.table = table
        root = os.path.join(warehouse, table)
        if lake.is_lake(root):
            # ndslake logs carry row-level deletes; raw file enumeration
            # would resurrect them
            raise StreamUnsupported(
                f"table {table} is an ndslake ACID table; streaming scan "
                f"needs a plain parquet layout")
        paths = sorted(glob.glob(os.path.join(root, "**", "*.parquet"),
                                 recursive=True))
        if not paths:
            raise StreamUnsupported(
                f"no parquet files for table {table} under {warehouse}")
        schemas = {**nds_schema.get_schemas(use_decimal),
                   **nds_schema.get_maintenance_schemas(use_decimal)}
        self._schema = schemas.get(table)
        file_cols = set(pq.ParquetFile(paths[0]).schema_arrow.names)
        if columns is None:
            columns = [c for c in file_cols]
        missing = [c for c in columns if c not in file_cols]
        if missing:
            raise StreamUnsupported(
                f"columns {missing} not in {table} parquet files "
                f"(hive partition keys cannot stream)")
        self._cols = self.columns = list(columns)
        self._gdicts = gdict.table_dicts(root, table)
        if self._schema is not None:
            for c in self._cols:
                try:
                    kind = self._schema.column(c).dtype.kind
                except KeyError:
                    continue
                if kind == "string" and c not in self._gdicts:
                    raise _string_stream_reject(table, c)
        # global row index: (path, row_group, global_start, n_rows)
        self._groups: List[tuple] = []
        total = 0
        for p in paths:
            md = pq.ParquetFile(p).metadata
            for g in range(md.num_row_groups):
                n = md.row_group(g).num_rows
                self._groups.append((p, g, total, n))
                total += n
        self.num_rows = total
        self._meta: Optional[Dict[str, tuple]] = None

    def column_meta(self) -> Dict[str, tuple]:
        if self._meta is None:
            t = self._decode(*self._groups[0][:2])
            meta = {}
            for n in self._cols:
                c = t.column(n)
                if c.ctype.kind == "string" and n not in self._gdicts:
                    raise _string_stream_reject(self.table, n)
                meta[n] = (c.ctype, c.data.dtype,
                           self._gdicts[n].values
                           if c.ctype.kind == "string" else None)
            self._meta = meta
        return self._meta

    def _decode(self, path: str, group: int) -> columnar.Table:
        at = self._pq.ParquetFile(path).read_row_group(
            group, columns=self._cols)
        t = columnar.from_arrow(at.select(self._cols), self._schema,
                                gdicts=self._gdicts or None)
        return _check_gdict_decode(t, self.table)

    def read(self, start: int, count: int) -> ChunkPayload:
        from ndstpu import faults, obs
        faults.check("io.read", key=f"{self.table}@{start}")
        end = min(start + count, self.num_rows)
        pieces: List[columnar.Table] = []
        nbytes = 0
        for path, g, g_start, g_n in self._groups:
            if g_start + g_n <= start or g_start >= end:
                continue
            t = self._decode(path, g)
            lo = max(start - g_start, 0)
            hi = min(end - g_start, g_n)
            pieces.append(columnar.Table({
                n: columnar.Column(
                    c.data[lo:hi], c.ctype,
                    None if c.valid is None else c.valid[lo:hi],
                    c.dictionary)
                for n, c in t.columns.items()}))
        out: ChunkPayload = {}
        for n in self._cols:
            cols = [p.column(n) for p in pieces]
            data = np.concatenate([c.data for c in cols]) if cols \
                else np.empty(0, dtype=self.column_meta()[n][1])
            valid = np.concatenate([c.validity() for c in cols]) if cols \
                else np.empty(0, dtype=bool)
            nbytes += data.nbytes + valid.nbytes
            out[n] = (data, valid)
        obs.inc("io.scan.bytes", nbytes)
        return out


class LakeChunkSource(ChunkSource):
    """Snapshot-pinned out-of-core scan over an ACID lake table
    (``ndslake`` or ``ndsdelta``).

    Where :class:`ParquetChunkSource` refuses lake tables outright,
    this source reads a PINNED snapshot version (default: CURRENT at
    construction): the data-file list comes from that version's
    manifest/log replay, and ndslake deletion vectors are applied as
    keep-masks at scan time — so appends and deletes committed *after*
    the pin land in snapshots this source never consults.  This is the
    chunk-source half of snapshot-pinned reads (docs/ARCHITECTURE.md):
    an in-flight streaming query keeps scanning its admission-time
    version while ingest advances the table underneath it.

    File-granular rather than row-group-granular: lake data files are
    micro-batch sized (one per refresh-function commit), so a read
    decodes each overlapping file, masks its deleted rows, and slices
    the requested live-row window.  String columns stream against the
    global-dictionary sidecar version matching the PIN (gdict entries
    are stamped with the lake version that introduced them), so a
    pinned reader decodes with the dictionary its snapshot was
    committed under even while ingest grows the dict; without sidecar
    coverage they are rejected like ParquetChunkSource.
    """

    def __init__(self, table_dir: str, table: Optional[str] = None,
                 columns: Optional[Sequence[str]] = None,
                 version: Optional[int] = None,
                 use_decimal: bool = True):
        import pyarrow.parquet as pq
        self._pq = pq
        self._dir = table_dir
        self.table = table or os.path.basename(
            os.path.normpath(table_dir))
        mod = lake.detect(table_dir)
        if mod is None:
            raise StreamUnsupported(
                f"{table_dir} is not an ACID lake table")
        from ndstpu.io import acid as _acid
        self.version = mod.current_version(table_dir) \
            if version is None else version
        if mod is _acid:
            snap = _acid.load_snapshot(table_dir, self.version)
            file_metas = [(fm["path"], fm.get("deletes"))
                          for fm in snap.files]
        else:
            st = mod._replay(table_dir, self.version)
            # ndsdelta deletes are copy-on-write: no mask needed
            file_metas = [(fm["path"], None)
                          for fm in st.files.values()]
        schemas = {**nds_schema.get_schemas(use_decimal),
                   **nds_schema.get_maintenance_schemas(use_decimal)}
        self._schema = schemas.get(self.table)
        # global live-row index: (abs path, keep-mask-or-None,
        # global_start, live_rows)
        self._files: List[tuple] = []
        total = 0
        first_cols: Optional[List[str]] = None
        for rel, drel in file_metas:
            fp = os.path.join(table_dir, rel)
            n = pq.ParquetFile(fp).metadata.num_rows
            if first_cols is None:
                first_cols = list(pq.ParquetFile(fp).schema_arrow.names)
            keep = None
            live = n
            if drel:
                dels = np.load(os.path.join(table_dir, drel))
                keep = np.ones(n, dtype=bool)
                keep[dels] = False
                live = int(keep.sum())
            if live:
                self._files.append((fp, keep, total, live))
                total += live
        self.num_rows = total
        if columns is None:
            columns = list(first_cols or [])
        missing = [c for c in columns if c not in (first_cols or [])]
        if missing:
            raise StreamUnsupported(
                f"columns {missing} not in {self.table} data files")
        self._cols = self.columns = list(columns)
        self._gdicts = gdict.table_dicts(
            table_dir, self.table, pin_table_version=self.version)
        if self._schema is not None:
            for c in self._cols:
                try:
                    kind = self._schema.column(c).dtype.kind
                except KeyError:
                    continue
                if kind == "string" and c not in self._gdicts:
                    raise _string_stream_reject(self.table, c)
        self._meta: Optional[Dict[str, tuple]] = None

    def column_meta(self) -> Dict[str, tuple]:
        if self._meta is None:
            if not self._files:
                raise StreamUnsupported(
                    f"pinned snapshot v{self.version} of {self.table} "
                    f"has no live rows to derive column metadata from")
            t = self._decode(*self._files[0][:2])
            meta = {}
            for n in self._cols:
                c = t.column(n)
                if c.ctype.kind == "string" and n not in self._gdicts:
                    raise _string_stream_reject(self.table, n)
                meta[n] = (c.ctype, c.data.dtype,
                           self._gdicts[n].values
                           if c.ctype.kind == "string" else None)
            self._meta = meta
        return self._meta

    def _decode(self, path: str,
                keep: Optional[np.ndarray]) -> columnar.Table:
        at = self._pq.read_table(path, columns=self._cols)
        t = columnar.from_arrow(at.select(self._cols), self._schema,
                                gdicts=self._gdicts or None)
        _check_gdict_decode(t, self.table)
        if keep is not None:
            t = t.filter(keep)
        return t

    def read(self, start: int, count: int) -> ChunkPayload:
        from ndstpu import faults, obs
        faults.check("io.read", key=f"{self.table}@{start}")
        end = min(start + count, self.num_rows)
        pieces: List[columnar.Table] = []
        nbytes = 0
        for fp, keep, g_start, g_live in self._files:
            if g_start + g_live <= start or g_start >= end:
                continue
            t = self._decode(fp, keep)
            lo = max(start - g_start, 0)
            hi = min(end - g_start, g_live)
            pieces.append(columnar.Table({
                n: columnar.Column(
                    c.data[lo:hi], c.ctype,
                    None if c.valid is None else c.valid[lo:hi],
                    c.dictionary)
                for n, c in t.columns.items()}))
        out: ChunkPayload = {}
        for n in self._cols:
            cols = [p.column(n) for p in pieces]
            data = np.concatenate([c.data for c in cols]) if cols \
                else np.empty(0, dtype=self.column_meta()[n][1])
            valid = np.concatenate([c.validity() for c in cols]) if cols \
                else np.empty(0, dtype=bool)
            nbytes += data.nbytes + valid.nbytes
            out[n] = (data, valid)
        obs.inc("io.scan.bytes", nbytes)
        return out


class ChunkScanPool:
    """Bounded read-ahead scan/decode pool in front of the executor.

    Workers read + decode the next ``depth`` chunks (in consumption
    order) while the executor computes on the current one; ``get``
    blocks only when the pipeline is behind, and that block time is
    the honest ``io.scan.wait_s`` evidence for the overlap claim.
    A failing worker read degrades the pool to synchronous streaming
    (``io.scan.degraded``) instead of wedging the run — the PR-5
    ``io.read`` fault site fires inside ``ChunkSource.read``.

    Per-chunk :class:`ndstpu.engine.latch.KeyedLatch` keeps a sync
    fallback and a late worker from decoding the same chunk twice.
    """

    def __init__(self, read_fn: Callable[[int], ChunkPayload],
                 starts: Sequence[int], workers: int = 2,
                 depth: int = 2):
        import threading

        from ndstpu.engine.latch import KeyedLatch
        self._read = read_fn
        self._starts = list(starts)
        self._depth = max(int(depth), 0)
        self._workers = max(int(workers), 1)
        self._futs: Dict[int, object] = {}
        self._next = 0          # index into _starts not yet scheduled
        self._pool = None
        self._degraded = False
        self._latch = KeyedLatch()
        # get() is called from the executor AND the H2D staging thread
        # (sync fallbacks vs background staging) — scheduling
        # bookkeeping must not race
        self._sched_lock = threading.Lock()

    def _ensure_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix="ndstpu-scan")
        return self._pool

    def _guarded_read(self, start: int) -> ChunkPayload:
        with self._latch.holding(start):
            return self._read(start)

    def start_ahead(self) -> None:
        """Kick the read-ahead window before the first ``get`` — called
        at pipeline build so compile time hides the cold reads."""
        self._schedule_ahead(-1)

    def reset(self, next_idx: int = 0) -> None:
        """Rewind the read-ahead window for another pass over the same
        chunk sequence (repeat execution of a cached chunked query).
        A degraded pool stays degraded — the source already failed."""
        with self._sched_lock:
            for fut in self._futs.values():
                fut.cancel()
            self._futs.clear()
            self._next = max(int(next_idx), 0)
        self._schedule_ahead(next_idx - 1)

    def _schedule_ahead(self, upto_idx: int) -> None:
        if self._degraded or self._depth == 0:
            return
        with self._sched_lock:
            limit = min(upto_idx + 1 + self._depth, len(self._starts))
            while self._next < limit:
                s = self._starts[self._next]
                self._futs[s] = self._ensure_pool().submit(
                    self._guarded_read, s)
                self._next += 1

    @staticmethod
    def _wait_counter() -> str:
        """Scan blocking on the H2D staging thread is latency the ring
        absorbs, not executor stall — attribute it separately so
        ``io.scan.wait_s`` stays the honest overlap-claim numerator."""
        import threading
        if threading.current_thread().name.startswith("ndstpu-h2d"):
            return "io.scan.wait_bg_s"
        return "io.scan.wait_s"

    def get(self, start: int) -> ChunkPayload:
        from ndstpu import obs
        try:
            idx = self._starts.index(start)
            with self._sched_lock:
                self._next = max(self._next, idx)
            self._schedule_ahead(idx)
        except ValueError:
            idx = None   # off-schedule read: serve synchronously
        with self._sched_lock:
            fut = self._futs.pop(start, None)
        if fut is not None:
            obs.inc("io.scan.ahead.hit" if fut.done()
                    else "io.scan.ahead.miss")
            t0 = time.monotonic()
            try:
                payload = fut.result()
                obs.inc(self._wait_counter(), time.monotonic() - t0)
                if idx is not None:
                    self._schedule_ahead(idx + 1)
                return payload
            except Exception as e:  # noqa: BLE001 — degrade, don't wedge
                self._degrade(e)
        else:
            obs.inc("io.scan.ahead.miss")
        t0 = time.monotonic()
        try:
            return self._guarded_read(start)
        finally:
            obs.inc(self._wait_counter(), time.monotonic() - t0)

    def _degrade(self, exc: Exception) -> None:
        from ndstpu import obs
        if not self._degraded:
            self._degraded = True
            obs.inc("io.scan.degraded")
            obs.annotate(io_scan_degraded=f"{type(exc).__name__}: {exc}")
        with self._sched_lock:
            for fut in self._futs.values():
                fut.cancel()
            self._futs.clear()

    def close(self) -> None:
        with self._sched_lock:
            for fut in self._futs.values():
                fut.cancel()
            self._futs.clear()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


def attach_stream_source(catalog: Catalog, name: str,
                         source: ChunkSource) -> None:
    """Register an out-of-core scan source for a catalog table.  The
    chunked SPMD executor streams this table's rows from the source;
    every other path keeps using the resident copy."""
    if name not in catalog.tables:
        raise KeyError(f"table {name} not in catalog")
    if source.num_rows != catalog.get(name).num_rows:
        raise ValueError(
            f"stream source rows ({source.num_rows}) != resident rows "
            f"({catalog.get(name).num_rows}) for {name}")
    # string chunks must decode into the RESIDENT code space: the traced
    # spine bakes the resident dictionary in as a compile-time constant
    resident = catalog.get(name)
    if any(col in resident.columns
           and resident.column(col).ctype.kind == "string"
           for col in source.columns):
        for col, (ct, _dt, d) in source.column_meta().items():
            if ct.kind != "string" or col not in resident.columns:
                continue
            rd = resident.column(col).dictionary
            if d is None or rd is None or not np.array_equal(
                    np.asarray(d, dtype=object),
                    np.asarray(rd, dtype=object)):
                raise ValueError(
                    f"stream source dictionary for {name}.{col} does "
                    f"not match the resident dictionary — codes would "
                    f"disagree across chunks (was the "
                    f"{gdict.GDICT_FILE} sidecar rebuilt after the "
                    f"catalog loaded?)")
    streams = getattr(catalog, "streams", None)
    if streams is None:       # catalogs unpickled from older snapshots
        streams = catalog.streams = {}
    streams[name] = source


def raw_table_paths(data_dir: str, table: str) -> List[str]:
    return sorted(glob.glob(os.path.join(data_dir, table, "*.dat")))
