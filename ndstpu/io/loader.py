"""Warehouse loader: transcode output -> engine Tables (host or device).

Loads per-table warehouse directories (hive-partitioned parquet datasets,
single parquet/orc files, or ndslake ACID tables) into
:class:`ndstpu.engine.columnar.Table`, recording per-table key metadata the
engine exploits:

* dense surrogate keys — every dimension's primary key is `1..N` (or
  offset-dense like date_dim's Julian day sk), so FK->PK joins lower to a
  bounds-checked gather instead of a hash table (TPU-friendly).

This is the analog of the reference's table registration step
(nds_power.py:78-121 setup_tables / register_delta_tables), with Spark
TempViews replaced by an in-process catalog.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads

from ndstpu import schema as nds_schema
from ndstpu.engine import columnar
from ndstpu.io import lake


@dataclass
class TableMeta:
    name: str
    num_rows: int
    # primary key column with dense values pk_min..pk_min+N-1, if detected
    dense_key: Optional[str] = None
    dense_min: int = 0


@dataclass
class Catalog:
    """Named engine tables + metadata, the engine's table registry."""

    tables: Dict[str, columnar.Table] = field(default_factory=dict)
    meta: Dict[str, TableMeta] = field(default_factory=dict)
    # per-table monotonic version, bumped on every (re)register — the
    # invalidation key for device-resident caches (id() reuse is not sound)
    versions: Dict[str, int] = field(default_factory=dict)

    def register(self, name: str, table: columnar.Table) -> None:
        self.tables[name] = table
        self.meta[name] = TableMeta(name, table.num_rows)
        self.versions[name] = self.versions.get(name, 0) + 1
        key = _primary_key_column(name, table)
        if key is not None:
            col = table.column(key)
            if col.valid is None and len(col.data):
                data = col.data
                lo = int(data.min())
                hi = int(data.max())
                if hi - lo + 1 == len(data) and _is_permutation(data, lo, hi):
                    self.meta[name].dense_key = key
                    self.meta[name].dense_min = lo

    def unregister(self, name: str) -> None:
        self.tables.pop(name, None)
        self.meta.pop(name, None)
        self.versions[name] = self.versions.get(name, 0) + 1

    def get(self, name: str) -> columnar.Table:
        return self.tables[name]

    def __contains__(self, name: str) -> bool:
        return name in self.tables


def _is_permutation(data: np.ndarray, lo: int, hi: int) -> bool:
    seen = np.zeros(hi - lo + 1, dtype=bool)
    seen[data - lo] = True
    return bool(seen.all())


_PK_OVERRIDES = {
    "date_dim": "d_date_sk",
    "time_dim": "t_time_sk",
}


def _primary_key_column(name: str, table: columnar.Table) -> Optional[str]:
    if name in _PK_OVERRIDES:
        return _PK_OVERRIDES[name]
    # convention: first column ending in _sk is the surrogate PK
    first = table.column_names[0] if table.column_names else None
    if first and first.endswith("_sk"):
        return first
    return None


def read_warehouse_table(warehouse: str, table: str,
                         columns: Optional[List[str]] = None) -> pa.Table:
    """Read one table from a transcoded warehouse, any supported layout."""
    root = os.path.join(warehouse, table)
    if lake.is_lake(root):
        return lake.read(root, columns=columns)
    singles = sorted(glob.glob(os.path.join(root, f"{table}*.parquet")))
    if singles:
        import pyarrow.parquet as pq
        parts = [pq.read_table(p, columns=columns) for p in singles]
        return pa.concat_tables(parts) if len(parts) > 1 else parts[0]
    for ext, fmt in (("orc", "orc"), ("avro", "avro"), ("csv", "csv"),
                     ("json", "json")):
        paths = sorted(glob.glob(os.path.join(root, f"{table}*.{ext}")))
        if paths:
            parts = []
            for p in paths:
                if fmt == "orc":
                    import pyarrow.orc as paorc
                    parts.append(paorc.read_table(p))
                elif fmt == "avro":
                    from ndstpu.io import avroio
                    parts.append(avroio.read_table(p))
                elif fmt == "csv":
                    import pyarrow.csv as pacsv
                    parts.append(pacsv.read_csv(
                        p, convert_options=pacsv.ConvertOptions(
                            strings_can_be_null=True)))
                else:
                    import pandas as pd
                    parts.append(
                        pa.Table.from_pandas(pd.read_json(p, lines=True)))
            t = pa.concat_tables(parts) if len(parts) > 1 else parts[0]
            return t.select(columns) if columns else t
    if os.path.isdir(root):
        # hive-partitioned parquet dataset
        dset = pads.dataset(root, format="parquet", partitioning="hive")
        at = dset.to_table(columns=columns)
        return at
    raise FileNotFoundError(f"table {table} not found under {warehouse}")


def _postprocess_partition_dtypes(table: str, at: pa.Table) -> pa.Table:
    """Hive partition keys come back as inferred ints; restore int32 for the
    *_date_sk partition columns so schemas round-trip."""
    part_col = nds_schema.TABLE_PARTITIONING.get(table)
    if part_col and part_col in at.column_names:
        idx = at.column_names.index(part_col)
        col = at.column(idx)
        if not pa.types.is_int32(col.type):
            at = at.set_column(idx, part_col, col.cast(pa.int32()))
    return at


def load_catalog(warehouse: str, tables: Optional[List[str]] = None,
                 use_decimal: bool = True) -> Catalog:
    """Load a transcoded warehouse into an engine catalog."""
    if tables is None:
        tables = [t for t in nds_schema.SOURCE_TABLE_NAMES
                  if os.path.isdir(os.path.join(warehouse, t))]
    schemas = {**nds_schema.get_schemas(use_decimal),
               **nds_schema.get_maintenance_schemas(use_decimal)}
    cat = Catalog()
    for t in tables:
        at = read_warehouse_table(warehouse, t)
        at = _postprocess_partition_dtypes(t, at)
        sch = schemas.get(t)
        if sch is not None:
            # restore declared column order (partitioned reads reorder)
            order = [c.name for c in sch.columns if c.name in at.column_names]
            at = at.select(order)
        cat.register(t, columnar.from_arrow(at, sch))
    return cat


def raw_table_paths(data_dir: str, table: str) -> List[str]:
    return sorted(glob.glob(os.path.join(data_dir, table, "*.dat")))
