"""`ndslake` — a minimal ACID snapshot table format (Iceberg/Delta analog).

The reference runs its data-maintenance phase (LF_*/DF_* refresh functions)
against Iceberg or Delta Lake for ACID INSERT/DELETE plus time-travel
rollback between repeated benchmark runs (nds_maintenance.py, nds_rollback.py:37-59).
This module provides the same capabilities natively:

Layout:
    table_dir/
      _ndslake/v{N:08d}.json   immutable snapshot manifests
      _ndslake/CURRENT         pointer to the live snapshot version
      data/part-*.parquet      immutable data files
      deletes/d-*.npy          per-data-file deleted-row-index vectors

Semantics:
  * append(...)        -> new data file + new snapshot (INSERT INTO)
  * delete_rows(...)   -> merge-on-read deletion vectors + new snapshot
  * read(...)          -> current (or historical) table view
  * rollback_to_timestamp / rollback_to_version -> move CURRENT
    (undoes maintenance writes exactly like the reference's
    `CALL spark_catalog.system.rollback_to_timestamp`)

Writers are single-process per table (the benchmark's DM phase runs one
maintenance stream per table family), so CURRENT is updated by atomic
rename.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


@dataclass
class Snapshot:
    version: int
    timestamp: float
    # list of {"path": str, "rows": int, "deletes": Optional[str]}
    files: List[Dict] = field(default_factory=list)
    partition_col: Optional[str] = None
    operation: str = "create"


def _meta_dir(table_dir: str) -> str:
    return os.path.join(table_dir, "_ndslake")


def _snap_path(table_dir: str, version: int) -> str:
    return os.path.join(_meta_dir(table_dir), f"v{version:08d}.json")


def is_ndslake(table_dir: str) -> bool:
    return os.path.isdir(_meta_dir(table_dir))


def _write_snapshot(table_dir: str, snap: Snapshot) -> None:
    os.makedirs(_meta_dir(table_dir), exist_ok=True)
    with open(_snap_path(table_dir, snap.version), "w") as f:
        json.dump({
            "version": snap.version,
            "timestamp": snap.timestamp,
            "files": snap.files,
            "partition_col": snap.partition_col,
            "operation": snap.operation,
        }, f, indent=1)
    tmp = os.path.join(_meta_dir(table_dir), f".CURRENT.{uuid.uuid4().hex}")
    with open(tmp, "w") as f:
        f.write(str(snap.version))
    os.replace(tmp, os.path.join(_meta_dir(table_dir), "CURRENT"))


def current_version(table_dir: str) -> int:
    with open(os.path.join(_meta_dir(table_dir), "CURRENT")) as f:
        return int(f.read().strip())


def _next_version(table_dir: str) -> int:
    """Version numbers are monotonic over ALL snapshots ever written (not
    CURRENT+1): after a rollback, new writes must not clobber the abandoned
    branch's snapshot files."""
    vs = [int(n[1:9]) for n in os.listdir(_meta_dir(table_dir))
          if n.startswith("v") and n.endswith(".json")]
    return max(vs) + 1 if vs else 0


def load_snapshot(table_dir: str,
                  version: Optional[int] = None) -> Snapshot:
    if version is None:
        version = current_version(table_dir)
    with open(_snap_path(table_dir, version)) as f:
        d = json.load(f)
    return Snapshot(d["version"], d["timestamp"], d["files"],
                    d.get("partition_col"), d.get("operation", "?"))


def snapshots(table_dir: str) -> List[Snapshot]:
    out = []
    for name in sorted(os.listdir(_meta_dir(table_dir))):
        if name.startswith("v") and name.endswith(".json"):
            out.append(load_snapshot(table_dir, int(name[1:9])))
    return out


def _new_data_file(table_dir: str, at: pa.Table) -> Dict:
    os.makedirs(os.path.join(table_dir, "data"), exist_ok=True)
    rel = os.path.join("data", f"part-{uuid.uuid4().hex}.parquet")
    pq.write_table(at, os.path.join(table_dir, rel), compression="snappy")
    return {"path": rel, "rows": at.num_rows, "deletes": None}


def create_table(table_dir: str, at: pa.Table,
                 partition_col: Optional[str] = None) -> None:
    """Create/overwrite a table with an initial snapshot (CTAS analog)."""
    os.makedirs(table_dir, exist_ok=True)
    if partition_col is not None:
        at = at.sort_by([(partition_col, "ascending")])
    version = _next_version(table_dir) if is_ndslake(table_dir) else 0
    snap = Snapshot(version, time.time(), [_new_data_file(table_dir, at)],
                    partition_col, "create")
    _write_snapshot(table_dir, snap)


def append(table_dir: str, at: pa.Table) -> None:
    """INSERT INTO: add a data file in a new snapshot."""
    prev = load_snapshot(table_dir)
    if prev.partition_col is not None and prev.partition_col in at.column_names:
        at = at.sort_by([(prev.partition_col, "ascending")])
    snap = Snapshot(_next_version(table_dir), time.time(),
                    prev.files + [_new_data_file(table_dir, at)],
                    prev.partition_col, "append")
    _write_snapshot(table_dir, snap)


def delete_rows(table_dir: str,
                predicate: Callable[[pa.Table], np.ndarray]) -> int:
    """DELETE FROM ... WHERE: merge-on-read deletion vectors.

    `predicate` maps a data-file's (live-row) arrow table to a boolean
    delete-mask over those rows.  Returns number of rows deleted."""
    prev = load_snapshot(table_dir)
    os.makedirs(os.path.join(table_dir, "deletes"), exist_ok=True)
    new_files: List[Dict] = []
    total = 0
    for fmeta in prev.files:
        at = pq.read_table(os.path.join(table_dir, fmeta["path"]))
        already = (np.load(os.path.join(table_dir, fmeta["deletes"]))
                   if fmeta["deletes"] else
                   np.empty(0, dtype=np.int64))
        live = np.ones(at.num_rows, dtype=bool)
        live[already] = False
        live_idx = np.nonzero(live)[0]
        mask = np.asarray(predicate(at.take(live_idx)), dtype=bool)
        kill = live_idx[mask]
        total += len(kill)
        if len(kill) == 0:
            new_files.append(dict(fmeta))
            continue
        alldel = np.union1d(already, kill).astype(np.int64)
        rel = os.path.join("deletes", f"d-{uuid.uuid4().hex}.npy")
        np.save(os.path.join(table_dir, rel), alldel)
        nf = dict(fmeta)
        nf["deletes"] = rel
        new_files.append(nf)
    snap = Snapshot(_next_version(table_dir), time.time(), new_files,
                    prev.partition_col, "delete")
    _write_snapshot(table_dir, snap)
    return total


def read(table_dir: str, version: Optional[int] = None,
         columns: Optional[List[str]] = None) -> pa.Table:
    """Current (or historical) view of the table."""
    snap = load_snapshot(table_dir, version)
    parts = []
    for fmeta in snap.files:
        at = pq.read_table(os.path.join(table_dir, fmeta["path"]),
                           columns=columns)
        if fmeta["deletes"]:
            dels = np.load(os.path.join(table_dir, fmeta["deletes"]))
            keep = np.ones(at.num_rows, dtype=bool)
            keep[dels] = False
            at = at.filter(pa.array(keep))
        parts.append(at)
    return pa.concat_tables(parts) if len(parts) > 1 else parts[0]


def rollback_to_version(table_dir: str, version: int) -> int:
    """Restore the state of snapshot `version` by writing a NEW snapshot
    with its file list (Iceberg-style: history stays linear and monotonic,
    so later timestamp rollbacks can't resurrect an abandoned branch).
    Returns the new snapshot's version."""
    target = load_snapshot(table_dir, version)
    snap = Snapshot(_next_version(table_dir), time.time(),
                    [dict(f) for f in target.files], target.partition_col,
                    f"rollback(v{version})")
    _write_snapshot(table_dir, snap)
    return snap.version


def rollback_to_timestamp(table_dir: str, ts: float) -> int:
    """Restore the newest snapshot at-or-before `ts`
    (reference parity: nds_rollback.py:37-59)."""
    candidates = [s for s in snapshots(table_dir) if s.timestamp <= ts]
    if not candidates:
        raise ValueError(f"no snapshot at or before {ts}")
    target = max(candidates, key=lambda s: s.version)
    return rollback_to_version(table_dir, target.version)
