"""`ndslake` — a minimal ACID snapshot table format (Iceberg/Delta analog).

The reference runs its data-maintenance phase (LF_*/DF_* refresh functions)
against Iceberg or Delta Lake for ACID INSERT/DELETE plus time-travel
rollback between repeated benchmark runs (nds_maintenance.py, nds_rollback.py:37-59).
This module provides the same capabilities natively:

Layout:
    table_dir/
      _ndslake/v{N:08d}.json   immutable snapshot manifests
      _ndslake/CURRENT         pointer to the live snapshot version
      data/part-*.parquet      immutable data files
      deletes/d-*.npy          per-data-file deleted-row-index vectors

Semantics:
  * append(...)        -> new data file + new snapshot (INSERT INTO)
  * delete_rows(...)   -> merge-on-read deletion vectors + new snapshot
  * read(...)          -> current (or historical) table view
  * rollback_to_timestamp / rollback_to_version -> move CURRENT
    (undoes maintenance writes exactly like the reference's
    `CALL spark_catalog.system.rollback_to_timestamp`)

Commit protocol (docs/ROBUSTNESS.md "Ingest commit protocol"): CURRENT
advances by a journaled compare-and-swap under the table's commit lock
(io/commit.py) — every writer states the version its write is based on
and loses with a typed, retryable `CommitConflict` (transient in the
faults taxonomy) when another writer got there first.  The manifest is
fully written and fsynced before the single atomic CURRENT publish, so
a SIGKILL anywhere mid-commit leaves the old or the new snapshot
current, never a torn pointer; `faults.check("ingest.commit")` probes
exactly that window.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ndstpu.io import commit as commit_proto


@dataclass
class Snapshot:
    version: int
    timestamp: float
    # list of {"path": str, "rows": int, "deletes": Optional[str]}
    files: List[Dict] = field(default_factory=list)
    partition_col: Optional[str] = None
    operation: str = "create"


def _meta_dir(table_dir: str) -> str:
    return os.path.join(table_dir, "_ndslake")


def _snap_path(table_dir: str, version: int) -> str:
    return os.path.join(_meta_dir(table_dir), f"v{version:08d}.json")


def is_ndslake(table_dir: str) -> bool:
    return os.path.isdir(_meta_dir(table_dir))


def _commit_snapshot(table_dir: str, files: List[Dict],
                     partition_col: Optional[str], operation: str,
                     expected_version: Optional[int]) -> Snapshot:
    """Journaled compare-and-swap commit.  Under the table's commit
    lock: verify CURRENT still points at ``expected_version`` (None =
    the table must not exist yet), allocate the next monotonic
    version, durably write the manifest, journal the commit, then
    atomically swing CURRENT.  The loser of a race gets
    ``CommitConflict`` (transient: reload + rebase + retry); a SIGKILL
    anywhere in here leaves the old or the new snapshot current —
    the manifest/journal written before a crash are orphans, never a
    torn pointer."""
    from ndstpu import faults, obs
    from ndstpu.io import atomic
    md = _meta_dir(table_dir)
    os.makedirs(md, exist_ok=True)
    with commit_proto.commit_lock(md):
        found = current_version(table_dir) \
            if os.path.exists(os.path.join(md, "CURRENT")) else None
        if found != expected_version:
            obs.inc("engine.ingest.conflicts")
            raise commit_proto.CommitConflict(
                table_dir, expected_version, found)
        snap = Snapshot(_next_version(table_dir), time.time(), files,
                        partition_col, operation)
        atomic.atomic_write_json(_snap_path(table_dir, snap.version), {
            "version": snap.version,
            "timestamp": snap.timestamp,
            "files": snap.files,
            "partition_col": snap.partition_col,
            "operation": snap.operation,
        }, indent=1)
        commit_proto.journal(md, {
            "version": snap.version, "prev": expected_version,
            "operation": operation, "ts": round(snap.timestamp, 3)})
        # the crash-mid-commit probe: a fault injected here fires after
        # the manifest+journal exist but before CURRENT moves, exactly
        # the window the atomicity guarantee covers
        faults.check("ingest.commit", key=table_dir)
        atomic.atomic_write_text(
            os.path.join(md, "CURRENT"), str(snap.version))
        obs.inc("engine.ingest.commits")
    return snap


def current_version(table_dir: str) -> int:
    with open(os.path.join(_meta_dir(table_dir), "CURRENT")) as f:
        return int(f.read().strip())


def _next_version(table_dir: str) -> int:
    """Version numbers are monotonic over ALL snapshots ever written (not
    CURRENT+1): after a rollback, new writes must not clobber the abandoned
    branch's snapshot files."""
    vs = [int(n[1:9]) for n in os.listdir(_meta_dir(table_dir))
          if n.startswith("v") and n.endswith(".json")]
    return max(vs) + 1 if vs else 0


def abort_to_version(table_dir: str, version: int) -> int:
    """Crash-recovery retraction: point CURRENT back at ``version`` and
    physically remove every snapshot manifest above it.  Unlike
    :func:`rollback_to_version` (which publishes a NEW snapshot and
    keeps history linear — the user-facing time-travel path), this
    rewrites history, so it is only sound when no reader can hold the
    retracted versions: recovering a micro-batch whose journal intent
    never reached done (harness/ingest.py), before query serving
    resumes.  Pins taken before the batch reference versions <= the
    recorded pre-version and are untouched.  CURRENT swings first, then
    the manifests unlink, so a crash mid-abort leaves a valid pointer
    plus orphans a re-run GCs.  Retracted data files stay on disk —
    unreachable garbage, never corruption."""
    from ndstpu.io import atomic
    md = _meta_dir(table_dir)
    with commit_proto.commit_lock(md):
        load_snapshot(table_dir, version)  # target must exist
        retract = [int(n[1:9]) for n in os.listdir(md)
                   if n.startswith("v") and n.endswith(".json")
                   and int(n[1:9]) > version]
        atomic.atomic_write_text(
            os.path.join(md, "CURRENT"), str(version))
        for v in sorted(retract):
            os.unlink(_snap_path(table_dir, v))
        if retract:
            commit_proto.journal(md, {
                "operation": f"abort_to(v{version})",
                "retracted": sorted(retract),
                "ts": round(time.time(), 3)})
    return version


def gc_orphan_manifests(table_dir: str) -> List[int]:
    """Remove snapshot manifests that were written but never published
    to CURRENT (a crash or injected fault between manifest write and
    pointer swing).  No reader can hold one — pins resolve through
    CURRENT — but they skew ``_next_version``, so a killed-and-resumed
    ingest would number its snapshots differently from a clean run.
    Runs under the commit lock so it never races an in-flight commit;
    the COMMITS.jsonl journal record survives as the crash diagnostic."""
    md = _meta_dir(table_dir)
    if not os.path.exists(os.path.join(md, "CURRENT")):
        return []
    removed: List[int] = []
    with commit_proto.commit_lock(md):
        cur = current_version(table_dir)
        for name in os.listdir(md):
            if not (name.startswith("v") and name.endswith(".json")):
                continue
            try:
                v = int(name[1:9])
            except ValueError:
                continue
            if v > cur:
                os.unlink(os.path.join(md, name))
                removed.append(v)
    return sorted(removed)


def load_snapshot(table_dir: str,
                  version: Optional[int] = None) -> Snapshot:
    if version is None:
        version = current_version(table_dir)
    with open(_snap_path(table_dir, version)) as f:
        d = json.load(f)
    return Snapshot(d["version"], d["timestamp"], d["files"],
                    d.get("partition_col"), d.get("operation", "?"))


def snapshots(table_dir: str) -> List[Snapshot]:
    out = []
    for name in sorted(os.listdir(_meta_dir(table_dir))):
        if name.startswith("v") and name.endswith(".json"):
            out.append(load_snapshot(table_dir, int(name[1:9])))
    return out


def _new_data_file(table_dir: str, at: pa.Table) -> Dict:
    os.makedirs(os.path.join(table_dir, "data"), exist_ok=True)
    rel = os.path.join("data", f"part-{uuid.uuid4().hex}.parquet")
    pq.write_table(at, os.path.join(table_dir, rel), compression="snappy")
    return {"path": rel, "rows": at.num_rows, "deletes": None}


def create_table(table_dir: str, at: pa.Table,
                 partition_col: Optional[str] = None) -> None:
    """Create/overwrite a table with an initial snapshot (CTAS analog)."""
    os.makedirs(table_dir, exist_ok=True)
    if partition_col is not None:
        at = at.sort_by([(partition_col, "ascending")])
    has_current = is_ndslake(table_dir) and os.path.exists(
        os.path.join(_meta_dir(table_dir), "CURRENT"))
    expected = current_version(table_dir) if has_current else None
    _commit_snapshot(table_dir, [_new_data_file(table_dir, at)],
                     partition_col, "create", expected)


def append(table_dir: str, at: pa.Table,
           expected_version: Optional[int] = None) -> None:
    """INSERT INTO: add a data file in a new snapshot.

    ``expected_version`` is the snapshot this write is based on
    (default: CURRENT at load time); if another writer advances the
    table before this commit publishes, the CAS raises
    ``CommitConflict`` instead of silently clobbering."""
    prev = load_snapshot(table_dir, expected_version)
    if prev.partition_col is not None and prev.partition_col in at.column_names:
        at = at.sort_by([(prev.partition_col, "ascending")])
    _commit_snapshot(table_dir,
                     prev.files + [_new_data_file(table_dir, at)],
                     prev.partition_col, "append", prev.version)


def delete_rows(table_dir: str,
                predicate: Callable[[pa.Table], np.ndarray],
                expected_version: Optional[int] = None) -> int:
    """DELETE FROM ... WHERE: merge-on-read deletion vectors.

    `predicate` maps a data-file's (live-row) arrow table to a boolean
    delete-mask over those rows.  Returns number of rows deleted.
    ``expected_version`` as in :func:`append`."""
    prev = load_snapshot(table_dir, expected_version)
    os.makedirs(os.path.join(table_dir, "deletes"), exist_ok=True)
    new_files: List[Dict] = []
    total = 0
    for fmeta in prev.files:
        at = pq.read_table(os.path.join(table_dir, fmeta["path"]))
        already = (np.load(os.path.join(table_dir, fmeta["deletes"]))
                   if fmeta["deletes"] else
                   np.empty(0, dtype=np.int64))
        live = np.ones(at.num_rows, dtype=bool)
        live[already] = False
        live_idx = np.nonzero(live)[0]
        mask = np.asarray(predicate(at.take(live_idx)), dtype=bool)
        kill = live_idx[mask]
        total += len(kill)
        if len(kill) == 0:
            new_files.append(dict(fmeta))
            continue
        alldel = np.union1d(already, kill).astype(np.int64)
        rel = os.path.join("deletes", f"d-{uuid.uuid4().hex}.npy")
        np.save(os.path.join(table_dir, rel), alldel)
        nf = dict(fmeta)
        nf["deletes"] = rel
        new_files.append(nf)
    _commit_snapshot(table_dir, new_files, prev.partition_col,
                     "delete", prev.version)
    return total


def read(table_dir: str, version: Optional[int] = None,
         columns: Optional[List[str]] = None) -> pa.Table:
    """Current (or historical) view of the table."""
    snap = load_snapshot(table_dir, version)
    parts = []
    for fmeta in snap.files:
        at = pq.read_table(os.path.join(table_dir, fmeta["path"]),
                           columns=columns)
        if fmeta["deletes"]:
            dels = np.load(os.path.join(table_dir, fmeta["deletes"]))
            keep = np.ones(at.num_rows, dtype=bool)
            keep[dels] = False
            at = at.filter(pa.array(keep))
        parts.append(at)
    return pa.concat_tables(parts) if len(parts) > 1 else parts[0]


def rollback_to_version(table_dir: str, version: int) -> int:
    """Restore the state of snapshot `version` by writing a NEW snapshot
    with its file list (Iceberg-style: history stays linear and monotonic,
    so later timestamp rollbacks can't resurrect an abandoned branch).
    Returns the new snapshot's version."""
    target = load_snapshot(table_dir, version)
    snap = _commit_snapshot(table_dir, [dict(f) for f in target.files],
                            target.partition_col,
                            f"rollback(v{version})",
                            current_version(table_dir))
    return snap.version


def rollback_to_timestamp(table_dir: str, ts: float) -> int:
    """Restore the newest snapshot at-or-before `ts`
    (reference parity: nds_rollback.py:37-59)."""
    candidates = [s for s in snapshots(table_dir) if s.timestamp <= ts]
    if not candidates:
        raise ValueError(f"no snapshot at or before {ts}")
    target = max(candidates, key=lambda s: s.version)
    return rollback_to_version(table_dir, target.version)
