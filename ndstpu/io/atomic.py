"""Atomic artifact writes: temp file + fsync + ``os.replace``.

Every JSON/CSV artifact the harness publishes (sidecars, overlap
reports, time logs, summaries, reports, RUN_STATE journal snapshots)
goes through this module so a ``kill -9`` mid-write can never leave a
truncated or half-serialized file behind: readers either see the old
complete artifact or the new complete artifact, never a torn one.

The mechanism is the standard POSIX dance — write to a uniquely-named
temp file *in the same directory* (``os.replace`` is only atomic within
a filesystem), flush + fsync the data, then ``os.replace`` onto the
final name.  ``append_jsonl`` is the complement for append-only
journals (ledger, RUN_STATE): one line per record, flushed and fsynced
per call, so a crash can at worst lose the final in-flight line —
readers skip a torn trailing line, they never misparse earlier ones.

All writers carry the ``io.write`` fault-injection probe
(docs/ROBUSTNESS.md), so chaos runs exercise the failure-mid-write
path the atomicity guarantee exists for.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
from typing import Iterator, Optional

from ndstpu import faults


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync so the rename itself is durable."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_writer(path: str, mode: str = "w",
                  encoding: Optional[str] = None,
                  newline: Optional[str] = None) -> Iterator:
    """Context manager yielding a file handle for a temp file that is
    atomically renamed onto ``path`` on clean exit (and unlinked on
    error)."""
    if "a" in mode:
        raise ValueError("atomic_writer cannot append; use append_jsonl")
    faults.check("io.write", key=path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    if encoding is None and "b" not in mode:
        encoding = "utf-8"
    fd, tmp = tempfile.mkstemp(
        prefix=f".{os.path.basename(path)}.", suffix=".tmp", dir=d)
    try:
        kw = {} if "b" in mode else {"encoding": encoding,
                                     "newline": newline}
        with os.fdopen(fd, mode, **kw) as f:
            yield f
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def atomic_write_text(path: str, text: str) -> None:
    with atomic_writer(path, "w") as f:
        f.write(text)


def atomic_write_bytes(path: str, data: bytes) -> None:
    with atomic_writer(path, "wb") as f:
        f.write(data)


def atomic_write_json(path: str, obj, *, indent: Optional[int] = 2,
                      default=str) -> None:
    with atomic_writer(path, "w") as f:
        json.dump(obj, f, indent=indent, default=default)
        f.write("\n")


def append_jsonl(path: str, record: dict, *, default=str) -> None:
    """Durably append one JSON record to an append-only journal."""
    faults.check("io.write", key=path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    line = json.dumps(record, default=default)
    if "\n" in line:
        raise ValueError("journal record serialized to multiple lines")
    with open(path, "a", encoding="utf-8") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())


def read_jsonl(path: str) -> list:
    """Read a journal, tolerating a torn trailing line (crash mid-
    append) — any other malformed line raises, since append_jsonl
    fsyncs per record."""
    records = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
    except FileNotFoundError:
        return records
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                break  # torn final line from a crash mid-append
            raise
    return records
