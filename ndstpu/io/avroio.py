"""Minimal Avro Object Container File codec (write + read).

Format parity with the reference's `--output_format avro` load test
(nds/nds_transcode.py:121-144 via the spark-avro package): the subset of
Avro 1.11 needed for NDS tables — records of nullable primitives with
the standard logical types:

  int32 -> ["null","int"]          date  -> ["null",{"int","date"}]
  int64 -> ["null","long"]         string-> ["null","string"]
  float64 -> ["null","double"]
  decimal(p,s) -> ["null",{"bytes","decimal",precision,scale}]

Self-contained (no external avro dependency is baked into this image);
null codec; one block per row-group.  Values are framed row-by-row in
Python — adequate for load-test format parity at bench scale factors;
parquet remains the performance path (the reference's avro support is
likewise a compatibility format, not its fast path).
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import List, Tuple

import numpy as np
import pyarrow as pa

_MAGIC = b"Obj\x01"
_SYNC = bytes(range(16))  # deterministic sync marker
_BLOCK_ROWS = 65536


# -- varint helpers ----------------------------------------------------------


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _write_long(buf: io.BytesIO, n: int) -> None:
    z = _zigzag(int(n)) & (2 ** 64 - 1)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            buf.write(bytes((b | 0x80,)))
        else:
            buf.write(bytes((b,)))
            return


def _read_long(view: memoryview, pos: int) -> Tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = view[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


def _write_bytes(buf: io.BytesIO, data: bytes) -> None:
    _write_long(buf, len(data))
    buf.write(data)


# -- schema mapping ----------------------------------------------------------


def _avro_field(name: str, typ: pa.DataType) -> dict:
    if pa.types.is_int32(typ):
        t: object = "int"
    elif pa.types.is_int64(typ):
        t = "long"
    elif pa.types.is_float64(typ):
        t = "double"
    elif pa.types.is_string(typ) or pa.types.is_large_string(typ):
        t = "string"
    elif pa.types.is_date32(typ):
        t = {"type": "int", "logicalType": "date"}
    elif pa.types.is_decimal(typ):
        t = {"type": "bytes", "logicalType": "decimal",
             "precision": typ.precision, "scale": typ.scale}
    else:
        raise ValueError(f"avro: unsupported arrow type {typ}")
    return {"name": name, "type": ["null", t]}


def _schema_json(at: pa.Table, name: str) -> str:
    return json.dumps({
        "type": "record", "name": name,
        "fields": [_avro_field(f.name, f.type) for f in at.schema]})


# -- write -------------------------------------------------------------------


def _decimal_bytes(unscaled: int) -> bytes:
    """Two's-complement big-endian minimal representation."""
    length = max(1, (unscaled.bit_length() + 8) // 8)
    return int(unscaled).to_bytes(length, "big", signed=True)


def write_table(at: pa.Table, path: str, name: str = "nds") -> None:
    cols = []
    for i, f in enumerate(at.schema):
        col = at.column(i).combine_chunks()
        cols.append((f.type, col))
    with open(path, "wb") as f:
        head = io.BytesIO()
        head.write(_MAGIC)
        meta = {"avro.schema": _schema_json(at, name).encode(),
                "avro.codec": b"null"}
        _write_long(head, len(meta))
        for k, v in meta.items():
            _write_bytes(head, k.encode())
            _write_bytes(head, v)
        _write_long(head, 0)
        head.write(_SYNC)
        f.write(head.getvalue())
        n = at.num_rows
        for start in range(0, max(n, 1), _BLOCK_ROWS):
            count = min(_BLOCK_ROWS, n - start)
            if count <= 0:
                break
            block = io.BytesIO()
            _encode_block(block, cols, start, count)
            framed = io.BytesIO()
            _write_long(framed, count)
            _write_long(framed, block.getbuffer().nbytes)
            f.write(framed.getvalue())
            f.write(block.getvalue())
            f.write(_SYNC)


def _encode_block(buf: io.BytesIO, cols, start: int, count: int) -> None:
    # pre-extract python-friendly views per column
    views = []
    for typ, col in cols:
        sl = col.slice(start, count)
        mask = np.asarray(sl.is_null())
        if pa.types.is_string(typ) or pa.types.is_large_string(typ):
            vals = sl.to_pylist()
        elif pa.types.is_decimal(typ):
            scale = typ.scale
            vals = [None if v is None else int(v.scaleb(scale))
                    for v in sl.to_pylist()]
        elif pa.types.is_date32(typ):
            vals = sl.cast(pa.int32()).to_pylist()
        else:
            vals = sl.to_pylist()
        views.append((typ, mask, vals))
    for r in range(count):
        for typ, mask, vals in views:
            if mask[r]:
                _write_long(buf, 0)  # union branch: null
                continue
            _write_long(buf, 1)      # union branch: value
            v = vals[r]
            if pa.types.is_string(typ) or pa.types.is_large_string(typ):
                _write_bytes(buf, v.encode())
            elif pa.types.is_float64(typ):
                buf.write(struct.pack("<d", v))
            elif pa.types.is_decimal(typ):
                _write_bytes(buf, _decimal_bytes(v))
            else:  # int / long / date
                _write_long(buf, v)


# -- read --------------------------------------------------------------------


def read_table(path: str) -> pa.Table:
    data = memoryview(open(path, "rb").read())
    if bytes(data[:4]) != _MAGIC:
        raise ValueError(f"{path}: not an avro container file")
    pos = 4
    meta = {}
    while True:
        n, pos = _read_long(data, pos)
        if n == 0:
            break
        if n < 0:
            # spec: negative map-block count is followed by the block's
            # byte size (which we don't need when parsing sequentially)
            _size, pos = _read_long(data, pos)
        for _ in range(abs(n)):
            klen, pos = _read_long(data, pos)
            key = bytes(data[pos:pos + klen]).decode()
            pos += klen
            vlen, pos = _read_long(data, pos)
            meta[key] = bytes(data[pos:pos + vlen])
            pos += vlen
    sync = bytes(data[pos:pos + 16])
    pos += 16
    schema = json.loads(meta["avro.schema"].decode())
    if meta.get("avro.codec", b"null") not in (b"null", b""):
        raise ValueError("avro: only the null codec is supported")
    fields = schema["fields"]
    out: List[List] = [[] for _ in fields]
    while pos < len(data):
        count, pos = _read_long(data, pos)
        _size, pos = _read_long(data, pos)
        for _ in range(count):
            for fi, field in enumerate(fields):
                branch, pos = _read_long(data, pos)
                if branch == 0:
                    out[fi].append(None)
                    continue
                t = field["type"][1]
                base = t["type"] if isinstance(t, dict) else t
                if base == "string":
                    ln, pos = _read_long(data, pos)
                    out[fi].append(bytes(data[pos:pos + ln]).decode())
                    pos += ln
                elif base == "double":
                    out[fi].append(
                        struct.unpack("<d", data[pos:pos + 8])[0])
                    pos += 8
                elif base == "bytes":  # decimal
                    ln, pos = _read_long(data, pos)
                    out[fi].append(int.from_bytes(
                        data[pos:pos + ln], "big", signed=True))
                    pos += ln
                else:  # int / long / date
                    v, pos = _read_long(data, pos)
                    out[fi].append(v)
        if bytes(data[pos:pos + 16]) != sync:
            raise ValueError(f"{path}: bad block sync marker")
        pos += 16
    arrays = []
    names = []
    for field, vals in zip(fields, out):
        t = field["type"][1]
        names.append(field["name"])
        if isinstance(t, dict) and t.get("logicalType") == "decimal":
            typ = pa.decimal128(t["precision"], t["scale"])
            import decimal as _dec
            scale = t["scale"]
            pyvals = [None if v is None else
                      _dec.Decimal(v).scaleb(-scale) for v in vals]
            arrays.append(pa.array(pyvals, type=typ))
        elif isinstance(t, dict) and t.get("logicalType") == "date":
            arrays.append(pa.array(vals, type=pa.date32()))
        elif t == "int":
            arrays.append(pa.array(vals, type=pa.int32()))
        elif t == "long":
            arrays.append(pa.array(vals, type=pa.int64()))
        elif t == "double":
            arrays.append(pa.array(vals, type=pa.float64()))
        elif t == "string":
            arrays.append(pa.array(vals, type=pa.string()))
        else:
            raise ValueError(f"avro: unsupported field type {t}")
    return pa.Table.from_arrays(arrays, names=names)
