"""Minimal Avro Object Container File codec (write + read).

Format parity with the reference's `--output_format avro` load test
(nds/nds_transcode.py:121-144 via the spark-avro package): the subset of
Avro 1.11 needed for NDS tables — records of nullable primitives with
the standard logical types:

  int32 -> ["null","int"]          date  -> ["null",{"int","date"}]
  int64 -> ["null","long"]         string-> ["null","string"]
  float64 -> ["null","double"]
  decimal(p,s) -> ["null",{"bytes","decimal",precision,scale}]

Self-contained (no external avro dependency is baked into this image);
null codec; one block per row-group.  The WRITE path (what the load
test times) is numpy-vectorized: per column, union-branch varints and
value bytes are built as ragged byte streams and interleaved row-wise
with one scatter — no per-row Python loop (~1M cells/s; the reference's
spark-avro writer is the JVM-vectorized analog).  The read path remains
simple row framing: only avro-input warehouses use it, and parquet is
the performance path on both sides.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import List, Tuple

import numpy as np
import pyarrow as pa

_MAGIC = b"Obj\x01"
_SYNC = bytes(range(16))  # deterministic sync marker
_BLOCK_ROWS = 65536


# -- varint helpers ----------------------------------------------------------


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _write_long(buf: io.BytesIO, n: int) -> None:
    z = _zigzag(int(n)) & (2 ** 64 - 1)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            buf.write(bytes((b | 0x80,)))
        else:
            buf.write(bytes((b,)))
            return


def _read_long(view: memoryview, pos: int) -> Tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = view[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


def _write_bytes(buf: io.BytesIO, data: bytes) -> None:
    _write_long(buf, len(data))
    buf.write(data)


# -- schema mapping ----------------------------------------------------------


def _avro_field(name: str, typ: pa.DataType) -> dict:
    if pa.types.is_int32(typ):
        t: object = "int"
    elif pa.types.is_int64(typ):
        t = "long"
    elif pa.types.is_float64(typ):
        t = "double"
    elif pa.types.is_string(typ) or pa.types.is_large_string(typ):
        t = "string"
    elif pa.types.is_date32(typ):
        t = {"type": "int", "logicalType": "date"}
    elif pa.types.is_decimal(typ):
        t = {"type": "bytes", "logicalType": "decimal",
             "precision": typ.precision, "scale": typ.scale}
    else:
        raise ValueError(f"avro: unsupported arrow type {typ}")
    return {"name": name, "type": ["null", t]}


def _schema_json(at: pa.Table, name: str) -> str:
    return json.dumps({
        "type": "record", "name": name,
        "fields": [_avro_field(f.name, f.type) for f in at.schema]})


# -- write -------------------------------------------------------------------


def _decimal_bytes(unscaled: int) -> bytes:
    """Two's-complement big-endian minimal representation."""
    length = max(1, (unscaled.bit_length() + 8) // 8)
    return int(unscaled).to_bytes(length, "big", signed=True)


def write_table(at: pa.Table, path: str, name: str = "nds") -> None:
    cols = []
    for i, f in enumerate(at.schema):
        col = at.column(i).combine_chunks()
        cols.append((f.type, col))
    with open(path, "wb") as f:
        head = io.BytesIO()
        head.write(_MAGIC)
        meta = {"avro.schema": _schema_json(at, name).encode(),
                "avro.codec": b"null"}
        _write_long(head, len(meta))
        for k, v in meta.items():
            _write_bytes(head, k.encode())
            _write_bytes(head, v)
        _write_long(head, 0)
        head.write(_SYNC)
        f.write(head.getvalue())
        n = at.num_rows
        for start in range(0, max(n, 1), _BLOCK_ROWS):
            count = min(_BLOCK_ROWS, n - start)
            if count <= 0:
                break
            block = io.BytesIO()
            _encode_block(block, cols, start, count)
            framed = io.BytesIO()
            _write_long(framed, count)
            _write_long(framed, block.getbuffer().nbytes)
            f.write(framed.getvalue())
            f.write(block.getvalue())
            f.write(_SYNC)


def _varint_cells(z: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized zigzag-free varint encode of already-zigzagged uint64
    values: returns (flat bytes, per-value byte lengths)."""
    z = z.astype(np.uint64)
    n = len(z)
    mat = np.empty((n, 10), np.uint8)
    more = np.empty((n, 10), bool)
    acc = z.copy()
    for k in range(10):
        mat[:, k] = (acc & np.uint64(0x7F)).astype(np.uint8)
        acc >>= np.uint64(7)
        more[:, k] = acc != 0
    lens = 1 + more.sum(axis=1).astype(np.int64)
    keep = np.arange(10)[None, :] < lens[:, None]
    cont = np.arange(10)[None, :] < (lens - 1)[:, None]
    mat = np.where(cont, mat | 0x80, mat)
    return mat[keep], lens


def _zigzag_np(v: np.ndarray) -> np.ndarray:
    v = v.astype(np.int64)
    return ((v << 1) ^ (v >> 63)).astype(np.uint64)


def _cell_bytes(typ, sl: pa.ChunkedArray, mask: np.ndarray,
                count: int) -> Tuple[np.ndarray, np.ndarray]:
    """(flat value bytes, per-row value lengths) for one column slice;
    null rows contribute zero value bytes (the union branch varint is
    added by the caller)."""
    if isinstance(sl, pa.ChunkedArray):
        sl = sl.combine_chunks()
    if pa.types.is_string(typ) or pa.types.is_large_string(typ):
        arr = sl.cast(pa.large_binary())
        offs = np.frombuffer(arr.buffers()[1], np.int64,
                             count + 1, arr.offset * 8)
        data = np.frombuffer(arr.buffers()[2] or b"", np.uint8)
        lens = (offs[1:] - offs[:-1]).astype(np.int64)
        lens[mask] = 0
        # length varint per row + the utf8 payload, interleaved
        lmat, llens = _varint_cells(_zigzag_np(lens))
        lmat = lmat[np.repeat(~mask, llens)]   # drop null rows' bytes
        return _ragged_interleave([(lmat, np.where(mask, 0, llens)),
                                   (_ragged_take(data, offs, mask), lens)])
    if pa.types.is_float64(typ):
        vals = np.asarray(sl.fill_null(0.0))
        raw = vals.astype("<f8").view(np.uint8).reshape(count, 8)
        lens = np.where(mask, 0, 8).astype(np.int64)
        return raw[~mask].reshape(-1), lens
    if pa.types.is_decimal(typ):
        # unscaled int from the decimal128 storage (16B little-endian
        # two's complement); NDS decimals fit the low signed word —
        # reject anything wider instead of silently truncating
        if typ.precision > 18:
            raise NotImplementedError(
                f"avro encode: decimal precision {typ.precision} > 18 "
                f"needs >64-bit unscaled values")
        arr = sl
        raw = np.frombuffer(arr.buffers()[1], np.int64,
                            2 * count, arr.offset * 16).reshape(count, 2)
        unscaled = np.ascontiguousarray(raw[:, 0])
        unscaled[mask] = 0
        # big-endian two's complement, minimal length (1..9 bytes)
        be = unscaled.astype(">i8").view(np.uint8).reshape(count, 8)
        bits = np.where(unscaled >= 0, unscaled, ~unscaled)
        nbytes = ((64 - _clz64(bits.astype(np.uint64))) // 8 + 1)
        nbytes = np.clip(nbytes, 1, 8).astype(np.int64)
        # 9-byte case (values using the full 64 bits) cannot occur for
        # NDS decimals (precision <= 38 stored in int64 < 2^63)
        keep = np.arange(8)[None, :] >= (8 - nbytes)[:, None]
        vlens = np.where(mask, 0, nbytes)
        val_bytes = be[keep & ~mask[:, None]]
        lmat, llens = _varint_cells(_zigzag_np(nbytes))
        lmat = lmat[np.repeat(~mask, llens)]   # drop null rows' bytes
        return _ragged_interleave([(lmat, np.where(mask, 0, llens)),
                                   (val_bytes, vlens)])
    if pa.types.is_date32(typ):
        vals = np.asarray(sl.cast(pa.int32()).fill_null(0), np.int64)
    else:
        vals = np.asarray(sl.fill_null(0), np.int64)
    mat, lens = _varint_cells(_zigzag_np(vals))
    keep_rows = np.repeat(~mask, lens)
    return mat[keep_rows], np.where(mask, 0, lens)


def _clz64(x: np.ndarray) -> np.ndarray:
    """Count leading zeros of uint64 values (numpy has no clz)."""
    out = np.full(len(x), 64, np.int64)
    cur = x.copy()
    n = np.zeros(len(x), np.int64)
    for shift in (32, 16, 8, 4, 2, 1):
        big = cur >> np.uint64(shift) != 0
        n = np.where(big, n + shift, n)
        cur = np.where(big, cur >> np.uint64(shift), cur)
    return np.where(x == 0, out, 64 - (n + 1))


def _ragged_take(data: np.ndarray, offs: np.ndarray,
                 mask: np.ndarray) -> np.ndarray:
    """Concatenate the byte ranges offs[i]:offs[i+1] for non-null rows."""
    lens = (offs[1:] - offs[:-1]).copy()
    lens[mask] = 0
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, np.uint8)
    starts = offs[:-1]
    pos = np.repeat(starts, lens) + _intra(lens)
    return data[pos]


def _intra(lens: np.ndarray) -> np.ndarray:
    """arange within each ragged cell: [0..l0), [0..l1), ..."""
    total = int(lens.sum())
    cum = np.cumsum(lens) - lens
    return np.arange(total, dtype=np.int64) - np.repeat(cum, lens)


def _ragged_interleave(parts: List[Tuple[np.ndarray, np.ndarray]]
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Interleave K ragged byte streams row-wise: row r's output is the
    concatenation of part_k's r-th cell for k = 0..K-1."""
    all_lens = np.stack([lens for _, lens in parts])      # (K, n)
    row_lens = all_lens.sum(axis=0)
    total = int(row_lens.sum())
    out = np.empty(total, np.uint8)
    row_starts = np.cumsum(row_lens) - row_lens
    prefix = np.zeros_like(all_lens)
    prefix[1:] = np.cumsum(all_lens, axis=0)[:-1]
    for (data, lens), pre in zip(parts, prefix):
        if not len(data):
            continue
        starts = row_starts + pre
        pos = np.repeat(starts, lens) + _intra(lens)
        out[pos] = data
    return out, row_lens


def _encode_block(buf: io.BytesIO, cols, start: int, count: int) -> None:
    """Vectorized row framing: per column, build (union-branch varint +
    value bytes) as ragged byte streams, then interleave all columns
    row-wise with one numpy scatter — no per-row Python loop (the
    reference's spark-avro writer is JVM-vectorized; this is the numpy
    equivalent)."""
    streams: List[Tuple[np.ndarray, np.ndarray]] = []
    for typ, col in cols:
        sl = col.slice(start, count)
        mask = np.asarray(sl.is_null())
        branch = np.where(mask, 0x00, 0x02).astype(np.uint8)  # zigzag 0/1
        streams.append((branch, np.ones(count, np.int64)))
        vals, vlens = _cell_bytes(typ, sl, mask, count)
        streams.append((vals, vlens))
    out, _ = _ragged_interleave(streams)
    buf.write(out.tobytes())


# -- read --------------------------------------------------------------------


def read_table(path: str) -> pa.Table:
    data = memoryview(open(path, "rb").read())
    if bytes(data[:4]) != _MAGIC:
        raise ValueError(f"{path}: not an avro container file")
    pos = 4
    meta = {}
    while True:
        n, pos = _read_long(data, pos)
        if n == 0:
            break
        if n < 0:
            # spec: negative map-block count is followed by the block's
            # byte size (which we don't need when parsing sequentially)
            _size, pos = _read_long(data, pos)
        for _ in range(abs(n)):
            klen, pos = _read_long(data, pos)
            key = bytes(data[pos:pos + klen]).decode()
            pos += klen
            vlen, pos = _read_long(data, pos)
            meta[key] = bytes(data[pos:pos + vlen])
            pos += vlen
    sync = bytes(data[pos:pos + 16])
    pos += 16
    schema = json.loads(meta["avro.schema"].decode())
    if meta.get("avro.codec", b"null") not in (b"null", b""):
        raise ValueError("avro: only the null codec is supported")
    fields = schema["fields"]
    out: List[List] = [[] for _ in fields]
    while pos < len(data):
        count, pos = _read_long(data, pos)
        _size, pos = _read_long(data, pos)
        for _ in range(count):
            for fi, field in enumerate(fields):
                branch, pos = _read_long(data, pos)
                if branch == 0:
                    out[fi].append(None)
                    continue
                t = field["type"][1]
                base = t["type"] if isinstance(t, dict) else t
                if base == "string":
                    ln, pos = _read_long(data, pos)
                    out[fi].append(bytes(data[pos:pos + ln]).decode())
                    pos += ln
                elif base == "double":
                    out[fi].append(
                        struct.unpack("<d", data[pos:pos + 8])[0])
                    pos += 8
                elif base == "bytes":  # decimal
                    ln, pos = _read_long(data, pos)
                    out[fi].append(int.from_bytes(
                        data[pos:pos + ln], "big", signed=True))
                    pos += ln
                else:  # int / long / date
                    v, pos = _read_long(data, pos)
                    out[fi].append(v)
        if bytes(data[pos:pos + 16]) != sync:
            raise ValueError(f"{path}: bad block sync marker")
        pos += 16
    arrays = []
    names = []
    for field, vals in zip(fields, out):
        t = field["type"][1]
        names.append(field["name"])
        if isinstance(t, dict) and t.get("logicalType") == "decimal":
            typ = pa.decimal128(t["precision"], t["scale"])
            import decimal as _dec
            scale = t["scale"]
            pyvals = [None if v is None else
                      _dec.Decimal(v).scaleb(-scale) for v in vals]
            arrays.append(pa.array(pyvals, type=typ))
        elif isinstance(t, dict) and t.get("logicalType") == "date":
            arrays.append(pa.array(vals, type=pa.date32()))
        elif t == "int":
            arrays.append(pa.array(vals, type=pa.int32()))
        elif t == "long":
            arrays.append(pa.array(vals, type=pa.int64()))
        elif t == "double":
            arrays.append(pa.array(vals, type=pa.float64()))
        elif t == "string":
            arrays.append(pa.array(vals, type=pa.string()))
        else:
            raise ValueError(f"avro: unsupported field type {t}")
    return pa.Table.from_arrays(arrays, names=names)
