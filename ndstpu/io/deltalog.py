"""`ndsdelta` — a Delta-Lake-style transaction-log ACID table format.

Second ACID format cell (reference benchmarks BOTH Iceberg and Delta:
nds/nds_power.py:107-121, nds/power_run_gpu_iceberg.template:24-27,
nds/nds_maintenance.py:146-185).  `ndstpu.io.acid` (ndslake) is the
Iceberg analog — immutable snapshot *manifests* + merge-on-read deletion
vectors; this module is the Delta analog with genuinely different
mechanics:

Layout:
    table_dir/
      _delta_log/{N:020d}.json             ordered commits (one JSON
                                           action per line: commitInfo,
                                           metaData, add, remove)
      _delta_log/{N:020d}.checkpoint.json  full state every CHECKPOINT
                                           commits (replay shortcut)
      _delta_log/_last_checkpoint          pointer to newest checkpoint
      part-*.parquet                       immutable data files

Semantics:
  * table state = replay of add/remove actions from the newest
    checkpoint at-or-below the requested version (Delta's protocol),
    NOT a per-version full file list.
  * DELETE is copy-on-write: affected files are rewritten without the
    deleted rows (remove + add in one commit) — the Delta default,
    where ndslake uses deletion vectors.
  * time travel by version or timestamp; RESTORE (rollback) is a new
    commit whose add/remove set reconciles current state to the target
    version, preserving linear history exactly like `RESTORE TABLE ...
    TO VERSION AS OF` (reference rollback parity: nds_rollback.py:37-59).

Commit protocol (docs/ROBUSTNESS.md "Ingest commit protocol"): the
version-numbered commit filename IS the compare-and-swap — commits are
published create-exclusive (fsynced temp + ``os.link``), so two writers
racing to the same version each write a temp and exactly one link wins;
the loser gets a typed, retryable ``CommitConflict`` (transient in the
faults taxonomy) instead of silently clobbering — Delta's optimistic
concurrency, where ndslake serializes under a lock file.  Readers never
observe a half-written log entry, and a SIGKILL mid-commit leaves at
worst an unlinked temp.  Checkpoints and ``_last_checkpoint`` remain
clobbering renames: they are derived, idempotent state.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ndstpu.io import commit as commit_proto

CHECKPOINT_EVERY = 10


def _log_dir(table_dir: str) -> str:
    return os.path.join(table_dir, "_delta_log")


def _commit_path(table_dir: str, version: int) -> str:
    return os.path.join(_log_dir(table_dir), f"{version:020d}.json")


def is_ndsdelta(table_dir: str) -> bool:
    return os.path.isdir(_log_dir(table_dir))


@dataclass
class _State:
    """Replayed table state at one version."""

    version: int
    timestamp: float
    # path -> {"path", "rows"}
    files: Dict[str, Dict] = field(default_factory=dict)
    partition_col: Optional[str] = None
    # relative path of a data file carrying the CURRENT schema (written
    # at create/replace time): empty reads must not guess from an
    # arbitrary historical part file, whose pre-replace schema may differ
    schema_file: Optional[str] = None


def _versions(table_dir: str) -> List[int]:
    out = []
    for name in os.listdir(_log_dir(table_dir)):
        if name.endswith(".json") and not name.endswith(".checkpoint.json"):
            out.append(int(name[:-5]))
    return sorted(out)


def current_version(table_dir: str) -> int:
    vs = _versions(table_dir)
    if not vs:
        raise FileNotFoundError(f"empty delta log in {table_dir}")
    return vs[-1]


def _publish(path: str, lines: List[str]) -> None:
    """Clobbering atomic publish — checkpoints/_last_checkpoint only
    (derived, idempotent state); commits go through _publish_commit."""
    tmp = path + f".tmp.{uuid.uuid4().hex}"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, path)


def _publish_commit(table_dir: str, version: int,
                    lines: List[str]) -> None:
    """Create-exclusive CAS publish of one commit file: fsynced temp +
    ``os.link``, so exactly one of N racing writers claims the version
    and the rest raise ``CommitConflict``."""
    from ndstpu import obs
    path = _commit_path(table_dir, version)
    tmp = path + f".tmp.{uuid.uuid4().hex}"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)
    except FileExistsError:
        obs.inc("engine.ingest.conflicts")
        raise commit_proto.CommitConflict(
            table_dir, version - 1, current_version(table_dir))
    finally:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
    from ndstpu.io.atomic import _fsync_dir
    _fsync_dir(_log_dir(table_dir))
    obs.inc("engine.ingest.commits")


def abort_to_version(table_dir: str, version: int) -> int:
    """Crash-recovery retraction: remove every commit file (and
    checkpoint) above ``version``.  Unlike :func:`rollback_to_version`
    (which appends a NEW replace-all commit — the time-travel path),
    this rewrites the log, so it is only sound when no reader can hold
    the retracted versions: recovering a micro-batch whose journal
    intent never reached done (harness/ingest.py), before serving
    resumes.  Commits unlink highest-first so ``current_version`` never
    crosses a gap mid-abort; stale ``_last_checkpoint`` is dropped
    (replay discovers checkpoints by listing, the pointer is
    advisory).  Retracted data files stay on disk as unreachable
    garbage."""
    _replay(table_dir, version)  # target must be replayable
    ld = _log_dir(table_dir)
    doomed = []
    for name in os.listdir(ld):
        if name.endswith(".checkpoint.json"):
            v = int(name.split(".")[0])
        elif name.endswith(".json"):
            v = int(name[:-5])
        else:
            continue
        if v > version:
            doomed.append((v, name))
    for _v, name in sorted(doomed, reverse=True):
        with contextlib.suppress(OSError):
            os.unlink(os.path.join(ld, name))
    lc = os.path.join(ld, "_last_checkpoint")
    if doomed and os.path.exists(lc):
        try:
            with open(lc) as f:
                if json.load(f).get("version", 0) > version:
                    os.unlink(lc)
        except (ValueError, OSError):
            with contextlib.suppress(OSError):
                os.unlink(lc)
    from ndstpu.io.atomic import _fsync_dir
    _fsync_dir(ld)
    return version


def gc_orphan_manifests(table_dir: str) -> List[str]:
    """Remove leftover ``.tmp.*`` commit files (a crash between temp
    write and ``os.link``).  ndsdelta versions are numbered from
    *published* commit files only, so — unlike ndslake manifests —
    orphan temps never skew numbering; this is pure hygiene."""
    ld = _log_dir(table_dir)
    removed: List[str] = []
    try:
        names = os.listdir(ld)
    except OSError:
        return removed
    for name in names:
        if ".tmp." in name:
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(ld, name))
                removed.append(name)
    return sorted(removed)


def _commit(table_dir: str, version: int, actions: List[Dict],
            operation: str, ts: Optional[float] = None) -> None:
    from ndstpu import faults
    ts = time.time() if ts is None else ts
    lines = [json.dumps({"commitInfo": {
        "timestamp": ts, "operation": operation}})]
    lines += [json.dumps(a) for a in actions]
    # crash-mid-commit probe: a fault here fires with the data files
    # already written but the commit unpublished — the old table state
    # stays current, the orphan parts are garbage, never corruption
    faults.check("ingest.commit", key=table_dir)
    _publish_commit(table_dir, version, lines)
    if version % CHECKPOINT_EVERY == 0 and version > 0:
        st = _replay(table_dir, version)
        cp = os.path.join(_log_dir(table_dir),
                          f"{version:020d}.checkpoint.json")
        _publish(cp, [json.dumps({
            "version": st.version, "timestamp": st.timestamp,
            "partition_col": st.partition_col,
            "schema_file": st.schema_file,
            "files": list(st.files.values())})])
        _publish(os.path.join(_log_dir(table_dir), "_last_checkpoint"),
                 [json.dumps({"version": version})])


def _checkpoint_at_or_below(table_dir: str, version: int) -> Optional[int]:
    best = None
    for name in os.listdir(_log_dir(table_dir)):
        if name.endswith(".checkpoint.json"):
            v = int(name.split(".")[0])
            if v <= version and (best is None or v > best):
                best = v
    return best


def _replay(table_dir: str, version: Optional[int] = None) -> _State:
    """Reconstruct table state by log replay from the newest checkpoint
    at-or-below `version` (the Delta read protocol)."""
    if version is None:
        version = current_version(table_dir)
    start = 0
    st = _State(version, 0.0)
    cp = _checkpoint_at_or_below(table_dir, version)
    if cp is not None:
        with open(os.path.join(_log_dir(table_dir),
                               f"{cp:020d}.checkpoint.json")) as f:
            d = json.loads(f.read().strip())
        st.files = {fm["path"]: fm for fm in d["files"]}
        st.partition_col = d.get("partition_col")
        st.schema_file = d.get("schema_file")
        st.timestamp = d["timestamp"]
        start = cp + 1
    for v in range(start, version + 1):
        path = _commit_path(table_dir, v)
        if not os.path.exists(path):
            raise FileNotFoundError(f"missing delta commit {v}")
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                a = json.loads(line)
                if "commitInfo" in a:
                    st.timestamp = a["commitInfo"]["timestamp"]
                elif "metaData" in a:
                    st.partition_col = a["metaData"].get("partition_col")
                    st.schema_file = a["metaData"].get(
                        "schema_file", st.schema_file)
                elif "add" in a:
                    st.files[a["add"]["path"]] = a["add"]
                elif "remove" in a:
                    st.files.pop(a["remove"]["path"], None)
    return st


def _commit_timestamp(table_dir: str, version: int) -> float:
    with open(_commit_path(table_dir, version)) as f:
        first = json.loads(f.readline())
    return first["commitInfo"]["timestamp"]


def _new_data_file(table_dir: str, at: pa.Table) -> Dict:
    rel = f"part-{uuid.uuid4().hex}.parquet"
    pq.write_table(at, os.path.join(table_dir, rel), compression="snappy")
    return {"path": rel, "rows": at.num_rows}


def create_table(table_dir: str, at: pa.Table,
                 partition_col: Optional[str] = None) -> None:
    """CTAS analog: commit 0 (or a replace-all commit on an existing
    table) with metaData + the initial add."""
    os.makedirs(_log_dir(table_dir), exist_ok=True)
    if partition_col is not None and partition_col in at.column_names:
        at = at.sort_by([(partition_col, "ascending")])
    if _versions(table_dir):
        prev = _replay(table_dir)
        version = prev.version + 1
        removes = [{"remove": {"path": p}} for p in prev.files]
    else:
        version, removes = 0, []
    fm = _new_data_file(table_dir, at)
    actions = removes + [
        {"metaData": {"partition_col": partition_col,
                      "schema_file": fm["path"]}},
        {"add": fm}]
    _commit(table_dir, version, actions, "CREATE OR REPLACE")


def append(table_dir: str, at: pa.Table,
           expected_version: Optional[int] = None) -> None:
    """INSERT INTO: one add action in a new commit.

    ``expected_version`` is the version this write is based on
    (default: current at replay time); when another writer claims
    ``expected_version + 1`` first, the create-exclusive publish
    raises ``CommitConflict``."""
    st = _replay(table_dir, expected_version)
    if st.partition_col is not None and st.partition_col in at.column_names:
        at = at.sort_by([(st.partition_col, "ascending")])
    _commit(table_dir, st.version + 1,
            [{"add": _new_data_file(table_dir, at)}], "WRITE")


def delete_rows(table_dir: str,
                predicate: Callable[[pa.Table], np.ndarray],
                expected_version: Optional[int] = None) -> int:
    """DELETE FROM ... WHERE, copy-on-write: every file with matches is
    rewritten without the deleted rows (remove+add in one commit).
    Returns the number of rows deleted.  ``expected_version`` as in
    :func:`append`."""
    st = _replay(table_dir, expected_version)
    actions: List[Dict] = []
    total = 0
    for fmeta in list(st.files.values()):
        at = pq.read_table(os.path.join(table_dir, fmeta["path"]))
        mask = np.asarray(predicate(at), dtype=bool)
        n = int(mask.sum())
        if n == 0:
            continue
        total += n
        actions.append({"remove": {"path": fmeta["path"]}})
        if n < at.num_rows:
            kept = at.filter(pa.array(~mask))
            actions.append({"add": _new_data_file(table_dir, kept)})
    if actions:
        _commit(table_dir, st.version + 1, actions, "DELETE")
    return total


def read(table_dir: str, version: Optional[int] = None,
         columns: Optional[List[str]] = None) -> pa.Table:
    """Current (or time-travel) view of the table."""
    st = _replay(table_dir, version)
    parts = [pq.read_table(os.path.join(table_dir, fm["path"]),
                           columns=columns)
             for fm in st.files.values()]
    if not parts:
        # fully-deleted table: 0 rows; schema from the metaData-recorded
        # file of the CURRENT table generation (an arbitrary historical
        # part file could carry a pre-replace schema), falling back to
        # any part file for logs created before schema_file existed
        # the recorded file first; if it was cleaned up externally,
        # fall through to scanning historical part files rather than
        # failing the read of an empty table — but WARN, because a
        # historical part can carry a pre-replace schema
        names = [st.schema_file] if st.schema_file else []
        if names and not os.path.exists(
                os.path.join(table_dir, names[0])):
            import warnings
            warnings.warn(
                f"deltalog: recorded schema file {names[0]} missing in "
                f"{table_dir}; falling back to historical part files "
                f"(schema may predate the last table replace)",
                stacklevel=2)
        names += sorted(n for n in os.listdir(table_dir)
                        if n.startswith("part-") and n.endswith(".parquet"))
        for name in names:
            fp = os.path.join(table_dir, name)
            if not os.path.exists(fp):
                continue
            sch = pq.read_schema(fp)
            if columns is not None:
                sch = pa.schema([sch.field(c) for c in columns])
            return sch.empty_table()
        raise FileNotFoundError(f"no data files in {table_dir}")
    return pa.concat_tables(parts) if len(parts) > 1 else parts[0]


def rollback_to_version(table_dir: str, version: int) -> int:
    """RESTORE TABLE ... TO VERSION AS OF: a new commit whose
    add/remove set reconciles the current state to `version`'s
    (history stays linear; nothing is deleted from the log)."""
    cur = _replay(table_dir)
    tgt = _replay(table_dir, version)
    actions: List[Dict] = []
    for p in cur.files:
        if p not in tgt.files:
            actions.append({"remove": {"path": p}})
    for p, fm in tgt.files.items():
        if p not in cur.files:
            actions.append({"add": fm})
    _commit(table_dir, cur.version + 1, actions,
            f"RESTORE(v{version})")
    return cur.version + 1


def rollback_to_timestamp(table_dir: str, ts: float) -> int:
    """RESTORE ... TO TIMESTAMP AS OF (reference parity:
    nds_rollback.py:37-59)."""
    candidates = [v for v in _versions(table_dir)
                  if _commit_timestamp(table_dir, v) <= ts]
    if not candidates:
        raise ValueError(f"no commit at or before {ts}")
    return rollback_to_version(table_dir, max(candidates))
