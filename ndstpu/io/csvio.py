"""Pipe-delimited CSV ingest with explicit schemas.

Reads the native generator's `.dat` chunk files (dsdgen wire format: '|'
separators, trailing '|', empty field == NULL) into pyarrow Tables using the
ndstpu.schema table specs — the analog of the reference's schema'd
``spark.read.csv`` (nds_transcode.py:56-58).
"""

from __future__ import annotations

import glob
import os
from typing import List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv

from ndstpu.schema import TableSchema

_TRAILING = "__trailing__"


def arrow_type(dtype) -> pa.DataType:
    k = dtype.kind
    if k == "int32":
        return pa.int32()
    if k == "int64":
        return pa.int64()
    if k == "float64":
        return pa.float64()
    if k == "decimal":
        return pa.decimal128(max(dtype.precision, dtype.scale + 1),
                             dtype.scale)
    if k == "date":
        return pa.date32()
    if k == "string":
        return pa.string()
    if k == "bool":
        return pa.bool_()
    raise ValueError(f"no arrow type for {dtype}")


def arrow_schema(schema: TableSchema) -> pa.Schema:
    return pa.schema([pa.field(c.name, arrow_type(c.dtype), c.nullable)
                      for c in schema.columns])


def read_dat_file(path: str, schema: TableSchema) -> pa.Table:
    names = [c.name for c in schema.columns] + [_TRAILING]
    types = {c.name: arrow_type(c.dtype) for c in schema.columns}
    types[_TRAILING] = pa.string()
    table = pacsv.read_csv(
        path,
        read_options=pacsv.ReadOptions(column_names=names),
        parse_options=pacsv.ParseOptions(delimiter="|"),
        convert_options=pacsv.ConvertOptions(
            column_types=types, null_values=[""], strings_can_be_null=True),
    )
    return table.drop_columns([_TRAILING])


def read_table_dir(data_dir: str, table: str, schema: TableSchema,
                   pattern: Optional[str] = None) -> pa.Table:
    """Read all chunk files of one table (directory of `.dat` chunks, or a
    single `{table}_*.dat` next to the dir — both layouts the driver
    produces)."""
    tdir = os.path.join(data_dir, table)
    if os.path.isdir(tdir):
        files = sorted(glob.glob(os.path.join(tdir, pattern or "*.dat")))
    else:
        # flat layout: chunk names are {table}_{child}_{parallel}.dat; the
        # [0-9] requirement keeps e.g. "customer" from matching
        # customer_address_1_1.dat
        files = sorted(glob.glob(os.path.join(data_dir,
                                              f"{table}_[0-9]*.dat")))
    if not files:
        raise FileNotFoundError(f"no .dat files for table {table} under "
                                f"{data_dir}")
    parts: List[pa.Table] = [read_dat_file(f, schema) for f in files]
    return pa.concat_tables(parts) if len(parts) > 1 else parts[0]
