"""Shared commit protocol for the ACID table formats (ingest layer 1).

Both table formats originally published new versions under a
single-writer assumption — ndslake swung ``CURRENT`` by atomic rename,
ndsdelta clobbered commit files with ``os.replace`` — which silently
last-writer-wins the moment two streams touch one table.  Continuous
ingest (docs/ROBUSTNESS.md "Ingest commit protocol") needs a journaled
compare-and-swap instead, built from three pieces shared here:

* :class:`CommitConflict` — the typed, *retryable* loser's outcome.
  Classified transient by ndstpu/faults/taxonomy.py, so it flows into
  the PR-5 retry machinery unchanged: reload the table state, rebase
  the write, commit again.
* :func:`commit_lock` — an ``O_CREAT|O_EXCL`` lock file serializing
  the check-version/allocate-version/publish window per table.  A
  writer SIGKILLed inside the window leaves the lock behind; a later
  writer breaks it once it is older than the lease
  (``NDSTPU_COMMIT_LEASE_S``, default 10 s) — safe because commits are
  sub-second and the expected-version check re-runs under the new
  lock either way.
* :func:`journal` — an append-only ``COMMITS.jsonl`` audit trail
  (io/atomic.append_jsonl) written *before* the pointer swing: a
  journal record whose version never became current is the diagnostic
  signature of a crash mid-commit, never a correctness hazard.

Crash atomicity is inherited from io/atomic.py: manifests and commit
files are complete before the single publishing rename/link, so a
``kill -9`` anywhere leaves either the old or the new snapshot
current, never a torn one.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Iterator, Optional

LEASE_ENV = "NDSTPU_COMMIT_LEASE_S"
DEFAULT_LEASE_S = 10.0
LOCK_BASENAME = "COMMIT.lock"
JOURNAL_BASENAME = "COMMITS.jsonl"


class CommitConflict(RuntimeError):
    """Another writer advanced the table between this writer's snapshot
    load and its commit publish.  Transient by taxonomy: the correct
    response is reload + rebase + retry, which faults/retry.py does for
    any caller inside run_with_retry and the micro-batch ingestor
    (harness/ingest.py) does batch-wide."""

    def __init__(self, table_dir: str, expected: Optional[int],
                 found: Optional[int]):
        exp = "<none>" if expected is None else f"v{expected}"
        fnd = "<none>" if found is None else f"v{found}"
        super().__init__(
            f"commit conflict in {table_dir}: write based on {exp} but "
            f"the table is at {fnd} — reload and retry")
        self.table_dir = table_dir
        self.expected = expected
        self.found = found


def lease_s() -> float:
    env = os.environ.get(LEASE_ENV)
    if env:
        try:
            return max(float(env), 0.1)
        except ValueError:
            pass
    return DEFAULT_LEASE_S


@contextlib.contextmanager
def commit_lock(meta_dir: str) -> Iterator[str]:
    """Exclusive per-table commit section via an ``O_EXCL`` lock file
    under the table's metadata dir.  Progress is guaranteed without a
    timeout: a dead holder's lock goes stale after the lease and is
    broken by the next writer (two breakers may race on the unlink;
    both then race on ``O_EXCL``, which exactly one wins)."""
    os.makedirs(meta_dir, exist_ok=True)
    path = os.path.join(meta_dir, LOCK_BASENAME)
    while True:
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            break
        except FileExistsError:
            try:
                age = time.time() - os.stat(path).st_mtime
            except OSError:
                continue  # holder released between open and stat
            if age > lease_s():
                with contextlib.suppress(OSError):
                    os.unlink(path)
                continue
            time.sleep(0.005)
    try:
        os.write(fd, json.dumps(
            {"pid": os.getpid(), "ts": time.time()}).encode())
        os.close(fd)
        yield path
    finally:
        with contextlib.suppress(OSError):
            os.unlink(path)


def journal_path(meta_dir: str) -> str:
    return os.path.join(meta_dir, JOURNAL_BASENAME)


def journal(meta_dir: str, record: dict) -> None:
    """Append one commit-audit record (durable, torn-tail tolerant)."""
    from ndstpu.io import atomic
    atomic.append_jsonl(journal_path(meta_dir), record)
