"""Load test: transcode raw pipe-CSV into the warehouse format, timed.

Parity with the reference transcoder (/root/reference/nds/nds_transcode.py):
per-table conversion timing, date-sk partitioning + within-partition sort for
the 7 fact tables (nds_transcode.py:44-53,123-131), single output file for
dimensions (the coalesce(1) analog), `--floats` decimal switch, `--update`
refresh-data mode, append/overwrite/ignore output modes, and a load report
whose "Load Test Time" / "RNGSEED used:" lines follow the same parse contract
(nds_transcode.py:196-220, consumed by nds_bench.py:60-90).  RNGSEED is the
load end-timestamp `%m%d%H%M%S%f` truncated — TPC-DS spec 4.3.1 chaining.

Output formats: parquet (primary TPU path), orc, avro, csv, json, and `ndslake` —
this framework's ACID snapshot table format (Iceberg/Delta analog, see
ndstpu.io.acid) used by the data-maintenance phase.
"""

from __future__ import annotations

import argparse
import os
import shutil
import time
from collections import OrderedDict
from datetime import datetime

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from ndstpu import faults
from ndstpu import schema as nds_schema
from ndstpu.io import atomic, csvio

FACT_PARTITION = nds_schema.TABLE_PARTITIONING


def _write_partitioned(at: pa.Table, out_dir: str, part_col: str,
                       compression: str) -> None:
    """Date-partitioned parquet write: sort by the partition key, then one
    file per key directory (hive-style `col=value/`), nulls in `col=__NULL__/`.
    Unique basenames make repeated appends additive rather than clobbering."""
    import uuid

    import pyarrow.dataset as ds

    at = at.sort_by([(part_col, "ascending")])
    ds.write_dataset(
        at, out_dir,
        format="parquet",
        partitioning=ds.partitioning(
            pa.schema([at.schema.field(part_col)]), flavor="hive"),
        existing_data_behavior="overwrite_or_ignore",
        basename_template="part-" + uuid.uuid4().hex + "-{i}.parquet",
        max_partitions=4096,  # day-grain partitioning: ~1800+NULL dirs
        file_options=ds.ParquetFileFormat().make_write_options(
            compression=compression),
    )


def _write_single(at: pa.Table, out_dir: str, table: str, fmt: str,
                  compression: str) -> None:
    import uuid

    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"{table}.{fmt}")
    if os.path.exists(path):  # append mode: add a second uniquely-named file
        path = os.path.join(out_dir, f"{table}-{uuid.uuid4().hex}.{fmt}")
    if fmt == "parquet":
        pq.write_table(at, path, compression=compression)
    elif fmt == "orc":
        import pyarrow.orc as paorc
        paorc.write_table(at, path)
    elif fmt == "csv":
        import pyarrow.csv as pacsv
        pacsv.write_csv(at, path)
    elif fmt == "json":
        import pandas as pd  # noqa: F401
        at.to_pandas().to_json(path, orient="records", lines=True,
                               date_format="iso")
    elif fmt == "avro":
        from ndstpu.io import avroio
        avroio.write_table(at, path, name=table)
    else:
        raise ValueError(f"unsupported format {fmt}")


def _success_marker(args, table: str) -> str:
    return os.path.join(args.output_prefix, table, "_SUCCESS")


def transcode_table(args, table: str, tschema) -> float:
    """Convert one table; returns elapsed seconds (cf. reference
    nds_transcode.py:179-194 timeit loop).

    Crash safety: a ``_SUCCESS`` marker is written inside the table dir
    only after the full write completes (loaders glob by extension, so
    the marker is invisible to them).  ``--resume`` skips marked tables;
    an UNMARKED existing dir on resume is a torn write from a killed
    run and is rebuilt from scratch."""
    start = time.time()
    out_root = os.path.join(args.output_prefix, table)
    marker = _success_marker(args, table)
    resume = getattr(args, "resume", False)
    if resume and os.path.exists(marker):
        print(f"[resume] {table}: _SUCCESS marker present — skipping")
        return 0.0
    faults.check("io.write", key=table)
    at = csvio.read_table_dir(args.input_prefix, table, tschema)
    if resume and os.path.exists(out_root) and \
            not os.path.exists(marker):
        # torn write from the killed run: rebuild the whole table
        print(f"[resume] {table}: incomplete output (no _SUCCESS) — "
              f"rebuilding")
        shutil.rmtree(out_root)
    if os.path.exists(out_root):
        if args.output_mode == "overwrite":
            shutil.rmtree(out_root)
        elif args.output_mode == "ignore":
            return 0.0
        elif args.output_mode == "errorifexists":
            raise RuntimeError(f"output for {table} already exists")
        # append: fall through, dataset write adds files
    if args.output_format in ("ndslake", "ndsdelta"):
        from ndstpu.io import lake
        if os.path.exists(out_root) and lake.is_lake(out_root):
            have = lake.detect(out_root)
            if have is not lake.module_for(args.output_format):
                raise RuntimeError(
                    f"{out_root} already holds the other ACID format; "
                    f"refusing to append {args.output_format} data into "
                    f"it (use --output_mode overwrite)")
            lake.append(out_root, at)  # append mode
        else:
            lake.create_table(args.output_format, out_root, at,
                              partition_col=FACT_PARTITION.get(table))
    elif table in FACT_PARTITION and args.output_format == "parquet":
        _write_partitioned(at, out_root, FACT_PARTITION[table],
                           args.compression)
    else:
        _write_single(at, out_root, table, args.output_format,
                      args.compression)
    _build_global_dicts(args, table, out_root, at)
    atomic.atomic_write_text(marker, "")
    return time.time() - start


def _build_global_dicts(args, table: str, out_root: str, at) -> None:
    """Build/grow the table's global string-dictionary sidecar
    (ndstpu/io/gdict.py) after the data write, before the _SUCCESS
    marker — so a marked table always has a sidecar covering it.
    Append mode unions with the existing sidecar (value set grows
    append-only); ACID formats stamp entries with the commit version
    so snapshot-pinned readers can select the dict matching their
    pin."""
    from ndstpu.io import gdict
    if not gdict.enabled():
        return
    uniques = gdict.string_uniques_arrow(at)
    if not uniques:
        return
    table_version = None
    if args.output_format in ("ndslake", "ndsdelta"):
        from ndstpu.io import lake
        table_version = lake.current_version(out_root)
    gdict.update_sidecar(out_root, table, uniques,
                         table_version=table_version)


def transcode(args) -> None:
    start_time = datetime.now()
    use_decimal = not args.floats
    if args.update:
        schemas = nds_schema.get_maintenance_schemas(use_decimal)
        # delete-date tables stay raw CSV; DM reads them directly
        schemas = {t: s for t, s in schemas.items()
                   if t not in ("delete", "inventory_delete")}
    else:
        schemas = nds_schema.get_schemas(use_decimal)
    if args.tables:
        keep = args.tables.split(",")
        missing = [t for t in keep if t not in schemas]
        if missing:
            raise ValueError(f"unknown tables: {missing}")
        schemas = {t: schemas[t] for t in keep}

    results: "OrderedDict[str, float]" = OrderedDict()
    for table, tschema in schemas.items():
        print(f"transcoding {table} ...")
        results[table] = transcode_table(args, table, tschema)

    end_time = datetime.now()
    delta = (end_time - start_time).total_seconds()
    end_time_formatted = end_time.strftime("%m%d%H%M%S%f")[:-5]
    report = []
    report.append(f"Load Test Time: {delta} seconds")
    report.append(f"Load Test Finished at: {end_time}")
    report.append(f"RNGSEED used: {end_time_formatted}")
    for table, duration in results.items():
        report.append("Time to convert '%s' was %.04fs" % (table, duration))
    report.append("")
    report.append("Engine configuration follows:")
    report.append(f"output_format={args.output_format}")
    report.append(f"compression={args.compression}")
    report.append(f"use_decimal={use_decimal}")
    text = "\n".join(report) + "\n"
    print(text)
    if args.report_file:
        atomic.atomic_write_text(args.report_file, text)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="NDS load test (CSV -> warehouse)")
    p.add_argument("--input_prefix", required=True,
                   help="directory holding per-table raw .dat dirs")
    p.add_argument("--output_prefix", required=True,
                   help="warehouse output directory")
    p.add_argument("--report_file", default="load_report.txt",
                   help="load test report path")
    p.add_argument("--output_format", default="parquet",
                   choices=["parquet", "orc", "avro", "csv", "json",
                            "ndslake", "ndsdelta"])
    p.add_argument("--output_mode", default="overwrite",
                   choices=["overwrite", "append", "ignore", "errorifexists"])
    p.add_argument("--tables", help="comma-separated subset of tables")
    p.add_argument("--compression", default="snappy",
                   help="parquet compression codec")
    p.add_argument("--floats", action="store_true",
                   help="use double instead of decimal for money columns")
    p.add_argument("--update", action="store_true",
                   help="transcode refresh (maintenance staging) data")
    p.add_argument("--resume", action="store_true",
                   help="crash-safe resume: skip tables whose _SUCCESS "
                        "marker exists; rebuild tables whose output dir "
                        "exists without one (torn write from a killed "
                        "run)")
    return p


if __name__ == "__main__":
    transcode(build_parser().parse_args())
