"""Warehouse-wide frozen global string dictionaries.

Per-column string dictionaries used to be an accident of whatever rows
a ``from_arrow`` call happened to see: two chunks of one table, or two
snapshots of one lake table, encoded the same string to different
codes.  That per-call scope was the single wall across three
north-star axes (ROADMAP item 3): SPMD string join keys needed a
build-dictionary translation, chunk sources rejected string tables
outright, and string binds could not ride the parameterized compile
cache.

This module gives every string column of a transcoded table ONE
authoritative sorted dictionary, persisted as a sidecar artifact next
to the table's data files (``_GLOBAL_DICTS.json`` — invisible to the
loaders, which glob by extension, exactly like ``_SUCCESS``):

* **frozen + content-hashed** — a dictionary version never mutates;
  its identity is the hash of its value list, so two columns (or two
  processes) holding the same hash hold the same code space and codes
  compare directly with no translation;
* **sorted per version** — the engine's string machinery assumes
  ``code order == lexical order`` everywhere (searchsorted
  translation, ORDER BY on codes, range predicates, merged-dict
  literals), so growth produces a NEW fully sorted version rather than
  appending values to the old one.  Codes are stable *within* a
  version; the value SET grows append-only across versions;
* **versioned with the table** — each entry is stamped with the lake
  table version whose commit introduced it (``table_version``; None
  for non-ACID layouts written once at transcode).  A snapshot-pinned
  reader selects the newest entry at-or-before its pin, so pinned
  queries decode with the dictionary matching their pin, and
  ``lake.warehouse_epoch`` — a hash over per-table CURRENT versions —
  already keys every epoch-invalidated cache, so dict growth rides
  the existing invalidation for free.

Kill switch: ``NDSTPU_GLOBAL_DICTS=0`` disables the layer everywhere
(loaders fall back to per-call dictionaries, chunk sources reject
string columns again, joins translate through merged dictionaries).
``scripts/dict_audit.py`` sweeps sidecar sizes + corpus coverage into
the ``DICT_AUDIT.*`` artifacts.

Counters (docs/OBSERVABILITY.md): ``engine.dict.lookups`` /
``engine.dict.misses`` per bind-time value lookup,
``engine.dict.bytes`` encoded bytes of loaded dictionaries,
``engine.dict.version_loads`` per sidecar entry materialized.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

#: sidecar file name inside a table directory (next to _SUCCESS)
GDICT_FILE = "_GLOBAL_DICTS.json"

#: sidecar schema version
FORMAT = 1


def enabled() -> bool:
    """NDSTPU_GLOBAL_DICTS=0 kills the global-dictionary layer."""
    return os.environ.get("NDSTPU_GLOBAL_DICTS", "1") not in ("", "0")


def _obs_inc(name: str, value: float = 1) -> None:
    from ndstpu import obs
    obs.inc(name, value)


def content_hash(values: Sequence[str]) -> str:
    """Stable identity of a dictionary's value list.  Equal hashes mean
    equal code spaces: codes compare across tables with no translation."""
    h = hashlib.sha256()
    for v in values:
        h.update(str(v).encode("utf-8"))
        h.update(b"\x1f")
    return "d" + h.hexdigest()[:16]


def dictionary_nbytes(values) -> int:
    """Actual encoded byte size of a dictionary's text (UTF-8) — what
    the strings really cost, vs the 8 B/entry object-pointer estimate
    that undercounted wide string columns (engine/spine.py)."""
    if values is None:
        return 0
    return int(sum(len(str(v).encode("utf-8")) for v in values))


@dataclasses.dataclass(frozen=True)
class GlobalDict:
    """One frozen, sorted dictionary version for one table column."""

    table: str
    column: str
    values: np.ndarray            # sorted object array of unique strings
    hash: str                     # content_hash(values)
    version: int                  # ordinal in the sidecar journal
    table_version: Optional[int]  # lake version that introduced it

    def __len__(self) -> int:
        return len(self.values)

    def lookup(self, value) -> Optional[int]:
        """Code of ``value`` in this dictionary, or None when absent.
        This is the bind-time path for scalar dict-code params, so it
        ticks the lookup/miss counters."""
        _obs_inc("engine.dict.lookups")
        v = str(value)
        n = len(self.values)
        if n:
            pos = int(np.searchsorted(self.values.astype(str), v))
            if pos < n and str(self.values[pos]) == v:
                return pos
        _obs_inc("engine.dict.misses")
        return None

    @property
    def nbytes(self) -> int:
        return dictionary_nbytes(self.values)


# ---------------------------------------------------------------------------
# sidecar I/O
# ---------------------------------------------------------------------------


def sidecar_path(table_dir: str) -> str:
    return os.path.join(table_dir, GDICT_FILE)


def _read_sidecar(table_dir: str) -> Optional[dict]:
    path = sidecar_path(table_dir)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("format") != FORMAT:
        return None
    return doc


def _write_sidecar(table_dir: str, doc: dict) -> None:
    from ndstpu.io import atomic
    atomic.atomic_write_text(sidecar_path(table_dir),
                             json.dumps(doc, indent=1, sort_keys=True))


def has_sidecar(table_dir: str) -> bool:
    return _read_sidecar(table_dir) is not None


def _select_entry(entries: List[dict],
                  pin_table_version: Optional[int]) -> Optional[dict]:
    """Newest entry visible at ``pin_table_version`` (None = newest
    overall).  Entries without a table stamp (plain-parquet transcode)
    are visible at every pin."""
    best = None
    for ent in entries:
        tv = ent.get("table_version")
        if pin_table_version is not None and tv is not None \
                and tv > pin_table_version:
            continue
        if best is None or ent["version"] > best["version"]:
            best = ent
    return best


def table_dicts(table_dir: str, table: Optional[str] = None,
                pin_table_version: Optional[int] = None
                ) -> Dict[str, GlobalDict]:
    """Load the frozen dictionaries for one table, selecting per column
    the version matching ``pin_table_version`` (snapshot-pinned chunk
    sources) or the newest (live loads)."""
    if not enabled():
        return {}
    doc = _read_sidecar(table_dir)
    if doc is None:
        return {}
    tname = table or doc.get("table") or os.path.basename(
        os.path.normpath(table_dir))
    out: Dict[str, GlobalDict] = {}
    for col, entries in sorted((doc.get("columns") or {}).items()):
        ent = _select_entry(entries, pin_table_version)
        if ent is None:
            continue
        values = np.asarray(ent["values"], dtype=object)
        gd = GlobalDict(table=tname, column=col, values=values,
                        hash=ent.get("hash") or content_hash(values),
                        version=int(ent["version"]),
                        table_version=ent.get("table_version"))
        _obs_inc("engine.dict.version_loads")
        _obs_inc("engine.dict.bytes", gd.nbytes)
        out[col] = gd
    return out


# ---------------------------------------------------------------------------
# build / growth
# ---------------------------------------------------------------------------


def string_uniques_arrow(at) -> Dict[str, np.ndarray]:
    """Sorted unique non-null values per string column of a pyarrow
    Table (the transcode-time build input)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    out: Dict[str, np.ndarray] = {}
    for i, name in enumerate(at.column_names):
        arr = at.column(i)
        typ = arr.type
        if pa.types.is_dictionary(typ):
            typ = typ.value_type
        if not (pa.types.is_string(typ) or pa.types.is_large_string(typ)):
            continue
        col = at.column(i)
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        if pa.types.is_dictionary(col.type):
            col = col.cast(col.type.value_type)
        uniq = pc.unique(col.drop_null()).to_pylist()
        vals = np.asarray(sorted(str(v) for v in uniq), dtype=object)
        out[name] = vals
    return out


def update_sidecar(table_dir: str, table: str,
                   values_by_col: Dict[str, np.ndarray],
                   table_version: Optional[int] = None) -> Dict[str, dict]:
    """Merge new column values into the sidecar: each column whose
    value SET actually grew gets a fresh sorted version entry stamped
    with ``table_version``; unchanged columns keep their newest entry.
    Idempotent — re-running with the same inputs writes nothing new."""
    doc = _read_sidecar(table_dir) or {
        "format": FORMAT, "table": table, "columns": {}}
    cols = doc.setdefault("columns", {})
    changed = False
    applied: Dict[str, dict] = {}
    for col, vals in sorted(values_by_col.items()):
        new_vals = [str(v) for v in vals]
        entries = cols.setdefault(col, [])
        latest = _select_entry(entries, None)
        if latest is not None:
            union = sorted(set(latest["values"]) | set(new_vals))
            if union == list(latest["values"]):
                applied[col] = latest
                continue
            new_vals = union
        else:
            new_vals = sorted(set(new_vals))
        ent = {"version": len(entries),
               "table_version": table_version,
               "hash": content_hash(new_vals),
               "values": new_vals}
        entries.append(ent)
        applied[col] = ent
        changed = True
    if changed or not os.path.exists(sidecar_path(table_dir)):
        os.makedirs(table_dir, exist_ok=True)
        _write_sidecar(table_dir, doc)
    return applied


def grow_for_table(table_dir: str, table: Optional[str] = None,
                   table_version: Optional[int] = None) -> Dict[str, dict]:
    """Grow the sidecar to cover the table's CURRENT committed rows —
    the post-commit ingest hook (harness/ingest.py).  Append-only per
    commit: only columns whose value set actually grew get a new
    version, stamped with the commit's lake version.  Idempotent, so a
    retried or resumed batch converges on the same sidecar."""
    if not enabled():
        return {}
    from ndstpu.io import lake
    tname = table or os.path.basename(os.path.normpath(table_dir))
    if not lake.is_lake(table_dir):
        return {}
    if table_version is None:
        table_version = lake.current_version(table_dir)
    at = lake.read(table_dir)
    vals = string_uniques_arrow(at)
    if not vals:
        return {}
    return update_sidecar(table_dir, tname, vals,
                          table_version=table_version)


def retract(table_dir: str, table_version: int) -> int:
    """Drop dictionary versions introduced after ``table_version`` —
    the crash-recovery twin of ``lake.abort_to_version`` (ingest
    restore).  Sound for the same reason the lake retraction is: no
    pin can hold an un-done batch's commits, so nothing can still
    reference the dropped versions.  Returns the number of entries
    dropped."""
    doc = _read_sidecar(table_dir)
    if doc is None:
        return 0
    dropped = 0
    for col, entries in list((doc.get("columns") or {}).items()):
        keep = [e for e in entries
                if e.get("table_version") is None
                or e["table_version"] <= table_version]
        dropped += len(entries) - len(keep)
        doc["columns"][col] = keep
    if dropped:
        _write_sidecar(table_dir, doc)
    return dropped
