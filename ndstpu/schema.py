"""Table schema registry for the NDS-TPU decision-support benchmark.

Covers the 25 source tables and 12 data-maintenance (staging/refresh) tables of
the TPC-DS-derived NDS schema, with the same column names, nullability and
logical types as the reference harness (see /root/reference/nds/nds_schema.py:49-716),
including its two policy switches:

  * ``use_decimal`` — money columns are exact DECIMAL(p,s) or DOUBLE
    (reference: nds_schema.py:43-47).  In this framework DECIMAL is executed on
    TPU as scale-shifted int64 ("scaled integer"), DOUBLE as float64 on the CPU
    interpreter / float32 accumulating in float64-emulation on TPU.
  * identifier width — surrogate keys are int32 except ``ss_ticket_number`` /
    ``sr_ticket_number`` which must be int64 at large scale factors
    (reference rationale: nds_schema.py:61-65, 328-331).

Schemas are declared in a compact text DSL (one column per line:
``name  type  [!]``) rather than nested constructor calls; they are parsed once
at import into `TableSchema` objects and exposed via :func:`get_schemas` /
:func:`get_maintenance_schemas` with the same signatures as the reference.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Logical types
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DType:
    """Logical column type.

    kind: one of 'int32', 'int64', 'float64', 'decimal', 'date', 'string'
    For 'decimal', precision/scale are set.  For fixed/var strings, length
    carries the declared CHAR(n)/VARCHAR(n) width (informational — storage is
    dictionary-encoded regardless).
    """

    kind: str
    precision: int = 0
    scale: int = 0
    length: int = 0
    fixed: bool = False  # CHAR(n) vs VARCHAR(n)

    @property
    def is_numeric(self) -> bool:
        return self.kind in ("int32", "int64", "float64", "decimal")

    @property
    def is_string(self) -> bool:
        return self.kind == "string"

    def __str__(self) -> str:
        if self.kind == "decimal":
            return f"decimal({self.precision},{self.scale})"
        if self.kind == "string" and self.length:
            return f"{'char' if self.fixed else 'varchar'}({self.length})"
        return self.kind


INT32 = DType("int32")
INT64 = DType("int64")
FLOAT64 = DType("float64")
DATE = DType("date")
STRING = DType("string")
BOOL = DType("bool")


def decimal(precision: int, scale: int) -> DType:
    return DType("decimal", precision=precision, scale=scale)


def char(n: int) -> DType:
    return DType("string", length=n, fixed=True)


def varchar(n: int) -> DType:
    return DType("string", length=n, fixed=False)


@dataclasses.dataclass(frozen=True)
class ColumnSpec:
    name: str
    dtype: DType
    nullable: bool = True


@dataclasses.dataclass(frozen=True)
class TableSchema:
    name: str
    columns: Tuple[ColumnSpec, ...]

    @property
    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column(self, name: str) -> ColumnSpec:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(f"{self.name}: no column {name}")

    def __len__(self) -> int:
        return len(self.columns)


# ---------------------------------------------------------------------------
# DSL parsing
# ---------------------------------------------------------------------------

_TYPE_RE = re.compile(
    r"^(?P<base>int|long|date|string|char|varchar|dec)(\((?P<args>[\d,]+)\))?$"
)


def _parse_type(tok: str, use_decimal: bool) -> DType:
    m = _TYPE_RE.match(tok)
    if not m:
        raise ValueError(f"bad type token: {tok}")
    base, args = m.group("base"), m.group("args")
    if base == "int":
        return INT32
    if base == "long":
        return INT64
    if base == "date":
        return DATE
    if base == "string":
        return STRING
    if base == "char":
        return char(int(args))
    if base == "varchar":
        return varchar(int(args))
    if base == "dec":
        p, s = (int(x) for x in args.split(","))
        return decimal(p, s) if use_decimal else FLOAT64
    raise ValueError(tok)


def _parse_table(name: str, body: str, use_decimal: bool) -> TableSchema:
    cols = []
    for line in body.strip().splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        cname, ctype = parts[0], parts[1]
        nullable = not (len(parts) > 2 and parts[2] == "!")
        cols.append(ColumnSpec(cname, _parse_type(ctype, use_decimal), nullable))
    return TableSchema(name, tuple(cols))


# ---------------------------------------------------------------------------
# Source table definitions (25 tables)
# ---------------------------------------------------------------------------
# Identifier policy: surrogate keys int32; ss_/sr_ticket_number int64
# (reference: nds_schema.py:61-65,328-331).  Numeric measures are declared
# `long` to match the reference's LongType for counts/quantities.

_SOURCE_TABLES: Dict[str, str] = {
    "customer_address": """
        ca_address_sk       int         !
        ca_address_id       char(16)    !
        ca_street_number    char(10)
        ca_street_name      varchar(60)
        ca_street_type      char(15)
        ca_suite_number     char(10)
        ca_city             varchar(60)
        ca_county           varchar(30)
        ca_state            char(2)
        ca_zip              char(10)
        ca_country          varchar(20)
        ca_gmt_offset       dec(5,2)
        ca_location_type    char(20)
    """,
    "customer_demographics": """
        cd_demo_sk              int     !
        cd_gender               char(1)
        cd_marital_status       char(1)
        cd_education_status     char(20)
        cd_purchase_estimate    long
        cd_credit_rating        char(10)
        cd_dep_count            long
        cd_dep_employed_count   long
        cd_dep_college_count    long
    """,
    "date_dim": """
        d_date_sk           int         !
        d_date_id           char(16)    !
        d_date              date
        d_month_seq         long
        d_week_seq          long
        d_quarter_seq       long
        d_year              long
        d_dow               long
        d_moy               long
        d_dom               long
        d_qoy               long
        d_fy_year           long
        d_fy_quarter_seq    long
        d_fy_week_seq       long
        d_day_name          char(9)
        d_quarter_name      char(6)
        d_holiday           char(1)
        d_weekend           char(1)
        d_following_holiday char(1)
        d_first_dom         long
        d_last_dom          long
        d_same_day_ly       long
        d_same_day_lq       long
        d_current_day       char(1)
        d_current_week      char(1)
        d_current_month     char(1)
        d_current_quarter   char(1)
        d_current_year      char(1)
    """,
    "warehouse": """
        w_warehouse_sk      int         !
        w_warehouse_id      char(16)    !
        w_warehouse_name    varchar(20)
        w_warehouse_sq_ft   long
        w_street_number     char(10)
        w_street_name       varchar(60)
        w_street_type       char(15)
        w_suite_number      char(10)
        w_city              varchar(60)
        w_county            varchar(30)
        w_state             char(2)
        w_zip               char(10)
        w_country           varchar(20)
        w_gmt_offset        dec(5,2)
    """,
    "ship_mode": """
        sm_ship_mode_sk     int         !
        sm_ship_mode_id     char(16)    !
        sm_type             char(30)
        sm_code             char(10)
        sm_carrier          char(20)
        sm_contract         char(20)
    """,
    "time_dim": """
        t_time_sk           int         !
        t_time_id           char(16)    !
        t_time              long        !
        t_hour              long
        t_minute            long
        t_second            long
        t_am_pm             char(2)
        t_shift             char(20)
        t_sub_shift         char(20)
        t_meal_time         char(20)
    """,
    "reason": """
        r_reason_sk         int         !
        r_reason_id         char(16)    !
        r_reason_desc       char(100)
    """,
    "income_band": """
        ib_income_band_sk   int         !
        ib_lower_bound      long
        ib_upper_bound      long
    """,
    "item": """
        i_item_sk           int         !
        i_item_id           char(16)    !
        i_rec_start_date    date
        i_rec_end_date      date
        i_item_desc         varchar(200)
        i_current_price     dec(7,2)
        i_wholesale_cost    dec(7,2)
        i_brand_id          long
        i_brand             char(50)
        i_class_id          long
        i_class             char(50)
        i_category_id       long
        i_category          char(50)
        i_manufact_id       long
        i_manufact          char(50)
        i_size              char(20)
        i_formulation       char(20)
        i_color             char(20)
        i_units             char(10)
        i_container         char(10)
        i_manager_id        long
        i_product_name      char(50)
    """,
    "store": """
        s_store_sk          int         !
        s_store_id          char(16)    !
        s_rec_start_date    date
        s_rec_end_date      date
        s_closed_date_sk    int
        s_store_name        varchar(50)
        s_number_employees  long
        s_floor_space       long
        s_hours             char(20)
        s_manager           varchar(40)
        s_market_id         long
        s_geography_class   varchar(100)
        s_market_desc       varchar(100)
        s_market_manager    varchar(40)
        s_division_id       long
        s_division_name     varchar(50)
        s_company_id        long
        s_company_name      varchar(50)
        s_street_number     varchar(10)
        s_street_name       varchar(60)
        s_street_type       char(15)
        s_suite_number      char(10)
        s_city              varchar(60)
        s_county            varchar(30)
        s_state             char(2)
        s_zip               char(10)
        s_country           varchar(20)
        s_gmt_offset        dec(5,2)
        s_tax_precentage    dec(5,2)
    """,
    "call_center": """
        cc_call_center_sk   int         !
        cc_call_center_id   char(16)    !
        cc_rec_start_date   date
        cc_rec_end_date     date
        cc_closed_date_sk   int
        cc_open_date_sk     int
        cc_name             varchar(50)
        cc_class            varchar(50)
        cc_employees        long
        cc_sq_ft            long
        cc_hours            char(20)
        cc_manager          varchar(40)
        cc_mkt_id           long
        cc_mkt_class        char(50)
        cc_mkt_desc         varchar(100)
        cc_market_manager   varchar(40)
        cc_division         long
        cc_division_name    varchar(50)
        cc_company          long
        cc_company_name     char(50)
        cc_street_number    char(10)
        cc_street_name      varchar(60)
        cc_street_type      char(15)
        cc_suite_number     char(10)
        cc_city             varchar(60)
        cc_county           varchar(30)
        cc_state            char(2)
        cc_zip              char(10)
        cc_country          varchar(20)
        cc_gmt_offset       dec(5,2)
        cc_tax_percentage   dec(5,2)
    """,
    "customer": """
        c_customer_sk           int         !
        c_customer_id           char(16)    !
        c_current_cdemo_sk      int
        c_current_hdemo_sk      int
        c_current_addr_sk       int
        c_first_shipto_date_sk  int
        c_first_sales_date_sk   int
        c_salutation            char(10)
        c_first_name            char(20)
        c_last_name             char(30)
        c_preferred_cust_flag   char(1)
        c_birth_day             long
        c_birth_month           long
        c_birth_year            long
        c_birth_country         varchar(20)
        c_login                 char(13)
        c_email_address         char(50)
        c_last_review_date_sk   int
    """,
    "web_site": """
        web_site_sk         int         !
        web_site_id         char(16)    !
        web_rec_start_date  date
        web_rec_end_date    date
        web_name            varchar(50)
        web_open_date_sk    int
        web_close_date_sk   int
        web_class           varchar(50)
        web_manager         varchar(40)
        web_mkt_id          long
        web_mkt_class       varchar(50)
        web_mkt_desc        varchar(100)
        web_market_manager  varchar(40)
        web_company_id      long
        web_company_name    char(50)
        web_street_number   char(10)
        web_street_name     varchar(60)
        web_street_type     char(15)
        web_suite_number    char(10)
        web_city            varchar(60)
        web_county          varchar(30)
        web_state           char(2)
        web_zip             char(10)
        web_country         varchar(20)
        web_gmt_offset      dec(5,2)
        web_tax_percentage  dec(5,2)
    """,
    "store_returns": """
        sr_returned_date_sk     int
        sr_return_time_sk       int
        sr_item_sk              int     !
        sr_customer_sk          int
        sr_cdemo_sk             int
        sr_hdemo_sk             int
        sr_addr_sk              int
        sr_store_sk             int
        sr_reason_sk            int
        sr_ticket_number        long    !
        sr_return_quantity      long
        sr_return_amt           dec(7,2)
        sr_return_tax           dec(7,2)
        sr_return_amt_inc_tax   dec(7,2)
        sr_fee                  dec(7,2)
        sr_return_ship_cost     dec(7,2)
        sr_refunded_cash        dec(7,2)
        sr_reversed_charge      dec(7,2)
        sr_store_credit         dec(7,2)
        sr_net_loss             dec(7,2)
    """,
    "household_demographics": """
        hd_demo_sk          int         !
        hd_income_band_sk   int
        hd_buy_potential    char(15)
        hd_dep_count        long
        hd_vehicle_count    long
    """,
    "web_page": """
        wp_web_page_sk      int         !
        wp_web_page_id      char(16)    !
        wp_rec_start_date   date
        wp_rec_end_date     date
        wp_creation_date_sk int
        wp_access_date_sk   int
        wp_autogen_flag     char(1)
        wp_customer_sk      int
        wp_url              varchar(100)
        wp_type             char(50)
        wp_char_count       long
        wp_link_count       long
        wp_image_count      long
        wp_max_ad_count     long
    """,
    "promotion": """
        p_promo_sk          int         !
        p_promo_id          char(16)    !
        p_start_date_sk     int
        p_end_date_sk       int
        p_item_sk           int
        p_cost              dec(15,2)
        p_response_target   long
        p_promo_name        char(50)
        p_channel_dmail     char(1)
        p_channel_email     char(1)
        p_channel_catalog   char(1)
        p_channel_tv        char(1)
        p_channel_radio     char(1)
        p_channel_press     char(1)
        p_channel_event     char(1)
        p_channel_demo      char(1)
        p_channel_details   varchar(100)
        p_purpose           char(15)
        p_discount_active   char(1)
    """,
    "catalog_page": """
        cp_catalog_page_sk      int         !
        cp_catalog_page_id      char(16)    !
        cp_start_date_sk        int
        cp_end_date_sk          int
        cp_department           varchar(50)
        cp_catalog_number       long
        cp_catalog_page_number  long
        cp_description          varchar(100)
        cp_type                 varchar(100)
    """,
    "inventory": """
        inv_date_sk             int     !
        inv_item_sk             int     !
        inv_warehouse_sk        int     !
        inv_quantity_on_hand    long
    """,
    "catalog_returns": """
        cr_returned_date_sk         int
        cr_returned_time_sk         int
        cr_item_sk                  int     !
        cr_refunded_customer_sk     int
        cr_refunded_cdemo_sk        int
        cr_refunded_hdemo_sk        int
        cr_refunded_addr_sk         int
        cr_returning_customer_sk    int
        cr_returning_cdemo_sk       int
        cr_returning_hdemo_sk       int
        cr_returning_addr_sk        int
        cr_call_center_sk           int
        cr_catalog_page_sk          int
        cr_ship_mode_sk             int
        cr_warehouse_sk             int
        cr_reason_sk                int
        cr_order_number             int     !
        cr_return_quantity          long
        cr_return_amount            dec(7,2)
        cr_return_tax               dec(7,2)
        cr_return_amt_inc_tax       dec(7,2)
        cr_fee                      dec(7,2)
        cr_return_ship_cost         dec(7,2)
        cr_refunded_cash            dec(7,2)
        cr_reversed_charge          dec(7,2)
        cr_store_credit             dec(7,2)
        cr_net_loss                 dec(7,2)
    """,
    "web_returns": """
        wr_returned_date_sk         int
        wr_returned_time_sk         int
        wr_item_sk                  int     !
        wr_refunded_customer_sk     int
        wr_refunded_cdemo_sk        int
        wr_refunded_hdemo_sk        int
        wr_refunded_addr_sk         int
        wr_returning_customer_sk    int
        wr_returning_cdemo_sk       int
        wr_returning_hdemo_sk       int
        wr_returning_addr_sk        int
        wr_web_page_sk              int
        wr_reason_sk                int
        wr_order_number             int     !
        wr_return_quantity          long
        wr_return_amt               dec(7,2)
        wr_return_tax               dec(7,2)
        wr_return_amt_inc_tax       dec(7,2)
        wr_fee                      dec(7,2)
        wr_return_ship_cost         dec(7,2)
        wr_refunded_cash            dec(7,2)
        wr_reversed_charge          dec(7,2)
        wr_account_credit           dec(7,2)
        wr_net_loss                 dec(7,2)
    """,
    "web_sales": """
        ws_sold_date_sk         int
        ws_sold_time_sk         int
        ws_ship_date_sk         int
        ws_item_sk              int     !
        ws_bill_customer_sk     int
        ws_bill_cdemo_sk        int
        ws_bill_hdemo_sk        int
        ws_bill_addr_sk         int
        ws_ship_customer_sk     int
        ws_ship_cdemo_sk        int
        ws_ship_hdemo_sk        int
        ws_ship_addr_sk         int
        ws_web_page_sk          int
        ws_web_site_sk          int
        ws_ship_mode_sk         int
        ws_warehouse_sk         int
        ws_promo_sk             int
        ws_order_number         int     !
        ws_quantity             long
        ws_wholesale_cost       dec(7,2)
        ws_list_price           dec(7,2)
        ws_sales_price          dec(7,2)
        ws_ext_discount_amt     dec(7,2)
        ws_ext_sales_price      dec(7,2)
        ws_ext_wholesale_cost   dec(7,2)
        ws_ext_list_price       dec(7,2)
        ws_ext_tax              dec(7,2)
        ws_coupon_amt           dec(7,2)
        ws_ext_ship_cost        dec(7,2)
        ws_net_paid             dec(7,2)
        ws_net_paid_inc_tax     dec(7,2)
        ws_net_paid_inc_ship    dec(7,2)
        ws_net_paid_inc_ship_tax dec(7,2)
        ws_net_profit           dec(7,2)
    """,
    "catalog_sales": """
        cs_sold_date_sk         int
        cs_sold_time_sk         int
        cs_ship_date_sk         int
        cs_bill_customer_sk     int
        cs_bill_cdemo_sk        int
        cs_bill_hdemo_sk        int
        cs_bill_addr_sk         int
        cs_ship_customer_sk     int
        cs_ship_cdemo_sk        int
        cs_ship_hdemo_sk        int
        cs_ship_addr_sk         int
        cs_call_center_sk       int
        cs_catalog_page_sk      int
        cs_ship_mode_sk         int
        cs_warehouse_sk         int
        cs_item_sk              int     !
        cs_promo_sk             int
        cs_order_number         int     !
        cs_quantity             long
        cs_wholesale_cost       dec(7,2)
        cs_list_price           dec(7,2)
        cs_sales_price          dec(7,2)
        cs_ext_discount_amt     dec(7,2)
        cs_ext_sales_price      dec(7,2)
        cs_ext_wholesale_cost   dec(7,2)
        cs_ext_list_price       dec(7,2)
        cs_ext_tax              dec(7,2)
        cs_coupon_amt           dec(7,2)
        cs_ext_ship_cost        dec(7,2)
        cs_net_paid             dec(7,2)
        cs_net_paid_inc_tax     dec(7,2)
        cs_net_paid_inc_ship    dec(7,2)
        cs_net_paid_inc_ship_tax dec(7,2)
        cs_net_profit           dec(7,2)
    """,
    "dbgen_version": """
        dv_version          varchar(16)
        dv_create_date      date
        dv_create_time      char(20)
        dv_cmdline_args     varchar(200)
    """,
    "store_sales": """
        ss_sold_date_sk         int
        ss_sold_time_sk         int
        ss_item_sk              int     !
        ss_customer_sk          int
        ss_cdemo_sk             int
        ss_hdemo_sk             int
        ss_addr_sk              int
        ss_store_sk             int
        ss_promo_sk             int
        ss_ticket_number        long    !
        ss_quantity             long
        ss_wholesale_cost       dec(7,2)
        ss_list_price           dec(7,2)
        ss_sales_price          dec(7,2)
        ss_ext_discount_amt     dec(7,2)
        ss_ext_sales_price      dec(7,2)
        ss_ext_wholesale_cost   dec(7,2)
        ss_ext_list_price       dec(7,2)
        ss_ext_tax              dec(7,2)
        ss_coupon_amt           dec(7,2)
        ss_net_paid             dec(7,2)
        ss_net_paid_inc_tax     dec(7,2)
        ss_net_profit           dec(7,2)
    """,
}

# ---------------------------------------------------------------------------
# Maintenance (staging/refresh) table definitions (12 tables)
# Reference: nds_schema.py:570-716.
# ---------------------------------------------------------------------------

_MAINTENANCE_TABLES: Dict[str, str] = {
    "s_purchase_lineitem": """
        plin_purchase_id    int         !
        plin_line_number    int         !
        plin_item_id        char(16)
        plin_promotion_id   char(16)
        plin_quantity       int
        plin_sale_price     dec(7,2)
        plin_coupon_amt     dec(7,2)
        plin_comment        varchar(100)
    """,
    "s_purchase": """
        purc_purchase_id    int         !
        purc_store_id       char(16)
        purc_customer_id    char(16)
        purc_purchase_date  char(10)
        purc_purchase_time  int
        purc_register_id    int
        purc_clerk_id       int
        purc_comment        char(100)
    """,
    "s_catalog_order": """
        cord_order_id           int     !
        cord_bill_customer_id   char(16)
        cord_ship_customer_id   char(16)
        cord_order_date         char(10)
        cord_order_time         int
        cord_ship_mode_id       char(16)
        cord_call_center_id     char(16)
        cord_order_comments     varchar(100)
    """,
    "s_web_order": """
        word_order_id           int     !
        word_bill_customer_id   char(16)
        word_ship_customer_id   char(16)
        word_order_date         char(10)
        word_order_time         int
        word_ship_mode_id       char(16)
        word_web_site_id        char(16)
        word_order_comments     char(100)
    """,
    "s_catalog_order_lineitem": """
        clin_order_id           int     !
        clin_line_number        int     !
        clin_item_id            char(16)
        clin_promotion_id       char(16)
        clin_quantity           int
        clin_sales_price        dec(7,2)
        clin_coupon_amt         dec(7,2)
        clin_warehouse_id       char(16)
        clin_ship_date          char(10)
        clin_catalog_number     int
        clin_catalog_page_number int
        clin_ship_cost          dec(7,2)
    """,
    "s_web_order_lineitem": """
        wlin_order_id           int     !
        wlin_line_number        int     !
        wlin_item_id            char(16)
        wlin_promotion_id       char(16)
        wlin_quantity           int
        wlin_sales_price        dec(7,2)
        wlin_coupon_amt         dec(7,2)
        wlin_warehouse_id       char(16)
        wlin_ship_date          char(10)
        wlin_ship_cost          dec(7,2)
        wlin_web_page_id        char(16)
    """,
    "s_store_returns": """
        sret_store_id           char(16)
        sret_purchase_id        char(16)    !
        sret_line_number        int         !
        sret_item_id            char(16)    !
        sret_customer_id        char(16)
        sret_return_date        char(10)
        sret_return_time        char(10)
        sret_ticket_number      long
        sret_return_qty         int
        sret_return_amt         dec(7,2)
        sret_return_tax         dec(7,2)
        sret_return_fee         dec(7,2)
        sret_return_ship_cost   dec(7,2)
        sret_refunded_cash      dec(7,2)
        sret_reversed_charge    dec(7,2)
        sret_store_credit       dec(7,2)
        sret_reason_id          char(16)
    """,
    "s_catalog_returns": """
        cret_call_center_id     char(16)
        cret_order_id           int         !
        cret_line_number        int         !
        cret_item_id            char(16)    !
        cret_return_customer_id char(16)
        cret_refund_customer_id char(16)
        cret_return_date        char(10)
        cret_return_time        char(10)
        cret_return_qty         int
        cret_return_amt         dec(7,2)
        cret_return_tax         dec(7,2)
        cret_return_fee         dec(7,2)
        cret_return_ship_cost   dec(7,2)
        cret_refunded_cash      dec(7,2)
        cret_reversed_charge    dec(7,2)
        cret_merchant_credit    dec(7,2)
        cret_reason_id          char(16)
        cret_shipmode_id        char(16)
        cret_catalog_page_id    char(16)
        cret_warehouse_id       char(16)
    """,
    "s_web_returns": """
        wret_web_page_id        char(16)
        wret_order_id           int         !
        wret_line_number        int         !
        wret_item_id            char(16)    !
        wret_return_customer_id char(16)
        wret_refund_customer_id char(16)
        wret_return_date        char(10)
        wret_return_time        char(10)
        wret_return_qty         int
        wret_return_amt         dec(7,2)
        wret_return_tax         dec(7,2)
        wret_return_fee         dec(7,2)
        wret_return_ship_cost   dec(7,2)
        wret_refunded_cash      dec(7,2)
        wret_reversed_charge    dec(7,2)
        wret_account_credit     dec(7,2)
        wret_reason_id          char(16)
    """,
    "s_inventory": """
        invn_warehouse_id   char(16)    !
        invn_item_id        char(16)    !
        invn_date           char(10)    !
        invn_qty_on_hand    int
    """,
    "delete": """
        date1   string  !
        date2   string  !
    """,
    "inventory_delete": """
        date1   string  !
        date2   string  !
    """,
}

# The 7 fact tables that are date-partitioned at transcode time, and the
# partition key for each (reference: nds_transcode.py:45-53).
TABLE_PARTITIONING: Dict[str, str] = {
    "catalog_sales": "cs_sold_date_sk",
    "catalog_returns": "cr_returned_date_sk",
    "inventory": "inv_date_sk",
    "store_sales": "ss_sold_date_sk",
    "store_returns": "sr_returned_date_sk",
    "web_sales": "ws_sold_date_sk",
    "web_returns": "wr_returned_date_sk",
}

SOURCE_TABLE_NAMES: List[str] = list(_SOURCE_TABLES)
MAINTENANCE_TABLE_NAMES: List[str] = list(_MAINTENANCE_TABLES)


def get_schemas(use_decimal: bool = True) -> Dict[str, TableSchema]:
    """Schemas of all 25 source tables.

    With ``use_decimal=False`` every DECIMAL column degrades to float64,
    mirroring the reference's ``--float`` mode (nds_schema.py:43-47).
    """
    return {
        name: _parse_table(name, body, use_decimal)
        for name, body in _SOURCE_TABLES.items()
    }


def get_maintenance_schemas(use_decimal: bool = True) -> Dict[str, TableSchema]:
    """Schemas of the 12 data-maintenance staging tables
    (reference: nds_schema.py:570-716)."""
    return {
        name: _parse_table(name, body, use_decimal)
        for name, body in _MAINTENANCE_TABLES.items()
    }


def get_schema(table: str, use_decimal: bool = True) -> TableSchema:
    """Schema for one table, searching source then maintenance tables."""
    if table in _SOURCE_TABLES:
        return _parse_table(table, _SOURCE_TABLES[table], use_decimal)
    if table in _MAINTENANCE_TABLES:
        return _parse_table(table, _MAINTENANCE_TABLES[table], use_decimal)
    raise KeyError(f"unknown table: {table}")


if __name__ == "__main__":
    for n, s in {**get_schemas(), **get_maintenance_schemas()}.items():
        print(f"{n}: {len(s)} columns")
