"""Pallas TPU kernels for the engine's hot operators."""
