"""Pallas TPU kernel: grouped aggregation as a one-hot MXU matmul.

The NDS power run's hot operator is the scan→filter→group-by spine
(SURVEY.md §3.1); its inner reduction is a masked segment-sum over a
dense, small key domain (dimension surrogate keys — items, brands,
stores).  XLA lowers ``segment_sum`` to scatter-adds; on TPU the
systolic array gives a faster formulation when the segment count is
small: a one-hot matrix product,

    partial[s] = Σ_i vals[i] · (gid[i] == s)  ==  vals @ one_hot(gid)

which runs on the MXU at matmul throughput instead of the VPU scatter
path.  The kernel tiles rows × segments on a 2-D grid, materializes the
one-hot block in VMEM, and accumulates output tiles across row blocks
(sequential TPU grid).

Two entry points:

* :func:`segment_sum_f32` — float32 data (f32 matmul accumulation).
* :func:`segment_sum_decimal` — EXACT int64 sums: values are biased to
  non-negative and split into 8-bit limbs; each limb's one-hot matmul
  stays within f32's exact-integer range (block_rows · 255 < 2^24), the
  per-limb partials accumulate in int32, and the caller-side combine
  reassembles int64 and removes the bias with the per-segment count.
  Exactness bound: rows ≤ 2^31 / 255 ≈ 8.4M per call (chunk above it).

Tests run the interpreter (CPU); the real lowering targets the MXU.
"""

from __future__ import annotations

import functools

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl

_LANES = 128
# |value| must stay below the bias so biased values are non-negative
# and fit the limb planes: 2^41 cents ≈ $22B per single value
_BIAS_BITS = 41
_LIMB_BITS = 8
_N_LIMBS = 6              # biased values < 2^42; 6 limbs cover 48 bits


def _pad_to(x, mult: int, fill=0):
    n = x.shape[0]
    m = -(-max(n, 1) // mult) * mult
    if m == n:
        return x
    return jnp.concatenate([x, jnp.full((m - n,), fill, x.dtype)])


def _f32_kernel(vals_ref, gid_ref, out_ref):
    # grid = (segment blocks, row blocks): rows are the REDUCTION dim and
    # must be innermost — TPU Pallas only keeps an output block resident
    # across consecutive same-index grid steps, so accumulating across an
    # outer dim would revisit flushed blocks (wrong results on hardware).
    #
    # Formulated WITHOUT reshapes/transposes: collapsing the (sublane,
    # lane) block into one vector dim is the "unsupported shape cast"
    # Mosaic rejected.  Instead each sublane row r contributes a
    # (1, LANES) x (segs, LANES) dot_general contracting the lane dim —
    # a transposed one-hot product the MXU takes directly; the static
    # python loop unrolls over the block's sublanes.
    j = pl.program_id(0)
    i = pl.program_id(1)
    nseg = out_ref.shape[1]
    # keep index math in int32: under jax_enable_x64 the python-int
    # multiply promotes to int64 and the int64 (nseg, LANES) compare
    # crashes the Mosaic vector-layout pass (the historical
    # "unsupported shape cast" was the same class of failure)
    seg0 = (j * nseg).astype(jnp.int32)
    segs = seg0 + jax.lax.broadcasted_iota(jnp.int32, (nseg, _LANES), 0)
    acc = jnp.zeros((1, nseg), jnp.float32)
    for r in range(vals_ref.shape[0]):
        g = gid_ref[r:r + 1, :]                       # (1, LANES)
        v = vals_ref[r:r + 1, :]                      # (1, LANES)
        onehot_t = (jnp.broadcast_to(g, (nseg, _LANES)) == segs
                    ).astype(jnp.float32)             # (segs, LANES)
        # HIGHEST: the MXU's default bf16 passes would round the VALUE
        # operand (the 0/1 one-hot is bf16-exact; arbitrary f32 values
        # are not — observed ~1e-3 relative drift at default precision)
        acc = acc + jax.lax.dot_general(
            v, onehot_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)      # (1, segs)

    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += acc


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "block_rows",
                                    "block_segs", "interpret"))
def segment_sum_f32(vals: jnp.ndarray, gid: jnp.ndarray,
                    mask: jnp.ndarray, num_segments: int,
                    block_rows: int = 1024, block_segs: int = 512,
                    interpret: bool = False) -> jnp.ndarray:
    """Masked per-segment float32 sums via one-hot MXU matmuls.

    ``gid`` entries outside [0, num_segments) contribute nothing (the
    mask is folded the same way)."""
    v = jnp.where(mask, vals.astype(jnp.float32), 0.0)
    g = jnp.where(mask, gid.astype(jnp.int32), jnp.int32(-1))
    v = _pad_to(v, block_rows)
    g = _pad_to(g, block_rows, fill=-1)
    n = v.shape[0]
    s_pad = -(-max(num_segments, 1) // block_segs) * block_segs
    rows = block_rows // _LANES
    v2 = v.reshape(n // _LANES, _LANES)
    g2 = g.reshape(n // _LANES, _LANES)
    grid = (s_pad // block_segs, n // block_rows)
    # trace the kernel with x64 promotion OFF: under jax_enable_x64 the
    # pallas machinery emits int64 grid/index scalars and the Mosaic
    # vector-layout pass rejects the program (tpu_compile_helper exit 1
    # with no diagnostics); all kernel inputs are explicitly 32-bit
    with jax.enable_x64(False):
        out = pl.pallas_call(
            _f32_kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((rows, _LANES), lambda j, i: (i, 0)),
                pl.BlockSpec((rows, _LANES), lambda j, i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_segs), lambda j, i: (0, j)),
            out_shape=jax.ShapeDtypeStruct((1, s_pad), jnp.float32),
            interpret=interpret,
        )(v2, g2)
    return out[0, :num_segments]


def _limb_kernel(limbs_ref, gid_ref, out_ref):
    # same grid orientation and reshape-free formulation as _f32_kernel:
    # rows (reduction) innermost; per sublane row, all limb planes at
    # once via one (nl, LANES) x (segs, LANES) lane-contracting
    # dot_general
    j = pl.program_id(0)
    i = pl.program_id(1)
    nseg = out_ref.shape[1]
    seg0 = (j * nseg).astype(jnp.int32)  # int32: see _f32_kernel note
    nl = limbs_ref.shape[0]
    segs = seg0 + jax.lax.broadcasted_iota(jnp.int32, (nseg, _LANES), 0)
    acc = jnp.zeros((nl, nseg), jnp.float32)
    for r in range(limbs_ref.shape[1]):
        g = gid_ref[r:r + 1, :]                       # (1, LANES)
        lv = limbs_ref[:, r, :]                       # (nl, LANES)
        onehot_t = (jnp.broadcast_to(g, (nseg, _LANES)) == segs
                    ).astype(jnp.float32)             # (segs, LANES)
        # default MXU precision is EXACT here: 8-bit limbs (<=255) and
        # the 0/1 one-hot are both bf16-representable, and the f32
        # accumulator stays within its exact-integer range
        acc = acc + jax.lax.dot_general(
            lv, onehot_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (nl, segs)

    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += acc.astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "block_rows",
                                    "block_segs", "interpret"))
def segment_sum_decimal(vals: jnp.ndarray, gid: jnp.ndarray,
                        mask: jnp.ndarray, num_segments: int,
                        block_rows: int = 1024, block_segs: int = 256,
                        interpret: bool = False):
    """EXACT per-segment int64 sums + counts for scaled-decimal data.

    Returns ``(sums int64 [num_segments], counts int64 [num_segments])``.
    """
    if vals.shape[0] > (2 ** 31 - 1) // 255:
        raise ValueError("segment_sum_decimal: chunk rows above the "
                         "int32 accumulator bound")
    bias = jnp.int64(1) << _BIAS_BITS
    # enforce the documented |value| < 2^41 bound: an out-of-range input
    # would silently wrap in the limb planes; poison every sum with an
    # unmistakable sentinel instead so validation flags it immediately
    oob = jnp.any(mask & ((vals <= -bias) | (vals >= bias)))
    v = jnp.where(mask, vals.astype(jnp.int64) + bias, jnp.int64(0))
    g = jnp.where(mask, gid.astype(jnp.int32), jnp.int32(-1))
    v = _pad_to(v, block_rows)
    g = _pad_to(g, block_rows, fill=-1)
    n = v.shape[0]
    s_pad = -(-max(num_segments, 1) // block_segs) * block_segs
    rows = block_rows // _LANES
    # 8-bit limb planes (+ one plane of ones for the per-segment count)
    limbs = [((v >> (_LIMB_BITS * k)) & 0xFF).astype(jnp.float32)
             for k in range(_N_LIMBS)]
    limbs.append((v != 0).astype(jnp.float32))   # count plane
    lv = jnp.stack(limbs).reshape(_N_LIMBS + 1, n // _LANES, _LANES)
    g2 = g.reshape(n // _LANES, _LANES)
    grid = (s_pad // block_segs, n // block_rows)
    # x64 promotion off for the kernel trace — see segment_sum_f32
    with jax.enable_x64(False):
        out = pl.pallas_call(
            _limb_kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((_N_LIMBS + 1, rows, _LANES),
                             lambda j, i: (0, i, 0)),
                pl.BlockSpec((rows, _LANES), lambda j, i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((_N_LIMBS + 1, block_segs),
                                   lambda j, i: (0, j)),
            out_shape=jax.ShapeDtypeStruct((_N_LIMBS + 1, s_pad),
                                           jnp.int32),
            interpret=interpret,
        )(lv, g2)
    out = out[:, :num_segments].astype(jnp.int64)
    counts = out[_N_LIMBS]
    sums = jnp.zeros(num_segments, jnp.int64)
    for k in range(_N_LIMBS):
        sums = sums + (out[k] << (_LIMB_BITS * k))
    sums = sums - counts * (jnp.int64(1) << _BIAS_BITS)
    sums = jnp.where(oob, jnp.int64(-(2 ** 62)), sums)
    return sums, counts
