"""Distributed execution over a TPU device mesh.

Replaces the reference's two distribution mechanisms (SURVEY.md §2
parallelism table) with XLA collectives over ICI/DCN:

* Spark shuffle exchange / broadcast joins (power_run_cpu.template:28-33)
  -> ``all_to_all`` hash repartition and ``all_gather`` broadcast inside
  ``shard_map`` programs (:mod:`ndstpu.parallel.exchange`).
* Hadoop-MR fan-out of dsdgen chunks (GenTable.java:136-209)
  -> per-host sharded generation (ndstpu.datagen driver --parallel).
"""

from ndstpu.parallel.mesh import default_mesh, make_mesh  # noqa: F401
from ndstpu.parallel.exchange import (  # noqa: F401
    broadcast_gather,
    hash_repartition,
    sharded_segment_sum,
)
