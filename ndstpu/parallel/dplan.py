"""Distributed plan executor: SQL plans as single SPMD XLA programs.

Executes the planner/optimizer's logical plans over a ``jax.sharding.Mesh``
— the multi-chip analog of Spark's distributed SQL execution (reference:
executors + shuffle exchange, power_run_cpu.template:23-33) designed
TPU-first rather than translated:

* The **spine** — the operator chain over the single largest table — runs
  row-sharded over the mesh's data axis inside ONE ``jit(shard_map)``
  program: filters/projects are local, dimension joins are broadcast
  joins (host-resolved build side, searchsorted probe — surrogate keys
  are ints), aggregation is local sort-grouped partials combined via
  ``lax.all_gather`` over ICI and re-grouped replicated (exact, no hash
  collisions; the psum combine for dense keys lives in
  ndstpu.parallel.dquery, the all_to_all repartition in
  ndstpu.parallel.exchange).
* **Existence-join build sides containing a fact** (q10/q35/q69
  EXISTS-over-store_sales shape) are not host-executed wholesale: a
  child executor reduces the build subtree to its distinct
  (key, residual column) tuples distributed, and only that small
  reduction broadcasts (:meth:`_reduce_build`).
* **Window functions** whose exprs are ranking or whole-partition
  aggregates run sharded: rows are colocated by a partition-key hash
  exchange (all_to_all) and the window is computed per device with the
  original row id as the deterministic tiebreak.
* **Plan tails finalize on-device** where the shape allows: aggregate
  combines are already an all_gather of partials, and a final
  Sort+Limit (or bare Limit) above a row spine becomes a per-device
  top-k plus a k-row all_gather — only the (small) result is fetched,
  tracked by the ``engine.spmd.host_gather_bytes`` counter.
* **Build sides and the remaining plan tail** (dimension subtrees,
  final Project over a handful of groups) execute on the host numpy
  interpreter — the driver side of a broadcast join.
* Plans without a sharded-size table, or using operators outside the
  distributed subset, raise :class:`DistUnsupported`; callers fall back
  to the single-chip engine (ndstpu.engine.jaxexec).

Differentially tested against the numpy interpreter on a virtual
8-device CPU mesh (tests/test_parallel.py) and compile-checked by the
driver via __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ndstpu import obs
from ndstpu.analysis import lowering as lowreg
from ndstpu.engine import columnar, expr as ex, physical, plan as lp
from ndstpu.engine.columnar import BOOL, FLOAT64, INT64, Column, Table
from ndstpu.engine.jaxexec import (
    DCol,
    DTable,
    JEval,
    Unsupported,
    _DEAD_KEY,
    _NULL32,
    _NULL_KEY,
    _ORD_DEAD32,
    _group_ids,
    _key_col,
    _key_i64,
    _lexsort_order,
    _minmax_vals,
    _narrow_span,
    _sum_input,
    jnp_dtype,
)
from ndstpu.parallel.mesh import SHARD_AXIS, shard_map


class DistUnsupported(Exception):
    """Plan shape outside the distributed subset — fall back single-chip.

    ``code`` is the static analyzer's NDS3xx diagnostic for raise sites
    it models (ndstpu/analysis/diagnostics.py); data-dependent guards
    (dup runs, key-domain overflow, shuffle drops) stay uncoded."""

    def __init__(self, msg: str, code=None):
        super().__init__(msg)
        self.code = code


def _has_params(plan: lp.Plan) -> bool:
    """True when any expression in the plan carries a parameter slot.
    Parameterized (canonical) plans can still take the SPMD path when
    the caller supplies the binding — execute_plan substitutes the bound
    values back into literals (:func:`bind_plan_params`) and compiles
    the concrete plan, keyed upstream on fingerprint + value hash."""
    for node in plan.walk():
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            items = v if isinstance(v, (list, tuple)) else (v,)
            for it in items:
                if isinstance(it, tuple):  # sort keys: (expr, asc[, nf])
                    it = it[0] if it else None
                if isinstance(it, ex.Expr) and any(
                        isinstance(x, (ex.Param, ex.InParam))
                        for x in it.walk()):
                    return True
    return False


def _subst_params(e: ex.Expr, values) -> ex.Expr:
    """Rebuild `e` with every Param/InParam replaced by the bound
    literal / IN-list (slot-indexed into the canonicalizer's values)."""
    if isinstance(e, ex.Param):
        return ex.Literal(values[e.slot])
    if isinstance(e, ex.InParam):
        return ex.InList(_subst_params(e.operand, values),
                         list(values[e.slot]), e.negated)
    changed = False
    kw = {}
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ex.Expr):
            nv = _subst_params(v, values)
        elif isinstance(v, (list, tuple)):
            nv = type(v)(
                _subst_params(it, values) if isinstance(it, ex.Expr)
                else (tuple(_subst_params(x, values)
                            if isinstance(x, ex.Expr) else x for x in it)
                      if isinstance(it, tuple) else it)
                for it in v)
            if nv == v:
                nv = v
        else:
            nv = v
        kw[f.name] = nv
        changed = changed or nv is not v
    return dataclasses.replace(e, **kw) if changed else e


def bind_plan_params(plan: lp.Plan, binding) -> lp.Plan:
    """Concrete copy of a canonical exec_plan: every Param/InParam slot
    replaced by its bound value from ``binding`` (an
    :class:`~ndstpu.engine.expr.ParamBinding`).  The SPMD compiler then
    traces plain literals — shape slots were already substituted by the
    canonicalizer, so the result is exactly the original plan's shape."""
    values = binding.values if hasattr(binding, "values") else binding
    plan = copy.deepcopy(plan)
    for node in plan.walk():
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, ex.Expr):
                setattr(node, f.name, _subst_params(v, values))
            elif isinstance(v, (list, tuple)):
                out = []
                for it in v:
                    if isinstance(it, ex.Expr):
                        out.append(_subst_params(it, values))
                    elif isinstance(it, tuple):
                        out.append(tuple(
                            _subst_params(x, values)
                            if isinstance(x, ex.Expr) else x for x in it))
                    else:
                        out.append(it)
                setattr(node, f.name, type(v)(out))
    return plan


def _table_bytes(t: Table) -> int:
    """Replicated footprint of a host build table through memplan's
    row-width model (the same width the static analyzer estimates)."""
    from ndstpu.engine import memplan
    return memplan.row_bytes(
        [t.column(nm).data.dtype.itemsize
         for nm in t.column_names]) * int(t.num_rows)


_SPINE_NODES = (lp.Scan, lp.Filter, lp.Project, lp.Join, lp.SubqueryAlias)
# shardable key kinds and decomposable aggregates come from the shared
# supported-op registry so the static analyzer (NDS3xx) cannot drift
_KEY_KINDS = tuple(sorted(lowreg.SPMD_KEY_KINDS))
_AGG_FUNCS = tuple(sorted(lowreg.SPMD_AGG_FUNCS))


@dataclasses.dataclass
class _BroadcastJoin:
    """Host-resolved build side of a spine join (driver-side broadcast)."""
    kind: str
    mark: Optional[str]
    extra: Optional[ex.Expr]
    probe_key_exprs: List[ex.Expr]
    radices: List[Tuple[int, int]]   # (lo, span) per key part
    sorted_keys: np.ndarray          # valid build keys, sorted
    row_of: np.ndarray               # sorted position -> build row index
    build: Table                     # host build table (post plan)
    spine_left: bool                 # spine side is the join's left child
    build_has_null: bool = False     # any build row with a NULL key part
    build_empty: bool = False
    # per key part: the build dictionary for string keys (None = numeric)
    key_dicts: Optional[List[Optional[np.ndarray]]] = None
    # >0: duplicate build key runs — inner joins EXPAND the probe side
    # by this factor; semi/anti/mark residuals probe every duplicate
    dup_max: int = 0


@dataclasses.dataclass
class _ShuffleJoin:
    """Partitioned equi-join for build sides too large to broadcast —
    the fact-fact join path (e.g. store_sales ⋈ store_returns on
    item_sk+ticket_number).  The build side is hash-partitioned by key
    across devices on the host (each device holds its partition, sorted
    by key); the traced probe side repartitions the live spine rows with
    ``all_to_all`` using the same splitmix64 bucket hash, then joins
    locally with a searchsorted probe.  This is the Spark shuffle-
    exchange analog (power_run_cpu.template:30-32) as an ICI collective.
    """
    kind: str
    mark: Optional[str]
    extra: Optional[ex.Expr]
    probe_key_exprs: List[ex.Expr]
    radices: List[Tuple[int, int]]
    spine_left: bool
    build_has_null: bool
    build_empty: bool
    part_cap: int                    # rows per device partition (padded)
    # host-staged [n_dev * part_cap] arrays (device_put at spine launch):
    # partition-local keys sorted ascending, _DEAD_KEY padding
    keys_flat: np.ndarray
    # build columns gathered into partition order: name -> (data, valid,
    # ctype, dictionary)
    cols_flat: Dict[str, tuple]
    # filled per trace: index of this join's first arg in the flat
    # shard_map argument list
    arg_start: int = -1
    n_args: int = 0
    # per key part: the build dictionary for string keys (None = numeric)
    key_dicts: Optional[List[Optional[np.ndarray]]] = None
    # >0: semi/anti/mark residual probes every duplicate in a key run
    dup_max: int = 0


class DistributedPlanExecutor:
    """Compiles + runs one logical plan over the mesh (one-shot object)."""

    def __init__(self, catalog, mesh, shard_threshold_rows: int = 65536,
                 broadcast_limit_rows: int = lowreg.SPMD_BROADCAST_LIMIT_ROWS,
                 dev_cache: Optional[dict] = None,
                 chunk_rows=None,
                 prefetch_depth: Optional[int] = None,
                 cost_advisor="auto"):
        self.catalog = catalog
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.threshold = shard_threshold_rows
        self.broadcast_limit = broadcast_limit_rows
        # exchange-placement advisor (analysis/cost.py): "auto" resolves
        # to the cost model over the runtime device budget when
        # NDSTPU_COST is on; None restores the fixed rows-only rule
        if cost_advisor == "auto":
            from ndstpu.analysis import cost as _cost
            cost_advisor = _cost.default_advisor(broadcast_limit_rows) \
                if _cost.enabled() else None
        self.cost_advisor = cost_advisor
        # per-join advisor decisions for this plan (query-span attr ->
        # ledger extra); _order_safe: an aggregate spine is insensitive
        # to row placement, a row spine's output order is not
        self.cost_decisions: List[dict] = []
        self._order_safe = False
        # out-of-core: facts above this row count stream through the
        # device shard-major — device d owns fact rows
        # [d*shard_rows, (d+1)*shard_rows) and streams only its shard's
        # chunks (one compiled program, partials combined across chunks
        # on the host).  None = whole-fact resident; "auto" = the
        # spill-aware planner (engine/memplan.py) sizes chunk_rows and
        # the prefetch depth per fact from device memory stats
        self.chunk_rows = chunk_rows
        # H2D staging ring depth (chunks staged ahead of compute);
        # None = planner default, 0 = synchronous
        self.prefetch_depth = prefetch_depth
        self.np_exec = physical.Executor(catalog)
        # shared (table, column, version) -> device arrays cache so many
        # cached query executors don't pin duplicate fact copies in HBM
        self.dev_cache = dev_cache if dev_cache is not None else {}
        self.joins: Dict[int, object] = {}   # _BroadcastJoin | _ShuffleJoin
        self.fact: Optional[lp.Scan] = None
        # probe-shuffle receive bucket = slack * capacity / n_dev; doubled
        # on overflow up to n_dev (lossless) by _run_spine_retrying
        self.shuffle_slack = 2
        self._last_dropped = 0
        self._prepared = False
        # collect_partials mode: _post_spine returns raw finest-group
        # partials instead of a finalized Table (union-agg branches)
        self._emit_partials = False
        self._union_ctx = None
        # trace-time metadata side channels (static python values)
        self._row_meta: Optional[List[tuple]] = None
        self._key_meta: Optional[List[tuple]] = None
        self._leaf_meta: Optional[List[tuple]] = None
        # NDS3xx codes hit while probing candidates / child executors —
        # kept even on success so spmd_coverage can report which raise
        # sites the plan brushed against on its way to a working spine
        self.attempt_codes: List[str] = []
        # (join kind, reduced build rows) per _reduce_build success
        self.build_reduced: List[tuple] = []
        # on-device row-spine tail: (sort keys or None, LIMIT n)
        self._tail: Optional[tuple] = None
        # the spine absorbs Window nodes (rowid threading needed)
        self._has_win = False

    # -- public --------------------------------------------------------------

    def execute_plan(self, plan: lp.Plan, params=None) -> Table:
        """Try candidate fact tables largest-first (at tiny scale factors
        a fixed-size dimension like date_dim can out-size the fact, and
        some spines fail preparation, e.g. non-unique build keys)."""
        if _has_params(plan):
            if params is None:
                raise DistUnsupported(
                    "parameterized (canonical) plan on spmd path without "
                    "a binding", code="NDS301")
            plan = bind_plan_params(plan, params)
        union = self._try_union_agg(plan)
        if union is not None:
            self._annotate_decisions()
            return union
        offload = self._try_subquery_offload(plan)
        if offload is not None:
            self._annotate_decisions()
            return offload
        scans = [n for n in plan.walk() if isinstance(n, lp.Scan)]
        if not scans:
            raise DistUnsupported("no base-table scan in plan",
                                  code="NDS301")
        sized = sorted(((self.catalog.get(n.table).num_rows, i, n)
                        for i, n in enumerate(scans)),
                       key=lambda t: (-t[0], t[1]))
        last: Optional[DistUnsupported] = None
        for rows, _, target in sized:
            if rows < self.threshold:
                break
            self.joins = {}
            self.fact = None
            self.fact_target = target
            self._prepared = False
            self._tail = None
            self._has_win = False
            self.cost_decisions = []
            try:
                spine, top = self._split(plan)
                result = self._run_spine_retrying(spine)
            except DistUnsupported as e:
                if e.code:
                    self.attempt_codes.append(e.code)
                last = e
                continue
            self._spine, self._top = spine, top
            self._annotate_decisions()
            return self._finish(result)
        raise last or DistUnsupported("no sharded-size table in plan",
                                      code="NDS301")

    def _annotate_decisions(self) -> None:
        """Compact advisor trail on the query span (-> ledger extra
        ``cost_decisions``): one ``kind:strategy`` token per spine
        join, ``*`` marking a cost override of the structural rule."""
        if not self.cost_decisions:
            return
        obs.annotate(cost_decisions=" ".join(
            f"{d['kind']}:{d['strategy']}"
            + ("*" if d["overrode"] else "")
            for d in self.cost_decisions))

    def _try_subquery_offload(self, plan: lp.Plan) -> Optional[Table]:
        """q9 shape: the outer plan scans only sub-threshold tables (its
        FROM is the tiny `reason` dim) while uncorrelated SCALAR
        subqueries embedded in its expressions aggregate a sharded-size
        fact.  Execute each such subquery body distributed (one child
        executor per body), inline the scalars, and run the tiny outer
        plan on host — the reference distributes these trivially through
        Spark (query9.tpl's 15 store_sales aggregates)."""
        for n in plan.walk():
            if isinstance(n, lp.Scan) and n.table in self.catalog and \
                    self.catalog.get(n.table).num_rows >= self.threshold:
                return None     # normal spine path handles it
        from ndstpu.engine.optimizer import _plan_exprs

        subs: List[ex.SubqueryExpr] = []

        def collect(p: lp.Plan) -> None:
            for e in _plan_exprs(p):
                for x in e.walk():
                    if isinstance(x, ex.SubqueryExpr) and \
                            x.plan is not None and x.kind == "scalar" and \
                            not x.correlated_predicates:
                        subs.append(x)
            for c in p.children():
                collect(c)

        collect(plan)
        targets = [
            s for s in subs
            if any(isinstance(n, lp.Scan) and n.table in self.catalog and
                   self.catalog.get(n.table).num_rows >= self.threshold
                   for n in s.plan.walk())]
        if not targets:
            return None
        children: List[Tuple[ex.SubqueryExpr,
                             "DistributedPlanExecutor"]] = []
        firsts: List[Table] = []
        for s in targets:
            child = DistributedPlanExecutor(
                self.catalog, self.mesh,
                shard_threshold_rows=self.threshold,
                broadcast_limit_rows=self.broadcast_limit,
                dev_cache=self.dev_cache, chunk_rows=self.chunk_rows,
                prefetch_depth=self.prefetch_depth,
                cost_advisor=self.cost_advisor)
            firsts.append(child.execute_plan(s.plan))  # DistUnsupported
            self.attempt_codes += child.attempt_codes  # propagates
            self.cost_decisions += child.cost_decisions
            children.append((s, child))
        self._scalar_ctx = (plan, children)
        return self._scalar_finish(firsts)

    @staticmethod
    def _scalar_literal(t: Table) -> ex.Expr:
        return physical.scalar_subquery_literal(t, too_many=DistUnsupported)

    def _scalar_finish(self, results: Optional[List[Table]]) -> Table:
        """Inline distributed subquery results as literals (pre-seeding
        the host interpreter's subquery cache) and run the tiny outer
        plan; `results=None` re-runs the children's compiled spines."""
        plan, children = self._scalar_ctx
        self.np_exec = physical.Executor(self.catalog)
        for i, (s, child) in enumerate(children):
            out = results[i] if results is not None else \
                child.execute_again()
            self.np_exec._subq_cache[id(s)] = self._scalar_literal(out)
        return self.np_exec.execute(plan)

    def collect_partials(self, plan: lp.Aggregate):
        """Run an Aggregate-rooted plan over the mesh and return the raw
        finest-group (key_cols, leaf_parts) instead of finalizing — one
        branch of a union-all aggregate."""
        self._emit_partials = True
        scans = [n for n in plan.walk() if isinstance(n, lp.Scan)]
        if not scans:
            raise DistUnsupported("no base-table scan in branch",
                                  code="NDS301")
        sized = sorted(((self.catalog.get(n.table).num_rows, i, n)
                        for i, n in enumerate(scans)),
                       key=lambda t: (-t[0], t[1]))
        last: Optional[DistUnsupported] = None
        for rows, _, target in sized:
            if rows < self.threshold:
                break
            self.joins = {}
            self.fact = None
            self.fact_target = target
            self._prepared = False
            self._tail = None
            self._has_win = False
            self.cost_decisions = []
            try:
                spine, top = self._split(plan)
                if spine is not plan:
                    raise DistUnsupported(
                        "branch spine is not the union aggregate")
                out = self._run_spine_retrying(spine)
            except DistUnsupported as e:
                if e.code:
                    self.attempt_codes.append(e.code)
                last = e
                continue
            self._spine, self._top = spine, top
            return out
        raise last or DistUnsupported("no sharded-size table in branch",
                                      code="NDS301")

    def _run_spine_retrying(self, spine: lp.Plan) -> Table:
        """Run the spine; if a shuffle-join receive bucket overflowed
        (key skew), double the slack and re-trace.  slack >= n_dev makes
        every bucket as large as a whole shard, which cannot drop."""
        while True:
            result = self._run_spine(spine)
            if not self._last_dropped:
                return result
            if self.shuffle_slack >= self.n_dev:
                raise DistUnsupported(
                    "shuffle join dropped rows at lossless bucket size")
            self.shuffle_slack = min(self.shuffle_slack * 2, self.n_dev)

    def _finish(self, result: Table) -> Table:
        if self._top is None:
            return result
        grafted = _graft(self._top, self._spine,
                         lp.InlineTable(result, "__dist__"))
        return self.np_exec.execute(grafted)

    def execute_again(self) -> Table:
        """Re-run the already-compiled spine program (caller must have
        checked catalog versions are unchanged) and redo the host
        finalize + plan tail — the repeat-execution path for cached
        tpu-spmd queries (no re-trace, no re-compile, no host build)."""
        obs.inc("engine.spmd.reexecs")
        if self._union_ctx is not None:
            return self._union_again()
        if getattr(self, "_scalar_ctx", None) is not None:
            return self._scalar_finish(None)
        if getattr(self, "_chunk_info", (False,))[0]:
            return self._finish(self._run_chunks())
        out = jax.device_get(self._compiled_fn(*self._dev_args))
        return self._finish(self._post_spine(out))

    # -- union-all aggregates ------------------------------------------------

    def _try_union_agg(self, plan: lp.Plan) -> Optional[Table]:
        """Distribute an Aggregate over a UNION ALL of channel subplans
        (q2/q5/q33/q56/q60/q66/q71/q76... shape): run each branch as its
        own sharded spine (the union may sit under joins/projects inside
        the aggregate), collect finest-group partials, and combine the
        decomposable partials across branches on the host.  The plan
        remainder (outer rollups, second union sites from reused CTEs)
        recurses into a fresh executor so EVERY union site distributes.
        Returns None when no site matches or no branch distributes."""
        found = self._find_union_site(plan)
        if found is None:
            return None
        agg, setop = found
        try:
            self._check_agg(agg)
        except DistUnsupported:
            return None
        return self._run_union_site(plan, agg, setop)

    def _find_union_site(self, plan: lp.Plan):
        """Deepest Aggregate that directly dominates (no intervening
        aggregate) a union-all SetOp; among its unions, the one holding
        the largest base table."""

        def walk_depth(p, d=0):
            yield p, d
            for c in p.children():
                yield from walk_depth(c, d + 1)

        def union_size(s: lp.SetOp) -> int:
            rows = [self.catalog.get(n.table).num_rows
                    for n in s.walk() if isinstance(n, lp.Scan)]
            return max(rows, default=0)

        best = None
        for node, depth in walk_depth(plan):
            if not isinstance(node, lp.Aggregate):
                continue
            direct = [s for s in node.child.walk()
                      if isinstance(s, lp.SetOp) and s.kind == "union"
                      and s.all and _distributive_path(node.child, s)
                      and union_size(s) >= self.threshold]
            if not direct:
                continue
            # outermost first among sharded-size sites: nested unions
            # inside a branch are flattened by _expand_branches
            s = min(direct,
                    key=lambda s: (len(_path_to(node.child, s) or ()),
                                   -union_size(s)))
            if best is None or depth > best[0]:
                best = (depth, node, s)
        return (best[1], best[2]) if best is not None else None

    def _run_union_site(self, plan: lp.Plan, agg: lp.Aggregate,
                        setop: lp.SetOp) -> Optional[Table]:
        leaves = self._agg_leaves(agg)
        if any(a.distinct for a in leaves):
            return None    # cross-branch dedup not supported
        branches: List[lp.Plan] = []

        def flat(s: lp.SetOp) -> None:
            for side in (s.left, s.right):
                if isinstance(side, lp.SetOp) and side.kind == "union" \
                        and side.all:
                    flat(side)
                else:
                    branches.append(side)

        flat(setop)
        branches = self._expand_branches(branches)
        left_names = _output_names(branches[0], self.catalog)
        if left_names is None:
            return None
        sub_execs: List[Optional[DistributedPlanExecutor]] = []
        parts: List[tuple] = []   # (key_cols, leaf_parts, leaf_meta)
        any_dist = False
        for i, b in enumerate(branches):
            nb = b
            if i > 0:
                bn = _output_names(b, self.catalog)
                if bn is None or len(bn) != len(left_names):
                    return None
                # SetOp semantics are positional: align this branch's
                # output names with the left branch's
                nb = lp.Project(b, [(ln, ex.ColumnRef(n))
                                    for ln, n in zip(left_names, bn)])
            child = _graft(agg.child, setop, nb)
            bplan = lp.Aggregate(child, list(agg.group_by),
                                 list(agg.aggs), None)
            exe = DistributedPlanExecutor(
                self.catalog, self.mesh, self.threshold,
                self.broadcast_limit, self.dev_cache,
                chunk_rows=self.chunk_rows,
                prefetch_depth=self.prefetch_depth,
                cost_advisor=self.cost_advisor)
            try:
                kc, lps = exe.collect_partials(bplan)
                self.attempt_codes += exe.attempt_codes
                self.cost_decisions += exe.cost_decisions
                parts.append((kc, lps, list(exe._leaf_meta)))
                sub_execs.append(exe)
                any_dist = True
            except DistUnsupported as du:
                if du.code:
                    self.attempt_codes.append(du.code)
                self.attempt_codes += exe.attempt_codes
                try:
                    kc, lps, meta = self._host_partials(bplan)
                except Exception:  # noqa: BLE001 — any planner/eval gap
                    return None    # falls back to the non-union paths
                parts.append((kc, lps, meta))
                sub_execs.append(None)
        if not any_dist:
            return None
        result = self._finalize_union(agg, leaves, parts)
        self._union_ctx = (plan, agg, sub_execs, parts, leaves)
        if agg is plan:
            self._union_rest = None
            self._union_next = None
            return result
        # recurse on the remainder so further union sites (other
        # channels, a CTE's second instantiation) distribute too; the
        # recursion bottoms out in the single-spine path or numpy
        rest = _graft(plan, agg, lp.InlineTable(result, "__dist_union__"))
        self._union_rest = rest
        nxt = DistributedPlanExecutor(
            self.catalog, self.mesh, self.threshold,
            self.broadcast_limit, self.dev_cache,
            chunk_rows=self.chunk_rows,
            prefetch_depth=self.prefetch_depth,
            cost_advisor=self.cost_advisor)
        try:
            out = nxt.execute_plan(rest)
            self.attempt_codes += nxt.attempt_codes
            self.cost_decisions += nxt.cost_decisions
            self._union_next = nxt
            return out
        except DistUnsupported:
            self.attempt_codes += nxt.attempt_codes
            self._union_next = None
            return self.np_exec.execute(rest)

    def _expand_branches(self, branches: List[lp.Plan],
                         cap: int = 16) -> List[lp.Plan]:
        """Flatten unions NESTED inside branches into extra top-level
        branches while the path to them distributes over UNION ALL
        (q5 shape: each channel joins dims onto an inner sales∪returns
        union).  Branches beyond `cap` stay unexpanded (host fallback).
        Union semantics are positional, so every nested side is aligned
        to its union's left-side names before grafting."""
        work = list(branches)
        out: List[lp.Plan] = []
        while work:
            b = work.pop(0)
            inner = next(
                (s for s in b.walk()
                 if isinstance(s, lp.SetOp) and s.kind == "union"
                 and s.all and _distributive_path(b, s)), None)
            if inner is None:
                out.append(b)
                continue
            sides: List[lp.Plan] = []

            def flat(s: lp.SetOp) -> None:
                for side in (s.left, s.right):
                    if isinstance(side, lp.SetOp) and \
                            side.kind == "union" and side.all:
                        flat(side)
                    else:
                        sides.append(side)

            flat(inner)
            left_names = _output_names(sides[0], self.catalog)
            aligned: Optional[List[lp.Plan]] = []
            for i, s in enumerate(sides):
                if i == 0:
                    aligned.append(s)
                    continue
                sn = _output_names(s, self.catalog)
                if left_names is None or sn is None or \
                        len(sn) != len(left_names):
                    aligned = None
                    break
                aligned.append(lp.Project(
                    s, [(ln, ex.ColumnRef(n))
                        for ln, n in zip(left_names, sn)]))
            if aligned is None or \
                    len(out) + len(work) + len(aligned) > cap:
                out.append(b)   # unexpandable: keep whole (host path)
                continue
            work = [_graft(b, inner, s) for s in aligned] + work
        return out

    def _union_again(self) -> Table:
        plan, agg, sub_execs, first_parts, leaves = self._union_ctx
        parts = []
        for exe, cached in zip(sub_execs, first_parts):
            if exe is not None:
                kc, lps = exe.execute_again()
                parts.append((kc, lps, list(exe._leaf_meta)))
            else:
                # host-fallback branch: the caller only reuses this
                # executor when catalog versions are unchanged, so the
                # first run's numpy partials are still valid — no
                # re-execution of the branch subplan
                parts.append(cached)
        result = self._finalize_union(agg, leaves, parts)
        if agg is plan:
            return result
        # versions unchanged => identical union result; the remainder
        # plan staged at first execution (with that result inlined) is
        # still valid, so replay it
        if self._union_next is not None:
            return self._union_next.execute_again()
        return self.np_exec.execute(self._union_rest)

    def _host_partials(self, bplan: lp.Aggregate):
        """Numpy finest-group partials for one union branch that can't
        be distributed (sub-threshold fact or unsupported shape)."""
        rows = self.np_exec.execute(bplan.child)
        ev = ex.Evaluator(rows)
        key_cols: Dict[str, Column] = {}
        for name, e in bplan.group_by:
            key_cols[name] = ev.eval(
                self.np_exec._resolve_subqueries(e))
        n = rows.num_rows
        if bplan.group_by:
            gids, first = self.np_exec._factorize(
                list(key_cols.values()))
            ng = len(first)
            key_cols = {name: c.gather(first)
                        for name, c in key_cols.items()}
        else:
            gids = np.zeros(n, np.int64)
            ng = 1 if n else 0
        leaves = self._agg_leaves(bplan)
        leaf_parts, metas = [], []
        for a in leaves:
            p, meta = self._host_leaf_partial(rows, ev, a, gids, ng)
            leaf_parts.append(p)
            metas.append(meta)
        return key_cols, leaf_parts, metas

    def _host_leaf_partial(self, rows: Table, ev: ex.Evaluator,
                           a: ex.AggExpr, gids, ng):
        """Numpy mirror of the traced _leaf_partial."""
        if isinstance(a.arg, ex.Star) or a.arg is None:
            cnt = np.bincount(gids, minlength=ng).astype(np.int64) \
                if len(gids) else np.zeros(ng, np.int64)
            return [cnt], (a.func, None, None)
        c = ev.eval(self.np_exec._resolve_subqueries(a.arg))
        meta = (a.func, c.ctype, c.dictionary)
        valid = c.validity()
        cnt = np.zeros(ng, np.int64)
        np.add.at(cnt, gids[valid], 1)
        if a.func == "count":
            return [cnt], meta
        if a.func in ("sum", "avg"):
            if c.ctype.kind in ("decimal", "int32", "int64"):
                s = np.zeros(ng, np.int64)
                np.add.at(s, gids[valid], c.data[valid].astype(np.int64))
            else:
                s = np.zeros(ng, np.float64)
                np.add.at(s, gids[valid],
                          c.data[valid].astype(np.float64))
            return [s, cnt], meta
        if a.func in ("min", "max"):
            if c.ctype.kind == "float64":
                init = np.inf if a.func == "min" else -np.inf
                acc = np.full(ng, init, np.float64)
                vals = c.data[valid].astype(np.float64)
            else:
                init = np.int64(_DEAD_KEY if a.func == "min"
                                else -_DEAD_KEY)
                acc = np.full(ng, init, np.int64)
                vals = c.data[valid].astype(np.int64)
            fold = np.minimum if a.func == "min" else np.maximum
            fold.at(acc, gids[valid], vals)
            return [acc, cnt], meta
        # stddev family: partials are [s1, m2, cnt] with m2 the CENTERED
        # second moment (shifted two-pass); combines use Chan's formula —
        # raw sum-of-squares cancels catastrophically when mean >> stddev
        x = c.data[valid].astype(np.float64)
        if c.ctype.kind == "decimal":
            x = x / (10 ** c.ctype.scale)
        s1 = np.zeros(ng, np.float64)
        np.add.at(s1, gids[valid], x)
        mean = s1 / np.maximum(cnt, 1)
        d = x - mean[gids[valid]]
        d1 = np.zeros(ng, np.float64)
        m2 = np.zeros(ng, np.float64)
        np.add.at(d1, gids[valid], d)
        np.add.at(m2, gids[valid], d * d)
        m2 -= np.where(cnt > 0, d1 * d1 / np.maximum(cnt, 1), 0.0)
        return [s1, m2, cnt], meta

    def _finalize_union(self, agg: lp.Aggregate, leaves,
                        parts: List[tuple]) -> Table:
        """Concatenate per-branch finest groups and re-combine through
        the grouping-sets machinery (a plain GROUP BY is the single
        all-keys grouping set)."""
        names = [n for n, _ in agg.group_by]
        # merge group-key columns (Table.concat merges dictionaries)
        if names:
            merged = Table.concat([Table(kc) for kc, _, _ in parts])
            key_cols = dict(merged.columns)
        else:
            key_cols = {}
        leaf_parts: List[List[np.ndarray]] = []
        metas: List[tuple] = []
        for li, a in enumerate(leaves):
            bmetas = [m[li] for _, _, m in parts]
            func, ct0, _ = bmetas[0]

            def compatible(ct2) -> bool:
                # partials combine on kind + decimal scale; precision
                # widening (e.g. `0 - x`) doesn't change the encoding
                if ct0 is None or ct2 is None:
                    return ct0 is ct2
                ints = ("int32", "int64")
                if ct2.kind != ct0.kind and not (
                        ct2.kind in ints and ct0.kind in ints):
                    return False
                return ct0.kind != "decimal" or ct2.scale == ct0.scale

            for f2, ct2, _ in bmetas[1:]:
                if f2 != func or not compatible(ct2):
                    raise DistUnsupported(
                        "union branches disagree on aggregate type",
                        code="NDS302")
            dicts = [m[li][2] for _, _, m in parts]
            has_dict = any(d is not None for d in dicts)
            merged_dict = None
            branch_parts = [lp_[li] for _, lp_, _ in parts]
            if has_dict and func in ("min", "max"):
                # per-branch dictionary codes are not comparable across
                # branches: translate into the union dictionary
                arrs = [d for d in dicts if d is not None]
                merged_dict = arrs[0]
                for d in arrs[1:]:
                    merged_dict = np.union1d(merged_dict, d)
                init = np.int64(_DEAD_KEY if func == "min"
                                else -_DEAD_KEY)
                for bi, (bp, d) in enumerate(zip(branch_parts, dicts)):
                    if d is None:
                        continue
                    codes = bp[0]
                    cnt = bp[1]
                    safe = np.clip(codes, 0, len(d) - 1).astype(np.int64)
                    remap = np.searchsorted(
                        merged_dict, d[safe]).astype(np.int64)
                    branch_parts[bi] = [np.where(cnt > 0, remap, init)] \
                        + list(bp[1:])
            cat = [np.concatenate([bp[pi] for bp in branch_parts])
                   for pi in range(len(branch_parts[0]))]
            leaf_parts.append(cat)
            metas.append((func, ct0, merged_dict if merged_dict
                          is not None else dicts[0]))
        self._leaf_meta = metas
        sets = agg.grouping_sets if agg.grouping_sets is not None \
            else [list(range(len(names)))]
        shim = lp.Aggregate(agg.child, list(agg.group_by),
                            list(agg.aggs), sets)
        return self._grouping_sets_result(shim, leaves, key_cols,
                                          leaf_parts)

    # -- plan analysis -------------------------------------------------------

    def _split(self, plan: lp.Plan) -> Tuple[lp.Plan, Optional[lp.Plan]]:
        """Find the distributed spine: the chain from the single big Scan
        up to the first Aggregate above it (or the highest supported node).
        Returns (spine_head, top_plan); top_plan executes on host over the
        spine's result (None = the spine is the whole plan)."""
        target = self.fact_target

        chain: List[lp.Plan] = []

        def descend(node) -> bool:
            chain.append(node)
            if node is target:
                return True
            for c in node.children():
                if descend(c):
                    return True
            chain.pop()
            return False

        descend(plan)

        def spine_ok(node) -> bool:
            if isinstance(node, lp.Join):
                return node.kind in ("inner", "left", "semi", "anti",
                                    "nullaware_anti", "mark")
            if isinstance(node, lp.Window):
                # ranking / whole-partition aggregate windows run
                # sharded after a partition-colocating exchange
                # (shared legality check with the NDS310 audit)
                return lowreg.spmd_window_ok(node)
            return isinstance(node, _SPINE_NODES)

        # longest spine-ok suffix of the chain ending at the fact scan;
        # if the node directly above it is a supported Aggregate, take it
        # as the spine top (the DEEPEST aggregate — everything above,
        # including outer aggregates/windows over the now-small result,
        # runs on the host tail)
        ok_from = len(chain) - 1
        for i in range(len(chain) - 1, -1, -1):
            if spine_ok(chain[i]):
                ok_from = i
            else:
                break
        self._has_win = any(isinstance(nd, lp.Window)
                            for nd in chain[ok_from:])
        if ok_from > 0 and isinstance(chain[ok_from - 1], lp.Aggregate):
            self._check_agg(chain[ok_from - 1])
            spine = chain[ok_from - 1]
        else:
            spine = chain[ok_from]
        self._tail = None
        if not isinstance(spine, lp.Aggregate):
            # on-device row-spine tail: a Sort+Limit (or bare Limit)
            # directly above the spine becomes a per-device top-k by
            # (order keys, original row id) — the host then re-applies
            # the tiny Sort/Limit over exactly those k rows, so the
            # result is bit-identical to the single-chip path while
            # only k*n_dev rows ever leave the device
            i = ok_from - 1
            sort_keys = None
            if i >= 0 and isinstance(chain[i], lp.Sort):
                sort_keys = list(chain[i].keys)
                i -= 1
            if i >= 0 and isinstance(chain[i], lp.Limit) and \
                    chain[i].n and int(chain[i].n) > 0:
                self._tail = (sort_keys, int(chain[i].n))
        if not isinstance(spine, lp.Aggregate) and \
                self._tail is None and not self._has_win and not any(
                isinstance(nd, (lp.Join, lp.Filter)) or
                (isinstance(nd, lp.Scan) and nd.predicate is not None)
                for nd in spine.walk()):
            # a pass-through row spine (bare scan/project) would shard
            # the fact only to ship every row straight back to the host
            raise DistUnsupported("row spine does no distributed work",
                                  code="NDS306")
        top = plan if spine is not plan else None
        return spine, top

    def _check_agg(self, node: lp.Aggregate) -> None:
        for _, e in node.aggs:
            for sub in e.walk():
                if isinstance(sub, ex.AggExpr):
                    if sub.func not in _AGG_FUNCS:
                        raise DistUnsupported(f"agg {sub.func} on spine",
                                              code="NDS302")
                    if sub.distinct and (isinstance(sub.arg, ex.Star)
                                         or sub.arg is None):
                        raise DistUnsupported("distinct star agg",
                                              code="NDS302")
                    if sub.distinct and node.grouping_sets is not None:
                        # a distinct count at the finest grouping cannot
                        # be re-combined into coarser rollup groups (the
                        # same value can occur under many fine groups)
                        raise DistUnsupported(
                            "distinct agg under grouping sets",
                            code="NDS302")
                if isinstance(sub, ex.WindowExpr):
                    raise DistUnsupported("window inside aggregate",
                                          code="NDS302")

    # -- spine preparation ---------------------------------------------------

    def _evict_stale(self, table: str, col: str) -> None:
        """Drop superseded-version device copies of (table, col) so
        maintenance rounds don't accumulate dead fact copies in HBM."""
        for k in [k for k in self.dev_cache
                  if k[0] == table and k[1] == col]:
            del self.dev_cache[k]

    def _resolve_all(self, p: lp.Plan) -> None:
        for node in p.walk():
            if isinstance(node, lp.Scan) and node.predicate is not None:
                node.predicate = self.np_exec._resolve_subqueries(
                    node.predicate)
            elif isinstance(node, lp.Filter):
                node.condition = self.np_exec._resolve_subqueries(
                    node.condition)
            elif isinstance(node, lp.Project):
                node.exprs = [(n, self.np_exec._resolve_subqueries(e))
                              for n, e in node.exprs]

    def _prepare(self, p: lp.Plan) -> bool:
        """True when `p` contains the sharded scan; resolves broadcast-join
        build sides on the host as it walks."""
        if isinstance(p, lp.Scan):
            if p is self.fact_target:
                self.fact = p
                return True
            return False
        if isinstance(p, lp.Join):
            on_left = self._prepare(p.left)
            on_right = False if on_left else self._prepare(p.right)
            if not (on_left or on_right):
                return False
            kind = p.kind
            if kind not in lowreg.SPMD_SPINE_JOIN_KINDS:
                raise DistUnsupported(f"{kind} join on spine", code="NDS303")
            keys = list(p.keys)
            if not keys:
                raise DistUnsupported("non-equi join on spine", code="NDS304")
            if not on_left:
                if kind in lowreg.SPMD_REDUCIBLE_BUILD_JOIN_KINDS:
                    # this candidate can't continue (the join's output
                    # is the build side), but the probe-side anchor will
                    # take the join with a distributed reduced build —
                    # info, not a warning (see _reduce_build)
                    raise DistUnsupported(
                        f"sharded table on the build side of {kind} join",
                        code="NDS308")
                if kind != "inner":
                    raise DistUnsupported(
                        f"sharded table on the build side of {kind} join",
                        code="NDS303")
                keys = [(r, l) for l, r in keys]
            build_plan = p.right if on_left else p.left
            build = None
            if kind in lowreg.SPMD_REDUCIBLE_BUILD_JOIN_KINDS and not (
                    kind == "nullaware_anti" and p.extra is not None):
                reduced = self._reduce_build(p, keys, build_plan)
                if reduced is not None:
                    build, keys = reduced
            if build is None:
                build = self.np_exec.execute(build_plan)
            probe_exprs = [l for l, _ in keys]
            bvalid = np.ones(build.num_rows, dtype=bool)
            key_parts = []
            key_dicts: List[Optional[np.ndarray]] = []
            fixed_spans: List[Optional[Tuple[int, int]]] = []
            for _, be in keys:
                c = ex.Evaluator(build).eval(be)
                if c.ctype.kind == "string":
                    # string keys join in the BUILD dictionary's code
                    # space; the traced probe translates its own codes
                    # through a static mapping (both dictionaries are
                    # host metadata at trace time) — or uses them
                    # directly when both sides carry the same frozen
                    # global dictionary (_probe_keys identity path)
                    if c.dictionary is None:
                        raise DistUnsupported(
                            "string join key without dictionary (no "
                            "frozen global dict either — see "
                            "DICT_AUDIT.md coverage; "
                            "NDSTPU_GLOBAL_DICTS=0 disables the "
                            "global-dictionary path)",
                            code="NDS307")
                    key_parts.append(c.data.astype(np.int64))
                    key_dicts.append(c.dictionary)
                    fixed_spans.append((0, len(c.dictionary) + 1))
                elif c.ctype.kind in _KEY_KINDS:
                    key_parts.append(c.data.astype(np.int64))
                    key_dicts.append(None)
                    fixed_spans.append(None)
                else:
                    raise DistUnsupported(
                        f"{c.ctype.kind} join key on spine",
                        code="NDS307")
                bvalid &= c.validity()
            bkeys = np.zeros(build.num_rows, dtype=np.int64)
            radices: List[Tuple[int, int]] = []
            bound = 1
            for part, fixed in zip(key_parts, fixed_spans):
                if fixed is not None:
                    lo, span = fixed
                else:
                    lo = int(part.min()) if len(part) else 0
                    hi = int(part.max()) if len(part) else 0
                    span = hi - lo + 2
                bound *= span
                if bound >= 2 ** 62:
                    raise DistUnsupported("composite key domain overflow")
                radices.append((lo, span))
                bkeys = bkeys * span + np.clip(part - lo, 0, span - 1) + 1
            bkeys = np.where(bvalid, bkeys, np.int64(-1))
            order = np.argsort(bkeys, kind="stable")
            skeys = bkeys[order]
            first_valid = int(np.searchsorted(skeys, 0))
            skeys = skeys[first_valid:]
            row_of = order[first_valid:]
            unique = len(np.unique(skeys)) == len(skeys)
            if not unique and kind == "inner" and self._dup_insensitive \
                    and not (set(build.column_names)
                             & self._refs_above_join(p, build_plan)):
                # an expanding inner join none of whose build columns
                # survive past the join itself, feeding a
                # duplicate-insensitive aggregate (pure GROUP BY dedup or
                # min/max/distinct leaves): row multiplicity is
                # irrelevant, so probe existence suffices — run it as a
                # semi join (q37/q82 inventory-expansion shape)
                kind = "semi"
            dup_max = 0
            if not unique:
                if kind == "left":
                    # unmatched-row bookkeeping under expansion not built
                    raise DistUnsupported(
                        "non-unique build keys for left join")
                if kind == "inner":
                    # bounded duplicate EXPANSION: the probe side tiles
                    # d copies per row, copy k matching the k-th
                    # duplicate in the build key run (q72's d1-d2
                    # week_seq join: 7 days per week)
                    _, counts = np.unique(skeys, return_counts=True)
                    dup_max = int(counts.max()) if len(counts) else 0
                    if dup_max > 8:
                        raise DistUnsupported(
                            f"expanding inner join: build key runs too "
                            f"long ({dup_max})")
                elif p.extra is not None:
                    # semi/anti/mark with a residual: probe every
                    # duplicate in the key run (bounded unrolled loop,
                    # q16/q94 self-join EXISTS shape)
                    if kind == "nullaware_anti":
                        raise DistUnsupported(
                            "residual on nullaware anti join")
                    _, counts = np.unique(skeys, return_counts=True)
                    dup_max = int(counts.max()) if len(counts) else 0
                    if dup_max > 32:
                        raise DistUnsupported(
                            f"build key runs too long ({dup_max})")
            # exchange placement: the structural rule is rows-only; the
            # cost advisor (analysis/cost.py, same choose_strategy the
            # static NDS305/NDS601 analysis uses) may demote a
            # byte-heavy under-row-limit build to the shuffle path —
            # demote-only, and only on placement-order-insensitive
            # (aggregate) spines, so results stay bit-identical to
            # NDSTPU_COST=0
            strategy = "shuffle" if build.num_rows > self.broadcast_limit \
                else "broadcast"
            if self.cost_advisor is not None:
                d = self.cost_advisor.decide_join(
                    build_rows=build.num_rows,
                    build_bytes=_table_bytes(build), kind=kind,
                    dup_max=dup_max, order_safe=self._order_safe)
                obs.inc("engine.cost.decisions")
                if d.overrode:
                    obs.inc("engine.cost.overrides")
                self.cost_decisions.append({
                    "kind": kind, "strategy": d.strategy,
                    "structural": d.structural,
                    "build_rows": int(build.num_rows),
                    "build_bytes": _table_bytes(build),
                    "overrode": d.overrode, "reason": d.reason})
                strategy = d.strategy
            if strategy == "shuffle":
                if dup_max and kind == "inner":
                    raise DistUnsupported(
                        "expanding inner join on a shuffle build side")
                sj = self._stage_shuffle_join(
                    p, kind, probe_exprs, radices, skeys, row_of, build,
                    on_left, bool((~bvalid).any()))
                sj.key_dicts = key_dicts
                sj.dup_max = dup_max
                self.joins[id(p)] = sj
            else:
                self.joins[id(p)] = _BroadcastJoin(
                    kind, p.mark, p.extra, probe_exprs, radices, skeys,
                    row_of, build, on_left,
                    build_has_null=bool((~bvalid).any()),
                    build_empty=build.num_rows == 0,
                    key_dicts=key_dicts, dup_max=dup_max)
            return True
        spine = False
        for c in p.children():
            spine = self._prepare(c) or spine
        return spine

    def _refs_above_join(self, p: lp.Join, build_plan: lp.Plan) -> set:
        """Column names referenced anywhere on the spine OUTSIDE the
        given join's build subtree — i.e. the columns that must survive
        past the join.  The join's own build-side keys and residual are
        consumed by the join and excluded."""
        skip = {id(n) for n in build_plan.walk()}
        refs = set(self._agg_refs)

        def collect(e: ex.Expr) -> None:
            refs.update(nd.name for nd in e.walk()
                        if isinstance(nd, ex.ColumnRef))

        for nd in self._row_head.walk():
            if id(nd) in skip:
                continue
            if isinstance(nd, lp.Scan) and nd.predicate is not None:
                collect(nd.predicate)
            elif isinstance(nd, lp.Filter):
                collect(nd.condition)
            elif isinstance(nd, lp.Project):
                for _, e in nd.exprs:
                    collect(e)
            elif isinstance(nd, lp.Join):
                if nd is p:
                    continue   # own keys/extra are consumed here
                for le, re in nd.keys:
                    collect(le)
                    collect(re)
                if nd.extra is not None:
                    collect(nd.extra)
        return refs

    def _reduce_build(self, p: lp.Join, keys, build_plan: lp.Plan):
        """Distributed reduction of an existence-join build side that
        contains a sharded-size fact (q10/q35/q69 EXISTS-over-store_sales
        shape): semi/anti/nullaware_anti/mark joins are insensitive to
        build-side row multiplicity, so instead of executing the whole
        build subtree on host numpy, a CHILD spine groups it by the join
        keys (plus any residual-referenced build columns) over the mesh
        and only the distinct tuples come back to broadcast.  Returns
        (reduced_build_table, rewritten_keys) or None to keep the host
        path (status quo) — any child failure degrades, never errors."""
        if not any(isinstance(n, lp.Scan) and n.table in self.catalog and
                   self.catalog.get(n.table).num_rows >= self.threshold
                   for n in build_plan.walk()):
            return None
        group = [(f"__bk{i}", be) for i, (_pe, be) in enumerate(keys)]
        if p.extra is not None:
            names = _output_names(build_plan, self.catalog)
            if names is None:
                return None
            used = {nd.name for nd in p.extra.walk()
                    if isinstance(nd, ex.ColumnRef)}
            group += [(c, ex.ColumnRef(c)) for c in sorted(used
                                                           & set(names))]
        bplan = lp.Aggregate(build_plan, group, [], None)
        child = DistributedPlanExecutor(
            self.catalog, self.mesh, self.threshold,
            self.broadcast_limit, self.dev_cache,
            chunk_rows=self.chunk_rows,
            prefetch_depth=self.prefetch_depth,
            cost_advisor=self.cost_advisor)
        try:
            reduced = child.execute_plan(bplan)
        except (DistUnsupported, Unsupported) as e:
            code = getattr(e, "code", None)
            if code:
                self.attempt_codes.append(code)
            self.attempt_codes += child.attempt_codes
            return None
        self.attempt_codes += child.attempt_codes
        self.cost_decisions += child.cost_decisions
        self.build_reduced.append((p.kind, reduced.num_rows))
        obs.inc("engine.spmd.build_reduce")
        if self.cost_advisor is not None:
            obs.inc("engine.cost.decisions")
            self.cost_decisions.append({
                "kind": p.kind, "strategy": "build-reduce",
                "structural": "build-reduce",
                "build_rows": int(reduced.num_rows),
                "build_bytes": _table_bytes(reduced),
                "overrode": False,
                "reason": "existence build reduced to distinct key "
                          "tuples distributed"})
        new_keys = [(pe, ex.ColumnRef(f"__bk{i}"))
                    for i, (pe, _be) in enumerate(keys)]
        return reduced, new_keys

    def _stage_shuffle_join(self, p: lp.Join, kind: str, probe_exprs,
                            radices, skeys: np.ndarray, row_of: np.ndarray,
                            build: Table, on_left: bool,
                            build_has_null: bool) -> _ShuffleJoin:
        """Hash-partition the (too-large-to-broadcast) build side across
        devices by the same splitmix64 bucket hash the traced probe
        shuffle uses; each partition is sorted by key for a local
        searchsorted probe, and build columns are gathered into
        partition order so the probe position indexes them directly."""
        from ndstpu.parallel import exchange
        nd = self.n_dev
        dest = (exchange.mix64_np(skeys.astype(np.uint64))
                % np.uint64(nd)).astype(np.int64)
        order = np.lexsort((skeys, dest))
        counts = np.bincount(dest, minlength=nd)
        part_cap = max(int(counts.max()) if len(skeys) else 0, 1)
        offs = np.concatenate([[0], np.cumsum(counts)])
        within = np.arange(len(skeys)) - offs[dest[order]]
        slot = dest[order] * part_cap + within
        keys_flat = np.full(nd * part_cap, _DEAD_KEY, np.int64)
        keys_flat[slot] = skeys[order]
        rowsel = row_of[order]
        cols_flat: Dict[str, tuple] = {}
        for name in build.column_names:
            c = build.column(name)
            data = np.zeros(nd * part_cap, c.data.dtype)
            valid = np.zeros(nd * part_cap, bool)
            data[slot] = c.data[rowsel]
            valid[slot] = c.validity()[rowsel]
            cols_flat[name] = (data, valid, c.ctype, c.dictionary)
        return _ShuffleJoin(
            kind, p.mark, p.extra, probe_exprs, radices, on_left,
            build_has_null, build.num_rows == 0, part_cap, keys_flat,
            cols_flat)

    # -- spine execution -----------------------------------------------------

    def _run_spine(self, spine: lp.Plan) -> Table:
        agg = spine if isinstance(spine, lp.Aggregate) else None
        row_head = agg.child if agg is not None else spine
        if not self._prepared:
            # host-side join staging runs ONCE per plan: skew retries
            # re-enter only to re-trace with a larger bucket slack
            with obs.span("spine_stage", cat="plan-node"):
                self._run_spine_stage(row_head, agg)
        return self._run_spine_traced(spine, agg, row_head)

    def _run_spine_stage(self, row_head, agg) -> None:
        if True:
            self._resolve_all(row_head)
            if agg is not None:
                for _, e in agg.aggs + agg.group_by:
                    for sub in e.walk():
                        if isinstance(sub, ex.SubqueryExpr):
                            raise DistUnsupported(
                                "subquery above row spine")
            # duplicate row multiplicity is invisible to the spine's
            # aggregate when every leaf is min/max or DISTINCT (or the
            # aggregate is a pure GROUP BY dedup) — _prepare may then
            # demote expanding inner joins to semi joins
            self._dup_insensitive = agg is not None and all(
                a.func in ("min", "max") or a.distinct
                for a in self._agg_leaves(agg))
            # an aggregate spine combines partials key-wise, so exchange
            # placement cannot change the observable result; a row spine
            # emits rows in placement order, so the cost advisor must
            # not re-place its joins (bit-identical vs NDSTPU_COST=0)
            self._order_safe = agg is not None
            self._row_head = row_head
            self._agg_refs = set()
            if agg is not None:
                for _, e in agg.aggs + agg.group_by:
                    self._agg_refs |= {
                        nd.name for nd in e.walk()
                        if isinstance(nd, ex.ColumnRef)}
            self._prepare(row_head)
            if (self._tail is not None or self._has_win) and any(
                    getattr(j, "dup_max", 0) and j.kind == "inner"
                    for j in self.joins.values()):
                # row ids number the pre-expansion fact rows; an
                # expanding inner join duplicates them, breaking the
                # deterministic tail/window tiebreak
                raise DistUnsupported(
                    "expanding inner join under a row-id tail/window")
            self._prepared = True

    def _run_spine_traced(self, spine: lp.Plan, agg, row_head) -> Table:
        if self.fact is None:
            raise DistUnsupported("no sharded scan on spine")
        fact_table = self.catalog.get(self.fact.table)

        cols = self.fact.columns
        names = list(cols) if cols is not None else \
            list(fact_table.column_names)
        if not names:
            names = fact_table.column_names[:1]
        n = fact_table.num_rows
        agg_leaves = self._agg_leaves(agg) if agg is not None else []
        has_distinct = any(a.distinct for a in agg_leaves)
        # out-of-core: stream the fact through the device chunk by chunk
        # (one compiled program, per-chunk partials combined on the host
        # exactly like union branches).  DISTINCT needs all rows of a
        # group in one program, so it keeps the resident path.
        # windows need every row of a partition resident in one program
        # (the colocating exchange is per-launch), so they disable
        # chunking; device tails chunk fine (per-chunk top-k supersets)
        chunk_rows, depth = self._resolve_stream(fact_table, names, n)
        chunked = (chunk_rows is not None and n > chunk_rows
                   and not has_distinct and not self._has_win)
        # shard-major streaming geometry: device d owns the contiguous
        # fact rows [d*shard_rows, (d+1)*shard_rows) and launch c
        # streams the shard-local window [c*m, c*m+m) from every shard
        # at once — each device only ever sees its own shard's chunks,
        # and its scan stays a sequential read over its shard.
        # Unchunked degenerates to m == shard_rows, one launch.
        shard_rows = -(-max(n, 1) // self.n_dev)
        m = min(max(-(-chunk_rows // self.n_dev), 1), shard_rows) \
            if chunked else shard_rows
        padded = m * self.n_dev
        n_launches = -(-shard_rows // m) if chunked else 1
        version = getattr(self.catalog, "versions", {}).get(
            self.fact.table)
        row_sh = NamedSharding(self.mesh, P(SHARD_AXIS))

        metas = [(name, fact_table.column(name).ctype,
                  fact_table.column(name).dictionary) for name in names]
        self._fact_metas = metas

        if chunked:
            fact_args = self._build_stream(fact_table, names, n,
                                           shard_rows, m, padded,
                                           n_launches, depth, row_sh)
        else:
            def fact_args(ci: int) -> list:
                args = []
                for name in names:
                    c = fact_table.column(name)
                    ckey = (self.fact.table, name, version, padded)
                    ent = self.dev_cache.get(ckey)
                    if ent is None:
                        self._evict_stale(self.fact.table, name)
                        data = np.zeros(padded, dtype=c.data.dtype)
                        data[:n] = c.data
                        valid = np.zeros(padded, dtype=bool)
                        valid[:n] = c.validity()
                        ent = (jax.device_put(data, row_sh),
                               jax.device_put(valid, row_sh))
                        self.dev_cache[ckey] = ent
                    args += [ent[0], ent[1]]
                akey = (self.fact.table, "__alive__", version, padded)
                al = self.dev_cache.get(akey)
                if al is None:
                    self._evict_stale(self.fact.table, "__alive__")
                    alive = np.zeros(padded, dtype=bool)
                    alive[:n] = True
                    al = jax.device_put(alive, row_sh)
                    self.dev_cache[akey] = al
                args.append(al)
                return args

        self._fact_args_fn = fact_args
        dev_args = fact_args(0)

        # shuffle-join build partitions ride in as extra sharded args
        # (closure constants would be replicated on every device)
        for sj in self.joins.values():
            if not isinstance(sj, _ShuffleJoin):
                continue
            sj.arg_start = len(dev_args)
            sj.n_args = 1 + 2 * len(sj.cols_flat)
            # cached on the join object (skew retries re-enter here) —
            # NOT in the shared dev_cache, whose id()-keyed entries could
            # alias a recycled object id from a dead executor
            dev = getattr(sj, "_dev", None)
            if dev is None:
                staged = [sj.keys_flat] + [
                    a for (d, v, _, _) in sj.cols_flat.values()
                    for a in (d, v)]
                dev = sj._dev = [jax.device_put(a, row_sh)
                                 for a in staged]
                # the device copies are the only ones read from here on;
                # drop the host staging arrays (a whole padded build side)
                # but keep the per-column (ctype, dictionary) metadata
                sj.keys_flat = None
                sj.cols_flat = {nm: (None, None, ct, dic)
                                for nm, (_d, _v, ct, dic)
                                in sj.cols_flat.items()}
            dev_args += dev
        # shard-local launch offset: a tiny replicated scalar traced
        # LAST (so the sharded fact/shuffle arg indices stay stable)
        # that gives every launch its true global row ids
        dev_args.append(np.int64(0))
        n_args = len(dev_args)
        n_fact_args = 2 * len(names) + 1

        # chunked row-mode launches interleave shards, so they also
        # need the global id to restore single-chip row order host-side
        need_rowid = self._tail is not None or self._has_win \
            or (chunked and agg is None)
        self._emit_rowid = chunked

        def body(*args):
            self._cur_args = args
            self._drop_terms = []
            nf = len(metas)
            col_args, alive_arg = args[:2 * nf], args[2 * nf]
            chunk_off = args[-1]
            dcols = {}
            for i, (name, ctype, dictionary) in enumerate(metas):
                dcols[name] = DCol(col_args[2 * i], col_args[2 * i + 1],
                                   ctype, dictionary)
            if need_rowid:
                # global pre-join row position: the deterministic
                # tiebreak that makes the device tail / sharded window
                # bit-identical to the single-chip stable sort.  Device
                # d's launch c covers global rows d*shard_rows +
                # chunk_off + [0, m); unchunked, chunk_off == 0 and
                # shard_rows == m
                base = (lax.axis_index(SHARD_AXIS).astype(jnp.int64)
                        * shard_rows + chunk_off
                        + lax.iota(jnp.int64, m))
                dcols["__rowid__"] = DCol(base, jnp.ones(m, bool), INT64)
            dt = self._exec(row_head, DTable(dcols, alive_arg))
            if has_distinct:
                # DISTINCT needs every row of a group on one device:
                # exchange rows by group-key hash so the local sort-dedup
                # in _leaf_partial is globally exact (the Spark distinct
                # exchange as an ICI all_to_all)
                dt = self._colocate_by_group(agg, dt)
            dropped = sum(self._drop_terms) if self._drop_terms \
                else jnp.int64(0)
            if agg is None:
                if self._tail is not None:
                    return self._device_tail(dt), dropped
                out_names = [nm for nm in dt.column_names
                             if nm != "__rowid__"]
                if chunked:
                    # carried through so _run_chunks can restore the
                    # global row order after the shard-interleaved
                    # launch concat (then dropped host-side)
                    out_names.append("__rowid__")
                self._row_meta = [(nm, dt.columns[nm].ctype,
                                   dt.columns[nm].dictionary)
                                  for nm in out_names]
                flat = []
                for nm in out_names:
                    flat += [dt.columns[nm].data, dt.columns[nm].valid]
                return tuple(flat) + (dt.alive,), dropped
            return self._agg_partials(agg, agg_leaves, dt), dropped

        row_spec = P(SHARD_AXIS) if (agg is None and self._tail is None) \
            else P()
        sharded = shard_map(
            body, mesh=self.mesh,
            in_specs=tuple(P(SHARD_AXIS) for _ in range(n_args - 1))
            + (P(),),
            out_specs=(row_spec, P()),
            check_vma=False)
        self._agg_ctx = (agg, agg_leaves)
        self._compiled_fn = jax.jit(sharded)
        self._dev_args = dev_args
        self._chunk_info = (chunked, n_launches, m, n_fact_args)
        obs.inc("engine.spmd.traces")
        if not chunked:
            # jit is lazy: this first call pays shard_map trace + XLA
            # compile, then runs — a mixed region, so it is left in the
            # statement's execute self-time rather than a cost bucket
            with obs.span("spine_trace_exec", cat="plan-node",
                          n_args=n_args):
                out = jax.device_get(self._compiled_fn(*dev_args))
            return self._post_spine(out)
        with obs.span("spine_trace_exec", cat="plan-node", chunked=True):
            return self._run_chunks()

    def _resolve_stream(self, fact_table, names, n):
        """Resolve the session's chunk_rows / prefetch_depth setting to
        concrete values for this fact.  ``"auto"`` defers to the
        spill-aware planner (engine/memplan.py): chunk size and staging
        depth come from the device memory budget and this fact's
        scanned row width, not a hand-tuned constant."""
        if self.chunk_rows == "auto":
            from ndstpu.engine import memplan
            from ndstpu.io import gdict
            bpr = memplan.row_bytes(
                [fact_table.column(nm).data.dtype.itemsize
                 for nm in names])
            # string codes stream per chunk, but their frozen
            # dictionaries ride every device whole-query — carve their
            # bytes out of the budget before sizing chunks
            dict_bytes = sum(
                gdict.dictionary_nbytes(fact_table.column(nm).dictionary)
                for nm in names
                if fact_table.column(nm).ctype.kind == "string")
            max_depth = self.prefetch_depth \
                if self.prefetch_depth is not None \
                else memplan.DEFAULT_MAX_DEPTH
            # cost-model working set: broadcast builds ride every device
            # whole-query (shuffle builds are partitioned 1/n_dev and
            # already inside COMPUTE_MULT slack) — carve their bytes out
            # so fat replicated builds buy smaller chunks, not spills
            resident = 0
            if self.cost_advisor is not None:
                resident = sum(
                    _table_bytes(j.build) for j in self.joins.values()
                    if isinstance(j, _BroadcastJoin))
            plan = memplan.plan_stream(n, bpr, self.n_dev,
                                       max_depth=max_depth,
                                       dict_bytes=dict_bytes,
                                       resident_bytes=resident)
            obs.annotate(stream_plan=plan.describe())
            obs.set_gauge("engine.stream.chunk_rows",
                          plan.chunk_rows or 0)
            obs.set_gauge("engine.stream.prefetch_depth",
                          plan.prefetch_depth)
            return plan.chunk_rows, plan.prefetch_depth
        depth = self.prefetch_depth if self.prefetch_depth is not None \
            else 2
        return self.chunk_rows, max(int(depth), 0)

    def _build_stream(self, fact_table, names, n, shard_rows, m,
                      padded, n_launches, depth, row_sh):
        """Wire the streaming pipeline for a chunked fact and return
        the per-launch device-arg function.

        Three overlapped stages (docs/ARCHITECTURE.md "Streaming
        out-of-core pipeline"): a :class:`~ndstpu.io.loader.ChunkScanPool`
        reads + decodes shard segments ahead on worker threads (from
        the catalog's registered :class:`~ndstpu.io.loader.ChunkSource`
        when one exists, else a ``TableChunkSource`` view of the
        resident copy, so both paths exercise the same machinery); a
        :class:`~ndstpu.engine.jaxexec.ChunkPrefetcher` stages the
        decoded chunks into HBM with ``jax.device_put`` on a background
        thread while the current launch computes.  ``depth == 0``
        collapses both to synchronous streaming."""
        from ndstpu.engine.jaxexec import ChunkPrefetcher
        from ndstpu.io import loader as io_loader
        source = getattr(self.catalog, "streams", {}).get(
            self.fact.table)
        if source is not None and (
                source.num_rows != n
                or not set(names) <= set(getattr(source, "columns", []))):
            source = None   # stale or partial source: resident scan
        if source is None:
            source = io_loader.TableChunkSource(
                fact_table, self.fact.table, names)

        def host_chunk(ci: int) -> list:
            """Scan/decode launch ci into padded shard-major host
            arrays: [data, valid] per column + the alive mask."""
            bufs = [(np.zeros(padded,
                              dtype=fact_table.column(nm).data.dtype),
                     np.zeros(padded, dtype=bool)) for nm in names]
            alive = np.zeros(padded, dtype=bool)
            off = ci * m
            for d in range(self.n_dev):
                g0 = d * shard_rows + off
                cnt = max(min(m, shard_rows - off, n - g0), 0)
                if cnt <= 0:
                    continue
                lo = d * m
                payload = source.read(g0, cnt)
                for (data, valid), nm in zip(bufs, names):
                    data[lo:lo + cnt] = payload[nm][0]
                    valid[lo:lo + cnt] = payload[nm][1]
                alive[lo:lo + cnt] = True
            flat = [a for pair in bufs for a in pair]
            flat.append(alive)
            return flat

        old_pool = getattr(self, "_stream_pool", None)
        if old_pool is not None:   # superseded by a slack retry retrace
            old_pool.close()
        old_pf = getattr(self, "_prefetch", None)
        if old_pf is not None:
            old_pf.close()
        # scan runs one chunk further ahead than staging so the
        # prefetcher's device_put never waits on a cold read
        pool = io_loader.ChunkScanPool(
            host_chunk, list(range(n_launches)),
            workers=min(max(depth + 1, 1), 4),
            depth=depth + 1 if depth else 0)
        pool.start_ahead()   # cold reads overlap whole-query compile
        self._stream_pool = pool
        self._stream_fresh = True

        def stage(ci: int) -> list:
            host = pool.get(ci)
            nbytes = sum(a.nbytes for a in host)
            devs = [jax.device_put(a, row_sh) for a in host]
            obs.inc("engine.h2d.bytes", nbytes)
            return devs

        self._prefetch = ChunkPrefetcher(stage, n_launches, depth=depth)
        return self._prefetch.get

    def _run_chunks(self):
        """Out-of-core execution: stream fact chunks through the one
        compiled spine program; combine per-chunk outputs on the host
        (aggregate partials re-group like union branches, row-mode
        chunks concatenate)."""
        _chunked, n_launches, m, n_fact_args = self._chunk_info
        shuffle_args = self._dev_args[n_fact_args:-1]
        agg, leaves = self._agg_ctx
        if getattr(self, "_stream_fresh", False):
            self._stream_fresh = False
        else:
            # repeat pass over a cached chunked query: rewind the scan
            # window and staging ring (chunk 0's device args persist
            # from the first pass, so pre-stage from chunk 1)
            pool = getattr(self, "_stream_pool", None)
            if pool is not None:
                pool.reset(next_idx=1)
            pf = getattr(self, "_prefetch", None)
            if pf is not None:
                pf.reset(next_i=1)
        outs = []
        dropped_total = 0
        t_wall = time.monotonic()
        for ci in range(n_launches):
            args = (self._dev_args[:n_fact_args] if ci == 0
                    else self._fact_args_fn(ci))
            off = np.int64(ci * m)
            out, dropped = jax.device_get(
                self._compiled_fn(*(list(args) + shuffle_args + [off])))
            dropped_total += int(np.asarray(dropped))
            outs.append(out)
            if dropped_total:
                break   # the whole pass is discarded and retried
        obs.inc("engine.stream.execute_s", time.monotonic() - t_wall)
        self._last_dropped = dropped_total
        if dropped_total:
            return None   # _run_spine_retrying re-traces with more slack
        for out in outs:
            self._note_host_gather(out)
        if agg is None:
            tables = []
            for out in outs:
                flat, alive_out = out[:-1], np.asarray(out[-1])
                sel = np.nonzero(alive_out)[0]
                cols = {}
                for i, (name, ctype, dictionary) in enumerate(
                        self._row_meta):
                    data = np.asarray(flat[2 * i])[sel]
                    valid = np.asarray(flat[2 * i + 1])[sel]
                    cols[name] = Column(
                        data, ctype, None if valid.all() else valid,
                        dictionary)
                tables.append(Table(cols))
            result = Table.concat(tables)
            rid = result.columns.get("__rowid__")
            if rid is not None:
                # shard-major launches interleave the shards' windows;
                # the threaded global row id restores the single-chip
                # row order exactly (stable: duplicates from expanding
                # joins keep their in-device expansion order)
                result = result.gather(
                    np.argsort(rid.data, kind="stable"))
                result.columns.pop("__rowid__", None)
            return result
        parts = [(*self._unpack_agg(out), list(self._leaf_meta))
                 for out in outs]
        if self._emit_partials:
            # one "branch" worth of partials: chunks simply concatenate
            # (the union combiner re-groups duplicate keys anyway)
            kcs = [p[0] for p in parts]
            merged = Table.concat([Table(kc) for kc in kcs]) \
                if agg.group_by else Table({})
            key_cols = dict(merged.columns)
            leaf_parts = [
                [np.concatenate([p[1][li][pi] for p in parts])
                 for pi in range(len(parts[0][1][li]))]
                for li in range(len(leaves))]
            return key_cols, leaf_parts
        return self._finalize_union(agg, leaves, parts)

    def _post_spine(self, out):
        out, dropped = out
        self._last_dropped = int(np.asarray(dropped))
        if self._last_dropped:
            # truncated by a shuffle bucket overflow: the retry loop
            # discards this result, skip the host finalize
            return None
        self._note_host_gather(out)
        agg, agg_leaves = self._agg_ctx
        if agg is not None:
            key_cols, leaf_parts = self._unpack_agg(out)
            if self._emit_partials:
                return key_cols, leaf_parts
            return self._finalize_from(agg, agg_leaves, key_cols,
                                       leaf_parts)
        flat, alive_out = out[:-1], np.asarray(out[-1])
        sel = np.nonzero(alive_out)[0]
        res = {}
        for i, (name, ctype, dictionary) in enumerate(self._row_meta):
            data = np.asarray(flat[2 * i])[sel]
            valid = np.asarray(flat[2 * i + 1])[sel]
            res[name] = Column(data, ctype,
                               None if valid.all() else valid, dictionary)
        return Table(res)

    # -- traced operators ----------------------------------------------------

    def _exec(self, p: lp.Plan, dt: DTable) -> DTable:
        if isinstance(p, lp.Scan):
            if p.predicate is not None:
                mask = JEval(dt).predicate(p.predicate)
                dt = DTable(dt.columns, dt.alive & mask)
            return dt
        if isinstance(p, lp.SubqueryAlias):
            dt = self._exec(p.child, dt)
            if p.column_aliases:
                cols = dict(dt.columns)
                rid = cols.pop("__rowid__", None)
                cols = dict(zip(p.column_aliases, cols.values()))
                if rid is not None:
                    cols["__rowid__"] = rid
                dt = DTable(cols, dt.alive)
            return dt
        if isinstance(p, lp.Filter):
            dt = self._exec(p.child, dt)
            mask = JEval(dt).predicate(p.condition)
            return DTable(dt.columns, dt.alive & mask)
        if isinstance(p, lp.Project):
            dt = self._exec(p.child, dt)
            evl = JEval(dt)
            out = {n: evl.eval(e) for n, e in p.exprs}
            rid = dt.columns.get("__rowid__")
            if rid is not None and "__rowid__" not in out:
                out["__rowid__"] = rid
            return DTable(out, dt.alive)
        if isinstance(p, lp.Window):
            dt = self._exec(p.child, dt)
            return self._exec_window_dist(p, dt)
        if isinstance(p, lp.Join):
            bj = self.joins.get(id(p))
            if bj is None:
                raise DistUnsupported("unprepared join on spine")
            dt = self._exec(p.left if bj.spine_left else p.right, dt)
            if isinstance(bj, _ShuffleJoin):
                return self._shuffle_join(bj, dt)
            return self._broadcast_join(bj, dt)
        raise DistUnsupported(f"{type(p).__name__} in traced spine")

    def _probe_keys(self, evl: JEval, key_exprs, radices, cap,
                    key_dicts=None):
        """Radix-encode the probe-side key parts into one int64 plus
        NULL/out-of-domain masks (shared by broadcast + shuffle joins).
        String parts translate probe dictionary codes into the build
        dictionary's code space via a static (trace-time) mapping."""
        pkey = jnp.zeros(cap, jnp.int64)
        pnull = jnp.zeros(cap, bool)
        in_dom = jnp.ones(cap, bool)
        dicts = key_dicts or [None] * len(radices)
        for e, (lo, span), kd in zip(key_exprs, radices, dicts):
            c = evl.eval(e)
            if kd is not None:
                if c.ctype.kind != "string" or c.dictionary is None:
                    raise DistUnsupported(
                        f"string key against {c.ctype.kind} probe "
                        f"(no shared global dictionary — see "
                        f"DICT_AUDIT.md; NDSTPU_GLOBAL_DICTS=0 "
                        f"disables the global-dictionary path)",
                        code="NDS307")
                np_dict = c.dictionary
                from ndstpu.io import gdict as _gdict
                if _gdict.enabled() and len(kd) == len(np_dict) and \
                        np.array_equal(kd, np_dict):
                    # both sides carry the same frozen code space
                    # (warehouse-wide global dictionary): codes ARE the
                    # key parts, no translation table.  Negative codes
                    # (NULL -1 / translate-miss -2) map out-of-domain.
                    obs.inc("engine.dict.identity_joins")
                    part = jnp.where(
                        c.data >= 0, c.data.astype(jnp.int64),
                        jnp.int64(len(kd)))
                elif len(np_dict) and len(kd):
                    pos = np.searchsorted(kd, np_dict)
                    posc = np.clip(pos, 0, len(kd) - 1)
                    ok = kd[posc] == np_dict
                    mapping = np.where(ok, posc,
                                       np.int64(len(kd))).astype(np.int64)
                    codes = jnp.clip(c.data.astype(jnp.int64), 0,
                                     max(len(np_dict) - 1, 0))
                    part = jnp.asarray(mapping)[codes]
                else:
                    mapping = np.full(max(len(np_dict), 1), len(kd),
                                      np.int64)
                    codes = jnp.clip(c.data.astype(jnp.int64), 0,
                                     max(len(np_dict) - 1, 0))
                    part = jnp.asarray(mapping)[codes]
            elif c.ctype.kind not in _KEY_KINDS:
                raise DistUnsupported(f"{c.ctype.kind} probe key",
                                      code="NDS307")
            else:
                part = c.data.astype(jnp.int64)
            pnull |= ~c.valid
            in_dom &= (part >= lo) & (part < lo + span - 1)
            pkey = pkey * span + jnp.clip(part - lo, 0, span - 1) + 1
        return pkey, pnull, in_dom

    def _shuffle_join(self, sj: _ShuffleJoin, dt: DTable) -> DTable:
        """all_to_all the live spine rows to the device owning their key
        bucket, then probe this device's sorted build partition."""
        from ndstpu.parallel import exchange
        cap = dt.capacity
        pkey, pnull, in_dom = self._probe_keys(
            JEval(dt), sj.probe_key_exprs, sj.radices, cap,
            sj.key_dicts)
        pok = ~pnull & in_dom
        # keyless-but-alive rows (NULL / out-of-domain) stay local: they
        # can't match anywhere but must survive left/anti/mark joins
        my = lax.axis_index(SHARD_AXIS).astype(jnp.int32)
        dest = jnp.where(
            pok,
            (exchange._mix64(pkey) % jnp.uint64(self.n_dev))
            .astype(jnp.int32),
            my)
        bucket_cap = max(16, -(-(cap * self.shuffle_slack) // self.n_dev))
        metas = [(n, c.ctype, c.dictionary) for n, c in dt.columns.items()]
        cols = {}
        for name, c in dt.columns.items():
            cols["d" + name] = c.data
            cols["v" + name] = c.valid
        cols["__pkey"] = pkey
        cols["__pok"] = pok
        cols["__pnull"] = pnull
        shuf, alive, n_dropped = exchange.repartition_by_dest(
            cols, dest, dt.alive, self.n_dev, bucket_cap)
        self._drop_terms.append(n_dropped)
        ncap = self.n_dev * bucket_cap
        dcols = {n: DCol(shuf["d" + n], shuf["v" + n], ct, dic)
                 for n, ct, dic in metas}
        pkey = shuf["__pkey"]
        pnull = shuf["__pnull"]
        pok = shuf["__pok"] & alive
        # local probe: this device's partition slice of the staged args
        sl = self._cur_args[sj.arg_start: sj.arg_start + sj.n_args]
        lkeys = sl[0]
        npart = lkeys.shape[0]
        if sj.dup_max and sj.extra is not None:
            # duplicate keys + residual (semi/anti/mark): probe the
            # whole key run; runs are contiguous inside a partition
            # because staging sorts each partition by key
            start = jnp.searchsorted(lkeys, pkey)
            found = jnp.zeros(ncap, bool)
            for k in range(sj.dup_max):
                posk = jnp.clip(start + k, 0, npart - 1)
                cand = (start + k < npart) & (lkeys[posk] == pkey) & pok
                bc = {}
                for i, (name, (_d, _v, ct, dic)) in enumerate(
                        sj.cols_flat.items()):
                    bc[name] = DCol(sl[1 + 2 * i][posk],
                                    sl[2 + 2 * i][posk] & cand, ct, dic)
                res = JEval(DTable({**dcols, **bc},
                                   alive)).predicate(sj.extra)
                found = found | (cand & res)
            combined = DTable(dcols, alive)
        else:
            pos = jnp.searchsorted(lkeys, pkey)
            posc = jnp.clip(pos, 0, npart - 1)
            found = (lkeys[posc] == pkey) & pok
            bcols: Dict[str, DCol] = {}
            for i, (name, (_d, _v, ct, dic)) in enumerate(
                    sj.cols_flat.items()):
                bcols[name] = DCol(sl[1 + 2 * i][posc],
                                   sl[2 + 2 * i][posc] & found, ct, dic)
            combined = DTable({**dcols, **bcols}, alive)
            if sj.extra is not None:
                found = found & JEval(combined).predicate(sj.extra)
                bcols = {n: DCol(c.data, c.valid & found, c.ctype,
                                 c.dictionary) for n, c in bcols.items()}
                combined = DTable({**dcols, **bcols}, alive)
        if sj.kind == "inner":
            return DTable(combined.columns, alive & found)
        if sj.kind == "left":
            return combined
        if sj.kind == "semi":
            return DTable(dcols, alive & found)
        if sj.kind == "anti":
            return DTable(dcols, alive & ~found)
        if sj.kind == "nullaware_anti":
            if sj.extra is not None:
                raise DistUnsupported("residual on nullaware anti join")
            if sj.build_has_null:   # NOT IN (... NULL ...): never TRUE
                return DTable(dcols, jnp.zeros(ncap, bool))
            if sj.build_empty:      # NOT IN (empty): keep everything
                return DTable(dcols, alive)
            return DTable(dcols, alive & ~found & ~pnull)
        # mark
        out = dict(dcols)
        out[sj.mark] = DCol(found, jnp.ones(ncap, bool), BOOL)
        return DTable(out, alive)

    def _broadcast_join(self, bj: _BroadcastJoin, dt: DTable) -> DTable:
        cap = dt.capacity
        pkey, pnull, in_dom = self._probe_keys(
            JEval(dt), bj.probe_key_exprs, bj.radices, cap,
            bj.key_dicts)
        pvalid = ~pnull & in_dom & dt.alive
        bcols: Dict[str, DCol] = {}
        if len(bj.sorted_keys) == 0:
            # empty build side (a filter left no rows): no matches, and
            # there is nothing to gather from — emit typed NULL columns
            found = jnp.zeros(cap, bool)
            for name in bj.build.column_names:
                c = bj.build.column(name)
                data = jnp.zeros(cap, jnp_dtype(c.ctype))
                bcols[name] = DCol(data, jnp.zeros(cap, bool), c.ctype,
                                   c.dictionary)
            combined = DTable({**dt.columns, **bcols}, dt.alive)
        elif bj.dup_max and bj.kind == "inner":
            # EXPANDING inner join: tile the probe side d times
            # (copy-major: expanded row k*cap+i is probe row i matched
            # against the k-th duplicate in its build key run); dead
            # copies are masked, downstream ops just see a d-times
            # capacity (q72's week_seq join, 7 days per week)
            d = bj.dup_max
            skeys = jnp.asarray(bj.sorted_keys)
            rowof = jnp.asarray(bj.row_of)
            nb = len(bj.sorted_keys)
            start = jnp.searchsorted(skeys, pkey)

            def tile(a):
                return jnp.concatenate([a] * d)

            pos = tile(start) + jnp.repeat(jnp.arange(d), cap)
            posc = jnp.clip(pos, 0, nb - 1)
            cand = (pos < nb) & (skeys[posc] == tile(pkey)) & tile(pvalid)
            bidx = rowof[posc]
            pcols = {n: DCol(tile(c.data), tile(c.valid), c.ctype,
                             c.dictionary)
                     for n, c in dt.columns.items()}
            for name in bj.build.column_names:
                c = bj.build.column(name)
                bcols[name] = DCol(
                    jnp.asarray(c.data)[bidx],
                    jnp.asarray(c.validity())[bidx] & cand,
                    c.ctype, c.dictionary)
            combined = DTable({**pcols, **bcols}, cand)
            if bj.extra is not None:
                cand = cand & JEval(combined).predicate(bj.extra)
                combined = DTable(combined.columns, cand)
            return combined
        elif bj.dup_max and bj.extra is not None:
            # duplicate build keys + residual (semi/anti/mark): probe
            # every candidate in the key run with an unrolled bounded
            # loop (q16/q94 correlated-EXISTS self-join shape)
            skeys = jnp.asarray(bj.sorted_keys)
            rowof = jnp.asarray(bj.row_of)
            nb = len(bj.sorted_keys)
            start = jnp.searchsorted(skeys, pkey)
            found = jnp.zeros(cap, bool)
            for k in range(bj.dup_max):
                posk = jnp.clip(start + k, 0, nb - 1)
                cand = (start + k < nb) & (skeys[posk] == pkey) & pvalid
                bidx_k = rowof[posk]
                bc = {}
                for name in bj.build.column_names:
                    c = bj.build.column(name)
                    bc[name] = DCol(
                        jnp.asarray(c.data)[bidx_k],
                        jnp.asarray(c.validity())[bidx_k] & cand,
                        c.ctype, c.dictionary)
                res = JEval(DTable({**dt.columns, **bc},
                                   dt.alive)).predicate(bj.extra)
                found = found | (cand & res)
            combined = DTable(dt.columns, dt.alive)
        else:
            skeys = jnp.asarray(bj.sorted_keys)
            pos = jnp.searchsorted(skeys, pkey)
            posc = jnp.clip(pos, 0, len(bj.sorted_keys) - 1)
            found = (skeys[posc] == pkey) & pvalid
            bidx = jnp.asarray(bj.row_of)[posc]
            for name in bj.build.column_names:
                c = bj.build.column(name)
                data = jnp.asarray(c.data)[bidx]
                valid = jnp.asarray(c.validity())[bidx] & found
                bcols[name] = DCol(data, valid, c.ctype, c.dictionary)
            combined = DTable({**dt.columns, **bcols}, dt.alive)
            if bj.extra is not None:
                found = found & JEval(combined).predicate(bj.extra)
                bcols = {n: DCol(c.data, c.valid & found, c.ctype,
                                 c.dictionary) for n, c in bcols.items()}
                combined = DTable({**dt.columns, **bcols}, dt.alive)
        if bj.kind == "inner":
            return DTable(combined.columns, dt.alive & found)
        if bj.kind == "left":
            return combined
        if bj.kind == "semi":
            return DTable(dt.columns, dt.alive & found)
        if bj.kind == "anti":
            return DTable(dt.columns, dt.alive & ~found)
        if bj.kind == "nullaware_anti":
            if bj.extra is not None:
                raise DistUnsupported("residual on nullaware anti join")
            if bj.build_has_null:   # NOT IN (... NULL ...): never TRUE
                return DTable(dt.columns, jnp.zeros(cap, bool))
            if bj.build_empty:      # NOT IN (empty): keep everything
                return DTable(dt.columns, dt.alive)
            return DTable(dt.columns, dt.alive & ~found & ~pnull)
        # mark
        cols = dict(dt.columns)
        cols[bj.mark] = DCol(found, jnp.ones(cap, bool), BOOL)
        return DTable(cols, dt.alive)

    # -- distributed aggregation ---------------------------------------------

    def _colocate_by_group(self, agg: lp.Aggregate, dt: DTable) -> DTable:
        """Repartition live rows so every row of one group lands on the
        device owning hash(group keys)."""
        return self._colocate_by_keys([e for _, e in agg.group_by], dt)

    def _colocate_by_keys(self, key_exprs, dt: DTable) -> DTable:
        """Repartition live rows so every row sharing the key tuple lands
        on the device owning hash(keys) — the group/partition-colocating
        all_to_all exchange (DISTINCT aggregation and sharded windows).
        Empty keys collapse everything onto device 0 (a global window
        partition); overflowed receive buckets report via _drop_terms and
        the slack-doubling retry makes the exchange lossless."""
        from ndstpu.parallel import exchange
        evl = JEval(dt)
        cap = dt.capacity
        keys = [_key_i64(evl.eval(e), dt.alive) for e in key_exprs]
        h = jnp.zeros(cap, jnp.uint64)
        for k in keys:
            # float64 group keys keep their float encoding in _key_i64;
            # hash their bits via int64 round-trip is unavailable on TPU,
            # so quantize through int64 cast (collisions only merge
            # devices, never corrupt results — grouping re-checks keys)
            ki = k.astype(jnp.int64) if k.dtype != jnp.int64 else k
            h = exchange._mix64(h ^ exchange._mix64(ki.astype(jnp.uint64)))
        dest = (h % jnp.uint64(self.n_dev)).astype(jnp.int32) \
            if keys else jnp.zeros(cap, jnp.int32)
        bucket_cap = max(16, -(-(cap * self.shuffle_slack) // self.n_dev))
        metas = [(n, c.ctype, c.dictionary)
                 for n, c in dt.columns.items()]
        cols = {}
        for name, c in dt.columns.items():
            cols["d" + name] = c.data
            cols["v" + name] = c.valid
        shuf, alive, n_dropped = exchange.repartition_by_dest(
            cols, dest, dt.alive, self.n_dev, bucket_cap)
        self._drop_terms.append(n_dropped)
        return DTable({n: DCol(shuf["d" + n], shuf["v" + n], ct, dic)
                       for n, ct, dic in metas}, alive)

    # -- sharded windows + device tail ---------------------------------------

    def _exec_window_dist(self, p: lp.Window, dt: DTable) -> DTable:
        """Sharded window functions: colocate rows by partition-key hash
        (one all_to_all per distinct PARTITION BY list), then mirror the
        single-chip _window_column per device with the original row id
        as the deterministic ranking tiebreak (the exchange scrambles
        local row order)."""
        groups: Dict[str, list] = {}
        gorder: List[str] = []
        for name, e in p.exprs:
            if not isinstance(e, ex.WindowExpr):
                raise DistUnsupported("non-window expr in Window node")
            gk = repr(tuple(e.partition_by))
            if gk not in groups:
                groups[gk] = []
                gorder.append(gk)
            groups[gk].append((name, e))
        for gk in gorder:
            exprs = groups[gk]
            dt = self._colocate_by_keys(list(exprs[0][1].partition_by), dt)
            cols = dict(dt.columns)
            for name, w in exprs:
                cols[name] = self._window_column_dist(dt, w)
            dt = DTable(cols, dt.alive)
        return dt

    def _window_column_dist(self, dt: DTable, w: ex.WindowExpr) -> DCol:
        """jaxexec._window_column mirror after the partition-colocating
        exchange: every row of a partition is resident on this device, so
        the local segment ops are globally exact.  Ranking sorts append
        __rowid__ as the last sort key (replays the original row order
        for ties); rank/dense_rank tie detection still looks at the
        ORDER BY keys only.  Running frames and subquery-bearing exprs
        never reach here (lowering.spmd_window_ok)."""
        cap = dt.capacity
        evl = JEval(dt)
        if w.partition_by:
            pcols = [evl.eval(e) for e in w.partition_by]
            pkeys = [_key_col(c, dt.alive) for c in pcols]
        else:
            pkeys = [jnp.where(dt.alive, 0, 1).astype(jnp.int32)]
        pid, _, _ = _group_ids(pkeys)
        okeys = []
        for e, asc in w.order_by:
            c = evl.eval(e)
            okeys.append(self._dev_order_key(evl, c, asc, None))
        if w.func in ("row_number", "rank", "dense_rank"):
            ridk = jnp.where(dt.alive, dt.columns["__rowid__"].data,
                             _DEAD_KEY)
            order = _lexsort_order([pid] + okeys + [ridk])
            idx = lax.iota(jnp.int32, cap)
            pid_s = pid[order]
            newpart = jnp.ones(cap, bool)
            if cap > 1:
                newpart = newpart.at[1:].set(pid_s[1:] != pid_s[:-1])
            part_start = lax.cummax(jnp.where(newpart, idx, 0))
            pos_in_part = idx - part_start
            inv = jnp.zeros(cap, jnp.int32).at[order].set(idx)
            if w.func == "row_number":
                return DCol((pos_in_part + 1)[inv].astype(jnp.int64),
                            jnp.ones(cap, bool), INT64)
            tie = jnp.zeros(cap, bool)
            if cap > 1:
                t = jnp.ones(cap - 1, bool)
                for k in okeys:
                    ks = k[order]
                    t = t & (ks[1:] == ks[:-1])
                tie = tie.at[1:].set(t & ~newpart[1:])
            if w.func == "rank":
                last_nontie = lax.cummax(jnp.where(~tie, idx, 0))
                ranks = pos_in_part[last_nontie] + 1
            else:
                incr = jnp.where(newpart, 0, (~tie).astype(jnp.int32))
                csum = jnp.cumsum(incr)
                base = lax.cummax(jnp.where(newpart, csum, 0))
                ranks = csum - base + 1
            return DCol(ranks[inv].astype(jnp.int64),
                        jnp.ones(cap, bool), INT64)
        if w.order_by:
            raise DistUnsupported("running window frame on spine")
        gid = pid
        if w.func == "count" and (w.arg is None or
                                  isinstance(w.arg, ex.Star)):
            cnt = jax.ops.segment_sum(dt.alive.astype(jnp.int32), gid,
                                      num_segments=cap)
            return DCol(cnt[gid].astype(jnp.int64), jnp.ones(cap, bool),
                        INT64)
        arg = evl.eval(w.arg)
        valid = arg.valid & dt.alive
        cnts = jax.ops.segment_sum(valid.astype(jnp.int32), gid,
                                   num_segments=cap)
        got = (cnts > 0)[gid]
        if w.func == "count":
            return DCol(cnts[gid].astype(jnp.int64),
                        jnp.ones(cap, bool), INT64)
        if w.func == "sum":
            tot = jax.ops.segment_sum(
                _sum_input(arg.data, valid, arg.ctype.kind), gid,
                num_segments=cap)
            if arg.ctype.kind == "decimal":
                return DCol(tot[gid], got,
                            columnar.decimal(38, arg.ctype.scale))
            if arg.ctype.kind in ("int32", "int64"):
                return DCol(tot[gid], got, INT64)
            return DCol(tot[gid], got, FLOAT64)
        if w.func == "avg":
            tot = jax.ops.segment_sum(
                _sum_input(arg.data, valid, arg.ctype.kind), gid,
                num_segments=cap)
            mean = tot.astype(jnp.float64) / jnp.maximum(cnts, 1)
            if arg.ctype.kind == "decimal":
                mean = mean / (10 ** arg.ctype.scale)
            return DCol(mean[gid], got, FLOAT64)
        if w.func in ("min", "max"):
            if arg.ctype.kind == "float64":
                init = jnp.inf if w.func == "min" else -jnp.inf
                vals = jnp.where(valid, arg.data, init)
                seg = (jax.ops.segment_min if w.func == "min"
                       else jax.ops.segment_max)
                return DCol(seg(vals, gid, num_segments=cap)[gid], got,
                            arg.ctype)
            vals = _minmax_vals(arg.data, valid, arg.ctype.kind,
                                w.func == "min")
            seg = (jax.ops.segment_min if w.func == "min"
                   else jax.ops.segment_max)
            out = seg(vals, gid, num_segments=cap)[gid]
            return DCol(out.astype(arg.data.dtype), got, arg.ctype,
                        arg.dictionary)
        raise DistUnsupported(f"window {w.func} on spine")

    def _dev_order_key(self, evl: JEval, c: DCol, asc: bool,
                       nulls_first) -> jnp.ndarray:
        """jaxexec._order_key mirror for traced spine sort keys (floats
        order via +/-inf, narrow ints in int32, else int64; NULLs follow
        nulls_first defaulting to the ascending side; dead rows strictly
        last)."""
        if nulls_first is None:
            nulls_first = asc
        alive = evl.t.alive
        if c.ctype.kind == "float64":
            data = c.data.astype(jnp.float64)
            key = data if asc else -data
            key = jnp.where(c.valid, key,
                            -jnp.inf if nulls_first else jnp.inf)
            return jnp.where(alive, key, jnp.inf)
        if _narrow_span(c) is not None:
            data = c.data.astype(jnp.int32)
            key = data if asc else -data
            key = jnp.where(c.valid, key,
                            _NULL32 if nulls_first else -_NULL32)
            return jnp.where(alive, key, _ORD_DEAD32)
        data = c.data.astype(jnp.int64)
        key = data if asc else -data
        key = jnp.where(c.valid, key,
                        _NULL_KEY if nulls_first else -_NULL_KEY)
        return jnp.where(alive, key, _DEAD_KEY)

    def _device_tail(self, dt: DTable):
        """On-device top-k tail: per-device top `limit` rows by
        (ORDER BY keys, original row id), then a k-row all_gather — the
        host fetches n_dev*k rows instead of the whole sharded relation
        and replays the suffix Sort/Limit over them.  The host's stable
        sort keeps exactly the (okeys, rowid)-least rows, which is the
        set selected here, so the differential stays bit-identical; a
        bare LIMIT degenerates to rowid order = original row order."""
        sort_keys, limit = self._tail
        cap = dt.capacity
        evl = JEval(dt)
        okeys = []
        for entry in (sort_keys or []):
            e, asc = entry[0], entry[1]
            nf = entry[2] if len(entry) > 2 else None
            try:
                c = evl.eval(e)
            except Unsupported as u:
                raise DistUnsupported(f"tail sort key: {u}", code=u.code)
            okeys.append(self._dev_order_key(evl, c, asc, nf))
        rid = dt.columns["__rowid__"].data
        ridk = jnp.where(dt.alive, rid, _DEAD_KEY)
        k = min(limit, cap)
        order = _lexsort_order(okeys + [ridk])[:k]

        def gather(x):
            obs.inc("exchange.collective.calls")
            obs.inc("exchange.all_gather.calls")
            obs.inc("exchange.shuffle_bytes",
                    int(x.size * x.dtype.itemsize
                        * self.n_dev * (self.n_dev - 1)))
            return lax.all_gather(x, SHARD_AXIS).reshape(
                (self.n_dev * k,) + x.shape[1:])

        # dead rows carry the dead-last order keys, so a device with
        # fewer than k live rows pads the gather with rows that sort
        # after every live one and are masked out host-side
        g_alive = gather(dt.alive[order])
        g_okeys = [gather(kk[order]) for kk in okeys]
        g_rid = gather(ridk[order])
        forder = _lexsort_order(g_okeys + [g_rid])[
            :min(limit, self.n_dev * k)]
        names = [nm for nm in dt.column_names if nm != "__rowid__"]
        if getattr(self, "_emit_rowid", False):
            # chunked tails: per-launch top-k supersets interleave the
            # shards, so the host combine needs the global row id to
            # restore original order before _finish replays Sort/Limit
            names.append("__rowid__")
        self._row_meta = [(nm, dt.columns[nm].ctype,
                           dt.columns[nm].dictionary) for nm in names]
        flat = []
        for nm in names:
            c = dt.columns[nm]
            flat += [gather(c.data[order])[forder],
                     gather(c.valid[order])[forder]]
        return tuple(flat) + (g_alive[forder],)

    @staticmethod
    def _note_host_gather(out) -> None:
        """Ledger evidence for the tail work: bytes actually fetched
        device->host per spine launch (whole row relations before this
        PR; agg partial tuples or a device tail's k-row result now)."""
        total = 0
        for a in out:
            total += int(np.asarray(a).nbytes)
        obs.inc("engine.spmd.host_gather_bytes", total)

    @staticmethod
    def _agg_leaves(agg: lp.Aggregate) -> List[ex.AggExpr]:
        leaves, seen = [], set()
        for _, e in agg.aggs:
            for sub in e.walk():
                if isinstance(sub, ex.AggExpr) and id(sub) not in seen:
                    seen.add(id(sub))
                    leaves.append(sub)
        return leaves

    def _agg_partials(self, agg: lp.Aggregate, leaves, dt: DTable):
        """Local sort-grouped partials -> all_gather over the mesh ->
        replicated exact final re-group.  Returns a flat tuple of
        replicated arrays; names/ctypes captured via side channels."""
        evl = JEval(dt)
        cap = dt.capacity
        key_cols = [(n, evl.eval(e)) for n, e in agg.group_by]
        self._key_meta = [(n, c.ctype, c.dictionary) for n, c in key_cols]
        if key_cols:
            keys = [_key_i64(c, dt.alive) for _, c in key_cols]
        else:
            keys = [jnp.where(dt.alive, jnp.int64(0), _DEAD_KEY)]
        gid, order, newgrp = _group_ids(keys)
        idx = jnp.arange(cap)
        first_pos = jnp.full(cap, cap, jnp.int64).at[
            (jnp.cumsum(newgrp) - 1)].min(idx)
        rep = order[jnp.clip(first_pos, 0, cap - 1)]
        slot_used = jnp.zeros(cap, bool).at[gid].set(True)
        galive = jax.ops.segment_sum(dt.alive.astype(jnp.int32), gid,
                                     num_segments=cap) > 0
        out_alive = slot_used & galive

        def gather(x):
            # traced-collective instrument: counted once per compiled
            # program (see exchange._note_collective)
            obs.inc("exchange.collective.calls")
            obs.inc("exchange.all_gather.calls")
            obs.inc("exchange.shuffle_bytes",
                    int(x.size * x.dtype.itemsize
                        * self.n_dev * (self.n_dev - 1)))
            return lax.all_gather(x, SHARD_AXIS).reshape(
                (self.n_dev * cap,) + x.shape[1:])

        g_alive = gather(out_alive)
        g_keys = [gather(jnp.where(out_alive, k[rep], _DEAD_KEY))
                  for k in keys]
        g_key_cols = [(gather(c.data[rep]),
                       gather(c.valid[rep] & out_alive))
                      for _, c in key_cols]

        self._leaf_meta = []
        g_leaves = []
        for a in leaves:
            parts, meta = self._leaf_partial(dt, evl, a, gid, cap, order)
            self._leaf_meta.append(meta)
            g_leaves.append([gather(p) for p in parts])

        # replicated exact final re-group over n_dev * cap slots
        total = self.n_dev * cap
        fgid, forder, fnew = _group_ids(g_keys)
        fidx = jnp.arange(total)
        ffirst = jnp.full(total, total, jnp.int64).at[
            (jnp.cumsum(fnew) - 1)].min(fidx)
        frep = forder[jnp.clip(ffirst, 0, total - 1)]
        fused = jnp.zeros(total, bool).at[fgid].set(True)
        fal = jax.ops.segment_sum(g_alive.astype(jnp.int32), fgid,
                                  num_segments=total) > 0
        final_alive = fused & fal

        flat = [final_alive]
        for gdata, gvalid in g_key_cols:
            flat += [gdata[frep], gvalid[frep] & final_alive]
        for a, parts in zip(leaves, g_leaves):
            flat += self._combine_partials(a, parts, fgid, total, g_alive)
        return tuple(flat)

    def _leaf_partial(self, dt: DTable, evl: JEval, a: ex.AggExpr, gid,
                      cap, order):
        """Per-slot partial arrays + static meta for one leaf aggregate.
        ``order`` sorts rows by gid — float sums use the compensated
        segmented scan (TPU f64 runs at f32 precision; df64 module)."""

        def fsum(vals):
            from ndstpu.engine import df64
            return df64.segment_sum_compensated(vals, gid, cap, order)

        alive = dt.alive
        if isinstance(a.arg, ex.Star) or a.arg is None:
            cnt = jax.ops.segment_sum(alive.astype(jnp.int64), gid,
                                      num_segments=cap)
            return [cnt], (a.func, None, None)
        c = evl.eval(a.arg)
        meta = (a.func, c.ctype, c.dictionary)
        valid = c.valid & alive
        if a.distinct:
            # rows were colocated by group key: keep only the first
            # (gid, value) occurrence on this device — globally unique.
            # dorder must NOT shadow `order` — fsum's compensated scan
            # requires the gid-sorted order, not this dedup order
            g2 = jnp.where(valid, gid, jnp.int64(cap))
            xkey = _key_i64(c, valid)
            dorder = _lexsort_order([g2, xkey])
            gs, xs = g2[dorder], xkey[dorder]
            first = jnp.ones(cap, bool).at[1:].set(
                (gs[1:] != gs[:-1]) | (xs[1:] != xs[:-1]))
            valid = valid & jnp.zeros(cap, bool).at[dorder].set(
                first & (gs < cap))
        cnt = jax.ops.segment_sum(valid.astype(jnp.int64), gid,
                                  num_segments=cap)
        if a.func == "count":
            return [cnt], meta
        if a.func in ("sum", "avg"):
            si = _sum_input(c.data, valid, c.ctype.kind)
            if c.ctype.kind in ("decimal", "int32", "int64"):
                s = jax.ops.segment_sum(si, gid, num_segments=cap)
            else:
                s = fsum(si)
            return [s, cnt], meta
        if a.func in ("min", "max"):
            if c.ctype.kind == "float64":
                init = jnp.inf if a.func == "min" else -jnp.inf
                vals = jnp.where(valid, c.data, init)
            else:
                init = _DEAD_KEY if a.func == "min" else -_DEAD_KEY
                vals = jnp.where(valid, c.data.astype(jnp.int64),
                                 jnp.int64(init))
            seg = jax.ops.segment_min if a.func == "min" \
                else jax.ops.segment_max
            return [seg(vals, gid, num_segments=cap), cnt], meta
        # stddev family: [s1, m2(centered), cnt] — see _host_leaf_partial;
        # Chan combine downstream keeps mean >> stddev cases exact
        x = jnp.where(valid, c.data.astype(jnp.float64), 0.0)
        if c.ctype.kind == "decimal":
            x = x / (10 ** c.ctype.scale)
        s1 = fsum(x)
        mean = s1 / jnp.maximum(cnt, 1)
        d = jnp.where(valid, x - mean[gid], 0.0)
        d1 = fsum(d)
        m2 = fsum(d * d) - jnp.where(
            cnt > 0, d1 * d1 / jnp.maximum(cnt, 1), 0.0)
        return [s1, m2, cnt], meta

    def _combine_partials(self, a: ex.AggExpr, parts, fgid, total,
                          g_alive):
        if a.func in ("stddev_samp", "var_samp", "stddev", "variance") \
                and len(parts) == 3:
            # Chan combine: M2 = sum m2_i + sum n_i (mean_i - mean)^2.
            # The correction MUST subtract the means before squaring —
            # expanding it reintroduces the raw-moment cancellation.
            s1, m2, cnt = [jnp.where(g_alive, p, jnp.zeros((), p.dtype))
                           for p in parts]
            S1 = jax.ops.segment_sum(s1, fgid, num_segments=total)
            CNT = jax.ops.segment_sum(cnt, fgid, num_segments=total)
            mean_tot = S1 / jnp.maximum(CNT, 1)
            mean_i = s1 / jnp.maximum(cnt, 1)
            dm = mean_i - mean_tot[fgid]
            corr = jax.ops.segment_sum(
                jnp.where(cnt > 0, cnt * dm * dm, 0.0), fgid,
                num_segments=total)
            M2 = jax.ops.segment_sum(m2, fgid, num_segments=total) + corr
            return [S1, M2, CNT]
        out = []
        minmax = a.func in ("min", "max")
        for pi, part in enumerate(parts):
            if minmax and pi == 0:
                seg = jax.ops.segment_min if a.func == "min" \
                    else jax.ops.segment_max
                if part.dtype == jnp.float64:
                    init = jnp.inf if a.func == "min" else -jnp.inf
                else:
                    init = jnp.int64(
                        _DEAD_KEY if a.func == "min" else -_DEAD_KEY)
                vals = jnp.where(g_alive, part, init)
                out.append(seg(vals, fgid, num_segments=total))
            else:
                vals = jnp.where(g_alive, part,
                                 jnp.zeros((), part.dtype))
                out.append(jax.ops.segment_sum(vals, fgid,
                                               num_segments=total))
        return out

    # -- host finalize -------------------------------------------------------

    _PARTS_PER_FUNC = {"count": 1, "sum": 2, "avg": 2, "min": 2, "max": 2,
                       "stddev_samp": 3, "var_samp": 3, "stddev": 3,
                       "variance": 3}

    def _unpack_agg(self, out):
        """Flat replicated spine output -> per-finest-group key Columns
        and raw leaf partial arrays."""
        flat = [np.asarray(a) for a in out]
        final_alive = flat[0]
        sel = np.nonzero(final_alive)[0]
        pos = 1
        key_cols: Dict[str, Column] = {}
        for name, ctype, dictionary in self._key_meta:
            data, valid = flat[pos][sel], flat[pos + 1][sel]
            pos += 2
            key_cols[name] = Column(
                data, ctype, None if valid.all() else valid, dictionary)
        leaf_parts: List[List[np.ndarray]] = []
        for a, meta in zip(self._agg_ctx[1], self._leaf_meta):
            func, _ctype, _dictionary = meta
            nparts = self._PARTS_PER_FUNC[func] if not (
                isinstance(a.arg, ex.Star) or a.arg is None) else 1
            leaf_parts.append([flat[pos + k][sel] for k in range(nparts)])
            pos += nparts
        return key_cols, leaf_parts

    def _finalize_from(self, agg: lp.Aggregate, leaves, key_cols,
                       leaf_parts) -> Table:
        if agg.grouping_sets is not None:
            return self._grouping_sets_result(agg, leaves, key_cols,
                                              leaf_parts)
        leaf_final = {li: self._finalize_leaf(a, meta, parts)
                      for li, (a, meta, parts) in enumerate(
                          zip(leaves, self._leaf_meta, leaf_parts))}
        n_fine = len(next(iter(key_cols.values())).data) if key_cols \
            else (len(leaf_parts[0][0]) if leaf_parts else 0)

        if not agg.group_by and n_fine == 0:
            # SQL global aggregate over zero rows: one row, count 0 / NULL
            for li, (a, meta) in enumerate(zip(leaves, self._leaf_meta)):
                c = leaf_final[li]
                if a.func == "count":
                    leaf_final[li] = Column(
                        np.zeros(1, np.int64), INT64)
                else:
                    leaf_final[li] = Column(
                        np.zeros(1, c.data.dtype), c.ctype,
                        np.zeros(1, bool), c.dictionary)

        sub_cols = {f"__agg{li}": c for li, c in leaf_final.items()}
        gtable = Table({**key_cols, **sub_cols})
        out_cols: Dict[str, Column] = {}
        for name, _ in agg.group_by:
            out_cols[name] = key_cols[name]
        for name, e in agg.aggs:
            out_cols[name] = ex.Evaluator(gtable).eval(
                self._lower_expr(e, leaves))
        return Table(out_cols)

    def _grouping_sets_result(self, agg: lp.Aggregate, leaves,
                              key_cols: Dict[str, Column],
                              leaf_parts) -> Table:
        """ROLLUP/grouping sets: the spine aggregated at the FINEST
        grouping (all keys); each set re-combines those decomposable
        partials on the host (sums add, counts add, min/max fold,
        moments add) — never re-touching the fact rows — then finalizes
        and evaluates the output expressions with ``grouping()``
        resolved per set (Spark semantics, reference rollup queries
        e.g. q18/q22/q27/q36/q67/q70/q86)."""
        names = [n for n, _ in agg.group_by]
        n_fine = len(key_cols[names[0]].data) if names else (
            len(leaf_parts[0][0]) if leaf_parts else 0)
        outs: List[Table] = []
        for subset in agg.grouping_sets:
            sub_keys: List[Tuple[str, Column]] = []
            for i, name in enumerate(names):
                c = key_cols[name]
                if i in subset:
                    sub_keys.append((name, c))
                else:
                    sub_keys.append((name, Column(
                        np.zeros_like(c.data), c.ctype,
                        np.zeros(n_fine, bool), c.dictionary)))
            if names:
                gids, first = self.np_exec._factorize(
                    [c for _, c in sub_keys])
                ng = len(first)
            else:
                # global aggregate: one output row even over no groups
                gids = np.zeros(n_fine, np.int64)
                first = np.zeros(1, np.int64)
                ng = 1
            out_cols: Dict[str, Column] = {}
            for name, c in sub_keys:
                out_cols[name] = c.gather(first) if n_fine else Column(
                    np.zeros(0, c.data.dtype), c.ctype,
                    np.zeros(0, bool), c.dictionary)
            leaf_final: Dict[int, Column] = {}
            for li, (a, meta, parts) in enumerate(
                    zip(leaves, self._leaf_meta, leaf_parts)):
                combined = self._combine_host(a, meta, parts, gids, ng)
                leaf_final[li] = self._finalize_leaf(a, meta, combined)
            # leaf columns are per-group (ng); key cols were gathered to
            # group granularity above — evaluate outputs at that grain
            gtable = Table({**out_cols,
                            **{f"__agg{li}": c
                               for li, c in leaf_final.items()}})
            for name, e in agg.aggs:
                out_cols[name] = ex.Evaluator(gtable).eval(
                    self._lower_expr(e, leaves, gctx=(names, subset)))
            outs.append(Table(out_cols))
        return Table.concat(outs)

    def _combine_host(self, a: ex.AggExpr, meta, parts, gids, ng):
        """Numpy re-combine of finest-group partials into one grouping
        set's groups (mirror of the traced _combine_partials)."""
        func = meta[0]
        has_arg = not (isinstance(a.arg, ex.Star) or a.arg is None)
        cnt = parts[-1] if has_arg and func != "count" else parts[0]
        if func in ("stddev_samp", "var_samp", "stddev", "variance") \
                and has_arg and len(parts) == 3:
            # numpy mirror of the traced Chan combine
            s1, m2, n_i = parts
            S1 = np.zeros(ng, np.float64)
            CNT = np.zeros(ng, np.int64)
            np.add.at(S1, gids, s1)
            np.add.at(CNT, gids, n_i)
            mean_tot = S1 / np.maximum(CNT, 1)
            mean_i = s1 / np.maximum(n_i, 1)
            dm = mean_i - mean_tot[gids]
            corr = np.zeros(ng, np.float64)
            np.add.at(corr, gids, np.where(n_i > 0, n_i * dm * dm, 0.0))
            M2 = np.zeros(ng, np.float64)
            np.add.at(M2, gids, m2)
            return [S1, M2 + corr, CNT]
        out = []
        for pi, part in enumerate(parts):
            if func in ("min", "max") and pi == 0 and has_arg:
                if part.dtype == np.float64:
                    init = np.inf if func == "min" else -np.inf
                else:
                    init = np.int64(_DEAD_KEY if func == "min"
                                    else -_DEAD_KEY)
                acc = np.full(ng, init, part.dtype)
                fold = np.minimum if func == "min" else np.maximum
                vals = np.where(cnt > 0, part, init)
                fold.at(acc, gids, vals)
                out.append(acc)
            else:
                acc = np.zeros(ng, part.dtype)
                np.add.at(acc, gids, part)
                out.append(acc)
        return out

    def _lower_expr(self, e: ex.Expr, leaves,
                    gctx: Optional[tuple] = None) -> ex.Expr:
        for li, a in enumerate(leaves):
            if a is e:
                return ex.ColumnRef(f"__agg{li}")
        if isinstance(e, ex.BinOp):
            return ex.BinOp(e.op, self._lower_expr(e.left, leaves, gctx),
                            self._lower_expr(e.right, leaves, gctx))
        if isinstance(e, ex.UnaryOp):
            return ex.UnaryOp(e.op,
                              self._lower_expr(e.operand, leaves, gctx))
        if isinstance(e, ex.Cast):
            return ex.Cast(self._lower_expr(e.operand, leaves, gctx),
                           e.target)
        if isinstance(e, ex.Func):
            if e.name == "grouping":
                # grouping(key) = 0 when the key participates in this
                # grouping set, 1 when rolled up (Spark semantics,
                # mirror of physical._eval_agg)
                if gctx is None:
                    return ex.Literal(0)
                names, subset = gctx
                arg = e.args[0]
                idx = names.index(arg.name) if isinstance(
                    arg, ex.ColumnRef) and arg.name in names else -1
                active = subset is None or idx in subset
                return ex.Literal(0 if active else 1)
            return ex.Func(e.name, tuple(self._lower_expr(a, leaves, gctx)
                                         for a in e.args))
        if isinstance(e, ex.Case):
            return ex.Case(
                tuple((self._lower_expr(c, leaves, gctx),
                       self._lower_expr(v, leaves, gctx))
                      for c, v in e.whens),
                self._lower_expr(e.default, leaves, gctx)
                if e.default is not None else None)
        if isinstance(e, ex.InList):
            return ex.InList(self._lower_expr(e.operand, leaves, gctx),
                             e.values, e.negated)
        if isinstance(e, ex.AggExpr):
            # an aggregate leaf the collection pass missed — bail to the
            # single-chip path rather than crash at finalize
            raise DistUnsupported("unlowered aggregate in output expr",
                                  code="NDS302")
        return e

    def _finalize_leaf(self, a: ex.AggExpr, meta, parts) -> Column:
        func, ctype, dictionary = meta
        if isinstance(a.arg, ex.Star) or a.arg is None or func == "count":
            return Column(parts[0].astype(np.int64), INT64)
        if func == "sum":
            s, cnt = parts
            got = cnt > 0
            vopt = None if got.all() else got
            if ctype.kind == "decimal":
                return Column(s.astype(np.int64),
                              columnar.decimal(38, ctype.scale), vopt)
            if ctype.kind in ("int32", "int64"):
                return Column(s.astype(np.int64), INT64, vopt)
            return Column(s.astype(np.float64), FLOAT64, vopt)
        if func == "avg":
            s, cnt = parts
            got = cnt > 0
            mean = s.astype(np.float64) / np.maximum(cnt, 1)
            if ctype.kind == "decimal":
                mean = mean / (10 ** ctype.scale)
            return Column(mean, FLOAT64, None if got.all() else got)
        if func in ("min", "max"):
            v, cnt = parts
            got = cnt > 0
            vopt = None if got.all() else got
            if ctype.kind == "float64":
                return Column(v.astype(np.float64), ctype, vopt)
            dtype = columnar.numpy_dtype(ctype)
            return Column(v.astype(dtype), ctype, vopt, dictionary)
        # stddev family: parts[1] is already the centered M2 (Chan
        # combine upstream) — no raw-moment subtraction left to cancel
        _s1, m2, cnt = parts
        ok = cnt > 1
        denom = np.where(ok, cnt - 1, 1)
        var = np.maximum(m2, 0.0) / denom
        data = var if func in ("var_samp", "variance") else np.sqrt(var)
        return Column(data, FLOAT64, None if ok.all() else ok)


# the union-distribution walk is shared with the static analyzer
# (lowering._audit_spine models the same split the executor performs)
_path_to = lowreg.plan_path_to
_distributive_path = lowreg.union_distributive_path


def _output_names(p: lp.Plan, catalog) -> Optional[List[str]]:
    """Static output column names of a plan (mirror of how the numpy
    executor names each node's output), or None when unknown."""
    if isinstance(p, lp.Scan):
        if p.columns is not None:
            return list(p.columns) or \
                [catalog.get(p.table).column_names[0]]
        return list(catalog.get(p.table).column_names)
    if isinstance(p, lp.InlineTable):
        return list(p.table.column_names)
    if isinstance(p, lp.Project):
        return [n for n, _ in p.exprs]
    if isinstance(p, lp.Aggregate):
        return [n for n, _ in p.group_by] + [n for n, _ in p.aggs]
    if isinstance(p, lp.Window):
        base = _output_names(p.child, catalog)
        if base is None:
            return None
        return base + [n for n, _ in p.exprs if n not in base]
    if isinstance(p, (lp.Filter, lp.Sort, lp.Limit, lp.Distinct)):
        return _output_names(p.child, catalog)
    if isinstance(p, lp.SubqueryAlias):
        if p.column_aliases:
            return list(p.column_aliases)
        return _output_names(p.child, catalog)
    if isinstance(p, lp.SetOp):
        return _output_names(p.left, catalog)
    if isinstance(p, lp.Join):
        left = _output_names(p.left, catalog)
        if p.kind in ("semi", "anti", "nullaware_anti"):
            return left
        if p.mark is not None:
            return None if left is None else left + [p.mark]
        right = _output_names(p.right, catalog)
        if left is None or right is None:
            return None
        return left + right
    return None


def _graft(top: lp.Plan, old: lp.Plan, new: lp.Plan) -> lp.Plan:
    """Copy of `top` with the subtree `old` replaced by `new`."""
    if top is old:
        return new
    n = copy.copy(top)
    for attr in ("child", "left", "right"):
        c = getattr(n, attr, None)
        if c is not None:
            setattr(n, attr, _graft(c, old, new))
    return n


def execute_distributed(catalog, mesh, plan: lp.Plan,
                        shard_threshold_rows: int = 65536,
                        broadcast_limit_rows: int = 8_000_000) -> Table:
    """One-shot helper: run `plan` over `mesh`, DistUnsupported on plans
    outside the distributed subset."""
    return DistributedPlanExecutor(
        catalog, mesh, shard_threshold_rows,
        broadcast_limit_rows).execute_plan(plan)
