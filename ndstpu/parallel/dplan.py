"""Distributed plan executor: SQL plans as single SPMD XLA programs.

Executes the planner/optimizer's logical plans over a ``jax.sharding.Mesh``
— the multi-chip analog of Spark's distributed SQL execution (reference:
executors + shuffle exchange, power_run_cpu.template:23-33) designed
TPU-first rather than translated:

* The **spine** — the operator chain over the single largest table — runs
  row-sharded over the mesh's data axis inside ONE ``jit(shard_map)``
  program: filters/projects are local, dimension joins are broadcast
  joins (host-resolved build side, searchsorted probe — surrogate keys
  are ints), aggregation is local sort-grouped partials combined via
  ``lax.all_gather`` over ICI and re-grouped replicated (exact, no hash
  collisions; the psum combine for dense keys lives in
  ndstpu.parallel.dquery, the all_to_all repartition in
  ndstpu.parallel.exchange).
* **Build sides and the plan tail** (the tiny part: dimension subtrees,
  final Sort/Limit/Project over a handful of groups) execute on the host
  numpy interpreter — the driver side of a broadcast join.
* Plans without a sharded-size table, or using operators outside the
  distributed subset, raise :class:`DistUnsupported`; callers fall back
  to the single-chip engine (ndstpu.engine.jaxexec).

Differentially tested against the numpy interpreter on a virtual
8-device CPU mesh (tests/test_parallel.py) and compile-checked by the
driver via __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ndstpu.engine import columnar, expr as ex, physical, plan as lp
from ndstpu.engine.columnar import BOOL, FLOAT64, INT64, Column, Table
from ndstpu.engine.jaxexec import (
    DCol,
    DTable,
    JEval,
    _DEAD_KEY,
    _group_ids,
    _key_i64,
    _sum_input,
)
from ndstpu.parallel.mesh import SHARD_AXIS


class DistUnsupported(Exception):
    """Plan shape outside the distributed subset — fall back single-chip."""


_SPINE_NODES = (lp.Scan, lp.Filter, lp.Project, lp.Join, lp.SubqueryAlias)
_KEY_KINDS = ("int32", "int64", "date")
_AGG_FUNCS = ("sum", "count", "avg", "min", "max",
              "stddev_samp", "var_samp", "stddev", "variance")


@dataclasses.dataclass
class _BroadcastJoin:
    """Host-resolved build side of a spine join (driver-side broadcast)."""
    kind: str
    mark: Optional[str]
    extra: Optional[ex.Expr]
    probe_key_exprs: List[ex.Expr]
    radices: List[Tuple[int, int]]   # (lo, span) per key part
    sorted_keys: np.ndarray          # valid build keys, sorted
    row_of: np.ndarray               # sorted position -> build row index
    build: Table                     # host build table (post plan)
    spine_left: bool                 # spine side is the join's left child
    build_has_null: bool = False     # any build row with a NULL key part
    build_empty: bool = False


class DistributedPlanExecutor:
    """Compiles + runs one logical plan over the mesh (one-shot object)."""

    def __init__(self, catalog, mesh, shard_threshold_rows: int = 65536,
                 broadcast_limit_rows: int = 8_000_000,
                 dev_cache: Optional[dict] = None):
        self.catalog = catalog
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.threshold = shard_threshold_rows
        self.broadcast_limit = broadcast_limit_rows
        self.np_exec = physical.Executor(catalog)
        # shared (table, column, version) -> device arrays cache so many
        # cached query executors don't pin duplicate fact copies in HBM
        self.dev_cache = dev_cache if dev_cache is not None else {}
        self.joins: Dict[int, _BroadcastJoin] = {}
        self.fact: Optional[lp.Scan] = None
        # trace-time metadata side channels (static python values)
        self._row_meta: Optional[List[tuple]] = None
        self._key_meta: Optional[List[tuple]] = None
        self._leaf_meta: Optional[List[tuple]] = None

    # -- public --------------------------------------------------------------

    def execute_plan(self, plan: lp.Plan) -> Table:
        """Try candidate fact tables largest-first (at tiny scale factors
        a fixed-size dimension like date_dim can out-size the fact, and
        some spines fail preparation, e.g. non-unique build keys)."""
        scans = [n for n in plan.walk() if isinstance(n, lp.Scan)]
        if not scans:
            raise DistUnsupported("no base-table scan in plan")
        sized = sorted(((self.catalog.get(n.table).num_rows, i, n)
                        for i, n in enumerate(scans)),
                       key=lambda t: (-t[0], t[1]))
        last: Optional[DistUnsupported] = None
        for rows, _, target in sized:
            if rows < self.threshold:
                break
            for r, _, n in sized:
                if n is not target and r > self.broadcast_limit:
                    raise DistUnsupported(
                        f"second large table {n.table} ({r} rows) "
                        "exceeds the broadcast limit (fact-fact join)")
            self.joins = {}
            self.fact = None
            self.fact_target = target
            try:
                spine, top = self._split(plan)
                result = self._run_spine(spine)
            except DistUnsupported as e:
                last = e
                continue
            self._spine, self._top = spine, top
            return self._finish(result)
        raise last or DistUnsupported("no sharded-size table in plan")

    def _finish(self, result: Table) -> Table:
        if self._top is None:
            return result
        grafted = _graft(self._top, self._spine,
                         lp.InlineTable(result, "__dist__"))
        return self.np_exec.execute(grafted)

    def execute_again(self) -> Table:
        """Re-run the already-compiled spine program (caller must have
        checked catalog versions are unchanged) and redo the host
        finalize + plan tail — the repeat-execution path for cached
        tpu-spmd queries (no re-trace, no re-compile, no host build)."""
        out = jax.device_get(self._compiled_fn(*self._dev_args))
        return self._finish(self._post_spine(out))

    # -- plan analysis -------------------------------------------------------

    def _split(self, plan: lp.Plan) -> Tuple[lp.Plan, Optional[lp.Plan]]:
        """Find the distributed spine: the chain from the single big Scan
        up to the first Aggregate above it (or the highest supported node).
        Returns (spine_head, top_plan); top_plan executes on host over the
        spine's result (None = the spine is the whole plan)."""
        target = self.fact_target

        chain: List[lp.Plan] = []

        def descend(node) -> bool:
            chain.append(node)
            if node is target:
                return True
            for c in node.children():
                if descend(c):
                    return True
            chain.pop()
            return False

        descend(plan)

        def spine_ok(node) -> bool:
            if isinstance(node, lp.Join):
                return node.kind in ("inner", "left", "semi", "anti",
                                    "nullaware_anti", "mark")
            return isinstance(node, _SPINE_NODES)

        agg_i = next((i for i, nd in enumerate(chain)
                      if isinstance(nd, lp.Aggregate)), None)
        if agg_i is not None:
            for nd in chain[agg_i + 1:]:
                if not spine_ok(nd):
                    raise DistUnsupported(
                        f"{type(nd).__name__} below spine aggregate")
            self._check_agg(chain[agg_i])
            spine = chain[agg_i]
        else:
            ok_from = len(chain) - 1
            for i in range(len(chain) - 1, -1, -1):
                if spine_ok(chain[i]):
                    ok_from = i
                else:
                    break
            spine = chain[ok_from]
        top = plan if spine is not plan else None
        return spine, top

    def _check_agg(self, node: lp.Aggregate) -> None:
        if node.grouping_sets is not None:
            raise DistUnsupported("grouping sets on spine")
        for _, e in node.aggs:
            for sub in e.walk():
                if isinstance(sub, ex.AggExpr):
                    if sub.distinct:
                        raise DistUnsupported("distinct agg on spine")
                    if sub.func not in _AGG_FUNCS:
                        raise DistUnsupported(f"agg {sub.func} on spine")
                if isinstance(sub, ex.WindowExpr):
                    raise DistUnsupported("window inside aggregate")

    # -- spine preparation ---------------------------------------------------

    def _evict_stale(self, table: str, col: str) -> None:
        """Drop superseded-version device copies of (table, col) so
        maintenance rounds don't accumulate dead fact copies in HBM."""
        for k in [k for k in self.dev_cache
                  if k[0] == table and k[1] == col]:
            del self.dev_cache[k]

    def _resolve_all(self, p: lp.Plan) -> None:
        for node in p.walk():
            if isinstance(node, lp.Scan) and node.predicate is not None:
                node.predicate = self.np_exec._resolve_subqueries(
                    node.predicate)
            elif isinstance(node, lp.Filter):
                node.condition = self.np_exec._resolve_subqueries(
                    node.condition)
            elif isinstance(node, lp.Project):
                node.exprs = [(n, self.np_exec._resolve_subqueries(e))
                              for n, e in node.exprs]

    def _prepare(self, p: lp.Plan) -> bool:
        """True when `p` contains the sharded scan; resolves broadcast-join
        build sides on the host as it walks."""
        if isinstance(p, lp.Scan):
            if p is self.fact_target:
                self.fact = p
                return True
            return False
        if isinstance(p, lp.Join):
            on_left = self._prepare(p.left)
            on_right = False if on_left else self._prepare(p.right)
            if not (on_left or on_right):
                return False
            kind = p.kind
            if kind not in ("inner", "left", "semi", "anti",
                            "nullaware_anti", "mark"):
                raise DistUnsupported(f"{kind} join on spine")
            keys = list(p.keys)
            if not keys:
                raise DistUnsupported("non-equi join on spine")
            if not on_left:
                if kind != "inner":
                    raise DistUnsupported(
                        f"sharded table on the build side of {kind} join")
                keys = [(r, l) for l, r in keys]
            build_plan = p.right if on_left else p.left
            build = self.np_exec.execute(build_plan)
            probe_exprs = [l for l, _ in keys]
            bvalid = np.ones(build.num_rows, dtype=bool)
            key_parts = []
            for _, be in keys:
                c = ex.Evaluator(build).eval(be)
                if c.ctype.kind not in _KEY_KINDS:
                    raise DistUnsupported(
                        f"{c.ctype.kind} join key on spine")
                key_parts.append(c.data.astype(np.int64))
                bvalid &= c.validity()
            bkeys = np.zeros(build.num_rows, dtype=np.int64)
            radices: List[Tuple[int, int]] = []
            bound = 1
            for part in key_parts:
                lo = int(part.min()) if len(part) else 0
                hi = int(part.max()) if len(part) else 0
                span = hi - lo + 2
                bound *= span
                if bound >= 2 ** 62:
                    raise DistUnsupported("composite key domain overflow")
                radices.append((lo, span))
                bkeys = bkeys * span + np.clip(part - lo, 0, span - 1) + 1
            bkeys = np.where(bvalid, bkeys, np.int64(-1))
            order = np.argsort(bkeys, kind="stable")
            skeys = bkeys[order]
            first_valid = int(np.searchsorted(skeys, 0))
            skeys = skeys[first_valid:]
            row_of = order[first_valid:]
            unique = len(np.unique(skeys)) == len(skeys)
            if not unique and (kind in ("inner", "left") or
                               p.extra is not None):
                # semi/anti/mark tolerate duplicate build keys ONLY when
                # there is no residual: the probe gathers a single
                # arbitrary duplicate, so a residual would be evaluated
                # against one of many candidate rows
                raise DistUnsupported(
                    f"non-unique build keys for {kind} broadcast join")
            self.joins[id(p)] = _BroadcastJoin(
                kind, p.mark, p.extra, probe_exprs, radices, skeys,
                row_of, build, on_left,
                build_has_null=bool((~bvalid).any()),
                build_empty=build.num_rows == 0)
            return True
        spine = False
        for c in p.children():
            spine = self._prepare(c) or spine
        return spine

    # -- spine execution -----------------------------------------------------

    def _run_spine(self, spine: lp.Plan) -> Table:
        agg = spine if isinstance(spine, lp.Aggregate) else None
        row_head = agg.child if agg is not None else spine
        self._resolve_all(row_head)
        if agg is not None:
            for _, e in agg.aggs + agg.group_by:
                for sub in e.walk():
                    if isinstance(sub, ex.SubqueryExpr):
                        raise DistUnsupported("subquery above row spine")
        self._prepare(row_head)
        if self.fact is None:
            raise DistUnsupported("no sharded scan on spine")
        fact_table = self.catalog.get(self.fact.table)

        cols = self.fact.columns
        names = list(cols) if cols is not None else \
            list(fact_table.column_names)
        if not names:
            names = fact_table.column_names[:1]
        n = fact_table.num_rows
        m = -(-max(n, 1) // self.n_dev)
        padded = m * self.n_dev
        version = getattr(self.catalog, "versions", {}).get(
            self.fact.table)
        row_sh = NamedSharding(self.mesh, P(SHARD_AXIS))

        dev_args = []
        metas = []
        for name in names:
            c = fact_table.column(name)
            metas.append((name, c.ctype, c.dictionary))
            ckey = (self.fact.table, name, version, padded)
            ent = self.dev_cache.get(ckey)
            if ent is None:
                self._evict_stale(self.fact.table, name)
                data = np.zeros(padded, dtype=c.data.dtype)
                data[:n] = c.data
                valid = np.zeros(padded, dtype=bool)
                valid[:n] = c.validity()
                ent = (jax.device_put(data, row_sh),
                       jax.device_put(valid, row_sh))
                self.dev_cache[ckey] = ent
            dev_args += [ent[0], ent[1]]
        akey = (self.fact.table, "__alive__", version, padded)
        al = self.dev_cache.get(akey)
        if al is None:
            self._evict_stale(self.fact.table, "__alive__")
            alive = np.zeros(padded, dtype=bool)
            alive[:n] = True
            al = jax.device_put(alive, row_sh)
            self.dev_cache[akey] = al
        dev_args.append(al)
        n_args = len(dev_args)
        self._fact_metas = metas

        agg_leaves = self._agg_leaves(agg) if agg is not None else []

        def body(*args):
            col_args, alive_arg = args[:-1], args[-1]
            dcols = {}
            for i, (name, ctype, dictionary) in enumerate(metas):
                dcols[name] = DCol(col_args[2 * i], col_args[2 * i + 1],
                                   ctype, dictionary)
            dt = self._exec(row_head, DTable(dcols, alive_arg))
            if agg is None:
                self._row_meta = [(nm, dt.columns[nm].ctype,
                                   dt.columns[nm].dictionary)
                                  for nm in dt.column_names]
                flat = []
                for nm in dt.column_names:
                    flat += [dt.columns[nm].data, dt.columns[nm].valid]
                return tuple(flat) + (dt.alive,)
            return self._agg_partials(agg, agg_leaves, dt)

        sharded = shard_map(
            body, mesh=self.mesh,
            in_specs=tuple(P(SHARD_AXIS) for _ in range(n_args)),
            out_specs=P(SHARD_AXIS) if agg is None else P(),
            check_vma=False)
        self._agg_ctx = (agg, agg_leaves)
        self._compiled_fn = jax.jit(sharded)
        self._dev_args = dev_args
        out = jax.device_get(self._compiled_fn(*dev_args))
        return self._post_spine(out)

    def _post_spine(self, out) -> Table:
        agg, agg_leaves = self._agg_ctx
        if agg is not None:
            return self._finalize_agg(agg, agg_leaves, out)
        flat, alive_out = out[:-1], np.asarray(out[-1])
        sel = np.nonzero(alive_out)[0]
        res = {}
        for i, (name, ctype, dictionary) in enumerate(self._row_meta):
            data = np.asarray(flat[2 * i])[sel]
            valid = np.asarray(flat[2 * i + 1])[sel]
            res[name] = Column(data, ctype,
                               None if valid.all() else valid, dictionary)
        return Table(res)

    # -- traced operators ----------------------------------------------------

    def _exec(self, p: lp.Plan, dt: DTable) -> DTable:
        if isinstance(p, lp.Scan):
            if p.predicate is not None:
                mask = JEval(dt).predicate(p.predicate)
                dt = DTable(dt.columns, dt.alive & mask)
            return dt
        if isinstance(p, lp.SubqueryAlias):
            dt = self._exec(p.child, dt)
            if p.column_aliases:
                dt = DTable(dict(zip(p.column_aliases,
                                     dt.columns.values())), dt.alive)
            return dt
        if isinstance(p, lp.Filter):
            dt = self._exec(p.child, dt)
            mask = JEval(dt).predicate(p.condition)
            return DTable(dt.columns, dt.alive & mask)
        if isinstance(p, lp.Project):
            dt = self._exec(p.child, dt)
            evl = JEval(dt)
            return DTable({n: evl.eval(e) for n, e in p.exprs}, dt.alive)
        if isinstance(p, lp.Join):
            bj = self.joins.get(id(p))
            if bj is None:
                raise DistUnsupported("unprepared join on spine")
            dt = self._exec(p.left if bj.spine_left else p.right, dt)
            return self._broadcast_join(bj, dt)
        raise DistUnsupported(f"{type(p).__name__} in traced spine")

    def _broadcast_join(self, bj: _BroadcastJoin, dt: DTable) -> DTable:
        evl = JEval(dt)
        cap = dt.capacity
        pkey = jnp.zeros(cap, jnp.int64)
        pnull = jnp.zeros(cap, bool)
        in_dom = jnp.ones(cap, bool)
        for e, (lo, span) in zip(bj.probe_key_exprs, bj.radices):
            c = evl.eval(e)
            if c.ctype.kind not in _KEY_KINDS:
                raise DistUnsupported(f"{c.ctype.kind} probe key")
            part = c.data.astype(jnp.int64)
            pnull |= ~c.valid
            in_dom &= (part >= lo) & (part < lo + span - 1)
            pkey = pkey * span + jnp.clip(part - lo, 0, span - 1) + 1
        pvalid = ~pnull & in_dom & dt.alive
        if len(bj.sorted_keys) == 0:
            found = jnp.zeros(cap, bool)
            bidx = jnp.zeros(cap, jnp.int64)
        else:
            skeys = jnp.asarray(bj.sorted_keys)
            pos = jnp.searchsorted(skeys, pkey)
            posc = jnp.clip(pos, 0, len(bj.sorted_keys) - 1)
            found = (skeys[posc] == pkey) & pvalid
            bidx = jnp.asarray(bj.row_of)[posc]
        bcols: Dict[str, DCol] = {}
        for name in bj.build.column_names:
            c = bj.build.column(name)
            data = jnp.asarray(c.data)[bidx]
            valid = jnp.asarray(c.validity())[bidx] & found
            bcols[name] = DCol(data, valid, c.ctype, c.dictionary)
        combined = DTable({**dt.columns, **bcols}, dt.alive)
        if bj.extra is not None:
            found = found & JEval(combined).predicate(bj.extra)
            bcols = {n: DCol(c.data, c.valid & found, c.ctype,
                             c.dictionary) for n, c in bcols.items()}
            combined = DTable({**dt.columns, **bcols}, dt.alive)
        if bj.kind == "inner":
            return DTable(combined.columns, dt.alive & found)
        if bj.kind == "left":
            return combined
        if bj.kind == "semi":
            return DTable(dt.columns, dt.alive & found)
        if bj.kind == "anti":
            return DTable(dt.columns, dt.alive & ~found)
        if bj.kind == "nullaware_anti":
            if bj.extra is not None:
                raise DistUnsupported("residual on nullaware anti join")
            if bj.build_has_null:   # NOT IN (... NULL ...): never TRUE
                return DTable(dt.columns, jnp.zeros(cap, bool))
            if bj.build_empty:      # NOT IN (empty): keep everything
                return DTable(dt.columns, dt.alive)
            return DTable(dt.columns, dt.alive & ~found & ~pnull)
        # mark
        cols = dict(dt.columns)
        cols[bj.mark] = DCol(found, jnp.ones(cap, bool), BOOL)
        return DTable(cols, dt.alive)

    # -- distributed aggregation ---------------------------------------------

    @staticmethod
    def _agg_leaves(agg: lp.Aggregate) -> List[ex.AggExpr]:
        leaves, seen = [], set()
        for _, e in agg.aggs:
            for sub in e.walk():
                if isinstance(sub, ex.AggExpr) and id(sub) not in seen:
                    seen.add(id(sub))
                    leaves.append(sub)
        return leaves

    def _agg_partials(self, agg: lp.Aggregate, leaves, dt: DTable):
        """Local sort-grouped partials -> all_gather over the mesh ->
        replicated exact final re-group.  Returns a flat tuple of
        replicated arrays; names/ctypes captured via side channels."""
        evl = JEval(dt)
        cap = dt.capacity
        key_cols = [(n, evl.eval(e)) for n, e in agg.group_by]
        self._key_meta = [(n, c.ctype, c.dictionary) for n, c in key_cols]
        if key_cols:
            keys = [_key_i64(c, dt.alive) for _, c in key_cols]
        else:
            keys = [jnp.where(dt.alive, jnp.int64(0), _DEAD_KEY)]
        gid, order, newgrp = _group_ids(keys)
        idx = jnp.arange(cap)
        first_pos = jnp.full(cap, cap, jnp.int64).at[
            (jnp.cumsum(newgrp) - 1)].min(idx)
        rep = order[jnp.clip(first_pos, 0, cap - 1)]
        slot_used = jnp.zeros(cap, bool).at[gid].set(True)
        galive = jax.ops.segment_sum(dt.alive.astype(jnp.int32), gid,
                                     num_segments=cap) > 0
        out_alive = slot_used & galive

        def gather(x):
            return lax.all_gather(x, SHARD_AXIS).reshape(
                (self.n_dev * cap,) + x.shape[1:])

        g_alive = gather(out_alive)
        g_keys = [gather(jnp.where(out_alive, k[rep], _DEAD_KEY))
                  for k in keys]
        g_key_cols = [(gather(c.data[rep]),
                       gather(c.valid[rep] & out_alive))
                      for _, c in key_cols]

        self._leaf_meta = []
        g_leaves = []
        for a in leaves:
            parts, meta = self._leaf_partial(dt, evl, a, gid, cap)
            self._leaf_meta.append(meta)
            g_leaves.append([gather(p) for p in parts])

        # replicated exact final re-group over n_dev * cap slots
        total = self.n_dev * cap
        fgid, forder, fnew = _group_ids(g_keys)
        fidx = jnp.arange(total)
        ffirst = jnp.full(total, total, jnp.int64).at[
            (jnp.cumsum(fnew) - 1)].min(fidx)
        frep = forder[jnp.clip(ffirst, 0, total - 1)]
        fused = jnp.zeros(total, bool).at[fgid].set(True)
        fal = jax.ops.segment_sum(g_alive.astype(jnp.int32), fgid,
                                  num_segments=total) > 0
        final_alive = fused & fal

        flat = [final_alive]
        for gdata, gvalid in g_key_cols:
            flat += [gdata[frep], gvalid[frep] & final_alive]
        for a, parts in zip(leaves, g_leaves):
            flat += self._combine_partials(a, parts, fgid, total, g_alive)
        return tuple(flat)

    def _leaf_partial(self, dt: DTable, evl: JEval, a: ex.AggExpr, gid,
                      cap):
        """Per-slot partial arrays + static meta for one leaf aggregate."""
        alive = dt.alive
        if isinstance(a.arg, ex.Star) or a.arg is None:
            cnt = jax.ops.segment_sum(alive.astype(jnp.int64), gid,
                                      num_segments=cap)
            return [cnt], (a.func, None, None)
        c = evl.eval(a.arg)
        meta = (a.func, c.ctype, c.dictionary)
        valid = c.valid & alive
        cnt = jax.ops.segment_sum(valid.astype(jnp.int64), gid,
                                  num_segments=cap)
        if a.func == "count":
            return [cnt], meta
        if a.func in ("sum", "avg"):
            s = jax.ops.segment_sum(
                _sum_input(c.data, valid, c.ctype.kind), gid,
                num_segments=cap)
            return [s, cnt], meta
        if a.func in ("min", "max"):
            if c.ctype.kind == "float64":
                init = jnp.inf if a.func == "min" else -jnp.inf
                vals = jnp.where(valid, c.data, init)
            else:
                init = _DEAD_KEY if a.func == "min" else -_DEAD_KEY
                vals = jnp.where(valid, c.data.astype(jnp.int64),
                                 jnp.int64(init))
            seg = jax.ops.segment_min if a.func == "min" \
                else jax.ops.segment_max
            return [seg(vals, gid, num_segments=cap), cnt], meta
        # stddev family
        x = jnp.where(valid, c.data.astype(jnp.float64), 0.0)
        if c.ctype.kind == "decimal":
            x = x / (10 ** c.ctype.scale)
        s1 = jax.ops.segment_sum(x, gid, num_segments=cap)
        s2 = jax.ops.segment_sum(x * x, gid, num_segments=cap)
        return [s1, s2, cnt], meta

    def _combine_partials(self, a: ex.AggExpr, parts, fgid, total,
                          g_alive):
        out = []
        minmax = a.func in ("min", "max")
        for pi, part in enumerate(parts):
            if minmax and pi == 0:
                seg = jax.ops.segment_min if a.func == "min" \
                    else jax.ops.segment_max
                if part.dtype == jnp.float64:
                    init = jnp.inf if a.func == "min" else -jnp.inf
                else:
                    init = jnp.int64(
                        _DEAD_KEY if a.func == "min" else -_DEAD_KEY)
                vals = jnp.where(g_alive, part, init)
                out.append(seg(vals, fgid, num_segments=total))
            else:
                vals = jnp.where(g_alive, part,
                                 jnp.zeros((), part.dtype))
                out.append(jax.ops.segment_sum(vals, fgid,
                                               num_segments=total))
        return out

    # -- host finalize -------------------------------------------------------

    _PARTS_PER_FUNC = {"count": 1, "sum": 2, "avg": 2, "min": 2, "max": 2,
                       "stddev_samp": 3, "var_samp": 3, "stddev": 3,
                       "variance": 3}

    def _finalize_agg(self, agg: lp.Aggregate, leaves, out) -> Table:
        flat = [np.asarray(a) for a in out]
        final_alive = flat[0]
        sel = np.nonzero(final_alive)[0]
        pos = 1
        key_cols: Dict[str, Column] = {}
        for name, ctype, dictionary in self._key_meta:
            data, valid = flat[pos][sel], flat[pos + 1][sel]
            pos += 2
            key_cols[name] = Column(
                data, ctype, None if valid.all() else valid, dictionary)
        leaf_final: Dict[int, Column] = {}
        for li, (a, meta) in enumerate(zip(leaves, self._leaf_meta)):
            func, ctype, dictionary = meta
            nparts = self._PARTS_PER_FUNC[func] if not (
                isinstance(a.arg, ex.Star) or a.arg is None) else 1
            parts = [flat[pos + k][sel] for k in range(nparts)]
            pos += nparts
            leaf_final[li] = self._finalize_leaf(a, meta, parts)

        if not agg.group_by and len(sel) == 0:
            # SQL global aggregate over zero rows: one row, count 0 / NULL
            for li, (a, meta) in enumerate(zip(leaves, self._leaf_meta)):
                c = leaf_final[li]
                if a.func == "count":
                    leaf_final[li] = Column(
                        np.zeros(1, np.int64), INT64)
                else:
                    leaf_final[li] = Column(
                        np.zeros(1, c.data.dtype), c.ctype,
                        np.zeros(1, bool), c.dictionary)

        sub_cols = {f"__agg{li}": c for li, c in leaf_final.items()}
        gtable = Table({**key_cols, **sub_cols})
        out_cols: Dict[str, Column] = {}
        for name, _ in agg.group_by:
            out_cols[name] = key_cols[name]
        for name, e in agg.aggs:
            out_cols[name] = ex.Evaluator(gtable).eval(
                self._lower_expr(e, leaves))
        return Table(out_cols)

    def _lower_expr(self, e: ex.Expr, leaves) -> ex.Expr:
        for li, a in enumerate(leaves):
            if a is e:
                return ex.ColumnRef(f"__agg{li}")
        if isinstance(e, ex.BinOp):
            return ex.BinOp(e.op, self._lower_expr(e.left, leaves),
                            self._lower_expr(e.right, leaves))
        if isinstance(e, ex.UnaryOp):
            return ex.UnaryOp(e.op, self._lower_expr(e.operand, leaves))
        if isinstance(e, ex.Cast):
            return ex.Cast(self._lower_expr(e.operand, leaves), e.target)
        if isinstance(e, ex.Func):
            return ex.Func(e.name, tuple(self._lower_expr(a, leaves)
                                         for a in e.args))
        if isinstance(e, ex.Case):
            return ex.Case(
                tuple((self._lower_expr(c, leaves),
                       self._lower_expr(v, leaves)) for c, v in e.whens),
                self._lower_expr(e.default, leaves)
                if e.default is not None else None)
        if isinstance(e, ex.InList):
            return ex.InList(self._lower_expr(e.operand, leaves),
                             e.values, e.negated)
        if isinstance(e, ex.AggExpr):
            # an aggregate leaf the collection pass missed — bail to the
            # single-chip path rather than crash at finalize
            raise DistUnsupported("unlowered aggregate in output expr")
        return e

    def _finalize_leaf(self, a: ex.AggExpr, meta, parts) -> Column:
        func, ctype, dictionary = meta
        if isinstance(a.arg, ex.Star) or a.arg is None or func == "count":
            return Column(parts[0].astype(np.int64), INT64)
        if func == "sum":
            s, cnt = parts
            got = cnt > 0
            vopt = None if got.all() else got
            if ctype.kind == "decimal":
                return Column(s.astype(np.int64),
                              columnar.decimal(38, ctype.scale), vopt)
            if ctype.kind in ("int32", "int64"):
                return Column(s.astype(np.int64), INT64, vopt)
            return Column(s.astype(np.float64), FLOAT64, vopt)
        if func == "avg":
            s, cnt = parts
            got = cnt > 0
            mean = s.astype(np.float64) / np.maximum(cnt, 1)
            if ctype.kind == "decimal":
                mean = mean / (10 ** ctype.scale)
            return Column(mean, FLOAT64, None if got.all() else got)
        if func in ("min", "max"):
            v, cnt = parts
            got = cnt > 0
            vopt = None if got.all() else got
            if ctype.kind == "float64":
                return Column(v.astype(np.float64), ctype, vopt)
            dtype = columnar.numpy_dtype(ctype)
            return Column(v.astype(dtype), ctype, vopt, dictionary)
        # stddev family
        s1, s2, cnt = parts
        ok = cnt > 1
        denom = np.where(ok, cnt - 1, 1)
        var = np.maximum(
            s2 - np.where(cnt > 0, s1 * s1 / np.maximum(cnt, 1), 0.0),
            0.0) / denom
        data = var if func in ("var_samp", "variance") else np.sqrt(var)
        return Column(data, FLOAT64, None if ok.all() else ok)


def _graft(top: lp.Plan, old: lp.Plan, new: lp.Plan) -> lp.Plan:
    """Copy of `top` with the subtree `old` replaced by `new`."""
    if top is old:
        return new
    n = copy.copy(top)
    for attr in ("child", "left", "right"):
        c = getattr(n, attr, None)
        if c is not None:
            setattr(n, attr, _graft(c, old, new))
    return n


def execute_distributed(catalog, mesh, plan: lp.Plan,
                        shard_threshold_rows: int = 65536,
                        broadcast_limit_rows: int = 8_000_000) -> Table:
    """One-shot helper: run `plan` over `mesh`, DistUnsupported on plans
    outside the distributed subset."""
    return DistributedPlanExecutor(
        catalog, mesh, shard_threshold_rows,
        broadcast_limit_rows).execute_plan(plan)
