"""Device mesh construction for distributed query execution.

The engine distributes over a 1-D data axis ("shards") — relational query
shuffles are row exchanges, so one axis suffices (the analog of Spark's
``spark.sql.shuffle.partitions`` topology, reference
power_run_cpu.template:30); multi-slice pods extend the same axis across
DCN transparently (XLA picks ICI within a slice, DCN across).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # newer jax: top-level export, replication check spelled check_vma
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"

SHARD_AXIS = "shards"


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Version-portable ``shard_map`` wrapper: the replication-check
    kwarg was renamed across jax releases (check_rep -> check_vma), and
    the symbol moved from jax.experimental to the top level."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})


def make_mesh(n_devices: Optional[int] = None,
              axis: str = SHARD_AXIS) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    import numpy as np
    return Mesh(np.array(devs[:n]), (axis,))


def default_mesh() -> Mesh:
    return make_mesh()


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows block-sharded across the mesh axis."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
