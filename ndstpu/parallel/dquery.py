"""Distributed query pipelines over the device mesh.

This is the multi-chip execution shape for the NDS power run's hot
pattern — fact-table scan -> dimension joins -> grouped aggregation
(e.g. query3: store_sales ⋈ date_dim ⋈ item, filter, GROUP BY brand,
SUM; reference template nds/tpcds-gen q3 via nds_power.py:124-134) —
expressed TPU-first:

* fact rows are block-sharded over the mesh's data axis,
* dimension tables are replicated (broadcast join; surrogate keys are
  dense, so the join is a bounds-checked gather, no hash table),
* grouped aggregation runs as local ``segment_sum`` partials combined
  with ``psum`` (exchange-free when the group key is a dense id),
* the shuffle path (hash repartition via ``all_to_all``) is used when
  keys must be colocated (e.g. distinct counting, fact-fact joins).

Everything compiles to one XLA program per step: jit(shard_map(body)).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)  # exact int64 decimal sums

import jax.numpy as jnp  # noqa: E402
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ndstpu.parallel.exchange import (
    hash_repartition,
    sharded_segment_sum,
)
from ndstpu.parallel.mesh import SHARD_AXIS, shard_map


def build_q3_step(mesh: Mesh, n_items: int, n_dates: int, d_base: int,
                  target_moy: int = 11, bucket_cap: int = None):
    """Compile the distributed q3-shaped step over `mesh`.

    Inputs (per call):
      ss_sold_date_sk, ss_item_sk : int32 [rows]   (row-sharded)
      ss_ext_sales_price          : int64 [rows]   (decimal cents, sharded)
      d_moy, d_year               : int32 [n_dates] (replicated dim)
      i_brand_id                  : int32 [n_items] (replicated dim)

    Returns (brand-slot sums int64 [n_items], filtered row count,
    shuffle-path sums — must equal the psum path, shuffle drop count —
    0 unless an explicit undersized bucket_cap was forced).

    ``bucket_cap=None`` sizes shuffle buckets to the per-shard row count
    (trace-time static), which can never drop rows.
    """
    n_dev = mesh.devices.size

    def body(sold, item, price, d_moy, d_year, i_brand_id):
        cap = bucket_cap if bucket_cap is not None else sold.shape[0]
        # broadcast join with date_dim: dense-sk gather + filter
        didx = jnp.clip(sold - d_base, 0, n_dates - 1)
        in_range = (sold >= d_base) & (sold < d_base + n_dates)
        keep = in_range & (d_moy[didx] == target_moy)
        # broadcast join with item: dense-sk gather
        iidx = jnp.clip(item - 1, 0, n_items - 1)
        keep = keep & (item >= 1) & (item <= n_items)
        vals = jnp.where(keep, price, 0)
        # partial aggregation by item, combined over ICI with psum
        per_item = sharded_segment_sum(vals, iidx, n_items)
        n_rows = lax.psum(jnp.sum(keep.astype(jnp.int64)), SHARD_AXIS)
        # shuffle path: colocate equal keys via all_to_all, then local sum
        cols, alive, dropped = hash_repartition(
            {"price": vals, "item": iidx.astype(jnp.int64)},
            item.astype(jnp.int64), keep, n_dev, cap)
        local = jax.ops.segment_sum(
            jnp.where(alive, cols["price"], 0),
            jnp.clip(cols["item"], 0, n_items - 1).astype(jnp.int32),
            num_segments=n_items)
        shuffled = lax.psum(local, SHARD_AXIS)
        return per_item, n_rows, shuffled, dropped

    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                  P(), P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False)

    @jax.jit
    def step(sold, item, price, d_moy, d_year, i_brand_id):
        per_item, n_rows, shuffled, dropped = sharded(
            sold, item, price, d_moy, d_year, i_brand_id)
        # brand rollup on the replicated per-item partials (tiny)
        brand_slot = jnp.clip(i_brand_id, 0, n_items - 1)
        per_brand = jax.ops.segment_sum(per_item, brand_slot,
                                        num_segments=n_items)
        return per_brand, n_rows, shuffled, dropped

    return step


def example_inputs(n_rows: int = 4096, n_items: int = 128,
                   n_dates: int = 64, d_base: int = 2450815,
                   seed: int = 0, n_dev: int = 1):
    """Synthetic q3-shaped inputs (deterministic, shard-divisible)."""
    rng = np.random.RandomState(seed)
    n_rows = (n_rows // max(n_dev, 1)) * max(n_dev, 1)
    sold = rng.randint(d_base, d_base + n_dates, n_rows).astype(np.int32)
    item = rng.randint(1, n_items + 1, n_rows).astype(np.int32)
    price = rng.randint(0, 10_000, n_rows).astype(np.int64)
    d_moy = ((np.arange(n_dates) // 30) % 12 + 1).astype(np.int32)
    d_year = np.full(n_dates, 2000, np.int32)
    i_brand_id = rng.randint(0, n_items, n_items).astype(np.int32)
    return (jnp.asarray(sold), jnp.asarray(item), jnp.asarray(price),
            jnp.asarray(d_moy), jnp.asarray(d_year),
            jnp.asarray(i_brand_id))


def reference_result(sold, item, price, d_moy, d_year, i_brand_id,
                     n_items: int, n_dates: int, d_base: int,
                     target_moy: int = 11):
    """Numpy oracle for build_q3_step (differential check)."""
    sold = np.asarray(sold)
    item = np.asarray(item)
    price = np.asarray(price)
    d_moy = np.asarray(d_moy)
    keep = (sold >= d_base) & (sold < d_base + n_dates)
    keep &= d_moy[np.clip(sold - d_base, 0, n_dates - 1)] == target_moy
    keep &= (item >= 1) & (item <= n_items)
    per_item = np.zeros(n_items, np.int64)
    np.add.at(per_item, item[keep] - 1, price[keep])
    brand_slot = np.clip(np.asarray(i_brand_id), 0, n_items - 1)
    per_brand = np.zeros(n_items, np.int64)
    np.add.at(per_brand, brand_slot, per_item)
    return per_brand, int(keep.sum()), per_item
