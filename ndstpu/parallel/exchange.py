"""Shuffle-exchange primitives as XLA collectives inside ``shard_map``.

The reference's comm backend is Spark's block shuffle + broadcast
(SURVEY.md §5 "Distributed communication backend"); here the same three
data-movement patterns are ICI/DCN collectives:

* hash repartition (shuffle exchange)  -> ``lax.all_to_all``
* broadcast join build side            -> ``lax.all_gather``
* partial-aggregate combine            -> ``lax.psum``

All functions are written to be called *inside* a ``shard_map`` body over
the 1-D data axis (ndstpu.parallel.mesh.SHARD_AXIS), on per-shard local
arrays, and are fully traceable (static bucket capacities).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ndstpu import obs
from ndstpu.parallel.mesh import SHARD_AXIS


def _note_collective(kind: str, bytes_est: int) -> None:
    """Record one traced collective + its estimated global wire bytes.

    These functions run inside ``shard_map`` tracing, so the counters
    tick once per COMPILED PROGRAM (at trace time), not per execution —
    they measure how much collective traffic a query's program commits
    to, from static shapes.  ``exchange.shuffle_bytes`` is the
    all-devices total for one execution of the traced op."""
    from ndstpu import faults
    faults.check("exchange.collective", key=kind)
    obs.inc("exchange.collective.calls")
    obs.inc(f"exchange.{kind}.calls")
    obs.inc("exchange.shuffle_bytes", int(bytes_est))


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — cheap, well-distributed bucket hash."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> 31)


def mix64_np(x):
    """Host-side splitmix64, bit-identical to :func:`_mix64` (numpy).
    Used to pre-partition a join build side with the same bucket
    assignment the traced probe shuffle will compute."""
    import numpy as np
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def hash_repartition(cols: Dict[str, jnp.ndarray], key: jnp.ndarray,
                     alive: jnp.ndarray, n_dev: int, bucket_cap: int,
                     axis: str = SHARD_AXIS
                     ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray,
                                jnp.ndarray]:
    """Shuffle local rows so equal keys land on the same device.

    Per-shard: bucket rows by ``hash(key) % n_dev`` into a [n_dev,
    bucket_cap] send buffer, exchange buckets with ``all_to_all``.
    Returns (local received columns of shape [n_dev * bucket_cap],
    alive mask, global count of rows dropped for overflowing
    ``bucket_cap``).  ``bucket_cap = rows_per_shard`` is always safe
    (zero drops); smaller caps trade memory for a skew-overflow risk
    the caller MUST check via the returned drop count.
    """
    dest = (_mix64(key) % jnp.uint64(n_dev)).astype(jnp.int32)
    return repartition_by_dest(cols, dest, alive, n_dev, bucket_cap, axis)


def repartition_by_dest(cols: Dict[str, jnp.ndarray], dest: jnp.ndarray,
                        alive: jnp.ndarray, n_dev: int, bucket_cap: int,
                        axis: str = SHARD_AXIS
                        ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray,
                                   jnp.ndarray]:
    """Shuffle local rows to explicit destination devices.

    ``dest`` is a per-row device index (meaningful where ``alive``);
    dead rows are dropped in transit.  Same contract as
    :func:`hash_repartition` otherwise.
    """
    n = dest.shape[0]
    dest = jnp.where(alive, dest.astype(jnp.int32), n_dev)
    order = jnp.argsort(dest, stable=True)
    dsort = dest[order]
    # rank within destination bucket
    first = jnp.searchsorted(dsort, jnp.arange(n_dev + 1))
    within = jnp.arange(n) - first[jnp.clip(dsort, 0, n_dev)]
    ok = (within < bucket_cap) & (dsort < n_dev)
    # dropped/overflow rows scatter into a dummy row that is sliced off
    # (duplicate-index scatter order is undefined, so they must never
    # alias a real slot)
    row = jnp.where(ok, jnp.clip(dsort, 0, n_dev - 1), n_dev)
    slot = jnp.clip(within, 0, bucket_cap - 1)

    def scatter(arr: jnp.ndarray) -> jnp.ndarray:
        buf = jnp.zeros((n_dev + 1, bucket_cap), arr.dtype)
        return buf.at[row, slot].set(arr[order])[:n_dev]

    # each device exchanges an [n_dev, bucket_cap] buffer per column
    # (+ the alive mask) with every peer: n_dev^2 * bucket_cap slots
    _note_collective(
        "all_to_all",
        n_dev * n_dev * bucket_cap *
        (sum(a.dtype.itemsize for a in cols.values()) + 1))
    sent_alive = jnp.zeros((n_dev + 1, bucket_cap), bool).at[
        row, slot].set(ok)[:n_dev]
    n_dropped = lax.psum(
        jnp.sum(((within >= bucket_cap) & (dsort < n_dev))
                .astype(jnp.int64)), axis)
    out_cols = {}
    for name, arr in cols.items():
        buf = scatter(arr)
        got = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0)
        out_cols[name] = got.reshape(n_dev * bucket_cap)
    alive_out = lax.all_to_all(sent_alive, axis, split_axis=0,
                               concat_axis=0).reshape(n_dev * bucket_cap)
    return out_cols, alive_out, n_dropped


def broadcast_gather(arr: jnp.ndarray, axis: str = SHARD_AXIS
                     ) -> jnp.ndarray:
    """Replicate all shards' rows on every device (broadcast join build
    side; analog of spark.sql.autoBroadcastJoinThreshold exchange)."""
    n_dev = jax.device_count()  # upper bound: mesh may be a sub-mesh
    _note_collective("all_gather",
                     arr.size * arr.dtype.itemsize * n_dev * (n_dev - 1))
    return lax.all_gather(arr, axis, tiled=True)


def sharded_segment_sum(values: jnp.ndarray, segment_ids: jnp.ndarray,
                        num_segments: int, axis: str = SHARD_AXIS
                        ) -> jnp.ndarray:
    """Partial aggregation: local segment_sum, then cross-device psum.
    The group-key -> segment-id mapping must be device-agnostic (e.g. a
    dense dimension key), so partials line up slot-for-slot."""
    n_dev = jax.device_count()  # upper bound: mesh may be a sub-mesh
    _note_collective("psum",
                     num_segments * values.dtype.itemsize * n_dev)
    partial = jax.ops.segment_sum(values, segment_ids,
                                  num_segments=num_segments)
    return lax.psum(partial, axis)
