"""nds-tpu: a TPU-native decision-support (TPC-DS/NDS) benchmark framework.

Capability parity with spark-rapids-benchmarks (NDS v2.0) — data generation,
transcode, query-stream generation, power/throughput runs, data maintenance,
validation, composite metric — with the Spark+CUDA execution engine replaced
by a JAX/XLA/Pallas columnar SQL engine running SPMD over a TPU mesh, and the
reference's native Java/C layer replaced by a C++ data generator.

Subpackages:
  schema    — 25 source + 12 maintenance table schemas (decimal/double switch)
  datagen   — seeded, chunk-parallel C++ data generator + driver CLI
  io        — CSV→Parquet transcode, columnar loader, ACID table layer
  engine    — SQL → logical plan → optimizer → JAX columnar execution
  parallel  — device mesh, shard_map distributed operators (ICI collectives)
  queries   — query templates + reproducible stream generation
  harness   — power/throughput/maintenance/validate/bench CLIs + reports
"""

__version__ = "0.1.0"
