"""Preflight checks and small shared CLI utilities.

Parity with the reference's check util (/root/reference/nds/check.py:38-152):
python-version gate, build-artifact discovery (here: the C++ `ndsgen` binary,
auto-built with g++ on first use instead of a Makefile+maven flow), range and
parallel-value validation, directory sizing, and report-folder guards.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from pathlib import Path

MIN_PYTHON = (3, 10)


def check_version() -> None:
    if sys.version_info < MIN_PYTHON:
        raise RuntimeError(
            f"Python {MIN_PYTHON[0]}.{MIN_PYTHON[1]}+ required, "
            f"found {sys.version_info.major}.{sys.version_info.minor}"
        )


_DATAGEN_DIR = Path(__file__).resolve().parent / "datagen"
_NDSGEN_SRC = _DATAGEN_DIR / "ndsgen.cpp"
_NDSGEN_BIN = _DATAGEN_DIR / "_build" / "ndsgen"
_DISTS_JSON = _DATAGEN_DIR / "dists.json"
_DISTS_HEADER = _DATAGEN_DIR / "_build" / "dists_gen.h"


def render_dists_header() -> Path:
    """Render dists.json into the C++ header the generator compiles
    against — the one mechanism keeping data generation and query-
    parameter generation on the SAME distribution tables (the dsdgen/
    dsqgen .dst-file sharing analog; streamgen.py reads the json
    directly)."""
    import json
    with open(_DISTS_JSON) as f:
        dists = json.load(f)

    def esc(s: str) -> str:
        return s.replace("\\", "\\\\").replace('"', '\\"')

    lines = [
        "// GENERATED from dists.json by ndstpu.check.render_dists_header",
        "// -- do not edit; edit dists.json.",
        "#pragma once",
        "struct DistEntry { const char* v; int w; };",
        "struct DistTable { const DistEntry* e; int n; int total; };",
    ]
    for name, d in dists.items():
        if name.startswith("_"):
            continue
        vals, weights = d["values"], d["weights"]
        if len(vals) != len(weights):
            raise RuntimeError(f"dists.json {name}: {len(vals)} values "
                               f"vs {len(weights)} weights")
        entries = ", ".join(f'{{"{esc(v)}", {w}}}'
                            for v, w in zip(vals, weights))
        lines.append(f"static const DistEntry kDist_{name}_e[] = "
                     f"{{{entries}}};")
        lines.append(f"static const DistTable kDist_{name} = "
                     f"{{kDist_{name}_e, {len(vals)}, {sum(weights)}}};")
    _DISTS_HEADER.parent.mkdir(parents=True, exist_ok=True)
    _DISTS_HEADER.write_text("\n".join(lines) + "\n")
    return _DISTS_HEADER


def check_build(rebuild: bool = False) -> Path:
    """Locate the native data-generation tool, compiling it if missing.

    Returns the path to the `ndsgen` binary (the analog of the reference's
    check_build returning the tpcds-gen jar + dsdgen paths,
    check.py:47-66)."""
    check_version()
    if _NDSGEN_BIN.exists() and not rebuild:
        if _NDSGEN_BIN.stat().st_mtime >= max(
                _NDSGEN_SRC.stat().st_mtime, _DISTS_JSON.stat().st_mtime):
            return _NDSGEN_BIN
    render_dists_header()
    cmd = ["g++", "-O2", f"-I{_DISTS_HEADER.parent}",
           "-o", str(_NDSGEN_BIN), str(_NDSGEN_SRC)]
    print("building native generator:", " ".join(cmd))
    subprocess.run(cmd, check=True)
    return _NDSGEN_BIN


def get_abs_path(input_path: str) -> str:
    return str(Path(input_path).expanduser().resolve())


def valid_range(range_str: str, parallel) -> tuple[int, int]:
    """Validate --range 'start,end' against the parallel value
    (reference: check.py:88-113)."""
    try:
        start, end = (int(x) for x in range_str.split(","))
    except Exception as exc:
        raise argparse.ArgumentTypeError(
            f'invalid range: "{range_str}", expected "start,end"'
        ) from exc
    if not (1 <= start <= end <= int(parallel)):
        raise argparse.ArgumentTypeError(
            f"range {start},{end} must satisfy 1 <= start <= end <= parallel"
            f" ({parallel})"
        )
    return start, end


def parallel_value_type(val: str) -> str:
    """--parallel must be an int >= 2 (reference: check.py:116-123)."""
    try:
        ival = int(val)
    except ValueError as exc:
        raise argparse.ArgumentTypeError(f"{val!r} is not an integer") from exc
    if ival < 2:
        raise argparse.ArgumentTypeError("PARALLEL must be >= 2")
    return val


def get_dir_size(path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for f in filenames:
            fp = os.path.join(dirpath, f)
            if not os.path.islink(fp):
                total += os.path.getsize(fp)
    return total


def check_json_summary_folder(folder: str | None) -> None:
    """Require an empty/new folder for per-query JSON summaries
    (reference: check.py:136-145)."""
    if folder is None:
        return
    if os.path.exists(folder):
        if not os.path.isdir(folder):
            raise RuntimeError(f"{folder} is not a directory")
        if os.listdir(folder):
            raise RuntimeError(
                f"json summary folder {folder} is not empty; "
                "choose an empty or new folder"
            )
    else:
        os.makedirs(folder)


def check_query_subset_exists(query_dict: dict, subset: list[str]) -> bool:
    """All requested sub-queries must exist in the stream
    (reference: check.py:147-152)."""
    for q in subset:
        if q not in query_dict:
            raise RuntimeError(f"query {q} not found in the query stream")
    return True
