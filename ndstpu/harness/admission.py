"""Cross-process device admission control for concurrent streams.

The reference bounds intra-device concurrency with
``spark.rapids.sql.concurrentGpuTasks`` (power_run_gpu.template:21) while
`nds-throughput` fans out N concurrent driver processes.  Here N
concurrent power-run processes share one TPU chip (or one tunnel), so an
unbounded fan-out just queues programs behind each other and inflates
every stream's tail latency.  This module is the TPU analog: a
file-lock semaphore in a shared directory grants at most ``slots``
streams device access at a time, acquired around each query.

Locks are ``flock``-based so a crashed stream releases its slot when the
OS closes its file descriptors — no stale-lock cleanup needed.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional


class DeviceAdmission:
    """A ``slots``-wide semaphore over lock files in ``lock_dir``."""

    def __init__(self, slots: int, lock_dir: str):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.slots = slots
        self.lock_dir = lock_dir
        os.makedirs(lock_dir, exist_ok=True)
        self._held: Optional[int] = None
        self._fds = {}

    def _fd(self, i: int) -> int:
        fd = self._fds.get(i)
        if fd is None:
            fd = os.open(os.path.join(self.lock_dir, f"slot{i}.lock"),
                         os.O_CREAT | os.O_RDWR, 0o644)
            self._fds[i] = fd
        return fd

    def acquire(self, poll_s: float = 0.02) -> int:
        """Block until one of the slots is free; returns the slot id."""
        import fcntl
        assert self._held is None, "admission slot already held"
        while True:
            for i in range(self.slots):
                try:
                    fcntl.flock(self._fd(i),
                                fcntl.LOCK_EX | fcntl.LOCK_NB)
                    self._held = i
                    return i
                except OSError:
                    continue
            time.sleep(poll_s)

    def release(self) -> None:
        import fcntl
        if self._held is None:
            return
        fcntl.flock(self._fd(self._held), fcntl.LOCK_UN)
        self._held = None

    @contextlib.contextmanager
    def slot(self):
        self.acquire()
        try:
            yield
        finally:
            self.release()

    def close(self) -> None:
        self.release()
        for fd in self._fds.values():
            os.close(fd)
        self._fds = {}


class InprocAdmission:
    """In-process ``slots`` semantics of :class:`DeviceAdmission` for
    the inproc throughput scheduler (ndstpu/harness/scheduler.py): the
    stream workers are threads in ONE process, so a plain semaphore
    replaces the lock files.  Tracks the observed concurrency peak and
    per-acquisition device intervals — the committed evidence that at
    most ``slots`` queries held the device at once."""

    def __init__(self, slots: int):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        import threading
        self.slots = slots
        self._sem = threading.Semaphore(slots)
        self._mu = threading.Lock()
        self._tl = threading.local()
        self._active = 0
        self.max_active = 0
        self.wait_s_total = 0.0
        self.intervals = []  # (t_acquired, t_released) epoch pairs

    def acquire(self) -> int:
        t0 = time.time()
        self._sem.acquire()
        now = time.time()
        with self._mu:
            self._active += 1
            self.max_active = max(self.max_active, self._active)
            self.wait_s_total += now - t0
        self._tl.t0 = now
        return 0

    def release(self) -> None:
        t0 = getattr(self._tl, "t0", None)
        self._tl.t0 = None
        with self._mu:
            self._active -= 1
            if t0 is not None:
                self.intervals.append((t0, time.time()))
        self._sem.release()

    @contextlib.contextmanager
    def slot(self):
        self.acquire()
        try:
            yield
        finally:
            self.release()

    def device_timeline(self) -> dict:
        """Admission-level overlap evidence for the overlap report."""
        with self._mu:
            ivs = list(self.intervals)
        return {
            "slots": self.slots,
            "max_concurrent": self.max_active,
            "gated_queries": len(ivs),
            "busy_s_total": round(sum(b - a for a, b in ivs), 3),
            "wait_s_total": round(self.wait_s_total, 3),
        }


def from_env() -> Optional[DeviceAdmission]:
    """Admission configured by the throughput runner via env vars
    (NDSTPU_ADMISSION_SLOTS / NDSTPU_ADMISSION_DIR), or None."""
    slots = os.environ.get("NDSTPU_ADMISSION_SLOTS")
    if not slots:
        return None
    lock_dir = os.environ.get("NDSTPU_ADMISSION_DIR")
    if not lock_dir:
        return None
    return DeviceAdmission(int(slots), lock_dir)
