"""Cross-process device admission control for concurrent streams.

The reference bounds intra-device concurrency with
``spark.rapids.sql.concurrentGpuTasks`` (power_run_gpu.template:21) while
`nds-throughput` fans out N concurrent driver processes.  Here N
concurrent power-run processes share one TPU chip (or one tunnel), so an
unbounded fan-out just queues programs behind each other and inflates
every stream's tail latency.  This module is the TPU analog: a
file-lock semaphore in a shared directory grants at most ``slots``
streams device access at a time, acquired around each query.

Locks are ``flock``-based so a crashed stream releases its slot when the
OS closes its file descriptors — no stale-lock cleanup needed.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional


class DeviceAdmission:
    """A ``slots``-wide semaphore over lock files in ``lock_dir``."""

    def __init__(self, slots: int, lock_dir: str):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.slots = slots
        self.lock_dir = lock_dir
        os.makedirs(lock_dir, exist_ok=True)
        self._held: Optional[int] = None
        self._fds = {}

    def _fd(self, i: int) -> int:
        fd = self._fds.get(i)
        if fd is None:
            fd = os.open(os.path.join(self.lock_dir, f"slot{i}.lock"),
                         os.O_CREAT | os.O_RDWR, 0o644)
            self._fds[i] = fd
        return fd

    def acquire(self, poll_s: float = 0.02) -> int:
        """Block until one of the slots is free; returns the slot id."""
        import fcntl
        assert self._held is None, "admission slot already held"
        while True:
            for i in range(self.slots):
                try:
                    fcntl.flock(self._fd(i),
                                fcntl.LOCK_EX | fcntl.LOCK_NB)
                    self._held = i
                    return i
                except OSError:
                    continue
            time.sleep(poll_s)

    def release(self) -> None:
        import fcntl
        if self._held is None:
            return
        fcntl.flock(self._fd(self._held), fcntl.LOCK_UN)
        self._held = None

    @contextlib.contextmanager
    def slot(self):
        self.acquire()
        try:
            yield
        finally:
            self.release()

    def close(self) -> None:
        self.release()
        for fd in self._fds.values():
            os.close(fd)
        self._fds = {}


def from_env() -> Optional[DeviceAdmission]:
    """Admission configured by the throughput runner via env vars
    (NDSTPU_ADMISSION_SLOTS / NDSTPU_ADMISSION_DIR), or None."""
    slots = os.environ.get("NDSTPU_ADMISSION_SLOTS")
    if not slots:
        return None
    lock_dir = os.environ.get("NDSTPU_ADMISSION_DIR")
    if not lock_dir:
        return None
    return DeviceAdmission(int(slots), lock_dir)
