"""In-process multi-stream throughput scheduler (shared-engine path).

The spec-faithful throughput shape (``--mode process``,
ndstpu/harness/throughput.py) fans out N OS processes the way the
reference fans out spark-submit drivers — each stream pays its own
warehouse load, its own device upload, and its own full plan+compile of
every query.  On one TPU that is maximally wasteful: the caches that
make repeat executions cheap (``Session._plan_cache``,
``JaxExecutor._compiled``, the run ledger's priors) are all per-process
and shared by nobody.

``--mode inproc`` runs the same N streams as worker THREADS against ONE
shared :class:`~ndstpu.engine.session.Session`:

* the warehouse is loaded (and uploaded to HBM) once;
* each distinct query text is planned/compiled once — the first stream
  to reach a text pays discovery under a per-key latch
  (ndstpu.engine.latch) while others wait, then every other stream
  replays the cached program (compile cost O(streams x queries) ->
  O(queries), proven by the ``engine.cache.plan.hit`` /
  ``engine.cache.compiled.hit`` counters);
* device access is serialized at query granularity by
  :class:`~ndstpu.harness.admission.InprocAdmission` — the same
  ``slots`` semantics as the file-lock ``DeviceAdmission``, no lock
  files;
* streams pick their next query via :class:`StreamScheduler` using
  ledger expected-cost priors — cheapest-cold-first so compiles
  front-load and warm replays pack the tail — with ``BudgetedQueue``
  budget semantics (explicit per-query ``partial_reason`` skips);
* all streams emit into ONE trace (stream id on every query span), one
  metrics sidecar, and one overlap report whose top-level
  ``max_concurrent`` is the device-level peak the admission gate
  enforced (``<= slots``), alongside the stream-wall
  ``concurrency_timeline`` evidence.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from ndstpu import faults, obs
from ndstpu.check import check_json_summary_folder
from ndstpu.harness import admission as adm
from ndstpu.harness import power, progress
from ndstpu.io import atomic, loader
from ndstpu.obs import ledger as ledger_mod
from ndstpu.obs import sentinel


class _StreamView:
    """One stream's queue facade over the shared :class:`StreamScheduler`
    — the ``BudgetedQueue`` protocol ``run_stream`` expects
    (``next(elapsed_s)`` / ``projected_s()`` / ``skipped`` /
    ``done(name, failed)``)."""

    def __init__(self, sched: "StreamScheduler", sid: str,
                 names: List[str]):
        self._sched = sched
        self.sid = sid
        self._names = list(names)
        self._order = {n: i for i, n in enumerate(names)}
        self.phase = f"{sched.phase}:{sid}"
        self.budget_s = sched.budget_s
        self.skipped: Dict[str, str] = {}
        self.reordered = False

    # -- cost model: warm prior once ANY stream compiled/queued the text
    def cost(self, name: str) -> float:
        return self._sched._cost(self.sid, name)

    def projected_s(self) -> float:
        with self._sched._lock:
            return sum(self.cost(n) for n in self._names)

    @property
    def remaining(self) -> List[str]:
        with self._sched._lock:
            return list(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def next(self, elapsed_s: float) -> Optional[str]:
        return self._sched._next(self, elapsed_s)

    def done(self, name: str, failed: bool = False) -> None:
        self._sched._done(self.sid, name, failed)


class StreamScheduler:
    """Shared ledger-prior-driven scheduler for N in-process streams.

    Pick order per stream (all under one lock, so streams see each
    other's state):

    1. **cold, not in flight anywhere** — cheapest cold prior first, so
       every stream starts a *different* compile and the expensive
       discoveries front-load across the phase;
    2. **already compiled by any stream** — cheapest warm prior first
       (cheap replays fill the gaps while other streams compile);
    3. **in flight on another stream** — last: by the time the stream
       gets there the text is compiled (or the per-key latch makes the
       wait explicit).

    Budget semantics mirror ``BudgetedQueue``: on projected overrun the
    view logs the reorder event once, and queries that cannot fit are
    skipped with an explicit per-query ``partial_reason``.
    """

    def __init__(self, stream_queries: "Dict[str, Dict[str, str]]",
                 budget_s: Optional[float] = None,
                 est_cold: Optional[Callable[[str],
                                             Optional[float]]] = None,
                 est_warm: Optional[Callable[[str],
                                             Optional[float]]] = None,
                 phase: str = "throughput",
                 default_cost_s: float = progress.DEFAULT_COST_S,
                 on_event: Callable[[str], None] = print,
                 key_fn: Optional[Callable[[str], str]] = None):
        # key_fn maps SQL text -> dedup key.  Default: normalized text.
        # The inproc runner passes Session.canonical_key so streams
        # whose renderings differ only in bindable literals share one
        # compile entry — with text keys each stream's fresh RNG values
        # looked "cold" and the cheapest-cold-first pick order re-paid
        # every compile per stream.
        from ndstpu.engine.sql import normalize_sql_key
        kf = key_fn or normalize_sql_key
        self._kf = kf
        self._lock = threading.RLock()
        # continuous-feed mode (serve layer): open streams may gain
        # work after construction; _next blocks on this condition when
        # an open stream's queue is momentarily empty
        self._cv = threading.Condition(self._lock)
        self._open: set = set()
        self.budget_s = budget_s if budget_s and budget_s > 0 else None
        self.phase = phase
        self.default_cost_s = default_cost_s
        self._est_cold = est_cold
        self._est_warm = est_warm
        self._on_event = on_event
        self.compiled: set = set()    # dedup keys known compiled
        self.inflight: Dict[str, str] = {}  # key -> stream building it
        self._key: Dict[tuple, str] = {}
        self._views: "OrderedDict[str, _StreamView]" = OrderedDict()
        for sid, qd in stream_queries.items():
            for name, sql in qd.items():
                self._key[(sid, name)] = kf(sql)
            self._views[sid] = _StreamView(self, sid, list(qd))

    def view(self, sid: str) -> _StreamView:
        return self._views[sid]

    # -- continuous-feed mode (serve layer) ---------------------------------
    #
    # The batch harness builds the scheduler from fixed per-stream work
    # lists.  The query server instead OPENS a stream per connection,
    # FEEDS it one request at a time, and CLOSES it when the client
    # hangs up; a view whose queue is momentarily empty but still open
    # blocks in next() instead of reporting done.  Cross-stream compile
    # dedup (compiled/inflight keyed by canonical key) works unchanged,
    # so concurrent connections sending the same plan shape share one
    # compile exactly like batch streams do.

    def open_stream(self, sid: str) -> _StreamView:
        """Create (or reopen) a continuously-fed stream."""
        with self._lock:
            if sid not in self._views:
                self._views[sid] = _StreamView(self, sid, [])
            self._open.add(sid)
            return self._views[sid]

    def feed(self, sid: str, name: str, sql: str) -> None:
        """Append one work item to an open stream; wakes its next()."""
        with self._lock:
            if sid not in self._open:
                raise ValueError(f"stream {sid!r} is not open for feed")
            view = self._views[sid]
            self._key[(sid, name)] = self._kf(sql)
            view._order[name] = len(view._order)
            view._names.append(name)
            self._cv.notify_all()

    def close(self, sid: str) -> None:
        """Stop feeding a stream: pending items still drain, then its
        next() returns None instead of blocking."""
        with self._lock:
            self._open.discard(sid)
            self._cv.notify_all()

    # -- internals (called by the views) -------------------------------------

    def _cost(self, sid: str, name: str) -> float:
        key = self._key[(sid, name)]
        warm = key in self.compiled or key in self.inflight
        est = self._est_warm if warm else self._est_cold
        c = est(name) if est else None
        return float(c) if c and c > 0 else self.default_cost_s

    def _class(self, sid: str, name: str) -> int:
        key = self._key[(sid, name)]
        if key in self.compiled:
            return 1
        if self.inflight.get(key) not in (None, sid):
            return 2
        return 0

    def _next(self, view: _StreamView, elapsed_s: float) -> Optional[str]:
        with self._lock:
            # continuous-feed: an open-but-empty stream waits for work
            # (or for close()); batch streams never enter the wait
            while not view._names and view.sid in self._open:
                self._cv.wait(timeout=0.5)
            if not view._names:
                return None
            if self.budget_s is not None:
                left = self.budget_s - elapsed_s
                projected = sum(view.cost(n) for n in view._names)
                if projected > left and not view.reordered:
                    view.reordered = True
                    self._on_event(
                        f"[budget] {view.phase}: projected "
                        f"{projected:.1f}s exceeds remaining "
                        f"{left:.1f}s of {self.budget_s:g}s budget - "
                        f"scheduling {len(view._names)} remaining "
                        f"queries cheapest-first (ledger priors)")
                    obs.inc("harness.budget.reordered")
                if left <= 0:
                    self._skip_all(view, lambda n: (
                        f"budget exhausted: {elapsed_s:.1f}s elapsed "
                        f">= {self.budget_s:g}s {view.phase} budget"))
                    return None
            pick = min(view._names,
                       key=lambda n: (self._class(view.sid, n),
                                      view.cost(n), view._order[n]))
            if self.budget_s is not None and \
                    view.cost(pick) > left:
                # cheapest-first means: if the cheapest remaining query
                # does not fit, nothing costlier will either
                self._skip_all(view, lambda n: (
                    f"budget: prior {view.cost(n):.2f}s exceeds "
                    f"remaining {left:.1f}s of {self.budget_s:g}s "
                    f"{view.phase} budget"))
                return None
            view._names.remove(pick)
            key = self._key[(view.sid, pick)]
            if key not in self.compiled:
                self.inflight.setdefault(key, view.sid)
            return pick

    def _done(self, sid: str, name: str, failed: bool) -> None:
        with self._lock:
            key = self._key[(sid, name)]
            if self.inflight.get(key) == sid:
                del self.inflight[key]
            if not failed:
                # a FAILED query must not publish its text as compiled:
                # other streams keep their own (cold) estimate and the
                # shared caches hold nothing for it (the engine only
                # caches successful plans/programs)
                self.compiled.add(key)

    def _skip_all(self, view: _StreamView,
                  reason_for: Callable[[str], str]) -> None:
        for n in view._names:
            view.skipped[n] = reason_for(n)
        if view._names:
            self._on_event(
                f"[budget] {view.phase}: cutting {len(view._names)} "
                f"queries ({', '.join(view._names[:8])}"
                + ("..." if len(view._names) > 8 else "")
                + ") - per-query partial_reason recorded in the report")
        view._names = []


@dataclasses.dataclass
class InprocRun:
    """Result of one in-process throughput phase (also the test hook:
    the shared session/scheduler/gate stay inspectable)."""
    rc: int
    records: List[dict]
    overlap: dict
    results: Dict[str, dict]
    errors: Dict[str, str]
    session: object
    scheduler: StreamScheduler
    gate: adm.InprocAdmission


def _power_tail(cmd_template: List[str]) -> List[str]:
    """The wrapped command must be a power-CLI invocation; return its
    argv tail (everything after the module name)."""
    for i, a in enumerate(cmd_template):
        if a == "ndstpu.harness.power":
            return list(cmd_template[i + 1:])
    raise ValueError(
        "--mode inproc requires the wrapped command to be "
        "`... -m ndstpu.harness.power <args>` (the scheduler reuses "
        "the power CLI's argument contract in-process); got: "
        + " ".join(cmd_template))


def run_streams_inproc(stream_ids: List[str], cmd_template: List[str],
                       concurrent: Optional[int] = None,
                       budget_s: Optional[float] = None,
                       overlap_report: Optional[str] = None
                       ) -> InprocRun:
    """Run N query streams as threads over one shared Session.

    ``cmd_template`` is the same ``{}``-placeholder power command the
    process mode would Popen; it is parsed per stream with the power
    CLI's own parser so both modes share one argument contract.
    """
    from ndstpu.harness import throughput as tp

    tail = _power_tail(cmd_template)
    parser = power.build_parser()
    streams: "OrderedDict[str, object]" = OrderedDict()
    for sid in stream_ids:
        streams[sid] = parser.parse_args(
            [a.replace("{}", sid) for a in tail])
    ns0 = next(iter(streams.values()))
    # the whole point is ONE engine: refuse stream templates that
    # resolve to different warehouses/engines instead of guessing
    for flag in ("input_prefix", "engine", "input_format", "floats",
                 "property_file", "compile_records", "xla_cache_dir"):
        vals = {getattr(ns, flag, None) for ns in streams.values()}
        if len(vals) > 1:
            raise ValueError(
                f"inproc streams must share one {flag}; the {{}} "
                f"placeholder resolved to {sorted(map(str, vals))}")

    t0 = time.time()
    engine = ns0.engine
    accel = engine in ("tpu", "tpu-spmd")
    engine_conf: Dict[str, str] = {}
    if ns0.property_file:
        engine_conf.update(power.load_properties(ns0.property_file))
    engine_conf.setdefault("engine", engine)
    engine_conf.setdefault("input_format", ns0.input_format)
    engine_conf.setdefault("throughput_mode", "inproc")
    if getattr(ns0, "xla_cache_dir", None) and accel:
        engine_conf.setdefault("jax.compilation_cache_dir",
                               ns0.xla_cache_dir)
        engine_conf.setdefault(
            "jax.persistent_cache_min_compile_time_secs", "2.0")
    power.apply_engine_properties(engine_conf)

    # shared context: ONE catalog load / session / HBM upload for all
    # streams (vs one per process in --mode process)
    load_start = time.time()
    with obs.span("load_catalog", cat="phase"):
        catalog = loader.load_catalog(ns0.input_prefix,
                                      use_decimal=not ns0.floats)
        session = power.Session(catalog, backend=engine)
    if engine_conf.get("spmd.threshold_rows"):
        session.spmd_threshold = int(engine_conf["spmd.threshold_rows"])
    if engine_conf.get("spmd.chunk_rows"):
        raw = engine_conf["spmd.chunk_rows"]
        session.spmd_chunk_rows = raw if raw == "auto" else int(raw)
    if engine_conf.get("spmd.prefetch_depth"):
        session.spmd_prefetch_depth = int(
            engine_conf["spmd.prefetch_depth"])
    load_ms = int((time.time() - load_start) * 1000)
    if ns0.compile_records and accel:
        obs.set_gauge("harness.compile_records.present",
                      1 if os.path.exists(ns0.compile_records) else 0)
        try:
            with obs.span("preload_compile_records", cat="phase"):
                n = session.preload_compiled(ns0.compile_records)
            obs.inc("harness.compile_records.preloaded", n)
            print(f"preloaded {n} compile records (shared)")
        except Exception as e:  # stale records must never kill the run
            print(f"WARNING: compile records not loaded: {e}")

    # per-stream query dicts (+ the power CLI's folder/subset checks)
    stream_queries: "OrderedDict[str, OrderedDict]" = OrderedDict()
    for sid, ns in streams.items():
        qd = power.gen_sql_from_stream(ns.query_stream_file)
        if ns.sub_queries:
            qd = power.get_query_subset(qd, ns.sub_queries.split(","))
        stream_queries[sid] = qd
    for folder in {ns.json_summary_folder for ns in streams.values()}:
        check_json_summary_folder(folder)

    if any(getattr(ns, "static_check", False)
           for ns in streams.values()):
        merged: "OrderedDict[str, str]" = OrderedDict()
        for qd in stream_queries.values():
            merged.update(qd)
        with obs.span("static_check", cat="phase"):
            offenders = power.static_check(
                session, merged, engine,
                scale_factor=getattr(ns0, "scale_factor", None))
        if offenders:
            raise SystemExit(
                "static check failed: query part(s) "
                f"{', '.join(offenders)} cannot lower on {engine}")

    # ledger priors drive the cheapest-cold-first pick order
    run_scale_factor = getattr(ns0, "scale_factor", "unknown")
    run_seed = getattr(ns0, "run_seed", "unknown")
    led = None
    ledger_path = getattr(ns0, "ledger", None) or \
        ledger_mod.default_path()
    if ledger_path and ledger_path.lower() != "none":
        try:
            led = ledger_mod.Ledger(ledger_path)
        except Exception as e:  # a corrupt ledger must not kill a run
            print(f"WARNING: ledger {ledger_path} not loaded: {e}")
    if budget_s is None:
        ns_budget = getattr(ns0, "budget_s", None)
        budget_s = ns_budget if ns_budget and ns_budget > 0 else None
    warm_records = bool(ns0.compile_records and
                        os.path.exists(ns0.compile_records))
    est_cold = progress.ledger_estimator(
        led, engine=engine, scale_factor=run_scale_factor,
        warmth="warm" if (not accel or warm_records) else "cold")
    est_warm = progress.ledger_estimator(
        led, engine=engine, scale_factor=run_scale_factor,
        warmth="warm")
    sched = StreamScheduler(
        {sid: dict(qd) for sid, qd in stream_queries.items()},
        budget_s=budget_s, est_cold=est_cold, est_warm=est_warm,
        key_fn=session.canonical_key)
    _install_spine_cache(session, stream_queries)

    slots = concurrent if concurrent else 1
    gate = adm.InprocAdmission(slots)

    results: Dict[str, dict] = {}
    errors: Dict[str, str] = {}
    records: List[dict] = []
    rec_lock = threading.Lock()

    # shared across all stream threads: a query key poisoned in one
    # stream is quarantined for every other stream too (they run the
    # same permuted query set against one shared session)
    retry_policy = faults.RetryPolicy.from_env()
    quarantine = faults.Quarantine()

    def worker(sid: str, ns, qd) -> None:
        stream_name = os.path.splitext(
            os.path.basename(ns.query_stream_file))[0]
        hb = progress.Heartbeat(f"throughput:{sid}", total=len(qd),
                                budget_s=budget_s)
        if ns.json_summary_folder and ns.property_file:
            summary_prefix = os.path.join(
                ns.json_summary_folder,
                os.path.basename(ns.property_file).split(".")[0])
        else:
            summary_prefix = os.path.join(
                ns.json_summary_folder or "", "")

        def runner(sql, name):
            power.run_one_query(session, sql, name, ns.output_prefix,
                                ns.output_format)

        obs.inc("harness.throughput.streams_launched")
        start = time.time()
        code = 0
        try:
            faults.check("stream.worker", key=sid)
            res = power.run_stream(
                qd, queue=sched.view(sid), runner=runner, heartbeat=hb,
                engine=engine, stream_name=stream_name,
                engine_conf=engine_conf, gate=gate,
                json_summary_folder=ns.json_summary_folder,
                summary_prefix=summary_prefix,
                xla_cache_dir=ns.xla_cache_dir, t0=t0,
                span_attrs={"stream": stream_name, "stream_id": sid,
                            "mode": "inproc"},
                retry_policy=retry_policy, quarantine=quarantine)
            results[sid] = res
            _write_stream_time_log(ns, res, load_ms, t0)
        except Exception as e:  # noqa: BLE001 — one stream's crash
            # must not take down the others
            import traceback
            traceback.print_exc()
            errors[sid] = f"{type(e).__name__}: {e}"
            obs.inc("harness.throughput.streams_failed")
            code = 1
        end = time.time()
        with rec_lock:
            rec = {
                "stream": sid,
                "start_epoch_s": round(start, 3),
                "end_epoch_s": round(end, 3),
                "wall_s": round(end - start, 3),
                "returncode": code,
            }
            res = results.get(sid)
            if res is not None:
                rec["executed"] = len(res["executed"])
                rec["failures"] = res["failures"]
                rec["skipped"] = len(res["skipped"])
            records.append(rec)

    threads = [threading.Thread(
        target=worker, args=(sid, ns, stream_queries[sid]),
        name=f"stream-{sid}", daemon=True)
        for sid, ns in streams.items()]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    if ns0.compile_records and accel:
        try:
            session.save_compiled(ns0.compile_records)
        except Exception as e:
            print(f"WARNING: compile records not saved: {e}")

    rc = 1 if errors else 0
    device_tl = gate.device_timeline()
    # top-level max_concurrent is what the admission gate ENFORCED at
    # the device (<= slots by construction); the stream-wall sweep —
    # which overlaps up to N streams, that being the point of the
    # shared engine — stays as stream_max_concurrent
    overlap_doc = tp.write_overlap_report(
        overlap_report, records, slots, budget_s, mode="inproc",
        extra={"max_concurrent": device_tl["max_concurrent"],
               "device_timeline": device_tl,
               "shared_load_ms": load_ms,
               "errors": errors or None})
    obs.set_gauge("harness.throughput.device_max_concurrent",
                  device_tl["max_concurrent"])

    _export_inproc_run(streams, results, errors, records, overlap_doc,
                       overlap_report, led, engine, run_scale_factor,
                       run_seed, budget_s, t0)
    return InprocRun(rc=rc, records=records, overlap=overlap_doc,
                     results=results, errors=errors, session=session,
                     scheduler=sched, gate=gate)


def _install_spine_cache(session, stream_queries) -> None:
    """Flag the spine value-keys that recur across this phase's streams
    and install the shared materialization cache on the session
    (engine/spine.py).  Planning already happened — the StreamScheduler
    constructor ran every text through ``session.canonical_key`` — so
    counting candidates here reuses the plan + spine-site memos.  A key
    occurring once shares with nobody and is not worth publishing.
    NDSTPU_SPINES=0 disables; any defect degrades to no sharing."""
    from ndstpu.engine import spine as spine_mod
    if not spine_mod.enabled():
        return
    try:
        counts: Dict[str, int] = {}
        for qd in stream_queries.values():
            for sql in qd.values():
                for vk in session.spine_candidate_keys(sql):
                    counts[vk] = counts.get(vk, 0) + 1
        flagged = {vk for vk, n in counts.items() if n >= 2}
        if not flagged:
            return
        budget, source = spine_mod.runtime_budget_bytes()
        session.spine_cache = spine_mod.SpineCache(budget, flagged)
        obs.set_gauge("engine.spine.flagged", len(flagged))
        print(f"[spine] {len(flagged)} shared spine(s) flagged across "
              f"{len(stream_queries)} streams "
              f"(budget {budget >> 20}MiB/{source})")
    except Exception as e:  # noqa: BLE001 — sharing is an optimization
        print(f"WARNING: spine cache not installed: {e}")


def _write_stream_time_log(ns, res: dict, load_ms: int,
                           t0: float) -> None:
    """Per-stream CSV time log with the same row contract as the power
    CLI (bench.get_throughput_time parses the Power Start/End rows), so
    the bench driver's throughput-elapsed math is mode-agnostic."""
    import csv
    app_id = res["app_id"]
    rows = [(app_id, "CreateTempView all tables (shared)", load_ms)]
    rows.extend(res["rows"])
    power_start = int(res["start_epoch_s"])
    power_end = int(res["end_epoch_s"])
    rows.append((app_id, "Power Start Time", power_start))
    rows.append((app_id, "Power End Time", power_end))
    rows.append((app_id, "Power Test Time",
                 int((res["end_epoch_s"] - res["start_epoch_s"]) * 1000)))
    rows.append((app_id, "Total Time",
                 int((res["end_epoch_s"] - t0) * 1000)))
    header = ["application_id", "query", "time/milliseconds"]
    for path in (ns.time_log, ns.extra_time_log):
        if not path:
            continue
        with atomic.atomic_writer(path, "w", encoding="UTF8",
                                  newline="") as f:
            w = csv.writer(f)
            w.writerow(header)
            w.writerows(rows)


def _merge_taxonomy(results: Dict[str, dict]) -> dict:
    """Phase-level failure taxonomy: per-class counts summed across
    streams plus the per-(stream, query) class map."""
    counts: Dict[str, int] = {}
    queries: Dict[str, str] = {}
    for sid, res in results.items():
        tx = res.get("taxonomy") or {}
        for klass, n in (tx.get("counts") or {}).items():
            counts[klass] = counts.get(klass, 0) + n
        for qname, klass in (tx.get("queries") or {}).items():
            queries[f"{sid}:{qname}"] = klass
    return {"counts": counts, "queries": queries}


def _export_inproc_run(streams, results, errors, records, overlap_doc,
                       overlap_report, led, engine, scale_factor,
                       run_seed, budget_s, t0) -> None:
    """ONE trace + ONE metrics sidecar for the whole phase (process
    mode writes one per stream subprocess), plus stream-tagged ledger
    rows and the sentinel verdict."""
    if not obs.enabled():
        return
    ns0 = next(iter(streams.values()))
    trace_dir = os.environ.get("NDSTPU_TRACE_DIR") or \
        (os.path.dirname(overlap_report or ns0.time_log) or ".")
    base = os.path.basename(overlap_report) if overlap_report \
        else "throughput_inproc"
    executed = {sid: set(res["executed"])
                for sid, res in results.items()}
    by_stream_name = {}
    for sid, ns in streams.items():
        stem = os.path.splitext(
            os.path.basename(ns.query_stream_file))[0]
        by_stream_name[stem] = sid
    qsums = []
    for q in obs.tracer().query_summaries():
        attrs = q.get("attrs") or {}
        sid = attrs.get("stream_id") or \
            by_stream_name.get(attrs.get("stream"))
        if sid is not None and q["query"] in executed.get(sid, ()):
            qsums.append(q)
    sentinel_block = None
    ledger_block = None
    if led is not None and qsums:
        try:
            # same epoch scoping as the power path (obs/sentinel.py):
            # baselines never cross a data-version change
            run_epoch = None
            try:
                from ndstpu.io import lake as lake_mod
                run_epoch = lake_mod.warehouse_epoch(ns0.input_prefix)
            except Exception:  # noqa: BLE001 — stamp is best-effort
                pass
            sentinel_block = sentinel.classify_run(
                qsums, led, engine=engine, scale_factor=scale_factor,
                snapshot_epoch=run_epoch)
            entries = [ledger_mod.make_entry(
                q["query"], q["wall_s"], q["compile_s"],
                q["execute_s"], engine=engine,
                scale_factor=scale_factor, seed=run_seed,
                source=base,
                extra={k: v for k, v in {
                    "stream": (q.get("attrs") or {}).get("stream"),
                    "mode": "inproc",
                    "snapshot_epoch": run_epoch,
                    "fallback_codes":
                        (q.get("attrs") or {}).get("fallback_codes"),
                    "spmd_fallback":
                        (q.get("attrs") or {}).get("spmd_fallback"),
                    "retry_attempts":
                        (q.get("attrs") or {}).get("retry_attempts"),
                    "spine_hits":
                        (q.get("attrs") or {}).get("spine_hits"),
                    "spine_bytes_saved":
                        (q.get("attrs") or {}).get("spine_bytes_saved"),
                    "cost_decisions":
                        (q.get("attrs") or {}).get("cost_decisions"),
                    "result_rows":
                        (q.get("attrs") or {}).get("result_rows"),
                }.items() if v})
                for q in qsums
                if not (q.get("attrs") or {}).get("error")]
            led.append(entries)
            ledger_block = {"path": led.path, "appended": len(entries)}
            if sentinel_block["regressions"]:
                print(f"WARNING: sentinel flagged warm-path "
                      f"regressions: {sentinel_block['regressions']}")
        except Exception as e:  # ledger must never fail the run
            print(f"WARNING: ledger/sentinel update failed: {e}")
    try:
        paths = obs.export_run(trace_dir, base)
        sidecar = os.path.join(trace_dir, base + ".metrics.json")
        with atomic.atomic_writer(sidecar, "w") as f:
            json.dump(obs.run_metrics({
                "mode": "inproc",
                "engine": engine,
                "streams": records,
                "stream_apps": {sid: res["app_id"]
                                for sid, res in results.items()},
                "errors": errors or None,
                "budget_s": budget_s,
                "partial": any(res["skipped"]
                               for res in results.values()),
                "partial_reasons": {sid: res["skipped"]
                                    for sid, res in results.items()
                                    if res["skipped"]},
                "faultTaxonomy": _merge_taxonomy(results),
                "quarantined": next(
                    (res["quarantined"] for res in results.values()
                     if res.get("quarantined")), None),
                "overlap": {k: overlap_doc[k] for k in
                            ("max_concurrent", "stream_max_concurrent",
                             "admission_slots",
                             "total_pairwise_overlap_s")
                            if k in overlap_doc},
                "total_elapse_ms": int((time.time() - t0) * 1000),
                "ledger": ledger_block,
                "sentinel": sentinel_block,
            }), f, indent=2)
        print(f"====== Trace: {paths['jsonl']} | {paths['chrome']} "
              f"| {sidecar} ======")
    except Exception as e:  # observability must never fail the run
        print(f"WARNING: trace export failed: {e}")
