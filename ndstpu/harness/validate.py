"""Differential output validation — the framework's correctness test.

Parity with the reference validator (/root/reference/nds/nds_validate.py):
per-query comparison of two power-run output directories (e.g. the TPU
engine vs the CPU interpreter, the analog of the reference's GPU-vs-CPU
diff) with:

* row-count check, then row-by-row comparison
* epsilon tolerance for floats (default 1e-5, relative for large values),
  NaN == NaN, Decimal/float cross-compare, None == None
  (nds_validate.py:166-215)
* optional canonical ordering with non-float columns as leading sort keys
  (--ignore_ordering, nds_validate.py:116-144)
* documented per-query carve-outs: q65 skipped, q67 skipped for floats,
  q78-style rounding-instability columns with +-0.01001 tolerance
  (nds_validate.py:146-192,231-237)
* queryValidationStatus Pass/Fail/NotAttempted written back into the
  per-query JSON summaries (nds_validate.py:262-296)
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
from decimal import Decimal
from typing import List, Optional

import pyarrow.parquet as pq

from ndstpu.harness.power import gen_sql_from_stream
from ndstpu.io import atomic

SKIP_QUERIES = {"query65"}
SKIP_FLOAT_QUERIES = {"query67"}
# queries carrying a rounding-unstable `ratio` column whose position is
# located per stream from the SQL text (reference q78 semantics,
# nds_validate.py:146-192 — the column can sit at different positions in
# different streams/engines, so it must not be hardcoded)
ROUND_UNSTABLE_QUERIES = {"query78"}
ROUND_EPSILON = 0.01001


def _outer_select_items(sql: str) -> List[str]:
    """Split the final top-level SELECT list into its expressions,
    respecting parentheses (``round(a/(b+c),2) ratio`` is ONE item).
    The outer select is the LAST ``select`` at paren depth 0 — selects
    inside CTE bodies, derived tables, or scalar subqueries all sit
    inside parentheses and are skipped."""
    low = sql.lower()
    start = -1
    depth = 0
    for m in re.finditer(r"[()]|\bselect\b", low):
        tok = m.group(0)
        if tok == "(":
            depth += 1
        elif tok == ")":
            depth -= 1
        elif depth == 0:
            start = m.start()
    if start < 0:
        return []
    items: List[str] = []
    buf: List[str] = []
    depth = 0
    i = start + len("select")
    while i < len(sql):
        ch = sql[i]
        if depth == 0 and low.startswith("from", i) and \
                not (low[i - 1].isalnum() or low[i - 1] == "_") and \
                (i + 4 == len(sql) or
                 not (low[i + 4].isalnum() or low[i + 4] == "_")):
            break
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            items.append("".join(buf).strip())
            buf = []
        else:
            buf.append(ch)
        i += 1
    if buf and "".join(buf).strip():
        items.append("".join(buf).strip())
    return items


def locate_unstable_cols(query_name: str,
                         sql: Optional[str]) -> Optional[List[int]]:
    """0-based positions of rounding-unstable output columns, found from
    the query text (dynamic per stream — reference
    check_nth_col_problematic_q78, nds_validate.py:146-165)."""
    base = query_name.split("_part")[0]
    if base not in ROUND_UNSTABLE_QUERIES or not sql:
        return None
    idxs = [i for i, item in enumerate(_outer_select_items(sql))
            if "ratio" in item.lower()]
    if not idxs:
        raise ValueError(
            f"{query_name}: no `ratio` column found in the final select "
            f"list — cannot locate the rounding-unstable column")
    return idxs


def _read_output(path: str):
    files = sorted(glob.glob(os.path.join(path, "*.parquet")))
    if not files:
        files = sorted(glob.glob(os.path.join(path, "*.csv")))
        import pyarrow.csv as pacsv
        tables = [pacsv.read_csv(f) for f in files]
    else:
        tables = [pq.read_table(f) for f in files]
    if not tables:
        raise FileNotFoundError(f"no output files under {path}")
    import pyarrow as pa
    t = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    return t


def _is_float_col(col) -> bool:
    import pyarrow as pa
    return pa.types.is_floating(col.type)


def collect_results(path: str, ignore_ordering: bool,
                    use_iterator: bool = False):
    """Rows of one query output; with --ignore_ordering, canonically sorted
    with non-float columns first (reference: nds_validate.py:116-144)."""
    t = _read_output(path)
    rows = [tuple(r.values()) for r in t.to_pylist()]
    if ignore_ordering:
        float_idx = [i for i, c in enumerate(t.columns) if _is_float_col(c)]
        nonfloat = [i for i in range(t.num_columns) if i not in float_idx]

        def keyfn(row):
            def k(v):
                return (v is None, str(v))
            return tuple(k(row[i]) for i in nonfloat) + \
                tuple(k(row[i]) for i in float_idx)
        rows.sort(key=keyfn)
    return rows


def value_equal(a, b, epsilon: float) -> bool:
    if a is None and b is None:
        return True
    if a is None or b is None:
        return False
    if isinstance(a, (float, Decimal)) and isinstance(b, (float, Decimal)):
        fa, fb = float(a), float(b)
        if math.isnan(fa) and math.isnan(fb):
            return True
        if abs(fb) > 1.0:
            return abs(fa - fb) / abs(fb) < epsilon
        return abs(fa - fb) < epsilon
    if isinstance(a, (int, float, Decimal)) and \
            isinstance(b, (int, float, Decimal)):
        return float(a) == float(b)
    return a == b


def row_equal(ra, rb, epsilon: float,
              unstable_cols: Optional[List[int]] = None) -> bool:
    for i, (a, b) in enumerate(zip(ra, rb)):
        if unstable_cols and i in unstable_cols:
            if a is None and b is None:
                continue
            if a is None or b is None:
                return False
            if abs(float(a) - float(b)) > ROUND_EPSILON:
                return False
            continue
        if not value_equal(a, b, epsilon):
            return False
    return True


def compare_results(path_a: str, path_b: str, query_name: str,
                    ignore_ordering: bool, epsilon: float = 1e-5,
                    use_decimal: bool = True,
                    max_errors: int = 10,
                    query_sql: Optional[str] = None) -> bool:
    """Compare one query's two output dirs (reference:
    nds_validate.py:48-114).  `query_sql` (the stream's rendered text)
    drives positional detection of rounding-unstable columns."""
    if query_name in SKIP_QUERIES:
        print(f"=== Skipping {query_name} (documented carve-out) ===")
        return True
    if query_name in SKIP_FLOAT_QUERIES and not use_decimal:
        print(f"=== Skipping {query_name} in float mode ===")
        return True
    a = collect_results(path_a, ignore_ordering)
    b = collect_results(path_b, ignore_ordering)
    if len(a) != len(b):
        print(f"[{query_name}] row count mismatch: {len(a)} vs {len(b)}")
        return False
    unstable = locate_unstable_cols(query_name, query_sql)
    errors = 0
    for i, (ra, rb) in enumerate(zip(a, b)):
        if not row_equal(ra, rb, epsilon, unstable):
            if errors < max_errors:
                print(f"[{query_name}] row {i} differs:\n  A={ra}\n  B={rb}")
            errors += 1
    if errors:
        print(f"[{query_name}] {errors} mismatching rows")
        return False
    print(f"=== Result match for {query_name} ({len(a)} rows) ===")
    return True


def iterate_queries(args) -> List[str]:
    query_dict = gen_sql_from_stream(args.query_stream_file)
    names = (args.sub_queries.split(",") if args.sub_queries
             else list(query_dict))
    failures = []
    for q in names:
        pa_ = os.path.join(args.input1, q)
        pb_ = os.path.join(args.input2, q)
        status = "NotAttempted"
        try:
            ok = compare_results(pa_, pb_, q, args.ignore_ordering,
                                 args.epsilon, not args.floats,
                                 args.max_errors,
                                 query_sql=query_dict.get(q))
            status = "Pass" if ok else "Fail"
        except FileNotFoundError as e:
            print(f"[{q}] missing output: {e}")
            ok = False
        except ValueError as e:
            # e.g. unstable-column detection failed on a malformed q78
            # stream entry — fail THIS query, keep validating the rest
            print(f"[{q}] validation error: {e}")
            status = "Fail"
            ok = False
        if not ok:
            failures.append(q)
        if args.json_summary_folder:
            update_summary(args.json_summary_folder, q, status)
    if failures:
        print("Queries with mismatch results:", failures)
    else:
        print("All queries match.")
    return failures


def update_summary(folder: str, query_name: str, status: str) -> None:
    """Write queryValidationStatus back into the per-query JSON summary
    (reference: nds_validate.py:262-296)."""
    pattern = os.path.join(folder, f"*-{query_name}-*.json")
    for path in glob.glob(pattern):
        with open(path) as f:
            summary = json.load(f)
        if summary.get("query") != query_name:
            continue
        summary["queryValidationStatus"] = [status]
        atomic.atomic_write_json(path, summary)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="validate power-run outputs between two engines")
    p.add_argument("input1", help="first output prefix (e.g. TPU run)")
    p.add_argument("input2", help="second output prefix (e.g. CPU run)")
    p.add_argument("query_stream_file")
    p.add_argument("--ignore_ordering", action="store_true",
                   help="sort rows canonically before compare")
    p.add_argument("--epsilon", type=float, default=1e-5)
    p.add_argument("--floats", action="store_true")
    p.add_argument("--sub_queries")
    p.add_argument("--json_summary_folder",
                   help="update queryValidationStatus in summaries here")
    p.add_argument("--max_errors", type=int, default=10)
    return p


if __name__ == "__main__":
    fails = iterate_queries(build_parser().parse_args())
    raise SystemExit(1 if fails else 0)
