"""Per-query benchmark report (JSON summary contract).

Mirrors the reference's PysparkBenchReport (/root/reference/nds/PysparkBenchReport.py:42-122):
captures env vars (TOKEN/SECRET/PASSWORD redacted), engine configuration and
version, wall time, status taxonomy Completed / CompletedWithTaskFailures /
Failed with exception strings, and writes `{prefix}-{query}-{startTime}.json`
(the filename format is a downstream-pipeline contract).

The reference's JVM task-failure listener maps here to an in-process warning
collector: engine warnings during a query (e.g. schema coercion fallbacks)
mark the run CompletedWithTaskFailures.
"""

from __future__ import annotations

import json
import os
import time
import traceback
import warnings
from typing import Callable

import ndstpu
from ndstpu import obs
from ndstpu.faults import taxonomy
from ndstpu.io import atomic


class BenchReport:
    """Wraps one measured callable; accumulates the JSON summary."""

    def __init__(self, engine_conf: dict | None = None):
        self.engine_conf = dict(engine_conf or {})
        self.summary = {
            "env": {
                "envVars": {},
                "engineConf": {},
                "engineVersion": None,
            },
            "queryStatus": [],
            "exceptions": [],
            "taskFailures": [],
            "startTime": None,
            "queryTimes": [],
        }
        # Seed provenance: spec 4.3.1 chains the stream RNGSEED from
        # the load end timestamp unconditionally (reference
        # nds_bench.py:413-414).  The bench driver publishes which
        # policy this run used via NDSTPU_SEED_POLICY; a pinned seed is
        # a deliberate cache-warm trade and every summary carries the
        # non-compliance flag so the artifact cannot pass as spec.
        policy = os.environ.get("NDSTPU_SEED_POLICY")
        if policy:
            self.summary["specCompliance"] = {
                "seed_policy": policy,
                "rngseed_pinned": policy.startswith("pinned"),
                "spec_compliant_seed": not policy.startswith("pinned"),
                "note": ("spec 4.3.1 requires RNGSEED chained from the "
                         "load end timestamp (nds_bench.py:413-414); "
                         "pinned seeds reuse a warmed corpus"),
            }

    def report_on(self, fn: Callable, *args, query_name: str = None,
                  span_attrs: dict | None = None):
        redacted = ("TOKEN", "SECRET", "PASSWORD")
        self.summary["env"]["envVars"] = {
            k: v for k, v in os.environ.items()
            if not any(r in k.upper() for r in redacted)}
        self.summary["env"]["engineConf"] = self.engine_conf
        self.summary["env"]["engineVersion"] = ndstpu.__version__
        start_time = int(time.time() * 1000)
        counters_before = obs.counters_snapshot()
        # span_attrs tags the query span for trace/ledger consumers —
        # the throughput harness stamps the stream id on every query
        # span so one shared trace stays attributable per stream
        qspan = obs.span(query_name or getattr(fn, "__name__", "query"),
                         cat="query", collect=True, **(span_attrs or {}))
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                with qspan:
                    fn(*args)
            end_time = int(time.time() * 1000)
            if caught:
                self.summary["queryStatus"].append(
                    "CompletedWithTaskFailures")
                self.summary["taskFailures"].extend(
                    str(w.message) for w in caught)
            else:
                self.summary["queryStatus"].append("Completed")
        except Exception as e:  # noqa: BLE001 — benchmark must keep going
            print("ERROR BEGIN")
            print(e)
            traceback.print_tb(e.__traceback__)
            print("ERROR END")
            end_time = int(time.time() * 1000)
            self.summary["queryStatus"].append("Failed")
            self.summary["exceptions"].append(str(e))
            # classified failure contract (docs/ROBUSTNESS.md): every
            # failure carries its taxonomy class, never a bare string
            klass = getattr(e, "taxonomy", None) or taxonomy.classify(e)
            self.summary.setdefault("failureTaxonomy", []).append({
                "query": query_name,
                "class": klass,
                "type": type(e).__name__,
                "attempts": getattr(e, "attempts", 1),
            })
        finally:
            self.summary["startTime"] = start_time
            self.summary["queryTimes"].append(end_time - start_time)
            if obs.enabled():
                b = qspan.buckets or {}
                wall = qspan.wall_s
                compile_s = round(b.get("compile_s", 0.0), 6)
                execute_s = round(b.get("execute_s", 0.0), 6)
                self.summary.setdefault("metrics", []).append({
                    "query": query_name,
                    "wall_s": round(wall, 6),
                    "compile_s": compile_s,
                    "execute_s": execute_s,
                    "attributed_frac": round(
                        (compile_s + execute_s) / wall, 4)
                        if wall > 0 else 0.0,
                    "mode": "cold"
                        if compile_s > max(0.05 * wall, 1e-4) else "warm",
                    "buckets": {k: round(v, 6) for k, v in b.items()},
                    "counters": obs.counter_delta(counters_before),
                })
        return self.summary

    def write_summary(self, query_name: str, prefix: str = "") -> str:
        self.summary["query"] = query_name
        filename = (f"{prefix}-{query_name}-"
                    f"{self.summary['startTime']}.json")
        self.summary["filename"] = filename
        atomic.atomic_write_json(filename, self.summary)
        return filename
