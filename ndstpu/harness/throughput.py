"""Throughput test: N concurrent power runs (the `nds-throughput` analog).

The reference fans out concurrent spark-submit processes with
`xargs -d, -P<n> -I{}` substituting the stream id into the command
(/root/reference/nds/nds-throughput:18-23).  Here each stream is one OS
process running the power CLI with `{}` placeholders substituted the same
way.  `--concurrent N` bounds how many streams execute on the shared
device at once (the `spark.rapids.sql.concurrentGpuTasks` analog,
power_run_gpu.template:21) via a cross-process file-lock semaphore —
see ndstpu.harness.admission.

    python -m ndstpu.harness.throughput 1,2,3 --concurrent 2 -- \\
        python -m ndstpu.harness.power ./query_{}.sql ./wh ./time_{}.csv
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import List, Optional

from ndstpu import obs


def run_throughput(stream_ids: List[str], cmd_template: List[str],
                   concurrent: Optional[int] = None) -> int:
    env = None
    lock_dir = None
    if concurrent is not None:
        lock_dir = tempfile.mkdtemp(prefix="ndstpu_adm")
        env = dict(os.environ,
                   NDSTPU_ADMISSION_SLOTS=str(concurrent),
                   NDSTPU_ADMISSION_DIR=lock_dir)
    try:
        procs = []
        starts = {}
        for sid in stream_ids:
            cmd = [arg.replace("{}", sid) for arg in cmd_template]
            print("launch:", " ".join(cmd))
            starts[sid] = time.time()
            obs.inc("harness.throughput.streams_launched")
            procs.append((sid, subprocess.Popen(cmd, env=env)))
        rc = 0
        for sid, p in procs:
            p.wait()
            # stream lifetimes overlap, so a context-manager span cannot
            # express them — record each with explicit timestamps (the
            # per-query detail lives in each stream process's own trace)
            obs.record(f"stream_{sid}", "stream", starts[sid],
                       time.time() - starts[sid],
                       returncode=p.returncode)
            if p.returncode:
                obs.inc("harness.throughput.streams_failed")
            rc = rc or p.returncode
        return rc
    finally:
        if lock_dir is not None:
            import shutil
            shutil.rmtree(lock_dir, ignore_errors=True)


def main(argv: List[str]) -> int:
    # --concurrent belongs to the wrapper: parse it only from the part
    # BEFORE the "--" separator so the wrapped command's flags are safe
    sep = argv.index("--") if "--" in argv else None
    head = argv[:sep] if sep is not None else argv
    concurrent = None
    if "--concurrent" in head:
        i = head.index("--concurrent")
        if i + 1 >= len(head):
            print("--concurrent requires a value", file=sys.stderr)
            return 2
        try:
            concurrent = int(head[i + 1])
        except ValueError:
            print(f"--concurrent: not an integer: {head[i + 1]}",
                  file=sys.stderr)
            return 2
        if concurrent < 1:
            print("--concurrent must be >= 1", file=sys.stderr)
            return 2
        head = head[:i] + head[i + 2:]
    if sep is not None:
        ids_arg, cmd = head, argv[sep + 1:]
    else:
        ids_arg, cmd = head[:1], head[1:]
    if not ids_arg or not cmd:
        print("usage: throughput <id,id,...> [--concurrent N] -- "
              "<command with {} placeholders>", file=sys.stderr)
        return 2
    stream_ids = [s for s in ids_arg[0].split(",") if s]
    return run_throughput(stream_ids, cmd, concurrent)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
