"""Throughput test: N concurrent power runs (the `nds-throughput` analog).

The reference fans out concurrent spark-submit processes with
`xargs -d, -P<n> -I{}` substituting the stream id into the command
(/root/reference/nds/nds-throughput:18-23).  Two modes:

* ``--mode process`` (default, spec-faithful shape): each stream is one
  OS process running the power CLI with `{}` placeholders substituted
  the same way.  `--concurrent N` bounds how many streams execute on
  the shared device at once (the `spark.rapids.sql.concurrentGpuTasks`
  analog, power_run_gpu.template:21) via a cross-process file-lock
  semaphore — see ndstpu.harness.admission.
* ``--mode inproc`` (fast path): the same N streams run as worker
  threads over ONE shared session/executor so the warehouse loads once
  and each distinct query compiles once — see
  ndstpu.harness.scheduler.  Same `--concurrent` slot semantics
  (in-process gate), same overlap-report format, same time-log
  contract.
* ``--mode serve --serve_socket SPEC``: the streams become N client
  connections to a RUNNING query server (ndstpu/serve) — the spec's
  throughput phase doubling as a server load test.  Admission slots,
  tenant budgets, and shedding are the server's; each stream runs as
  its own tenant and the shared overlap-report format records what the
  server let overlap.  SPEC may be one endpoint (unix path or
  ``tcp:HOST:PORT``) or a comma-separated FLEET of them — clients then
  fail over between replicas, and the overlap report gains per-stream
  ``failovers`` plus per-replica health attribution.

    python -m ndstpu.harness.throughput 1,2,3 --concurrent 2 -- \\
        python -m ndstpu.harness.power ./query_{}.sql ./wh ./time_{}.csv
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from ndstpu import obs
from ndstpu.faults import taxonomy
from ndstpu.harness import progress
from ndstpu.io import atomic


def concurrency_timeline(records: List[dict]) -> dict:
    """Overlap evidence from per-stream (start, end) intervals: max
    concurrent streams (event sweep) + pairwise overlap seconds.  This
    is the committed evidence the ``admission.py`` ``concurrent: N``
    cap is judged against — with admission working, max_concurrent at
    the *device* stays <= N while the wall-clock streams still overlap
    (they queue at the gate, not in the driver)."""
    points = []
    for r in records:
        points.append((r["start_epoch_s"], 1))
        points.append((r["end_epoch_s"], -1))
    points.sort()
    cur = peak = 0
    for _, d in points:
        cur += d
        peak = max(peak, cur)
    pairwise: Dict[str, float] = {}
    total_overlap = 0.0
    for i, a in enumerate(records):
        for b in records[i + 1:]:
            ov = min(a["end_epoch_s"], b["end_epoch_s"]) - \
                max(a["start_epoch_s"], b["start_epoch_s"])
            ov = max(ov, 0.0)
            # records arrive in completion order; key order-stably
            key = "&".join(sorted((a["stream"], b["stream"])))
            pairwise[key] = round(ov, 3)
            total_overlap += ov
    return {
        "max_concurrent": peak,
        "pairwise_overlap_s": pairwise,
        "total_pairwise_overlap_s": round(total_overlap, 3),
    }


def write_overlap_report(overlap_report: Optional[str],
                         records: List[dict],
                         concurrent: Optional[int],
                         budget_s: Optional[float],
                         mode: str = "process",
                         extra: Optional[dict] = None) -> dict:
    """Build (and, when a path is given, write) the overlap-evidence
    document both throughput modes share.  ``stream_max_concurrent`` is
    always the stream-wall event sweep; in process mode
    ``max_concurrent`` is the same number (each stream process holds
    the device for its whole wall), while the inproc scheduler
    overrides it via ``extra`` with the admission gate's device-level
    peak — the number the ``concurrent: N`` cap is judged against."""
    timeline = concurrency_timeline(records)
    obs.set_gauge("harness.throughput.max_concurrent_streams",
                  timeline["max_concurrent"])
    doc = {
        "format": "ndstpu-throughput-overlap-v1",
        "mode": mode,
        "admission_slots": concurrent,
        "budget_s": budget_s,
        "streams": sorted(records, key=lambda r: r["start_epoch_s"]),
        **timeline,
        "stream_max_concurrent": timeline["max_concurrent"],
    }
    if extra:
        doc.update({k: v for k, v in extra.items() if v is not None})
    if overlap_report:
        atomic.atomic_write_json(overlap_report, doc)
        print(f"====== Overlap evidence: {overlap_report} "
              f"(max_concurrent={doc['max_concurrent']}, "
              f"admission_slots={concurrent}) ======")
    return doc


def run_throughput(stream_ids: List[str], cmd_template: List[str],
                   concurrent: Optional[int] = None,
                   budget_s: Optional[float] = None,
                   overlap_report: Optional[str] = None) -> int:
    env = None
    lock_dir = None
    child_env: Dict[str, str] = {}
    if concurrent is not None:
        lock_dir = tempfile.mkdtemp(prefix="ndstpu_adm")
        child_env.update(NDSTPU_ADMISSION_SLOTS=str(concurrent),
                         NDSTPU_ADMISSION_DIR=lock_dir)
    if budget_s:
        # each stream is a full power run on the same phase deadline;
        # the power CLI picks this up and degrades explicitly
        child_env["NDSTPU_PHASE_BUDGET_S"] = str(budget_s)
    if child_env:
        env = dict(os.environ, **child_env)
    try:
        t0 = time.time()
        pending = {}
        starts = {}
        for sid in stream_ids:
            cmd = [arg.replace("{}", sid) for arg in cmd_template]
            print("launch:", " ".join(cmd))
            starts[sid] = time.time()
            obs.inc("harness.throughput.streams_launched")
            pending[sid] = subprocess.Popen(cmd, env=env)
        rc = 0
        records: List[dict] = []
        # a stream subprocess that dies nonzero is restarted ONCE
        # (taxonomy: transient — a fresh process may succeed) before
        # the stream counts as failed; the overlap report records both
        # the restart and the first attempt's envelope
        restarted: Dict[str, dict] = {}
        hb = progress.Heartbeat("throughput", total=len(stream_ids),
                                budget_s=budget_s)
        last_hb = time.time()
        # poll instead of wait() so each stream's end timestamp is
        # observed when it actually exits (sequential wait() would
        # charge an early finisher the laggards' runtime and inflate
        # the overlap evidence); the poll interval backs off
        # exponentially while nothing exits — streams run minutes, so
        # a fixed short poll is pure busy-wait — and snaps back to
        # fine-grained on each completion so end timestamps stay sharp
        poll_s = 0.01
        while pending:
            completed = False
            for sid, p in list(pending.items()):
                code = p.poll()
                if code is None:
                    continue
                completed = True
                del pending[sid]
                end = time.time()
                wall = end - starts[sid]
                if code and sid not in restarted:
                    restarted[sid] = {
                        "returncode": code,
                        "start_epoch_s": round(starts[sid], 3),
                        "end_epoch_s": round(end, 3),
                        "wall_s": round(wall, 3),
                    }
                    cmd = [arg.replace("{}", sid)
                           for arg in cmd_template]
                    print(f"WARNING: stream {sid} exited {code} — "
                          f"restarting once (taxonomy: "
                          f"{taxonomy.TRANSIENT})")
                    obs.inc("harness.retry.stream_restarts")
                    starts[sid] = time.time()
                    pending[sid] = subprocess.Popen(cmd, env=env)
                    continue
                # stream lifetimes overlap, so a context-manager span
                # cannot express them — record each with explicit
                # timestamps (the per-query detail lives in each
                # stream process's own trace)
                obs.record(f"stream_{sid}", "stream", starts[sid],
                           wall, returncode=code)
                rec = {
                    "stream": sid,
                    "start_epoch_s": round(starts[sid], 3),
                    "end_epoch_s": round(end, 3),
                    "wall_s": round(wall, 3),
                    "returncode": code,
                }
                if sid in restarted:
                    rec["restarts"] = 1
                    rec["first_attempt"] = restarted[sid]
                    rec["taxonomy"] = taxonomy.TRANSIENT if code == 0 \
                        else taxonomy.PERMANENT
                records.append(rec)
                hb.beat(len(records), f"stream_{sid} done "
                        f"wall={wall:.1f}s", end - t0)
                if code:
                    obs.inc("harness.throughput.streams_failed")
                rc = rc or code
            if pending:
                poll_s = 0.01 if completed else min(poll_s * 2, 0.5)
                time.sleep(poll_s)
                if time.time() - last_hb >= 30.0:
                    last_hb = time.time()
                    hb.beat(len(records), "waiting", last_hb - t0)
        write_overlap_report(overlap_report, records, concurrent,
                             budget_s, mode="process")
        return rc
    finally:
        if lock_dir is not None:
            import shutil
            shutil.rmtree(lock_dir, ignore_errors=True)


def run_streams_serve(stream_ids: List[str], cmd_template: List[str],
                      serve_socket: str,
                      budget_s: Optional[float] = None,
                      overlap_report: Optional[str] = None) -> int:
    """Route the throughput phase through a running query server.

    ``cmd_template`` is the same ``{}``-placeholder power command the
    other modes take — parsed per stream with the power CLI's parser so
    all three modes share one argument contract — but here only the
    stream files/subsets matter: execution, admission, and output
    writing happen inside the server.  Each stream is one client
    connection (= one server-side scheduler stream) under its own
    tenant; queries go up serially per stream like a power run, and the
    server decides what overlaps.

    ``serve_socket`` may be a **fleet spec** — a comma-separated
    endpoint list (serve/transport.py grammar) such as a
    FleetSupervisor's ``endpoints_spec()``.  Each stream client then
    fails over between replicas on connection faults and sheds; the
    overlap report records per-stream ``failovers``/``endpoint`` and
    per-replica health attribution under ``extra.replica_health``."""
    import threading

    from ndstpu.harness import power, scheduler
    from ndstpu.serve.client import ServeClient

    tail = scheduler._power_tail(cmd_template)
    parser = power.build_parser()
    t0 = time.time()
    records: List[dict] = []
    rec_lock = threading.Lock()
    health = {}

    def worker(sid: str) -> None:
        ns = parser.parse_args([a.replace("{}", sid) for a in tail])
        qd = power.gen_sql_from_stream(ns.query_stream_file)
        if ns.sub_queries:
            qd = power.get_query_subset(qd, ns.sub_queries.split(","))
        stem = os.path.splitext(
            os.path.basename(ns.query_stream_file))[0]
        # fleet specs get a larger attempt budget: under depth-1
        # backpressure every replica can shed for a full service
        # time, and the bench must ride it out rather than fail
        n_eps = len(str(serve_socket).split(","))
        cli = ServeClient(serve_socket, tenant=f"stream-{sid}",
                          retries=8 if n_eps == 1 else 8 + 4 * n_eps)
        start = time.time()
        code = executed = failures = skipped = 0
        obs.inc("harness.throughput.streams_launched")
        try:
            if not cli.wait_ready(60.0):
                raise ConnectionError(
                    f"server at {serve_socket} not ready")
            for qname, sql in qd.items():
                elapsed = time.time() - start
                if budget_s and elapsed >= budget_s:
                    skipped = len(qd) - executed - failures
                    print(f"[serve-stream {sid}] budget exhausted "
                          f"({elapsed:.1f}s >= {budget_s:g}s): "
                          f"skipping {skipped} queries")
                    break
                deadline = (budget_s - elapsed) if budget_s else None
                try:
                    cli.sql(sql, name=f"{stem}/{qname}"
                            if ns.output_prefix else None,
                            deadline_s=deadline)
                    executed += 1
                except Exception as e:  # noqa: BLE001 — per-query
                    failures += 1
                    print(f"[serve-stream {sid}] {qname} failed: "
                          f"{type(e).__name__}: {e}")
            code = 1 if failures else 0
        except Exception as e:  # noqa: BLE001 — stream-fatal
            print(f"[serve-stream {sid}] failed: "
                  f"{type(e).__name__}: {e}")
            obs.inc("harness.throughput.streams_failed")
            code = 1
        finally:
            try:
                health.update(cli.health())
            except Exception:  # noqa: BLE001 — evidence only
                pass
            cli.close()
        end = time.time()
        with rec_lock:
            records.append({
                "stream": sid,
                "start_epoch_s": round(start, 3),
                "end_epoch_s": round(end, 3),
                "wall_s": round(end - start, 3),
                "returncode": code,
                "executed": executed,
                "failures": failures,
                "skipped": skipped,
                "client_retries": cli.retried,
                "failovers": cli.failovers,
                "endpoint": cli.endpoint.spec,
            })

    threads = [threading.Thread(target=worker, args=(sid,),
                                name=f"serve-stream-{sid}",
                                daemon=True)
               for sid in stream_ids]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    rc = 1 if any(r["returncode"] for r in records) else 0
    # per-replica attribution: each endpoint answers its OWN health
    # doc (counters are per-process), so a fleet run shows how load
    # and sheds distributed across replicas
    replica_health = {}
    from ndstpu.serve import transport
    endpoints = transport.parse_endpoints(serve_socket)
    if len(endpoints) > 1:
        for ep in endpoints:
            one = ServeClient(ep.spec, retries=0,
                              connect_timeout_s=2.0)
            try:
                replica_health[ep.spec] = one.health()
            except Exception as e:  # noqa: BLE001 — evidence only
                replica_health[ep.spec] = {"alive": False,
                                           "error": str(e)}
            finally:
                one.close()
    # overlap evidence: stream walls from the client side; the device-
    # level peak is whatever the server's admission gate enforced,
    # reported via its health doc
    write_overlap_report(
        overlap_report, records, health.get("admitted_peak"),
        budget_s, mode="serve",
        extra={"serve_socket": serve_socket,
               "server_health": health or None,
               "replica_health": replica_health or None,
               "failovers_total": sum(r.get("failovers", 0)
                                      for r in records),
               "total_elapse_s": round(time.time() - t0, 3)})
    return rc


def main(argv: List[str]) -> int:
    # wrapper flags are parsed only from the part BEFORE the "--"
    # separator so the wrapped command's own flags are safe
    sep = argv.index("--") if "--" in argv else None
    head = argv[:sep] if sep is not None else argv

    def take(flag: str, cast, check=None):
        if flag not in head:
            return None, None
        i = head.index(flag)
        if i + 1 >= len(head):
            return None, f"{flag} requires a value"
        try:
            val = cast(head[i + 1])
        except ValueError:
            return None, f"{flag}: bad value: {head[i + 1]}"
        if check and not check(val):
            return None, f"{flag}: out of range: {val}"
        del head[i:i + 2]
        return val, None

    concurrent, err = take("--concurrent", int, lambda v: v >= 1)
    if err:
        print(err, file=sys.stderr)
        return 2
    budget_s, err = take("--budget_s", float, lambda v: v > 0)
    if err:
        print(err, file=sys.stderr)
        return 2
    overlap_report, err = take("--overlap_report", str)
    if err:
        print(err, file=sys.stderr)
        return 2
    mode, err = take("--mode", str,
                     lambda v: v in ("process", "inproc", "serve"))
    if err:
        print(err, file=sys.stderr)
        return 2
    serve_socket, err = take("--serve_socket", str)
    if err:
        print(err, file=sys.stderr)
        return 2
    if mode == "serve" and not serve_socket:
        print("--mode serve requires --serve_socket SPEC "
              "(a running ndstpu-serve server or comma-separated "
              "fleet endpoints)", file=sys.stderr)
        return 2
    if budget_s is None and os.environ.get("NDSTPU_PHASE_BUDGET_S"):
        try:
            budget_s = float(os.environ["NDSTPU_PHASE_BUDGET_S"])
        except ValueError:
            pass
    if sep is not None:
        ids_arg, cmd = head, argv[sep + 1:]
    else:
        ids_arg, cmd = head[:1], head[1:]
    if not ids_arg or not cmd:
        print("usage: throughput <id,id,...> [--concurrent N] "
              "[--budget_s S] [--overlap_report PATH] "
              "[--mode process|inproc|serve] "
              "[--serve_socket SPEC[,SPEC...]] -- "
              "<command with {} placeholders>", file=sys.stderr)
        return 2
    stream_ids = [s for s in ids_arg[0].split(",") if s]
    if mode == "serve":
        return run_streams_serve(
            stream_ids, cmd, serve_socket, budget_s=budget_s,
            overlap_report=overlap_report)
    if mode == "inproc":
        from ndstpu.harness import scheduler
        return scheduler.run_streams_inproc(
            stream_ids, cmd, concurrent, budget_s=budget_s,
            overlap_report=overlap_report).rc
    return run_throughput(stream_ids, cmd, concurrent,
                          budget_s=budget_s,
                          overlap_report=overlap_report)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
