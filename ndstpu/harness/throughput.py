"""Throughput test: N concurrent power runs (the `nds-throughput` analog).

The reference fans out concurrent spark-submit processes with
`xargs -d, -P<n> -I{}` substituting the stream id into the command
(/root/reference/nds/nds-throughput:18-23).  Here each stream is one OS
process running the power CLI with `{}` placeholders substituted the same
way.

    python -m ndstpu.harness.throughput 1,2,3 -- \\
        python -m ndstpu.harness.power ./query_{}.sql ./wh ./time_{}.csv
"""

from __future__ import annotations

import subprocess
import sys
from typing import List


def run_throughput(stream_ids: List[str], cmd_template: List[str]) -> int:
    procs = []
    for sid in stream_ids:
        cmd = [arg.replace("{}", sid) for arg in cmd_template]
        print("launch:", " ".join(cmd))
        procs.append(subprocess.Popen(cmd))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def main(argv: List[str]) -> int:
    if "--" in argv:
        sep = argv.index("--")
        ids_arg, cmd = argv[:sep], argv[sep + 1:]
    else:
        ids_arg, cmd = argv[:1], argv[1:]
    if not ids_arg or not cmd:
        print("usage: throughput <id,id,...> -- <command with {} "
              "placeholders>", file=sys.stderr)
        return 2
    stream_ids = [s for s in ids_arg[0].split(",") if s]
    return run_throughput(stream_ids, cmd)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
