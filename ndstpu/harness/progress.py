"""Budget-aware progress heartbeat + deadline degradation.

Round 5's driver silently burned a 1740 s budget on a cold
re-baseline; nothing printed "you will not finish".  This module is
the visible layer: each harness phase gets a deadline budget (from the
bench YAML / ``--budget_s``), the runner emits heartbeat lines + span
events (query i/N, elapsed, ETA from ledger priors, remaining budget),
and when the projection exceeds the budget the run degrades
*explicitly* instead of just dying at the deadline:

* remaining queries are reordered **cheapest-first** by ledger prior,
  so a deadline cut maximizes coverage;
* queries that cannot fit are skipped with a per-query
  ``partial_reason`` recorded into the report — never a bare
  ``partial: true``.

Heartbeat line grammar (greppable, one per query start plus phase
boundaries)::

    [heartbeat] power 7/103 query48 elapsed=34.2s eta=512.3s \
budget=1740s remaining=1705.8s
    [budget] power: projected 812.3s exceeds remaining 400.0s of \
1740s budget - reordering 57 remaining queries cheapest-first
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ndstpu import obs

DEFAULT_COST_S = 5.0  # prior for never-seen queries (mid-pack warm-ish)


class Heartbeat:
    """One progress line + tracer event per beat."""

    def __init__(self, phase: str, total: int,
                 budget_s: Optional[float] = None,
                 out: Callable[[str], None] = print):
        self.phase = phase
        self.total = total
        self.budget_s = budget_s
        self.out = out

    def beat(self, i: int, name: str, elapsed_s: float,
             eta_s: Optional[float] = None) -> None:
        line = (f"[heartbeat] {self.phase} {i}/{self.total} {name} "
                f"elapsed={elapsed_s:.1f}s")
        attrs = {"phase": self.phase, "i": i, "total": self.total,
                 "query": name, "elapsed_s": round(elapsed_s, 3)}
        if eta_s is not None:
            line += f" eta={eta_s:.1f}s"
            attrs["eta_s"] = round(eta_s, 3)
        if self.budget_s:
            left = self.budget_s - elapsed_s
            line += f" budget={self.budget_s:g}s remaining={left:.1f}s"
            attrs["budget_s"] = self.budget_s
            attrs["budget_remaining_s"] = round(left, 3)
        self.out(line)
        obs.record("heartbeat", "heartbeat", time.time(), 0.0, **attrs)


class BudgetedQueue:
    """Deadline-budgeted work queue over query names.

    ``next(elapsed_s)`` pops the next name to run, or ``None`` when
    done/cut.  On the first overrun projection the remaining names are
    reordered cheapest-first (by the supplied ledger-prior estimator);
    names that cannot fit land in ``skipped`` with one human-readable
    reason each.  Without a budget it degenerates to plain FIFO.
    """

    def __init__(self, names, budget_s: Optional[float],
                 estimate: Optional[Callable[[str], Optional[float]]],
                 phase: str = "run",
                 default_cost_s: float = DEFAULT_COST_S,
                 on_event: Callable[[str], None] = print):
        self._names: List[str] = list(names)
        self.budget_s = budget_s if budget_s and budget_s > 0 else None
        self._estimate = estimate
        self.default_cost_s = default_cost_s
        self.phase = phase
        self.reordered = False
        self.skipped: Dict[str, str] = {}
        self._on_event = on_event

    def cost(self, name: str) -> float:
        c = self._estimate(name) if self._estimate else None
        return float(c) if c and c > 0 else self.default_cost_s

    def projected_s(self) -> float:
        return sum(self.cost(n) for n in self._names)

    @property
    def remaining(self) -> List[str]:
        return list(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def _skip_all(self, reason_for: Callable[[str], str]) -> None:
        for n in self._names:
            self.skipped[n] = reason_for(n)
        if self._names:
            self._on_event(
                f"[budget] {self.phase}: cutting {len(self._names)} "
                f"queries ({', '.join(self._names[:8])}"
                + ("..." if len(self._names) > 8 else "")
                + ") - per-query partial_reason recorded in the report")
        self._names = []

    def next(self, elapsed_s: float) -> Optional[str]:
        if not self._names:
            return None
        if self.budget_s is None:
            return self._names.pop(0)
        left = self.budget_s - elapsed_s
        projected = self.projected_s()
        if projected > left and not self.reordered:
            self._names.sort(key=self.cost)
            self.reordered = True
            self._on_event(
                f"[budget] {self.phase}: projected {projected:.1f}s "
                f"exceeds remaining {left:.1f}s of {self.budget_s:g}s "
                f"budget - reordering {len(self._names)} remaining "
                f"queries cheapest-first (ledger priors)")
            obs.inc("harness.budget.reordered")
        if left <= 0:
            self._skip_all(lambda n: (
                f"budget exhausted: {elapsed_s:.1f}s elapsed >= "
                f"{self.budget_s:g}s {self.phase} budget"))
            return None
        # cheapest-first means: if the cheapest remaining query does
        # not fit, nothing costlier will either
        if self.reordered and self.cost(self._names[0]) > left:
            self._skip_all(lambda n: (
                f"budget: prior {self.cost(n):.2f}s exceeds remaining "
                f"{left:.1f}s of {self.budget_s:g}s "
                f"{self.phase} budget"))
            return None
        return self._names.pop(0)


def ledger_estimator(led, engine: Optional[str] = None,
                     scale_factor=None, warmth: str = "warm"):
    """Estimator closure over ledger priors for BudgetedQueue /
    Heartbeat ETA.  ``led`` may be None (no priors -> default cost)."""
    if led is None:
        return lambda name: None
    return lambda name: led.estimate(name, engine=engine,
                                     scale_factor=scale_factor,
                                     warmth=warmth)
