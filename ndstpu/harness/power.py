"""Power run: execute a query stream serially on the engine, timed.

Parity with the reference's power runner (/root/reference/nds/nds_power.py):
stream-file parsing on the `-- start` marker contract incl. two-part query
splitting (nds_power.py:49-76), per-query BenchReport JSON summaries, the
`application_id,query,time/milliseconds` CSV time log with Power Start/End/
Test/Total rows (nds_power.py:247-299), `--sub_queries` subsets, and query
output collection or writing (with output column-name sanitization,
nds_power.py:136-173).

The Spark-submit + session-build layer maps to: load the warehouse catalog
(TempView registration analog, nds_power.py:78-121), optional property file
of engine knobs, and `--engine cpu|tpu` to pick the numpy interpreter or the
JAX/XLA path.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import re
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

from ndstpu import faults, obs
from ndstpu.check import check_json_summary_folder, check_query_subset_exists
from ndstpu.engine import columnar
from ndstpu.engine.session import Session
from ndstpu.harness import progress
from ndstpu.harness.report import BenchReport
from ndstpu.io import atomic, loader
from ndstpu.obs import ledger as ledger_mod
from ndstpu.obs import sentinel


# One `-- start query N in stream M using template queryX.tpl` marker
# opens each query block (the spark.tpl dialect contract the stream
# generator reproduces; cf. reference nds_power.py:49-76).
_STREAM_MARKER = re.compile(
    r"^--\s*start\s+query\s+\d+\s+in\s+stream\s+\d+\s+using\s+template\s+"
    r"(?P<name>\w+)\.tpl\s*$",
    re.MULTILINE | re.IGNORECASE)


def _sql_statements(block: str) -> List[str]:
    """Non-empty SQL statements in a query block, split on semicolons
    that are real statement terminators — a ``;`` inside a quoted
    literal or a ``--`` line comment does not split.  Fragments with no
    code outside comments (e.g. the trailing ``-- end query`` marker
    after the final semicolon) are not statements."""
    frags: List[str] = []
    cur: List[str] = []
    has_code = False
    in_str = in_comment = False
    for i, ch in enumerate(block):
        if in_comment:
            in_comment = ch != "\n"
        elif in_str:
            in_str = ch != "'"
        elif ch == "'":
            in_str = True
            has_code = True
        elif ch == "-" and block[i + 1:i + 2] == "-":
            in_comment = True
        elif ch == ";":
            if has_code:
                frags.append("".join(cur))
            cur, has_code = [], False
            continue
        elif not ch.isspace():
            has_code = True
        cur.append(ch)
    if has_code:
        frags.append("".join(cur))
    return frags


def gen_sql_from_stream(query_stream_file_path: str) -> "OrderedDict[str, str]":
    """Split a stream file into {query_name: sql}, splitting the
    multi-statement templates (14/23/24/39) into `_part1`/`_part2`
    entries (contract: nds_power.py:49-76)."""
    with open(query_stream_file_path) as f:
        text = f.read()
    markers = list(_STREAM_MARKER.finditer(text))
    queries: "OrderedDict[str, str]" = OrderedDict()
    for marker, nxt in zip(markers, markers[1:] + [None]):
        name = marker.group("name")
        block_end = nxt.start() if nxt is not None else len(text)
        body = text[marker.end():block_end]
        stmts = _sql_statements(body)
        if len(stmts) > 1:
            for k, stmt in enumerate(stmts, start=1):
                queries[f"{name}_part{k}"] = stmt + ";"
        else:
            # single-statement: keep the whole block, markers included
            queries[name] = text[marker.start():block_end]
    return queries


def ensure_valid_column_names(table: columnar.Table) -> columnar.Table:
    """Sanitize output column names for file formats
    (reference: nds_power.py:136-173)."""
    def ok(name: str) -> bool:
        return re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", name) is not None

    cols = {}
    for i, (n, c) in enumerate(table.columns.items()):
        cols[n if ok(n) else f"column_{i}"] = c
    return columnar.Table(cols)


def get_query_subset(query_dict, subset: List[str]):
    check_query_subset_exists(query_dict, subset)
    return OrderedDict((q, query_dict[q]) for q in subset)


def static_check(sess: Session, query_dict, engine: str,
                 scale_factor=None) -> List[str]:
    """``--static_check`` gate: run the static analyzer over every queued
    query (plan-only — no data, no XLA compile) and return the queries
    with error-severity lowering diagnostics, printing each diagnostic's
    code and plan location.  Error-severity NDS2xx means jaxexec WILL
    fall back mid-run after paying the compile, so accel engines reject
    the stream up front; the cpu interpreter executes everything, so
    nothing gates there."""
    from ndstpu import analysis

    if engine not in ("tpu", "tpu-spmd"):
        print("static check: cpu engine lowers everything; skipping")
        return []
    try:
        sf = float(scale_factor)
    except (TypeError, ValueError):
        sf = None
    tables = analysis.schema_tables()
    offenders: List[str] = []
    for name, sql in query_dict.items():
        try:
            plan, _cols = sess.plan(sql)
        except Exception as e:
            # parse/plan/optimize rejection: the run would die on this
            # statement anyway, so it gates
            offenders.append(name)
            print(f"STATIC CHECK {name}: NDS000 at plan: {e}")
            continue
        try:
            res = analysis.analyze_plan(plan, tables=tables, query=name,
                                        scale_factor=sf)
        except Exception as e:  # analyzer gaps must not block a run
            print(f"WARNING: static check could not analyze {name}: {e}")
            continue
        gating = [d for d in res.diagnostics if d.severity == "error"
                  and "/subquery[" not in d.path]
        if gating:
            offenders.append(name)
            for d in gating:
                print(f"STATIC CHECK {name}: {d.code} at {d.path}: "
                      f"{d.message}")
    return offenders


def run_one_query(session: Session, query: str, query_name: str,
                  output_path: Optional[str], output_format: str) -> None:
    result = session.sql(query)
    if result is None:
        return
    # observed output cardinality on the query span -> ledger extra:
    # the calibration source for the static cost model
    # (scripts/cost_lint.py --calibrate, NDS604)
    from ndstpu import obs
    obs.annotate(result_rows=int(result.num_rows))
    if not output_path:
        result.to_rows()  # the collect() analog — materialize to host
        return
    out = ensure_valid_column_names(result)
    dest = os.path.join(output_path, query_name)
    os.makedirs(dest, exist_ok=True)
    at = columnar.to_arrow(out)
    if output_format == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(at, os.path.join(dest, "part-0.parquet"))
    elif output_format == "csv":
        import pyarrow.csv as pacsv
        pacsv.write_csv(at, os.path.join(dest, "part-0.csv"))
    else:
        raise ValueError(f"unsupported output format {output_format}")


def load_properties(filename: str) -> Dict[str, str]:
    """java-properties style engine config (reference: nds_power.py:306-312)."""
    props = {}
    with open(filename) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" in line:
                k, v = line.split("=", 1)
                props[k.strip()] = v.strip()
    return props


def apply_engine_properties(engine_conf: Dict[str, str]) -> None:
    """Apply `jax.*` properties to jax.config (the effective engine-knob
    channel — the analog of Spark conf flowing from the submit template
    into the SparkSession, nds_power.py:221-237).  Env vars cannot work
    here: jax is pre-imported by the image's sitecustomize."""
    jax_keys = {k: v for k, v in engine_conf.items() if k.startswith("jax.")}
    if not jax_keys:
        return
    import jax
    for k, v in jax_keys.items():
        name = "jax_" + k[len("jax."):]
        val: object = v
        for conv in (int, float):
            try:
                val = conv(v)
                break
            except ValueError:
                continue
        if v.lower() in ("true", "false"):
            val = v.lower() == "true"
        try:
            jax.config.update(name, val)
        except Exception as e:  # unknown knob: record, don't abort the run
            print(f"WARNING: engine property {k}={v} not applied: {e}")


def _dir_file_count(path: Optional[str]) -> int:
    """Recursive file count of the XLA persistent compile cache — the
    before/after gauge that distinguishes a genuinely warm run (no new
    cache entries) from one that recompiled behind preloaded records."""
    if not path or not os.path.isdir(path):
        return 0
    return sum(len(files) for _, _, files in os.walk(path))


def run_stream(query_dict, *, queue, runner,
               heartbeat: Optional[progress.Heartbeat] = None,
               engine: str = "cpu", app_id: Optional[str] = None,
               stream_name: str = "stream",
               engine_conf: Optional[Dict[str, str]] = None,
               gate=None, pre_query=None, post_query=None,
               json_summary_folder: Optional[str] = None,
               summary_prefix: str = "",
               xla_cache_dir: Optional[str] = None,
               t0: Optional[float] = None,
               span_attrs: Optional[dict] = None,
               retry_policy: Optional[faults.RetryPolicy] = None,
               quarantine: Optional[faults.Quarantine] = None,
               completed: Optional[set] = None) -> dict:
    """Run one query stream's per-query loop against an already-built
    execution context.  This is the reusable core the power CLI and the
    in-process throughput scheduler share: the CLI wraps it with its own
    session/watchdog/admission setup (one stream per OS process), the
    scheduler calls it once per stream THREAD against one shared session
    (ndstpu/harness/scheduler.py).

    * ``queue``      — BudgetedQueue or a scheduler stream view: needs
      ``next(elapsed_s)``, ``projected_s()``, ``skipped``; an optional
      ``done(name, failed=...)`` is called after each query (the
      scheduler uses it to publish compile-once state across streams).
    * ``runner``     — ``runner(sql, query_name)`` executes one query
      (the CLI passes its watchdog-guarded closure).
    * ``gate``       — admission with ``acquire()``/``release()``
      (DeviceAdmission or InprocAdmission), or None.
    * ``pre_query``  — optional hook returning a dict merged into the
      query summary (the CLI's zombie-thread bookkeeping).
    * ``post_query`` — optional ``post_query(name, summary, failed)``
      hook called after each query completes or fails (the resume
      journal appends its per-query record here).
    * ``retry_policy`` / ``quarantine`` — failure handling
      (ndstpu/faults/retry.py): transient failures retry with bounded
      deterministic backoff; a key that keeps failing is quarantined
      and later occurrences skip with an explicit ``partial_reason``.
    * ``completed``  — query names already finished by a previous run
      of the same fingerprint (crash-safe resume); skipped up front
      and reported under ``resumed``.

    Returns ``{"app_id", "rows", "executed", "skipped", "failures",
    "start_epoch_s", "end_epoch_s", "taxonomy", "quarantined",
    "resumed"}`` where ``rows`` are ``(app_id, query, millis)``
    time-log tuples.
    """
    t0 = time.time() if t0 is None else t0
    app_id = app_id or f"ndstpu-{uuid.uuid4().hex[:12]}"
    engine_conf = engine_conf or {}
    mark_done = getattr(queue, "done", None)
    rows: List[tuple] = []
    executed: List[str] = []
    failures = 0
    taxonomy_counts: Dict[str, int] = {}
    taxonomy_queries: Dict[str, str] = {}
    resumed: List[str] = []
    base_runner = runner
    if retry_policy is not None or quarantine is not None:
        # run_with_retry classifies + annotates even at max_attempts=1
        def runner(sql, qname):  # noqa: F811 — deliberate shadowing
            faults.run_with_retry(lambda: base_runner(sql, qname),
                                  qname, policy=retry_policy,
                                  quarantine=quarantine)
    start_epoch = time.time()
    stream_span = obs.span(stream_name, cat="stream", collect=True,
                           engine=engine, n_queries=len(query_dict),
                           **(span_attrs or {}))
    stream_span.__enter__()
    try:
        while True:
            query_name = queue.next(time.time() - t0)
            if query_name is None:
                break
            if completed and query_name in completed:
                # crash-safe resume: finished by a previous run of the
                # same fingerprint — skip without touching the engine
                print(f"====== Skip {query_name} (resume: already "
                      f"completed) ======")
                resumed.append(query_name)
                if mark_done is not None:
                    mark_done(query_name, failed=False)
                continue
            if quarantine is not None and \
                    quarantine.is_quarantined(query_name):
                reason = quarantine.reason(query_name)
                print(f"====== Skip {query_name} ({reason}) ======")
                queue.skipped[query_name] = reason
                obs.inc("harness.quarantine.skips")
                if mark_done is not None:
                    # failed=True: a quarantined key must never publish
                    # to the shared compile/plan caches (PR-4 invariant)
                    mark_done(query_name, failed=True)
                continue
            q_content = query_dict[query_name]
            if heartbeat is not None:
                heartbeat.beat(len(executed) + 1, query_name,
                               time.time() - t0,
                               eta_s=queue.projected_s())
            print(f"====== Run {query_name} ======")
            summary_extra = pre_query(query_name) if pre_query else None
            xla_files_before = _dir_file_count(xla_cache_dir)
            q_report = BenchReport(engine_conf)
            # NOTE metric difference vs the reference: its
            # concurrentGpuTasks semaphore is acquired inside task
            # execution, so queue wait is part of each reported query
            # time; here the gate sits outside report_on, so queryTimes
            # is pure execution and the wait is reported separately
            # (admissionWaitMs) to keep stream comparisons honest.
            wait_ms = 0
            if gate is not None:
                wait_start = time.time()
                gate.acquire()
                wait_ms = int((time.time() - wait_start) * 1000)
            try:
                summary = q_report.report_on(runner, q_content,
                                             query_name,
                                             query_name=query_name,
                                             span_attrs=span_attrs)
            finally:
                if gate is not None:
                    gate.release()
            if gate is not None:
                summary["admissionWaitMs"] = wait_ms
            if summary_extra:
                summary.update(summary_extra)
            failed = bool(summary["queryStatus"]) and \
                summary["queryStatus"][-1] == "Failed"
            if failed:
                failures += 1
                for tx in summary.get("failureTaxonomy", []):
                    if tx.get("query") == query_name:
                        taxonomy_counts[tx["class"]] = \
                            taxonomy_counts.get(tx["class"], 0) + 1
                        taxonomy_queries[query_name] = tx["class"]
            if mark_done is not None:
                mark_done(query_name, failed=failed)
            if xla_cache_dir:
                xla_files_after = _dir_file_count(xla_cache_dir)
                obs.set_gauge("xla.persistent_cache.files",
                              xla_files_after)
                if xla_files_after > xla_files_before:
                    obs.inc("xla.persistent_cache.new_entries",
                            xla_files_after - xla_files_before)
                if summary.get("metrics"):
                    summary["metrics"][-1]["xla_cache_files"] = {
                        "before": xla_files_before,
                        "after": xla_files_after}
            print(f"Time taken: {summary['queryTimes']} millis for "
                  f"{query_name}")
            rows.append((app_id, query_name, summary["queryTimes"][0]))
            if json_summary_folder:
                q_report.write_summary(query_name,
                                       prefix=summary_prefix)
            executed.append(query_name)
            if post_query is not None:
                post_query(query_name, summary, failed)
    finally:
        stream_span.__exit__(None, None, None)
    if queue.skipped:
        budget = getattr(queue, "budget_s", None)
        print(f"WARNING: {getattr(queue, 'phase', 'run')} run partial "
              f"- {len(queue.skipped)} queries cut by the "
              f"{budget:g}s budget; per-query partial_reason recorded "
              f"in the metrics sidecar" if budget else
              f"WARNING: {len(queue.skipped)} queries skipped")
        obs.inc("harness.budget.queries_skipped", len(queue.skipped))
    return {
        "app_id": app_id,
        "rows": rows,
        "executed": executed,
        "skipped": dict(queue.skipped),
        "failures": failures,
        "taxonomy": {"counts": taxonomy_counts,
                     "queries": taxonomy_queries},
        "quarantined": quarantine.snapshot() if quarantine else {},
        "resumed": resumed,
        "start_epoch_s": start_epoch,
        "end_epoch_s": time.time(),
    }


def power_fingerprint(args) -> str:
    """Identity of a power run for crash-safe resume: two runs with the
    same fingerprint execute the same queries against the same data, so
    a query completed by one needn't re-run in the other."""
    import hashlib
    parts = [
        str(getattr(args, "engine", "")),
        str(getattr(args, "scale_factor", "")),
        str(getattr(args, "run_seed", "")),
        os.path.basename(getattr(args, "query_stream_file", "") or ""),
        str(getattr(args, "sub_queries", "") or ""),
        os.path.abspath(getattr(args, "input_prefix", "") or ""),
    ]
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def run_query_stream(args) -> None:
    total_start = time.time()
    execution_times = []
    app_id = f"ndstpu-{uuid.uuid4().hex[:12]}"

    engine_conf: Dict[str, str] = {}
    if args.property_file:
        engine_conf.update(load_properties(args.property_file))
    engine_conf.setdefault("engine", args.engine)
    engine_conf.setdefault("input_format", args.input_format)
    if getattr(args, "xla_cache_dir", None) and \
            args.engine in ("tpu", "tpu-spmd"):
        # persistent XLA compile cache (like bench.py): without it every
        # power-run process pays the full per-query compile again even
        # when size-plan records preloaded fine (observed ~30 s/query)
        engine_conf.setdefault("jax.compilation_cache_dir",
                               args.xla_cache_dir)
        engine_conf.setdefault(
            "jax.persistent_cache_min_compile_time_secs", "2.0")
    apply_engine_properties(engine_conf)

    query_dict = gen_sql_from_stream(args.query_stream_file)

    # catalog load == table registration (TempView analog)
    load_start = time.time()
    with obs.span("load_catalog", cat="phase"):
        catalog = loader.load_catalog(args.input_prefix,
                                      use_decimal=not args.floats)
        sess = Session(catalog, backend=args.engine)
    # distributed-engine knobs via the property channel (the analog of
    # spark.sql.shuffle.partitions etc. flowing from the template)
    if engine_conf.get("spmd.threshold_rows"):
        sess.spmd_threshold = int(engine_conf["spmd.threshold_rows"])
    if engine_conf.get("spmd.chunk_rows"):
        raw = engine_conf["spmd.chunk_rows"]
        sess.spmd_chunk_rows = raw if raw == "auto" else int(raw)
    if engine_conf.get("spmd.prefetch_depth"):
        sess.spmd_prefetch_depth = int(engine_conf["spmd.prefetch_depth"])
    execution_times.append(
        (app_id, "CreateTempView all tables",
         int((time.time() - load_start) * 1000)))
    if args.compile_records and args.engine in ("tpu", "tpu-spmd"):
        # after the load-time row: preload re-plans every saved query and
        # must not be charged to table registration
        preload_start = time.time()
        obs.set_gauge("harness.compile_records.present",
                      1 if os.path.exists(args.compile_records) else 0)
        try:
            with obs.span("preload_compile_records", cat="phase"):
                n = sess.preload_compiled(args.compile_records)
            obs.inc("harness.compile_records.preloaded", n)
            print(f"preloaded {n} compile records")
        except Exception as e:  # stale records must never kill the run
            print(f"WARNING: compile records not loaded: {e}")
        execution_times.append(
            (app_id, "Preload compile records",
             int((time.time() - preload_start) * 1000)))

    check_json_summary_folder(args.json_summary_folder)
    if args.sub_queries:
        query_dict = get_query_subset(query_dict,
                                      args.sub_queries.split(","))

    if getattr(args, "static_check", False):
        with obs.span("static_check", cat="phase"):
            offenders = static_check(
                sess, query_dict, args.engine,
                scale_factor=getattr(args, "scale_factor", None))
        if offenders:
            raise SystemExit(
                "static check failed: query part(s) "
                f"{', '.join(offenders)} cannot lower on "
                f"{args.engine} (diagnostics above); fix the query "
                "or drop --static_check to run with runtime fallback")

    # concurrent-stream admission: at most N streams execute on the
    # device at once (the concurrentGpuTasks analog; set by the
    # throughput runner via env, see ndstpu.harness.admission)
    from ndstpu.harness import admission as adm
    gate = adm.from_env()

    # per-query watchdog (accel engines): a wedged remote-compile RPC
    # or a degraded tunnel otherwise blocks the stream forever — the
    # bench and warm drivers already abandon such queries in a daemon
    # thread; the power CLI gets the same protection.  The abandoned
    # thread keeps only the OLD session, so the stream continues on a
    # fresh one (records preloaded again).
    #
    # Device-sharing hazard: the abandoned thread still drives the old
    # session on the SAME TPU runtime the fresh session uses; a late
    # completion can contend for HBM, and warnings it raises are
    # captured by whichever later query's report window is open
    # (process-global warning capture).  Mitigation below: abandoned
    # threads are tracked in `zombies`; before each query the stream
    # grants them a short grace join, and any still-alive zombie is
    # recorded in the query's summary (`zombieQueries`) so a
    # CompletedWithTaskFailures status can be adjudicated.
    accel = args.engine in ("tpu", "tpu-spmd")
    watchdog_s = float(os.environ.get(
        "NDSTPU_POWER_QUERY_TIMEOUT_S", "1200")) if accel else 0.0
    sess_holder = {"s": sess}
    zombies: List[dict] = []  # abandoned runs: {th, name, graced}

    def live_zombies(grace_s: float = 0.0) -> List[str]:
        # each zombie gets ONE grace join — a permanently-wedged thread
        # must not charge every remaining query the full grace window
        for z in zombies:
            if not z["graced"]:
                z["th"].join(grace_s)
                z["graced"] = True
        zombies[:] = [z for z in zombies if z["th"].is_alive()]
        return [z["name"] for z in zombies]

    def run_guarded(q_content, query_name):
        if watchdog_s <= 0:
            return run_one_query(sess_holder["s"], q_content, query_name,
                                 args.output_prefix, args.output_format)
        import threading
        slot: dict = {}

        def work(s=sess_holder["s"]):
            try:
                run_one_query(s, q_content, query_name,
                              args.output_prefix, args.output_format)
                slot["ok"] = True
            except Exception as e:  # noqa: BLE001
                slot["err"] = e

        th = threading.Thread(target=work, daemon=True)
        th.start()
        th.join(watchdog_s)
        if th.is_alive():
            zombies.append({"th": th, "name": query_name, "graced": False})
            old = sess_holder["s"]
            try:
                fresh = Session(old.catalog, backend=args.engine,
                                views=dict(old.views),
                                warehouse=old.warehouse)
                fresh.spmd_threshold = old.spmd_threshold
                fresh.spmd_chunk_rows = old.spmd_chunk_rows
                fresh.spmd_prefetch_depth = old.spmd_prefetch_depth
                # swap FIRST: preload failure is non-fatal, but the
                # stream must never continue on the session the
                # zombie thread still drives
                sess_holder["s"] = fresh
                if args.compile_records:
                    fresh.preload_compiled(args.compile_records)
            except Exception as e:  # noqa: BLE001
                print(f"WARNING: fresh session setup after hang "
                      f"incomplete: {e}")
            raise TimeoutError(
                f"{query_name} hung > {watchdog_s:.0f}s; abandoned "
                f"(stream continues on a fresh session)")
        if "err" in slot:
            raise slot["err"]

    stream_name = os.path.splitext(
        os.path.basename(args.query_stream_file))[0]
    obs.set_gauge("xla.persistent_cache.files",
                  _dir_file_count(args.xla_cache_dir))

    # -- run ledger + budget heartbeat (docs/OBSERVABILITY.md) --------
    # priors feed the per-query ETA and the cheapest-first deadline
    # degradation; the ledger itself is appended to after the stream.
    # getattr: callers that build a Namespace by hand (tests, older
    # drivers) predate these flags
    run_scale_factor = getattr(args, "scale_factor", "unknown")
    run_seed = getattr(args, "run_seed", "unknown")
    led = None
    ledger_path = getattr(args, "ledger", None)
    if ledger_path is None:
        ledger_path = ledger_mod.default_path()
    if ledger_path and ledger_path.lower() != "none":
        try:
            led = ledger_mod.Ledger(ledger_path)
        except Exception as e:  # a corrupt ledger must not kill a run
            print(f"WARNING: ledger {ledger_path} not loaded: {e}")
    # expected warmth for ETA priors: accel engines pay compile unless
    # the size-plan records exist; the cpu interpreter never compiles
    expected_warmth = "warm"
    if args.engine in ("tpu", "tpu-spmd") and not (
            args.compile_records and
            os.path.exists(args.compile_records)):
        expected_warmth = "cold"
    budget_s = getattr(args, "budget_s", None)
    budget_s = budget_s if budget_s and budget_s > 0 else None
    est = progress.ledger_estimator(led, engine=args.engine,
                                    scale_factor=run_scale_factor,
                                    warmth=expected_warmth)
    queue = progress.BudgetedQueue(list(query_dict), budget_s, est,
                                   phase="power")
    hb = progress.Heartbeat("power", total=len(query_dict),
                            budget_s=budget_s)

    # -- failure handling + crash-safe resume -------------------------
    # transient failures retry (NDSTPU_RETRY_MAX attempts, deterministic
    # backoff); a per-query progress journal rides next to the time log
    # so a killed run can --resume past every query it already finished
    retry_policy = faults.RetryPolicy.from_env()
    quarantine = faults.Quarantine()
    progress_log = args.time_log + ".progress.jsonl"
    run_fp = power_fingerprint(args)
    completed: set = set()
    resumed_rows: List[tuple] = []
    if getattr(args, "resume", False):
        for rec in atomic.read_jsonl(progress_log):
            if rec.get("fp") == run_fp and not rec.get("failed") and \
                    rec.get("query") in query_dict and \
                    rec["query"] not in completed:
                completed.add(rec["query"])
                resumed_rows.append((rec.get("app_id", app_id),
                                     rec["query"],
                                     rec.get("millis") or 0))
        if completed:
            print(f"====== Resume: skipping {len(completed)} queries "
                  f"already completed (fingerprint {run_fp[:12]}) "
                  f"======")
            obs.inc("harness.resume.queries_skipped", len(completed))
    elif os.path.exists(progress_log):
        os.unlink(progress_log)  # fresh run: the old journal is stale

    def post_query(name, summary, failed):
        try:
            atomic.append_jsonl(progress_log, {
                "fp": run_fp, "query": name, "failed": bool(failed),
                "millis": summary["queryTimes"][0]
                if summary["queryTimes"] else None,
                "app_id": app_id, "ts_epoch_s": time.time()})
        except Exception as e:  # journal must never fail the run
            print(f"WARNING: progress journal append failed: {e}")

    def pre_query(query_name):
        # abandoned-thread gate: give zombies a short grace window to
        # drain before sharing the device with the next query
        active_zombies = live_zombies(grace_s=10.0) if zombies else []
        if not active_zombies:
            return None
        print(f"WARNING: abandoned query threads still running: "
              f"{active_zombies} — device contention possible; "
              f"captured warnings may belong to them")
        return {"zombieQueries": active_zombies}

    if args.json_summary_folder and args.property_file:
        summary_prefix = os.path.join(
            args.json_summary_folder,
            os.path.basename(args.property_file).split(".")[0])
    else:
        summary_prefix = os.path.join(args.json_summary_folder or "", "")

    power_start = int(time.time())
    res = run_stream(query_dict, queue=queue, runner=run_guarded,
                     heartbeat=hb, engine=args.engine, app_id=app_id,
                     stream_name=stream_name, engine_conf=engine_conf,
                     gate=gate, pre_query=pre_query,
                     post_query=post_query,
                     json_summary_folder=args.json_summary_folder,
                     summary_prefix=summary_prefix,
                     xla_cache_dir=args.xla_cache_dir,
                     t0=total_start,
                     span_attrs={"stream": stream_name},
                     retry_policy=retry_policy, quarantine=quarantine,
                     completed=completed)
    execution_times.extend(resumed_rows)
    execution_times.extend(res["rows"])
    executed = res["executed"]
    power_end = int(time.time())
    power_elapse = int((power_end - power_start) * 1000)
    total_elapse = int((time.time() - total_start) * 1000)
    print(f"====== Power Test Time: {power_elapse} milliseconds ======")
    print(f"====== Total Time: {total_elapse} milliseconds ======")
    execution_times.append((app_id, "Power Start Time", power_start))
    execution_times.append((app_id, "Power End Time", power_end))
    execution_times.append((app_id, "Power Test Time", power_elapse))
    execution_times.append((app_id, "Total Time", total_elapse))

    if args.compile_records and args.engine in ("tpu", "tpu-spmd"):
        try:
            sess_holder["s"].save_compiled(args.compile_records)
        except Exception as e:
            print(f"WARNING: compile records not saved: {e}")

    header = ["application_id", "query", "time/milliseconds"]
    with atomic.atomic_writer(args.time_log, "w",
                              encoding="UTF8", newline="") as f:
        w = csv.writer(f)
        w.writerow(header)
        w.writerows(execution_times)
    if args.extra_time_log:
        with atomic.atomic_writer(args.extra_time_log, "w",
                                  encoding="UTF8", newline="") as f:
            w = csv.writer(f)
            w.writerow(header)
            w.writerows(execution_times)

    if obs.enabled():
        # one JSONL event log + one Perfetto-loadable Chrome trace per
        # run, next to the time log (NDSTPU_TRACE_DIR overrides), plus a
        # machine-readable metrics sidecar the bench driver aggregates
        trace_dir = os.environ.get("NDSTPU_TRACE_DIR") or \
            (os.path.dirname(args.time_log) or ".")
        base = os.path.basename(args.time_log)
        # sentinel verdicts are judged against the PRE-run ledger, then
        # this run's measurements are appended so the next run has
        # priors; failed queries never contribute baselines
        sentinel_block = None
        ledger_block = None
        qsums = [q for q in obs.tracer().query_summaries()
                 if q["query"] in set(executed)]
        if led is not None and qsums:
            try:
                # data-version identity of the warehouse this run read:
                # the sentinel only compares warm walls within one
                # epoch (verdict data-changed across epochs), and rows
                # appended here carry the stamp for future runs
                run_epoch = None
                try:
                    from ndstpu.io import lake as lake_mod
                    run_epoch = lake_mod.warehouse_epoch(
                        args.input_prefix)
                except Exception:  # noqa: BLE001 — stamp is best-effort
                    pass
                sentinel_block = sentinel.classify_run(
                    qsums, led, engine=args.engine,
                    scale_factor=run_scale_factor,
                    snapshot_epoch=run_epoch)
                entries = [ledger_mod.make_entry(
                    q["query"], q["wall_s"], q["compile_s"],
                    q["execute_s"], engine=args.engine,
                    scale_factor=run_scale_factor, seed=run_seed,
                    source=os.path.basename(args.time_log),
                    # why the engine left the device path, as
                    # "NDSxxx:Node" analyzer codes (engine-annotated);
                    # plus the stream tag so a shared ledger stays
                    # attributable per stream
                    extra={k: v for k, v in {
                        "stream": stream_name,
                        "snapshot_epoch": run_epoch,
                        "fallback_codes":
                            (q.get("attrs") or {}).get("fallback_codes"),
                        "spmd_fallback":
                            (q.get("attrs") or {}).get("spmd_fallback"),
                        "retry_attempts":
                            (q.get("attrs") or {}).get("retry_attempts"),
                        "spine_hits":
                            (q.get("attrs") or {}).get("spine_hits"),
                        "spine_bytes_saved":
                            (q.get("attrs") or {}).get(
                                "spine_bytes_saved"),
                        # cost-model consumers: the advisor's exchange
                        # decisions and the observed output cardinality
                        # (NDS604 calibration, scripts/cost_lint.py)
                        "cost_decisions":
                            (q.get("attrs") or {}).get("cost_decisions"),
                        "result_rows":
                            (q.get("attrs") or {}).get("result_rows"),
                    }.items() if v})
                    for q in qsums
                    if not (q.get("attrs") or {}).get("error")]
                led.append(entries)
                ledger_block = {"path": led.path,
                                "appended": len(entries)}
                if sentinel_block["regressions"]:
                    print(f"WARNING: sentinel flagged warm-path "
                          f"regressions: "
                          f"{sentinel_block['regressions']} "
                          f"(scripts/regression_check.py exits "
                          f"nonzero on these)")
            except Exception as e:  # ledger must never fail the run
                print(f"WARNING: ledger/sentinel update failed: {e}")
        try:
            paths = obs.export_run(trace_dir, base)
            sidecar = args.time_log + ".metrics.json"
            with atomic.atomic_writer(sidecar, "w") as f:
                json.dump(obs.run_metrics({
                    "app_id": app_id,
                    "engine": args.engine,
                    "stream": stream_name,
                    "power_elapse_ms": power_elapse,
                    "total_elapse_ms": total_elapse,
                    "budget_s": budget_s,
                    "partial": bool(queue.skipped),
                    "partial_reasons": queue.skipped,
                    "faultTaxonomy": res["taxonomy"],
                    "quarantined": res["quarantined"] or None,
                    "resumed": res["resumed"] or None,
                    "ledger": ledger_block,
                    "sentinel": sentinel_block,
                }), f, indent=2)
            print(f"====== Trace: {paths['jsonl']} | {paths['chrome']} "
                  f"| {sidecar} ======")
        except Exception as e:  # observability must never fail the run
            print(f"WARNING: trace export failed: {e}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="NDS power run (TPU engine)")
    p.add_argument("query_stream_file",
                   help="query stream file (query_N.sql)")
    p.add_argument("input_prefix", help="warehouse directory")
    p.add_argument("time_log", help="per-query CSV time log output path")
    p.add_argument("--input_format", default="parquet",
                   choices=["parquet", "orc", "avro", "csv", "json",
                            "ndslake", "ndsdelta"],
                   help="warehouse table format")
    p.add_argument("--engine", default="cpu",
                   choices=["cpu", "tpu", "tpu-spmd"],
                   help="execution backend (tpu-spmd distributes over "
                        "the device mesh, falling back per-query)")
    p.add_argument("--output_prefix",
                   help="write per-query results under this dir "
                        "(for validation); default = collect only")
    p.add_argument("--output_format", default="parquet",
                   choices=["parquet", "csv"])
    p.add_argument("--property_file",
                   help="engine properties file (knobs recorded in reports)")
    p.add_argument("--json_summary_folder",
                   help="folder for per-query JSON summaries")
    p.add_argument("--sub_queries",
                   help="comma-separated query-name subset, e.g. "
                        "query1,query3_part1")
    p.add_argument("--extra_time_log",
                   help="secondary location for the CSV time log")
    p.add_argument("--xla_cache_dir",
                   default=os.environ.get("NDSTPU_XLA_CACHE_DIR"),
                   help="persistent XLA compile-cache dir (tpu engines); "
                   "default from NDSTPU_XLA_CACHE_DIR")
    p.add_argument("--compile_records",
                   help="path for persisted whole-query size-plan "
                        "records (skip per-query discovery on repeat "
                        "power runs; tpu engines only)")
    p.add_argument("--budget_s", type=float,
                   default=float(os.environ.get(
                       "NDSTPU_PHASE_BUDGET_S", "0") or 0),
                   help="phase deadline budget in seconds (0 = none; "
                        "default from NDSTPU_PHASE_BUDGET_S). On "
                        "projected overrun the run degrades "
                        "explicitly: remaining queries reorder "
                        "cheapest-first by ledger prior and cut "
                        "queries get a per-query partial_reason in "
                        "the metrics sidecar")
    p.add_argument("--ledger",
                   help="run-ledger JSONL path (default "
                        "$NDSTPU_LEDGER or .bench_cache/ledger.jsonl; "
                        "'none' disables). Serves ETA priors and "
                        "regression-sentinel baselines; executed "
                        "queries are appended after the run")
    p.add_argument("--scale_factor", default="unknown",
                   help="scale factor for ledger fingerprinting "
                        "(the bench driver passes it)")
    p.add_argument("--run_seed", default="unknown",
                   help="stream rngseed for ledger fingerprinting "
                        "(the bench driver passes the resolved seed)")
    p.add_argument("--floats", action="store_true",
                   help="double mode (no decimals)")
    p.add_argument("--resume", action="store_true",
                   help="crash-safe resume: replay the per-query "
                        "progress journal (<time_log>.progress.jsonl) "
                        "and skip queries already completed by a "
                        "previous run of the same fingerprint (engine, "
                        "scale factor, seed, stream, subset, "
                        "warehouse); their time-log rows are carried "
                        "over")
    p.add_argument("--static_check", action="store_true",
                   help="run the static plan analyzer over the stream "
                        "before executing anything; on accel engines, "
                        "reject queries with error-severity lowering "
                        "diagnostics (code + plan path printed) before "
                        "any compile")
    return p


if __name__ == "__main__":
    run_query_stream(build_parser().parse_args())
