"""Crash-consistent micro-batch ingest: LF_*/DF_* refresh functions
applied to a LIVE session while query streams keep serving.

The reference treats data maintenance as a quiesced batch phase
between benchmark runs (nds_maintenance.py); the ROADMAP north star is
a service that ingests while serving.  This module is the write side
of that HTAP shape, gated by the differential in
scripts/ingest_smoke.py: interleaved ingest+query must be bit-exact,
per snapshot epoch, against the same refresh functions replayed
quiesced.

Mechanics (docs/ROBUSTNESS.md "Ingest commit protocol"):

* **one micro-batch = one refresh function** (or one synthetic batch),
  applied wholly under the session's execution lock — concurrent query
  pins (Session.pin_snapshot takes the same lock) only ever observe
  batch boundaries, never half a refresh function;
* an **intent/done journal** (append-only JSONL via
  io/atomic.append_jsonl, the RUN_STATE idiom) brackets every batch:
  *intent* records the per-table lake pre-versions before the first
  statement, *done* the post-versions after the last commit.  A
  SIGKILL mid-batch leaves intent-without-done; :meth:`resume`
  retracts the touched tables to the recorded pre-versions
  (lake.abort_to_version — history-rewriting, sound because no pin can
  hold an un-done batch's commits), GCs unpublished manifest orphans,
  reloads the catalog, and the batch re-applies from scratch — atomic
  under crash;
* a **CommitConflict** (io/commit.py) or any transient fault inside a
  batch triggers the same retract-and-retry via faults/retry.py.
  Because retraction rewrites (rather than rolls forward over) the
  aborted commits, a retried or killed-and-resumed run ends on the
  SAME per-table snapshot versions as an uninterrupted one — which is
  what lets the differential compare epochs across chaos and clean
  runs.
  ``ingest.apply`` is the batch-level fault-injector site;
  ``ingest.commit`` fires inside the lake commit protocol itself.

Counters: ``engine.ingest.commits`` / ``engine.ingest.conflicts``
tick in the io layer; ``engine.ingest.retries`` ticks here per
re-applied attempt (docs/OBSERVABILITY.md).

CLI (the smoke's SIGKILL target — killable between batches via
``--batch_pause_s``, resumable with ``--resume``)::

    python -m ndstpu.harness.ingest WAREHOUSE \
        --refresh_data_path DIR --funcs LF_SS,DF_SS \
        [--resume] [--batch_pause_s S]
    python -m ndstpu.harness.ingest WAREHOUSE --synthetic N ...
"""

from __future__ import annotations

import argparse
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from ndstpu.faults import retry
from ndstpu.io import atomic, gdict, lake

JOURNAL_RELPATH = os.path.join("_ingest", "INGEST_STATE.jsonl")


class _NullLock:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_LOCK = _NullLock()


class MicroBatchIngestor:
    """Applies micro-batches to a lake warehouse (and, when a session
    is attached, its live in-memory catalog) with crash atomicity and
    conflict retry.  See the module docstring for the protocol."""

    def __init__(self, warehouse: str, sess=None,
                 journal_path: Optional[str] = None,
                 policy: Optional[retry.RetryPolicy] = None):
        self.warehouse = warehouse
        self.sess = sess
        self.journal_path = journal_path or os.path.join(
            warehouse, JOURNAL_RELPATH)
        self.policy = policy or retry.RetryPolicy.from_env()

    # -- journal ---------------------------------------------------------

    def records(self) -> List[dict]:
        return atomic.read_jsonl(self.journal_path)

    def pending_intent(self) -> Optional[dict]:
        """The last intent with no matching done/rolled_back — the
        signature of a crash mid-batch."""
        pend = None
        for r in self.records():
            ev = r.get("event")
            if ev == "intent":
                pend = r
            elif ev in ("done", "rolled_back"):
                pend = None
        return pend

    def done_funcs(self) -> List[str]:
        return [r["fn"] for r in self.records()
                if r.get("event") == "done"]

    # -- restore ---------------------------------------------------------

    def _versions(self) -> Dict[str, int]:
        return lake.versions_vector(self.warehouse)

    def _restore(self, pre_versions: Dict[str, int]) -> List[str]:
        """Retract every table that advanced past its recorded
        pre-batch version (lake.abort_to_version — history-rewriting,
        sound here because the aborted commits belong to a batch whose
        intent never reached done and no pin can hold them: pins only
        form at batch boundaries), GC unpublished manifest orphans,
        and reload touched tables into the live catalog.  Retraction —
        not a rollback snapshot — is what keeps a killed-and-resumed
        run's version trajectory identical to a clean run's, which the
        differential (scripts/ingest_smoke.py) depends on."""
        touched = []
        for table, pre in sorted(pre_versions.items()):
            root = os.path.join(self.warehouse, table)
            try:
                cur = lake.current_version(root)
            except (OSError, ValueError):
                continue
            if cur != pre:
                lake.abort_to_version(root, pre)
                # drop dictionary versions stamped past the retracted
                # snapshot — a re-applied batch regrows them, keeping the
                # dict-version trajectory identical to a clean run's
                gdict.retract(root, pre)
                touched.append(table)
                self._reload(table)
        lake.gc_orphans(self.warehouse)
        return touched

    def _reload(self, table: str) -> None:
        if self.sess is None:
            return
        from ndstpu import schema as nds_schema
        from ndstpu.engine import columnar
        root = os.path.join(self.warehouse, table)
        at = lake.read(root)
        try:
            sch = nds_schema.get_schema(table)
        except KeyError:
            sch = None
        gds = gdict.table_dicts(root, table)
        self.sess.catalog.register(
            table, columnar.from_arrow(at, sch, gdicts=gds or None))

    def _grow_dicts(self, pre: Dict[str, int],
                    post: Dict[str, int]) -> None:
        """Append-only global-dictionary growth for every table whose
        lake version advanced in this batch.  Runs before the done
        record inside the batch lock: a crash between commit and grow
        leaves intent-without-done, and :meth:`_restore` retracts both
        the lake commits and the dict versions stamped past them, so
        dict versions ride snapshot versions exactly.  Pinned readers
        keep selecting the dict entry matching their pinned snapshot;
        only new loads see the grown value set."""
        if not gdict.enabled():
            return
        for table, cur in sorted(post.items()):
            if pre.get(table) == cur:
                continue
            root = os.path.join(self.warehouse, table)
            grown = gdict.grow_for_table(root, table, table_version=cur)
            if self.sess is not None and any(
                    e.get("table_version") == cur
                    for e in grown.values()):
                # re-encode the live catalog entry against the grown
                # dict so new (unpinned) queries shard on its codes
                self._reload(table)

    # -- apply -----------------------------------------------------------

    def apply_batch(self, name: str, apply_fn: Callable[[], None]) -> dict:
        """Apply one micro-batch crash-consistently.  ``apply_fn()``
        performs the batch's writes (SQL statements through the
        session, or direct lake ops).  Returns the journal done
        record."""
        from ndstpu import faults as faults_mod, obs
        lock = self.sess._exec_lock if self.sess is not None \
            else _NULL_LOCK
        seq = len([r for r in self.records()
                   if r.get("event") == "intent"])
        batch = f"{seq:04d}-{name}"
        with lock:
            pre = self._versions()
            atomic.append_jsonl(self.journal_path, {
                "event": "intent", "batch": batch, "fn": name,
                "pre_versions": pre, "ts": round(time.time(), 3)})

            tries = [0]

            def attempt():
                tries[0] += 1
                if tries[0] > 1:
                    # a prior attempt failed: retract any partial
                    # commits and GC unpublished manifest orphans so
                    # the re-apply starts from exactly the recorded
                    # pre-batch state — applied exactly once overall,
                    # with the same version numbering as a clean run
                    self._restore(pre)
                faults_mod.check("ingest.apply", key=name)
                apply_fn()

            _res, attempts = retry.run_with_retry(
                attempt, f"ingest:{batch}", policy=self.policy)
            if attempts > 1:
                obs.inc("engine.ingest.retries", attempts - 1)
            post = self._versions()
            self._grow_dicts(pre, post)
            rec = {"event": "done", "batch": batch, "fn": name,
                   "post_versions": post,
                   "attempts": attempts, "ts": round(time.time(), 3)}
            atomic.append_jsonl(self.journal_path, rec)
        return rec

    def resume(self) -> Optional[str]:
        """Recover the journal after a crash: an intent without a done
        means the process died mid-batch — roll the touched tables
        back to the recorded pre-versions and journal the rollback.
        Returns the rolled-back batch's function name (it must be
        re-applied), or None when the journal is clean."""
        pend = self.pending_intent()
        if pend is None:
            return None
        restored = self._restore(pend.get("pre_versions") or {})
        atomic.append_jsonl(self.journal_path, {
            "event": "rolled_back", "batch": pend["batch"],
            "fn": pend.get("fn"), "restored": restored,
            "ts": round(time.time(), 3)})
        return pend.get("fn")

    def run(self, batches: List[Tuple[str, Callable[[], None]]],
            resume: bool = False,
            batch_pause_s: float = 0.0) -> List[dict]:
        """Apply named batches in order.  With ``resume``, first repair
        a crashed batch, then skip batches already journaled done (the
        RUN_STATE phase-skip idiom applied per micro-batch)."""
        done = set()
        if resume:
            rolled = self.resume()
            if rolled:
                print(f"[ingest] rolled back crashed batch {rolled}; "
                      f"re-applying")
            done = set(self.done_funcs())
        out = []
        for name, fn in batches:
            if name in done:
                print(f"[ingest] skip {name}: journaled done")
                continue
            rec = self.apply_batch(name, fn)
            print(f"[ingest] batch {rec['batch']} done "
                  f"(attempts={rec['attempts']})", flush=True)
            out.append(rec)
            if batch_pause_s:
                time.sleep(batch_pause_s)
        return out


def synthetic_batch(warehouse: str, i: int) -> Callable[[], None]:
    """One deterministic session-free micro-batch over every lake
    table: even batches re-append the table's first rows, odd batches
    delete a content-keyed slice (first column mod 7).  Exercises the
    commit/journal machinery without a generated dataset — the chaos
    smoke's SIGKILL-mid-ingest scenario and the unit tests both drive
    this.  Deterministic given the prior table state, so a killed-and-
    resumed run converges on the same snapshots as an uninterrupted
    one."""
    import numpy as np

    def apply():
        for name in lake.lake_tables(warehouse):
            root = os.path.join(warehouse, name)
            if i % 2 == 0:
                at = lake.read(root)
                lake.append(root, at.slice(0, min(3, at.num_rows)))
            else:
                def pred(at):
                    col = at.column(0).to_numpy(zero_copy_only=False)
                    return (col.astype(np.int64) % 7) == (i % 7)
                lake.delete_rows(root, pred)
    return apply


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="crash-consistent micro-batch ingest over a lake "
                    "warehouse")
    p.add_argument("warehouse_path")
    p.add_argument("--refresh_data_path",
                   help="transcoded refresh (staging) data dir for "
                        "LF_*/DF_* functions")
    p.add_argument("--funcs",
                   help="comma-separated refresh-function subset "
                        "(default: all)")
    p.add_argument("--synthetic", type=int, default=0, metavar="N",
                   help="apply N synthetic micro-batches instead of "
                        "refresh functions (no refresh data needed)")
    p.add_argument("--journal",
                   help=f"journal path (default: "
                        f"WAREHOUSE/{JOURNAL_RELPATH})")
    p.add_argument("--resume", action="store_true",
                   help="repair a crashed batch and skip completed ones")
    p.add_argument("--batch_pause_s", type=float, default=0.0,
                   help="sleep between batches (gives chaos harnesses "
                        "a deterministic kill window)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    journal = args.journal or os.path.join(
        args.warehouse_path, JOURNAL_RELPATH)
    if not args.resume and os.path.exists(journal):
        os.unlink(journal)
    if args.synthetic:
        ing = MicroBatchIngestor(args.warehouse_path,
                                 journal_path=journal)
        batches = [(f"syn{i}", synthetic_batch(args.warehouse_path, i))
                   for i in range(args.synthetic)]
    else:
        if not args.refresh_data_path:
            raise SystemExit(
                "--refresh_data_path is required without --synthetic")
        from ndstpu.engine.session import Session
        from ndstpu.harness import maintenance
        from ndstpu.io import loader
        catalog = loader.load_catalog(args.warehouse_path)
        sess = Session(catalog, warehouse=args.warehouse_path)
        maintenance.register_staging_views(sess, args.refresh_data_path)
        funcs = args.funcs.split(",") if args.funcs \
            else list(maintenance.DM_FUNCS)
        queries = maintenance.get_maintenance_queries(sess, funcs)
        ing = MicroBatchIngestor(args.warehouse_path, sess=sess,
                                 journal_path=journal)

        def sql_batch(stmts):
            def apply():
                for s in stmts:
                    sess.sql(s)
            return apply

        batches = [(fn, sql_batch(queries[fn])) for fn in funcs]
    ing.run(batches, resume=args.resume,
            batch_pause_s=args.batch_pause_s)
    print(f"[ingest] final versions: {lake.versions_vector(args.warehouse_path)} "
          f"epoch: {lake.warehouse_epoch(args.warehouse_path)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
