"""Benchmark harness: power/throughput/maintenance runners, differential
validation, full-bench orchestration and the composite NDS metric."""
