"""Roll fact tables back to a pre-maintenance snapshot (nds_rollback analog).

The reference calls Iceberg's `rollback_to_timestamp` on the 6 fact tables
to undo data maintenance between repeated benchmark runs
(/root/reference/nds/nds_rollback.py:37-59).  Here the same operation runs
against either ACID format: ndslake (snapshot manifests, Iceberg analog)
or ndsdelta (transaction log RESTORE, Delta analog).

Version-first rollback: the maintenance runner journals each table's
pre-maintenance snapshot VERSION before its first refresh function
(``_maintenance/PRE_DM_VERSIONS.jsonl``, written via
io/atomic.append_jsonl).  When that journal has a record at-or-before
the requested timestamp, rollback targets the recorded versions —
timestamp rollback is ambiguous when micro-batches commit sub-second
apart (two commits can share a clock tick, and the "newest snapshot
<= ts" rule then picks whichever sorted later).  Timestamp remains the
fallback for tables or warehouses with no recorded versions.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, Optional

from ndstpu.io import atomic, lake

FACT_TABLES = ["store_sales", "store_returns", "catalog_sales",
               "catalog_returns", "web_sales", "web_returns", "inventory"]

SNAPSHOT_JOURNAL_RELPATH = os.path.join("_maintenance",
                                        "PRE_DM_VERSIONS.jsonl")


def record_pre_maintenance_versions(warehouse: str) -> Optional[dict]:
    """Journal every lake table's CURRENT version before maintenance
    writes begin (called by harness/maintenance.py).  Returns the
    record, or None when the warehouse has no lake tables."""
    vec = lake.versions_vector(warehouse)
    if not vec:
        return None
    rec = {"ts": round(time.time(), 3), "versions": vec}
    atomic.append_jsonl(
        os.path.join(warehouse, SNAPSHOT_JOURNAL_RELPATH), rec)
    return rec


def recorded_versions_at(warehouse: str, ts: float) -> Optional[dict]:
    """The newest journaled pre-maintenance record at-or-before ``ts``,
    or None."""
    recs = [r for r in atomic.read_jsonl(
                os.path.join(warehouse, SNAPSHOT_JOURNAL_RELPATH))
            if isinstance(r.get("versions"), dict)
            and isinstance(r.get("ts"), (int, float)) and r["ts"] <= ts]
    return max(recs, key=lambda r: r["ts"]) if recs else None


def rollback(warehouse: str, timestamp: float,
             tables=None) -> Dict[str, str]:
    """Roll back each fact table independently; one bad table must not
    abort the remaining ones.  Returns ``{table: error}`` for the
    failures — the CLI exits nonzero if any, since a benchmark rerun
    against a half-rolled-back warehouse measures garbage."""
    rec = recorded_versions_at(warehouse, timestamp)
    recorded = (rec or {}).get("versions") or {}
    failures: Dict[str, str] = {}
    for table in tables or FACT_TABLES:
        root = os.path.join(warehouse, table)
        if not lake.is_lake(root):
            print(f"skip {table}: not an ACID (ndslake/ndsdelta) table")
            continue
        try:
            if table in recorded:
                v = lake.rollback_to_version(root, recorded[table])
                print(f"rolled back {table} to recorded "
                      f"pre-maintenance v{recorded[table]} "
                      f"(new snapshot v{v})")
            else:
                v = lake.rollback_to_timestamp(root, timestamp)
                print(f"rolled back {table} to snapshot v{v} "
                      f"(timestamp fallback)")
        except Exception as e:  # noqa: BLE001 — keep rolling the rest
            failures[table] = f"{type(e).__name__}: {e}"
            print(f"ERROR: rollback of {table} failed: {failures[table]}")
            continue
    return failures


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("warehouse_path")
    p.add_argument("timestamp", type=float,
                   help="unix timestamp to roll back to")
    p.add_argument("--tables", help="comma-separated subset")
    a = p.parse_args()
    failed = rollback(a.warehouse_path, a.timestamp,
                      a.tables.split(",") if a.tables else None)
    if failed:
        print(f"ERROR: {len(failed)} table rollback(s) failed: "
              f"{', '.join(sorted(failed))}")
        sys.exit(1)
