"""Roll fact tables back to a pre-maintenance snapshot (nds_rollback analog).

The reference calls Iceberg's `rollback_to_timestamp` on the 6 fact tables
to undo data maintenance between repeated benchmark runs
(/root/reference/nds/nds_rollback.py:37-59).  Here the same operation runs
against either ACID format: ndslake (snapshot manifests, Iceberg analog)
or ndsdelta (transaction log RESTORE, Delta analog).
"""

from __future__ import annotations

import argparse
import os

from ndstpu.io import lake

FACT_TABLES = ["store_sales", "store_returns", "catalog_sales",
               "catalog_returns", "web_sales", "web_returns", "inventory"]


def rollback(warehouse: str, timestamp: float,
             tables=None) -> None:
    for table in tables or FACT_TABLES:
        root = os.path.join(warehouse, table)
        if not lake.is_lake(root):
            print(f"skip {table}: not an ACID (ndslake/ndsdelta) table")
            continue
        v = lake.rollback_to_timestamp(root, timestamp)
        print(f"rolled back {table} to snapshot v{v}")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("warehouse_path")
    p.add_argument("timestamp", type=float,
                   help="unix timestamp to roll back to")
    p.add_argument("--tables", help="comma-separated subset")
    a = p.parse_args()
    rollback(a.warehouse_path, a.timestamp,
             a.tables.split(",") if a.tables else None)
