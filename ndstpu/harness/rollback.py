"""Roll fact tables back to a pre-maintenance snapshot (nds_rollback analog).

The reference calls Iceberg's `rollback_to_timestamp` on the 6 fact tables
to undo data maintenance between repeated benchmark runs
(/root/reference/nds/nds_rollback.py:37-59).  Here the same operation runs
against either ACID format: ndslake (snapshot manifests, Iceberg analog)
or ndsdelta (transaction log RESTORE, Delta analog).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict

from ndstpu.io import lake

FACT_TABLES = ["store_sales", "store_returns", "catalog_sales",
               "catalog_returns", "web_sales", "web_returns", "inventory"]


def rollback(warehouse: str, timestamp: float,
             tables=None) -> Dict[str, str]:
    """Roll back each fact table independently; one bad table must not
    abort the remaining ones.  Returns ``{table: error}`` for the
    failures — the CLI exits nonzero if any, since a benchmark rerun
    against a half-rolled-back warehouse measures garbage."""
    failures: Dict[str, str] = {}
    for table in tables or FACT_TABLES:
        root = os.path.join(warehouse, table)
        if not lake.is_lake(root):
            print(f"skip {table}: not an ACID (ndslake/ndsdelta) table")
            continue
        try:
            v = lake.rollback_to_timestamp(root, timestamp)
        except Exception as e:  # noqa: BLE001 — keep rolling the rest
            failures[table] = f"{type(e).__name__}: {e}"
            print(f"ERROR: rollback of {table} failed: {failures[table]}")
            continue
        print(f"rolled back {table} to snapshot v{v}")
    return failures


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("warehouse_path")
    p.add_argument("timestamp", type=float,
                   help="unix timestamp to roll back to")
    p.add_argument("--tables", help="comma-separated subset")
    a = p.parse_args()
    failed = rollback(a.warehouse_path, a.timestamp,
                      a.tables.split(",") if a.tables else None)
    if failed:
        print(f"ERROR: {len(failed)} table rollback(s) failed: "
              f"{', '.join(sorted(failed))}")
        sys.exit(1)
