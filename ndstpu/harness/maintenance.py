"""Data-maintenance executor (LF_* insert / DF_* delete refresh functions).

Parity with the reference runner (/root/reference/nds/nds_maintenance.py):
registers the 12 refresh staging tables as views from the raw refresh CSV
(nds_maintenance.py:267-271), loads the DM SQL corpus and substitutes
DATE1/DATE2 from the generated delete-date tables (nds_maintenance.py:60-96),
executes each function's statements under a BenchReport, and writes the
per-function CSV time log (nds_maintenance.py:204-265).

ACID semantics: the warehouse fact tables must be in an ACID format —
`ndslake` (snapshot manifests + deletion vectors, Iceberg analog) or
`ndsdelta` (transaction log + copy-on-write rewrites, Delta analog);
INSERT INTO appends, DELETE removes rows transactionally, and
`ndstpu.harness.rollback` restores pre-maintenance snapshots between runs.
"""

from __future__ import annotations

import argparse
import csv
import os
import time
import uuid
from pathlib import Path
from typing import Dict, List

from ndstpu import schema as nds_schema
from ndstpu.engine import columnar
from ndstpu.engine.session import Session
from ndstpu.harness.report import BenchReport
from ndstpu.io import atomic, csvio, loader

DM_DIR = Path(__file__).resolve().parent / "data_maintenance"

INSERT_FUNCS = ["LF_CR", "LF_CS", "LF_I", "LF_SR", "LF_SS", "LF_WR", "LF_WS"]
DELETE_FUNCS = ["DF_CS", "DF_SS", "DF_WS"]
INVENTORY_DELETE_FUNCS = ["DF_I"]
DM_FUNCS = INSERT_FUNCS + DELETE_FUNCS + INVENTORY_DELETE_FUNCS


def register_staging_views(sess: Session, refresh_dir: str) -> None:
    """Load the s_* staging tables + delete tables into the catalog
    (TempView analog)."""
    schemas = nds_schema.get_maintenance_schemas(True)
    for table, tschema in schemas.items():
        at = csvio.read_table_dir(refresh_dir, table, tschema)
        sess.catalog.register(table, columnar.from_arrow(at, tschema))


def get_delete_dates(sess: Session, table: str) -> List[tuple]:
    t = sess.catalog.get(table)
    d = t.to_pydict()
    return list(zip(d["date1"], d["date2"]))


def get_maintenance_queries(sess: Session,
                            funcs: List[str]) -> Dict[str, List[str]]:
    """{function: [statements]} with DATE1/DATE2 substituted per delete-date
    row (reference: nds_maintenance.py:118-144)."""
    out: Dict[str, List[str]] = {}
    for fn in funcs:
        text = (DM_DIR / f"{fn}.sql").read_text()
        if fn in DELETE_FUNCS or fn in INVENTORY_DELETE_FUNCS:
            dates = get_delete_dates(
                sess, "inventory_delete" if fn in INVENTORY_DELETE_FUNCS
                else "delete")
            stmts = []
            for d1, d2 in dates:
                sub = text.replace("DATE1", d1).replace("DATE2", d2)
                stmts += [s.strip() for s in sub.split(";") if s.strip()]
            out[fn] = stmts
        else:
            out[fn] = [s.strip() for s in text.split(";") if s.strip()]
    return out


def run_dm_query(sess: Session, statements: List[str]) -> None:
    for stmt in statements:
        sess.sql(stmt)


def run_query(args) -> None:
    app_id = f"ndstpu-dm-{uuid.uuid4().hex[:8]}"
    execution_times = []

    catalog = loader.load_catalog(args.warehouse_path)
    sess = Session(catalog, warehouse=args.warehouse_path)
    register_staging_views(sess, args.refresh_data_path)

    # journal per-table pre-maintenance snapshot versions so rollback
    # can target exact versions instead of an ambiguous timestamp when
    # micro-batches commit sub-second apart (harness/rollback.py)
    from ndstpu.harness import rollback as rollback_mod
    rollback_mod.record_pre_maintenance_versions(args.warehouse_path)

    queries = get_maintenance_queries(sess, DM_FUNCS)
    if args.dm_funcs:
        keep = args.dm_funcs.split(",")
        missing = [f for f in keep if f not in queries]
        if missing:
            raise ValueError(f"unknown DM functions {missing}")
        queries = {f: queries[f] for f in keep}

    ing = None
    if getattr(args, "micro_batch", False):
        # crash-consistent mode: each refresh function becomes one
        # journaled micro-batch (intent/done + restore-and-retry on
        # transient faults — harness/ingest.py)
        from ndstpu.harness.ingest import MicroBatchIngestor
        ing = MicroBatchIngestor(args.warehouse_path, sess=sess)

    start = time.time()
    for fn, stmts in queries.items():
        print(f"====== Run {fn} ======")
        rpt = BenchReport({"warehouse": args.warehouse_path})
        if ing is not None:
            def _apply(stmts=stmts):
                run_dm_query(sess, stmts)
            summary = rpt.report_on(ing.apply_batch, fn, _apply)
        else:
            summary = rpt.report_on(run_dm_query, sess, stmts)
        print(f"Time taken: {summary['queryTimes']} millis for {fn}")
        execution_times.append((app_id, fn, summary["queryTimes"][0]))
        if args.json_summary_folder:
            os.makedirs(args.json_summary_folder, exist_ok=True)
            rpt.write_summary(
                fn, prefix=os.path.join(args.json_summary_folder, ""))
    end = time.time()
    dm_elapse = end - start  # seconds, reference contract
    print(f"====== Data Maintenance Time: {dm_elapse} s ======")
    execution_times.append((app_id, "Data Maintenance Start Time", start))
    execution_times.append((app_id, "Data Maintenance End Time", end))
    execution_times.append((app_id, "Data Maintenance Time", dm_elapse))

    # header matches the reference (nds_maintenance.py:261); per-function
    # rows carry the report's millisecond values like the reference does
    header = ["application_id", "query", "time/s"]
    with atomic.atomic_writer(args.time_log, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(header)
        w.writerows(execution_times)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="NDS data maintenance (ACID)")
    p.add_argument("warehouse_path",
                   help="ACID (ndslake/ndsdelta) warehouse directory")
    p.add_argument("refresh_data_path",
                   help="raw refresh (update) data directory")
    p.add_argument("time_log", help="CSV time log output path")
    p.add_argument("--dm_funcs",
                   help="comma-separated subset of DM functions, e.g. "
                        "LF_SS,DF_SS")
    p.add_argument("--micro_batch", action="store_true",
                   help="apply each refresh function as one journaled "
                        "crash-consistent micro-batch "
                        "(harness/ingest.py)")
    p.add_argument("--json_summary_folder")
    return p


if __name__ == "__main__":
    run_query(build_parser().parse_args())
