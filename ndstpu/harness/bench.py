"""Full-benchmark orchestrator (the nds_bench analog).

Runs the five NDS phases end-to-end from a YAML config and computes the
composite TPC-DS-style metric (reference: /root/reference/nds/nds_bench.py):

  data gen -> load test (transcode) -> stream gen (RNGSEED chained from the
  load report, spec 4.3.1) -> Power Test -> Throughput Test 1 -> Data
  Maintenance 1 -> Throughput Test 2 -> Data Maintenance 2 -> metric

Phase parity details: per-phase `skip:` flags reusing prior reports
(nds_bench.py:368-399), throughput elapsed = max(end)-min(start) over the
stream time logs rounded up to 0.1s (nds_bench.py:138-157,207-208), stream
ranges split across the two throughput tests (nds_bench.py:120-135), and
metric = int(SF * Sq*Q / (Tpt*Ttt*Tdm*Tld)^(1/4)) in decimal hours
(nds_bench.py:334-357).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import time
from contextlib import contextmanager

import yaml

from ndstpu import faults, obs
from ndstpu.harness import runstate
from ndstpu.io import atomic

PY = [sys.executable, "-m"]


@contextmanager
def _phase(name: str, walls: dict, budget_s=None):
    """Time one bench phase: a tracer span (cat='phase') plus a wall
    entry for the HW metrics artifact.  Phases run as subprocesses, so
    per-query spans live in the power runner's own trace; the driver
    records the phase envelope and stitches the power sidecar in.
    ``budget_s`` (per-phase ``budget_s:`` in the YAML) makes the
    deadline visible: a start heartbeat, and an explicit overrun line +
    counter when the phase blows its budget — never a silent burn."""
    t0 = time.time()
    if budget_s:
        print(f"[heartbeat] phase {name} start budget={budget_s:g}s")
    with obs.span(name, cat="phase", budget_s=budget_s):
        yield
    wall = time.time() - t0
    walls[name] = round(wall, 3)
    if budget_s:
        if wall > budget_s:
            print(f"[budget] phase {name} OVERRAN: {wall:.1f}s > "
                  f"{budget_s:g}s budget (+{wall - budget_s:.1f}s)")
            obs.inc("harness.budget.phase_overruns")
        else:
            print(f"[heartbeat] phase {name} done {wall:.1f}s of "
                  f"{budget_s:g}s budget")


def round_up_to_nearest_10_percent(num: float) -> float:
    return math.ceil(num * 10) / 10


def get_load_time(load_report_file: str) -> str:
    with open(load_report_file) as f:
        for line in f:
            if "Load Test Time" in line:
                return line.split(":")[1].split(" ")[1]
    raise RuntimeError(f"Load Test Time not found in {load_report_file}")


def get_load_end_timestamp(load_report_file: str) -> str:
    with open(load_report_file) as f:
        for line in f:
            if "RNGSEED used:" in line:
                return line.split(":")[1].strip()
    raise RuntimeError(f"RNGSEED not found in {load_report_file}")


def resolve_stream_rngseed(stream_cfg: dict, load_report_file: str) -> str:
    """Seed for the query streams: an explicit ``rngseed:`` in the
    generate_query_stream config wins; otherwise it chains from the load
    end timestamp (spec 4.3.1, nds_bench.py:249-261).  The override is
    the orchestrated form of the reference stream generator's explicit
    ``--rngseed`` flag (nds_gen_query_stream.py:42-89, "for
    reproducibility"): a pinned seed renders the same stream corpus
    every run, so a pre-warmed compile-record/XLA cache can serve the
    power phase.  The sentinel ``rngseed: bench`` resolves to
    ``streamgen.BENCH_RNGSEED`` — the one seed every warm/bench script
    renders with — so configs cannot drift from the warmed corpus by
    duplicating the literal."""
    seed = stream_cfg.get("rngseed")
    if seed is None:
        return get_load_end_timestamp(load_report_file)
    if seed == "bench":
        from ndstpu.queries.streamgen import BENCH_RNGSEED
        return BENCH_RNGSEED
    if not isinstance(seed, str):
        # yaml parses unquoted digit seeds as ints.  PyYAML octal-parses
        # an unquoted 0-prefixed seed ONLY when its digits are all 0-7
        # (YAML 1.1 resolver `0[0-7]+`) — such a timestamp resolves to a
        # DIFFERENT number; a 0-prefixed seed containing an 8 or 9
        # matches neither the octal nor the decimal form and safely
        # stays a string.  Any seed that reached here as an int has at
        # minimum lost its leading zeros, so the pin would silently
        # render the wrong corpus.  Refuse instead of guessing.
        raise ValueError(
            f"generate_query_stream.rngseed must be a quoted string "
            f"(got {type(seed).__name__} {seed!r}; unquoted seeds lose "
            f"leading zeros, and 0-prefixed seeds whose digits are all "
            f"0-7 parse as octal) or the sentinel 'bench'")
    return seed


def get_power_time(power_report_file: str) -> str:
    with open(power_report_file) as f:
        for line in f:
            if "Power Test Time" in line:
                return line.split(",")[2].strip()
    raise RuntimeError(f"Power Test Time not found in {power_report_file}")


def get_start_end_time(report_file: str):
    start = end = None
    with open(report_file) as f:
        for line in f:
            if "Power Start Time" in line:
                start = line.split(",")[2].strip()
            if "Power End Time" in line:
                end = line.split(",")[2].strip()
    if start is None or end is None:
        raise RuntimeError(f"start/end time not found in {report_file}")
    return start, end


def get_stream_range(num_streams: int, first_or_second: int):
    if first_or_second == 1:
        return list(range(1, num_streams // 2 + 1))
    return list(range(num_streams // 2 + 1, num_streams))


def get_throughput_time(report_base: str, num_streams: int,
                        first_or_second: int) -> float:
    starts, ends = [], []
    for i in get_stream_range(num_streams, first_or_second):
        s, e = get_start_end_time(f"{report_base}_{i}.csv")
        starts.append(float(s))
        ends.append(float(e))
    return round_up_to_nearest_10_percent(max(ends) - min(starts))


def get_refresh_time(report_file: str) -> float:
    with open(report_file) as f:
        for line in f:
            if "Data Maintenance Time" in line:
                return float(line.split(",")[2].strip())
    raise RuntimeError(f"Data Maintenance Time not found in {report_file}")


def get_maintenance_time(report_base: str, num_streams: int,
                         first_or_second: int) -> float:
    tdm = 0.0
    for i in get_stream_range(num_streams, first_or_second):
        tdm += get_refresh_time(f"{report_base}_{i}.csv")
    return round_up_to_nearest_10_percent(tdm)


def get_perf_metric(scale_factor, num_streams_in_throughput, queries_per_stream,
                    Tload, Tpower, Ttt1, Ttt2, Tdm1, Tdm2) -> int:
    """Composite metric, times in decimal hours (nds_bench.py:334-357).
    Each component is clamped to the 0.1s rounding floor so a phase that
    measures 0 elapsed at tiny scale factors cannot zero the product
    (unreachable at spec-scale; the reference rounds to 0.1s upstream)."""
    Q = num_streams_in_throughput * queries_per_stream
    Tpt = max(Tpower * num_streams_in_throughput, 0.1) / 3600
    Ttt = max(Ttt1 + Ttt2, 0.1) / 3600
    Tdm = max(Tdm1 + Tdm2, 0.1) / 3600
    Tld = max(0.01 * num_streams_in_throughput * Tload, 0.1) / 3600
    return int(float(scale_factor) * Q / (Tpt * Ttt * Tdm * Tld) ** (1 / 4))


def write_metrics_report(path: str, metrics: dict) -> None:
    text = "".join(f"{k},{v}\n" for k, v in metrics.items())
    atomic.atomic_write_text(path, text)


def run(cmd, **kw):
    print("====", " ".join(str(c) for c in cmd))
    faults.check("phase.subprocess", key=str(cmd[0]) if cmd else None)
    subprocess.run([str(c) for c in cmd], check=True, **kw)


def run_full_bench(yaml_params: dict, resume: bool = False) -> None:
    d = yaml_params["data_gen"]
    l = yaml_params["load_test"]
    g = yaml_params["generate_query_stream"]
    p = yaml_params["power_test"]
    t = yaml_params["throughput_test"]
    m = yaml_params["maintenance_test"]
    mtr = yaml_params["metrics"]
    sf = str(d["scale_factor"])
    num_streams = int(g["num_streams"])
    sq = max(len(get_stream_range(num_streams, 1)), 1)
    phase_walls: dict = {}
    obs_cfg = yaml_params.get("observability") or {}
    ledger_path = obs_cfg.get("ledger")
    if ledger_path:
        ledger_path = os.path.abspath(ledger_path)

    # crash-safe resume: the RUN_STATE.json journal records each phase
    # completed under this config fingerprint; --resume auto-skips them
    # (replacing hand-edited per-phase skip: flags after a crash)
    state = runstate.RunState.for_bench(yaml_params)
    if resume:
        done = state.completed_phases()
        if done:
            print(f"[resume] {state.path}: skipping completed phases "
                  f"{sorted(done)} (fingerprint "
                  f"{state.fingerprint[:12]})")
            obs.inc("harness.resume.phases_skipped", len(done))
    else:
        state.reset()
        done = set()

    def phase_done(name: str) -> bool:
        if name in done:
            phase_walls[name] = 0.0
            print(f"[resume] phase {name} already completed — skipping")
            return True
        return False

    # seed policy: a pinned `rngseed:` breaks spec 4.3.1's unconditional
    # chaining (reference nds_bench.py:413-414 always chains from the
    # load end timestamp).  Publish which policy this run used so
    # report.py / the artifacts can carry the non-compliance flag.
    seed_pinned = g.get("rngseed") is not None
    os.environ["NDSTPU_SEED_POLICY"] = \
        "pinned" if seed_pinned else "chained"

    # 1. data generation (+ per-stream refresh sets)
    if not d.get("skip") and not phase_done("data_gen"):
        with _phase("data_gen", phase_walls, d.get("budget_s")):
            run(PY + ["ndstpu.datagen.driver", "local", sf,
                      str(d["parallel"]), d["data_path"],
                      "--overwrite_output"])
            for i in range(1, num_streams):
                run(PY + ["ndstpu.datagen.driver", "local", sf,
                          str(d["parallel"]), d["data_path"] + f"_{i}",
                          "--overwrite_output", "--update", str(i)])
        state.mark("data_gen", artifacts=[d["data_path"]])

    # 2. load test
    if not l.get("skip") and not phase_done("load_test"):
        with _phase("load_test", phase_walls, l.get("budget_s")):
            cmd = PY + ["ndstpu.io.transcode",
                        "--input_prefix", d["data_path"],
                        "--output_prefix", l["warehouse_path"],
                        "--report_file", l["report_file"],
                        "--output_format",
                        l.get("warehouse_format", "parquet")]
            if resume:
                # per-table _SUCCESS markers: finished tables skip
                cmd += ["--resume"]
            run(cmd)
        state.mark("load_test", artifacts=[l["warehouse_path"],
                                           l["report_file"]])
    load_elapse = get_load_time(l["report_file"])

    # 3. query streams (RNGSEED = load end timestamp, spec 4.3.1, or a
    #    pinned `rngseed:` override — see resolve_stream_rngseed)
    if not g.get("skip") and not phase_done("generate_query_stream"):
        with _phase("generate_query_stream", phase_walls,
                    g.get("budget_s")):
            rngseed = resolve_stream_rngseed(g, l["report_file"])
            cmd = PY + ["ndstpu.queries.streamgen",
                        "--output_dir", g["stream_output_path"],
                        "--rngseed", rngseed,
                        "--streams", str(num_streams)]
            if g.get("template_dir"):
                cmd += ["--template_dir", g["template_dir"]]
            run(cmd)
        state.mark("generate_query_stream",
                   artifacts=[g["stream_output_path"]])
    try:
        run_seed = resolve_stream_rngseed(g, l["report_file"])
    except Exception:
        run_seed = "unknown"

    # 4. power test
    if not p.get("skip") and not phase_done("power_test"):
        with _phase("power_test", phase_walls, p.get("budget_s")):
            if p.get("json_summary_folder") and not resume:
                import shutil
                shutil.rmtree(p["json_summary_folder"], ignore_errors=True)
            cmd = PY + ["ndstpu.harness.power",
                        os.path.join(g["stream_output_path"],
                                     "query_0.sql"),
                        l["warehouse_path"], p["report_file"],
                        "--engine", p.get("engine", "cpu"),
                        "--scale_factor", sf,
                        "--run_seed", run_seed]
            if p.get("budget_s"):
                cmd += ["--budget_s", str(p["budget_s"])]
            if ledger_path:
                cmd += ["--ledger", ledger_path]
            if p.get("json_summary_folder"):
                cmd += ["--json_summary_folder", p["json_summary_folder"]]
            if p.get("output_prefix"):
                cmd += ["--output_prefix", p["output_prefix"]]
            if p.get("compile_records"):
                # persisted size-plan records (+ the NDSTPU_XLA_CACHE_DIR
                # persistent cache): accel engines skip per-query
                # discovery.  Absolutized so subprocess cwd can't
                # silently miss it.
                rec = os.path.abspath(p["compile_records"])
                p["compile_records"] = rec
                if not os.path.exists(rec):
                    print(f"WARNING: compile_records {rec} does not "
                          f"exist yet — accel power runs will pay full "
                          f"discovery")
                cmd += ["--compile_records", rec]
            if resume:
                # mid-phase kill recovery: the power runner replays its
                # per-query progress journal and skips finished queries
                cmd += ["--resume"]
            run(cmd)
        state.mark("power_test", artifacts=[p["report_file"]])
    power_elapse = float(get_power_time(p["report_file"])) / 1000

    # 5./6. throughput + maintenance, twice
    ttt, tdm = {}, {}
    for fs in (1, 2):
        if not t.get("skip") and \
                not phase_done(f"throughput_test_{fs}"):
            with _phase(f"throughput_test_{fs}", phase_walls,
                        t.get("budget_s")):
                ids = ",".join(str(x) for x in
                               get_stream_range(num_streams, fs))
                tcmd = PY + ["ndstpu.harness.throughput", ids]
                if t.get("concurrent"):
                    # device admission: at most N streams on the chip at
                    # a time (the concurrentGpuTasks analog)
                    tcmd += ["--concurrent", str(t["concurrent"])]
                if t.get("budget_s"):
                    tcmd += ["--budget_s", str(t["budget_s"])]
                if t.get("mode"):
                    # inproc = shared-engine fast path (one warehouse
                    # load, compile-once across streams); process =
                    # spec-faithful N-driver fan-out (default)
                    tcmd += ["--mode", str(t["mode"])]
                # overlap evidence artifact: proves the streams really
                # ran concurrently under the admission cap
                overlap = t.get("overlap_report") or \
                    t["report_base"] + f"_overlap_{fs}.json"
                tcmd += ["--overlap_report",
                         overlap.replace("{}", str(fs))]
                pcmd = PY + ["ndstpu.harness.power",
                             os.path.join(g["stream_output_path"],
                                          "query_{}.sql"),
                             l["warehouse_path"],
                             t["report_base"] + "_{}.csv",
                             "--engine", p.get("engine", "cpu"),
                             "--scale_factor", sf,
                             "--run_seed", run_seed]
                if ledger_path:
                    pcmd += ["--ledger", ledger_path]
                if p.get("compile_records"):
                    pcmd += ["--compile_records", p["compile_records"]]
                run(tcmd + ["--"] + pcmd)
            state.mark(f"throughput_test_{fs}",
                       artifacts=[t["report_base"]])
        ttt[fs] = get_throughput_time(t["report_base"], num_streams, fs)
        if not m.get("skip") and \
                not phase_done(f"maintenance_test_{fs}"):
            with _phase(f"maintenance_test_{fs}", phase_walls,
                        m.get("budget_s")):
                for i in get_stream_range(num_streams, fs):
                    run(PY + ["ndstpu.harness.maintenance",
                              l["warehouse_path"],
                              d["data_path"] + f"_{i}",
                              m["report_base"] + f"_{i}.csv"])
            state.mark(f"maintenance_test_{fs}",
                       artifacts=[m["report_base"]])
        tdm[fs] = get_maintenance_time(m["report_base"], num_streams, fs)

    qps = len(__import__("ndstpu.queries.streamgen",
                         fromlist=["list_templates"])
              .list_templates(g.get("template_dir")))
    metric = get_perf_metric(sf, sq, qps, float(load_elapse), power_elapse,
                             ttt[1], ttt[2], tdm[1], tdm[2])
    metrics = {
        "scale_factor": sf,
        "num_streams": num_streams,
        "queries_per_stream": qps,
        "Tload(s)": load_elapse,
        "Tpower(s)": power_elapse,
        "Ttt1(s)": ttt[1], "Ttt2(s)": ttt[2],
        "Tdm1(s)": tdm[1], "Tdm2(s)": tdm[2],
        "metric": metric,
    }
    print(metrics)
    write_metrics_report(mtr["metrics_report"], metrics)
    write_hw_metrics(yaml_params, metrics, phase_walls)


def write_hw_metrics(yaml_params: dict, metrics: dict,
                     phase_walls: dict) -> str:
    """Phase-level hardware-run artifact (docs/HW_METRICS_*.json):
    driver phase walls + the composite metric + the power runner's
    per-query attribution sidecar (written by ndstpu.harness.power next
    to its time log when tracing is on).  Path from ``metrics:
    hw_metrics`` in the config; defaults to ``hw_metrics.json`` next to
    the metrics report."""
    p = yaml_params["power_test"]
    mtr = yaml_params["metrics"]
    power_sidecar = p["report_file"] + ".metrics.json"
    power_metrics = None
    if os.path.exists(power_sidecar):
        try:
            with open(power_sidecar) as f:
                power_metrics = json.load(f)
        except Exception as e:  # artifact is best-effort, never fatal
            print(f"WARNING: power metrics sidecar unreadable: {e}")
    g = yaml_params["generate_query_stream"]
    seed_pinned = g.get("rngseed") is not None
    phase_budgets = {
        ph: (yaml_params.get(ph) or {}).get("budget_s")
        for ph in ("data_gen", "load_test", "generate_query_stream",
                   "power_test", "throughput_test", "maintenance_test")
        if (yaml_params.get(ph) or {}).get("budget_s")}
    hw = {
        "format": "ndstpu-hw-metrics-v1",
        "scale_factor": yaml_params["data_gen"]["scale_factor"],
        "engine": p.get("engine", "cpu"),
        "num_streams": yaml_params["generate_query_stream"]["num_streams"],
        "phases": phase_walls,
        "phase_budgets": phase_budgets,
        "seed_policy": "pinned" if seed_pinned else "chained",
        # spec 4.3.1 chains RNGSEED from the load end timestamp
        # unconditionally (reference nds_bench.py:413-414); a pinned
        # seed is a deliberate cache-warm trade and the artifact says so
        "spec_compliant_seed": not seed_pinned,
        "summary": metrics,
        "power": power_metrics,
        "counters": obs.counters_snapshot(),
        "gauges": obs.gauges_snapshot(),
    }
    hw_path = mtr.get("hw_metrics") or os.path.join(
        os.path.dirname(mtr["metrics_report"]) or ".", "hw_metrics.json")
    atomic.atomic_write_json(hw_path, hw)
    print(f"HW metrics artifact: {hw_path}")
    return hw_path


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="NDS full benchmark")
    parser.add_argument("yaml_config", help="yaml config file (bench.yml)")
    parser.add_argument("--resume", action="store_true",
                        help="crash-safe resume: replay the "
                             "RUN_STATE.json journal (next to the "
                             "metrics report) and skip phases already "
                             "completed under the same config "
                             "fingerprint; the power and load phases "
                             "additionally resume mid-phase via their "
                             "own journals/markers")
    cli = parser.parse_args()
    with open(cli.yaml_config) as f:
        params = yaml.safe_load(f)
    run_full_bench(params, resume=cli.resume)
