"""Crash-safe bench resume: the append-only ``RUN_STATE.json`` journal.

The bench driver appends one record per completed phase — phase name,
config fingerprint, artifact paths — with per-line fsync
(ndstpu/io/atomic.py), so a ``kill -9`` between phases loses at most
the in-flight phase.  ``--resume`` replays the journal and auto-skips
every phase already completed under the SAME fingerprint, replacing the
reference harness's hand-edited per-phase ``skip:`` flags
(nds_bench.py:368-399).

The fingerprint is a sha256 over the canonicalized phase configs
(everything that changes what a phase computes: paths, scale factor,
seeds, engine).  Editing the config between runs changes the
fingerprint and invalidates all prior journal entries — a resume never
splices phases from two different benchmark definitions together.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import List, Optional, Set

from ndstpu.io import atomic

DEFAULT_BASENAME = "RUN_STATE.json"


def config_fingerprint(yaml_params: dict) -> str:
    """Stable identity of a bench config.  ``observability`` and
    per-phase ``budget_s`` knobs are excluded: changing where traces go
    or how long a phase may take does not change what it computes."""
    phases = {}
    for name, cfg in sorted(yaml_params.items()):
        if name == "observability" or not isinstance(cfg, dict):
            continue
        phases[name] = {k: v for k, v in sorted(cfg.items())
                        if k != "budget_s"}
    blob = json.dumps(phases, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


class RunState:
    """One bench run's phase-completion journal."""

    def __init__(self, path: str, fingerprint: str):
        self.path = path
        self.fingerprint = fingerprint

    @classmethod
    def for_bench(cls, yaml_params: dict) -> "RunState":
        mtr = yaml_params.get("metrics") or {}
        root = os.path.dirname(mtr.get("metrics_report") or "") or "."
        return cls(os.path.join(root, DEFAULT_BASENAME),
                   config_fingerprint(yaml_params))

    def records(self) -> List[dict]:
        return atomic.read_jsonl(self.path)

    def completed_phases(self) -> Set[str]:
        """Phases already completed under THIS config fingerprint."""
        return {r["phase"] for r in self.records()
                if r.get("fp") == self.fingerprint and r.get("phase")}

    def mark(self, phase: str,
             artifacts: Optional[List[str]] = None) -> None:
        atomic.append_jsonl(self.path, {
            "phase": phase,
            "fp": self.fingerprint,
            "ts_epoch_s": round(time.time(), 3),
            "artifacts": [str(a) for a in artifacts or []],
        })

    def reset(self) -> None:
        """Fresh (non-resume) run: prior journal entries are stale."""
        if os.path.exists(self.path):
            os.unlink(self.path)
