create or replace temp view crv as
select d_date_sk cr_returned_date_sk,
       t_time_sk cr_returned_time_sk,
       i_item_sk cr_item_sk,
       rc.c_customer_sk cr_refunded_customer_sk,
       rc.c_current_cdemo_sk cr_refunded_cdemo_sk,
       rc.c_current_hdemo_sk cr_refunded_hdemo_sk,
       rc.c_current_addr_sk cr_refunded_addr_sk,
       tc.c_customer_sk cr_returning_customer_sk,
       tc.c_current_cdemo_sk cr_returning_cdemo_sk,
       tc.c_current_hdemo_sk cr_returning_hdemo_sk,
       tc.c_current_addr_sk cr_returning_addr_sk,
       cc_call_center_sk cr_call_center_sk,
       cp_catalog_page_sk cr_catalog_page_sk,
       sm_ship_mode_sk cr_ship_mode_sk,
       w_warehouse_sk cr_warehouse_sk,
       r_reason_sk cr_reason_sk,
       cret_order_id cr_order_number,
       cret_return_qty cr_return_quantity,
       cret_return_amt cr_return_amount,
       cret_return_tax cr_return_tax,
       cret_return_amt + cret_return_tax cr_return_amt_inc_tax,
       cret_return_fee cr_fee,
       cret_return_ship_cost cr_return_ship_cost,
       cret_refunded_cash cr_refunded_cash,
       cret_reversed_charge cr_reversed_charge,
       cret_merchant_credit cr_store_credit,
       cret_return_fee + cret_return_ship_cost + cret_return_tax cr_net_loss
from s_catalog_returns
     join item on cret_item_id = i_item_id
     join date_dim on cast(cret_return_date as date) = d_date
     left join customer rc on cret_refund_customer_id = rc.c_customer_id
     left join customer tc on cret_return_customer_id = tc.c_customer_id
     left join call_center on cret_call_center_id = cc_call_center_id
     left join catalog_page on cret_catalog_page_id = cp_catalog_page_id
     left join ship_mode on cret_shipmode_id = sm_ship_mode_id
     left join warehouse on cret_warehouse_id = w_warehouse_id
     left join reason on cret_reason_id = r_reason_id
     left join time_dim on t_time = 43200;

insert into catalog_returns
select cr_returned_date_sk, cr_returned_time_sk, cr_item_sk,
       cr_refunded_customer_sk, cr_refunded_cdemo_sk, cr_refunded_hdemo_sk,
       cr_refunded_addr_sk, cr_returning_customer_sk, cr_returning_cdemo_sk,
       cr_returning_hdemo_sk, cr_returning_addr_sk, cr_call_center_sk,
       cr_catalog_page_sk, cr_ship_mode_sk, cr_warehouse_sk, cr_reason_sk,
       cr_order_number, cr_return_quantity, cr_return_amount, cr_return_tax,
       cr_return_amt_inc_tax, cr_fee, cr_return_ship_cost, cr_refunded_cash,
       cr_reversed_charge, cr_store_credit, cr_net_loss
from crv;
