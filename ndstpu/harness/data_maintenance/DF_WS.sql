delete from web_returns
where wr_order_number in
      (select ws_order_number from web_sales
       where ws_sold_date_sk >= (select min(d_date_sk) from date_dim
                                 where d_date between date 'DATE1'
                                                  and date 'DATE2')
         and ws_sold_date_sk <= (select max(d_date_sk) from date_dim
                                 where d_date between date 'DATE1'
                                                  and date 'DATE2'));

delete from web_sales
where ws_sold_date_sk >= (select min(d_date_sk) from date_dim
                          where d_date between date 'DATE1'
                                           and date 'DATE2')
  and ws_sold_date_sk <= (select max(d_date_sk) from date_dim
                          where d_date between date 'DATE1'
                                           and date 'DATE2');
