delete from catalog_returns
where cr_order_number in
      (select cs_order_number from catalog_sales
       where cs_sold_date_sk >= (select min(d_date_sk) from date_dim
                                 where d_date between date 'DATE1'
                                                  and date 'DATE2')
         and cs_sold_date_sk <= (select max(d_date_sk) from date_dim
                                 where d_date between date 'DATE1'
                                                  and date 'DATE2'));

delete from catalog_sales
where cs_sold_date_sk >= (select min(d_date_sk) from date_dim
                          where d_date between date 'DATE1'
                                           and date 'DATE2')
  and cs_sold_date_sk <= (select max(d_date_sk) from date_dim
                          where d_date between date 'DATE1'
                                           and date 'DATE2');
