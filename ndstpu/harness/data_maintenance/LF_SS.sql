create or replace temp view ssv as
select i_item_sk ss_item_sk,
       d_date_sk ss_sold_date_sk,
       c_customer_sk ss_customer_sk,
       c_current_cdemo_sk ss_cdemo_sk,
       c_current_hdemo_sk ss_hdemo_sk,
       c_current_addr_sk ss_addr_sk,
       s_store_sk ss_store_sk,
       p_promo_sk ss_promo_sk,
       purc_purchase_id ss_ticket_number,
       plin_quantity ss_quantity,
       purc_purchase_time ss_sold_time_sk,
       i_wholesale_cost ss_wholesale_cost,
       i_current_price ss_list_price,
       plin_sale_price ss_sales_price,
       plin_coupon_amt ss_coupon_amt
from s_purchase
     join customer on purc_customer_id = c_customer_id
     join store on purc_store_id = s_store_id
     join date_dim on cast(purc_purchase_date as date) = d_date
     join s_purchase_lineitem on purc_purchase_id = plin_purchase_id
     join item on plin_item_id = i_item_id
     left join promotion on plin_promotion_id = p_promo_id;

insert into store_sales
select ss_sold_date_sk, ss_sold_time_sk, ss_item_sk, ss_customer_sk,
       ss_cdemo_sk, ss_hdemo_sk, ss_addr_sk, ss_store_sk, ss_promo_sk,
       ss_ticket_number, ss_quantity, ss_wholesale_cost, ss_list_price,
       ss_sales_price,
       (ss_quantity * ss_list_price) - (ss_quantity * ss_sales_price)
           ss_ext_discount_amt,
       ss_quantity * ss_sales_price ss_ext_sales_price,
       ss_quantity * ss_wholesale_cost ss_ext_wholesale_cost,
       ss_quantity * ss_list_price ss_ext_list_price,
       cast(0.00 as decimal(7,2)) ss_ext_tax,
       ss_coupon_amt,
       (ss_quantity * ss_sales_price) - ss_coupon_amt ss_net_paid,
       (ss_quantity * ss_sales_price) - ss_coupon_amt ss_net_paid_inc_tax,
       ((ss_quantity * ss_sales_price) - ss_coupon_amt)
           - (ss_quantity * ss_wholesale_cost) ss_net_profit
from ssv;
