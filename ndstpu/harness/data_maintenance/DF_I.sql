delete from inventory
where inv_date_sk >= (select min(d_date_sk) from date_dim
                      where d_date between date 'DATE1' and date 'DATE2')
  and inv_date_sk <= (select max(d_date_sk) from date_dim
                      where d_date between date 'DATE1' and date 'DATE2');
