delete from store_returns
where sr_ticket_number in
      (select ss_ticket_number from store_sales
       where ss_sold_date_sk >= (select min(d_date_sk) from date_dim
                                 where d_date between date 'DATE1'
                                                  and date 'DATE2')
         and ss_sold_date_sk <= (select max(d_date_sk) from date_dim
                                 where d_date between date 'DATE1'
                                                  and date 'DATE2'));

delete from store_sales
where ss_sold_date_sk >= (select min(d_date_sk) from date_dim
                          where d_date between date 'DATE1'
                                           and date 'DATE2')
  and ss_sold_date_sk <= (select max(d_date_sk) from date_dim
                          where d_date between date 'DATE1'
                                           and date 'DATE2');
