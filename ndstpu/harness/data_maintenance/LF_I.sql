create or replace temp view iv as
select d_date_sk inv_date_sk,
       i_item_sk inv_item_sk,
       w_warehouse_sk inv_warehouse_sk,
       invn_qty_on_hand inv_quantity_on_hand
from s_inventory
     join warehouse on invn_warehouse_id = w_warehouse_id
     join item on invn_item_id = i_item_id
     join date_dim on cast(invn_date as date) = d_date;

insert into inventory
select inv_date_sk, inv_item_sk, inv_warehouse_sk, inv_quantity_on_hand
from iv;
