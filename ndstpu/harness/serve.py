"""``ndstpu-serve``: CLI front end for the always-on query service.

Three subcommands:

``server``
    Boot a :class:`~ndstpu.serve.server.QueryServer` over a warehouse
    and block until drained (SIGTERM/SIGINT run the graceful drain;
    SIGKILL is what the warm restart exists for).  State files
    (journal / compile records / SLO.json / ledger) default into
    ``--state_dir`` so a restart with the same flags finds them.
    ``--socket`` takes any serve/transport.py endpoint spec (unix
    path or ``tcp:HOST:PORT``); ``--tcp HOST:PORT`` adds a TCP
    listener beside it.

``fleet``
    Boot a :class:`~ndstpu.serve.fleet.FleetSupervisor`: N replica
    server processes over one warehouse, health-checked and restarted
    with bounded backoff.  SIGHUP triggers a rolling zero-downtime
    restart; SIGTERM drains the whole fleet.  Clients connect with
    the printed comma-separated endpoint spec and fail over between
    replicas.

``client``
    Ad-hoc requests against a running server or fleet: ``--sql``
    (repeatable), ``--op health|stats|ready|drain|ping|probe``, with
    the typed reconnect-retry-failover contract of
    :class:`~ndstpu.serve.client.ServeClient`.

Examples::

    ndstpu-serve server --socket /tmp/nds.sock \\
        --input_prefix wh --engine tpu --state_dir serve_state
    ndstpu-serve fleet --replicas 3 --input_prefix wh --engine tpu \\
        --run_dir fleet_state --queue_depth auto
    ndstpu-serve client --socket /tmp/nds.sock \\
        --sql "SELECT count(*) FROM store_sales"
    ndstpu-serve client --socket unix:/a.sock,tcp:127.0.0.1:9001 \\
        --op probe
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ndstpu-serve",
        description="always-on NDS query service (ndstpu/serve)")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("server", help="run the query server")
    s.add_argument("--socket", required=True,
                   help="endpoint to listen on (unix path, "
                        "unix:/path, or tcp:HOST:PORT)")
    s.add_argument("--tcp", default=None, metavar="HOST:PORT",
                   help="additional TCP listener beside --socket")
    s.add_argument("--input_prefix", required=True,
                   help="warehouse root (loader.load_catalog)")
    s.add_argument("--engine", default="cpu",
                   choices=("cpu", "tpu", "tpu-spmd"))
    s.add_argument("--output_prefix", default=None,
                   help="root for per-request result writes "
                        "(requests carrying a name)")
    s.add_argument("--output_format", default="csv",
                   choices=("csv", "parquet"))
    s.add_argument("--state_dir", default="serve_state",
                   help="journal/compile-records/SLO/ledger home")
    s.add_argument("--compile_records", default=None,
                   help="override the state_dir compile-record path")
    s.add_argument("--journal", default=None,
                   help="override the state_dir journal path")
    s.add_argument("--slo", default=None,
                   help="override the state_dir SLO.json path")
    s.add_argument("--ledger", default=None,
                   help="run-ledger path ('none' disables)")
    s.add_argument("--scale_factor", default="unknown")
    s.add_argument("--floats", action="store_true")
    s.add_argument("--slots", type=int, default=1,
                   help="device admission slots (InprocAdmission)")
    s.add_argument("--queue_depth", default="64",
                   help="admission queue depth; 'auto' derives it "
                        "from the memplan device-memory model")
    s.add_argument("--tenant_tokens", type=float, default=64.0)
    s.add_argument("--tenant_refill_per_s", type=float, default=16.0)
    s.add_argument("--breaker_cooldown_s", type=float, default=5.0)
    s.add_argument("--query_timeout_s", type=float, default=None,
                   help="per-query watchdog (default: env "
                        "NDSTPU_SERVE_QUERY_TIMEOUT_S or 300)")
    s.add_argument("--aot_corpus", default=None,
                   help="query stream file (or dir of query_*.sql) "
                        "to precompile before readiness flips")
    s.add_argument("--bind_early", action="store_true",
                   help="bind + answer probes before warm "
                        "restart/AOT complete (fleet supervisors)")
    s.add_argument("--replica_id", default=None,
                   help="fleet identity reported in probe/health")

    f = sub.add_parser("fleet", help="run a replicated serving fleet")
    f.add_argument("--input_prefix", required=True)
    f.add_argument("--replicas", type=int, default=2)
    f.add_argument("--run_dir", default="fleet_state",
                   help="per-replica state dirs + FLEET_HEALTH.json")
    f.add_argument("--endpoints", default=None,
                   help="comma-separated endpoint specs, one per "
                        "replica (default: unix sockets derived from "
                        "run_dir)")
    f.add_argument("--engine", default="cpu",
                   choices=("cpu", "tpu", "tpu-spmd"))
    f.add_argument("--output_prefix", default=None)
    f.add_argument("--output_format", default="csv",
                   choices=("csv", "parquet"))
    f.add_argument("--compile_records", default=None,
                   help="SHARED compile-record artifact (default: "
                        "run_dir/compile_records.json)")
    f.add_argument("--ledger", default="none")
    f.add_argument("--scale_factor", default="unknown")
    f.add_argument("--floats", action="store_true")
    f.add_argument("--slots", type=int, default=1)
    f.add_argument("--queue_depth", default="64",
                   help="per-replica admission depth; 'auto' derives "
                        "it from the memplan device-memory model")
    f.add_argument("--aot_corpus", default=None)
    f.add_argument("--query_timeout_s", type=float, default=None)
    f.add_argument("--probe_interval_s", type=float, default=0.5)
    f.add_argument("--restart_backoff_s", type=float, default=0.25)

    c = sub.add_parser("client", help="talk to a running server/fleet")
    c.add_argument("--socket", required=True,
                   help="endpoint spec; comma-separate for failover")
    c.add_argument("--sql", action="append", default=[],
                   help="statement to run (repeatable)")
    c.add_argument("--op", default=None,
                   choices=("ping", "health", "ready", "stats",
                            "drain", "probe"))
    c.add_argument("--tenant", default="default")
    c.add_argument("--name", default=None,
                   help="server-side output name for a single --sql")
    c.add_argument("--deadline_s", type=float, default=None)
    c.add_argument("--max_rows", type=int, default=100)
    c.add_argument("--retries", type=int, default=8)
    c.add_argument("--wait_ready_s", type=float, default=0.0,
                   help="poll readiness up to this long first")
    return p


def _parse_depth(raw) -> Optional[int]:
    """``auto`` (or 0) -> None: derive depth from the memplan
    device-memory model (memplan.admission_budget)."""
    if raw is None or str(raw).lower() in ("auto", "0", "none"):
        return None
    return int(raw)


def _run_server(args) -> int:
    from ndstpu.serve import lifecycle
    from ndstpu.serve.server import QueryServer, ServeConfig
    sd = args.state_dir
    os.makedirs(sd, exist_ok=True)
    cfg = ServeConfig(
        socket_path=args.socket,
        input_prefix=args.input_prefix,
        engine=args.engine,
        output_prefix=args.output_prefix,
        output_format=args.output_format,
        compile_records=args.compile_records
        or os.path.join(sd, "compile_records.json"),
        journal_path=args.journal
        or os.path.join(sd, "serve_journal.jsonl"),
        slo_path=args.slo or os.path.join(sd, "SLO.json"),
        ledger_path=args.ledger,
        scale_factor=args.scale_factor,
        floats=args.floats,
        slots=args.slots,
        queue_depth=_parse_depth(args.queue_depth),
        tenant_tokens=args.tenant_tokens,
        tenant_refill_per_s=args.tenant_refill_per_s,
        breaker_cooldown_s=args.breaker_cooldown_s,
        query_timeout_s=args.query_timeout_s,
        tcp=args.tcp,
        aot_corpus=args.aot_corpus,
        bind_early=args.bind_early,
        replica_id=args.replica_id)
    server = QueryServer(cfg)
    lifecycle.install_signal_handlers(server)
    server.serve_forever()
    return 0


def _run_fleet(args) -> int:
    from ndstpu.serve import fleet
    cfg = fleet.FleetConfig(
        input_prefix=args.input_prefix,
        replicas=args.replicas,
        run_dir=args.run_dir,
        endpoints=(args.endpoints.split(",") if args.endpoints
                   else None),
        engine=args.engine,
        output_prefix=args.output_prefix,
        output_format=args.output_format,
        compile_records=args.compile_records,
        ledger_path=args.ledger,
        scale_factor=args.scale_factor,
        floats=args.floats,
        slots=args.slots,
        queue_depth=_parse_depth(args.queue_depth),
        aot_corpus=args.aot_corpus,
        query_timeout_s=args.query_timeout_s,
        probe_interval_s=args.probe_interval_s,
        restart_backoff_s=args.restart_backoff_s)
    return fleet.serve_fleet_forever(cfg)


def _run_client(args) -> int:
    from ndstpu.serve.client import ServeClient
    cli = ServeClient(args.socket, tenant=args.tenant,
                      retries=args.retries)
    try:
        if args.wait_ready_s > 0 and \
                not cli.wait_ready(args.wait_ready_s):
            print(f"server not ready within {args.wait_ready_s:g}s",
                  file=sys.stderr)
            return 1
        if args.op:
            resp = cli.request({"op": args.op})
            print(json.dumps(resp, indent=2, default=str))
            if cli.failovers:
                print(f"# client.failovers={cli.failovers}",
                      file=sys.stderr)
        for sql in args.sql:
            name = args.name if len(args.sql) == 1 else None
            resp = cli.sql(sql, name=name,
                           deadline_s=args.deadline_s,
                           max_rows=args.max_rows)
            print(json.dumps(resp, indent=2, default=str))
        if not args.op and not args.sql:
            print(json.dumps(cli.health(), indent=2, default=str))
    finally:
        cli.close()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "server":
        return _run_server(args)
    if args.cmd == "fleet":
        return _run_fleet(args)
    return _run_client(args)


if __name__ == "__main__":
    sys.exit(main())
