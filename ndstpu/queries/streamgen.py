"""Query-stream generation — the dsqgen analog.

Renders the template corpus into per-stream query files with the same
contract as the reference (nds_gen_query_stream.py + patched spark.tpl
dialect):

* ``-- start query N in stream M using template queryX.tpl`` / matching
  ``-- end`` markers (the parsing contract of the power runner,
  reference nds_power.py:49-76)
* per-stream permuted query order and per-(stream, template) substitution
  parameters, both deterministic in ``--rngseed`` (TPC-DS spec 4.3.1
  reproducibility)
* ``--template`` single-template mode for testing, including the two-part
  split files (_part1/_part2) for the multi-statement templates
  (reference nds_gen_query_stream.py:91-103)

Templates declare parameters in a header line per parameter:
    --@ define NAME = uniform(lo, hi)      integer uniform inclusive
    --@ define NAME = choice(v1, v2, ...)  pick one literal
    --@ define NAME = dist(dname)          weighted pick from a named
                                           distribution (dsqgen
                                           `distmember` analog, cf.
                                           reference nds/tpcds-gen/
                                           patches/templates.patch
                                           `distmember(fips_county,...)`)
    --@ define NAME = distlist(dname, k)   k INDEPENDENT weighted picks
                                           (WITH replacement — dsqgen's
                                           distmember over independent
                                           [N.i] draws; the reference
                                           query16 deliberately repeats
                                           hot counties), substituted
                                           as [NAME.1] .. [NAME.k]
    --@ define NAME = distlistu(dname, k)  k DISTINCT weighted picks
                                           (dsqgen `ulist` analog —
                                           query34's county list)
``[NAME]`` occurrences in the body are substituted.  Arithmetic like
``[NAME] + 10`` stays in SQL.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import random
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

TEMPLATE_DIR = Path(__file__).resolve().parent / "templates"

_DEFINE_RE = re.compile(
    r"^--@\s*define\s+(\w+)\s*=\s*(uniform|choice|dist|distlistu|distlist)"
    r"\((.*)\)\s*$")

# Named weighted value distributions — the dsqgen distribution-table
# analog (the TPC toolkit ships these as .dst files; dsqgen's
# `distmember(fips_county, [N], 2)` picks weighted county names).
# Loaded from ndstpu/datagen/dists.json, the SAME file the native
# generator compiles its column-value tables from (ndstpu.check
# renders it into dists_gen.h at build time): data generation and
# query-parameter generation share one source of truth, so rendered
# predicates always land on domains the data actually has, with the
# same non-uniform selectivity the generator produced.


def _load_distributions() -> Dict[str, List[Tuple[str, int]]]:
    import json
    path = Path(__file__).resolve().parent.parent / "datagen" / "dists.json"
    with open(path) as f:
        raw = json.load(f)
    return {name: list(zip(d["values"], d["weights"]))
            for name, d in raw.items() if not name.startswith("_")}


_DISTRIBUTIONS = _load_distributions()


def _dist_pick(rng: random.Random, dname: str, k: int = 1,
               distinct: bool = False) -> List[str]:
    """k weighted picks from a named distribution.  Default is WITH
    replacement (dsqgen distmember over independent draws — duplicates
    are intentional and concentrate selectivity on hot values);
    ``distinct=True`` removes each pick from the pool (ulist)."""
    pool = list(_DISTRIBUTIONS[dname])
    out = []
    for _ in range(min(k, len(pool)) if distinct else k):
        total = sum(w for _, w in pool)
        x = rng.randrange(total)
        for i, (v, w) in enumerate(pool):
            x -= w
            if x < 0:
                out.append(v)
                if distinct:
                    del pool[i]
                break
    return out


def list_templates(template_dir: Optional[str] = None) -> List[str]:
    d = Path(template_dir) if template_dir else TEMPLATE_DIR
    return sorted((p.name for p in d.glob("query*.tpl")),
                  key=lambda n: int(re.findall(r"\d+", n)[0]))


#: the stream-0 seed every benchmark script renders with; keeping it in
#: one place means warm caches, CPU baselines, and TPU passes can only
#: ever compare timings of IDENTICAL rendered SQL
BENCH_RNGSEED = "07291122510"


def render_power_corpus(rngseed: str = BENCH_RNGSEED,
                        stream: int = 0) -> List[Tuple[str, str]]:
    """The canonical (query_name, sql) power-run corpus: every template,
    split into executable parts, rendered with ``rngseed``.  Shared by
    bench.py, warm_corpus, sf10_bench — per-script render loops drifted
    once (different seed -> same names, different literals -> silently
    wrong speedups)."""
    queries: List[Tuple[str, str]] = []
    for tpl in list_templates():
        queries.extend(render_template_parts(
            str(TEMPLATE_DIR / tpl), rngseed, stream))
    return queries


def _parse_template(text: str) -> Tuple[Dict[str, tuple], str]:
    params: Dict[str, tuple] = {}
    body_lines = []
    for line in text.splitlines():
        m = _DEFINE_RE.match(line.strip())
        if m:
            name, kind, args = m.groups()
            vals = [a.strip() for a in args.split(",")]
            params[name] = (kind, vals)
        else:
            body_lines.append(line)
    return params, "\n".join(body_lines).strip()


def _stable_seed(rngseed: str, stream: int, template: str) -> int:
    h = hashlib.sha256(f"{rngseed}|{stream}|{template}".encode()).digest()
    return int.from_bytes(h[:8], "big")


def _draw_params(params: Dict[str, tuple], tpl_name: str, rngseed: str,
                 stream: int) -> Dict[str, object]:
    """One rng pass over the parsed defines — {name: value} for scalar
    params, {name: [values]} for distlist params.  Deterministic in
    (rngseed, stream, template name)."""
    rng = random.Random(_stable_seed(rngseed, stream, tpl_name))
    out: Dict[str, object] = {}
    for name, (kind, vals) in params.items():
        if kind == "uniform":
            out[name] = str(rng.randint(int(vals[0]), int(vals[1])))
        elif kind == "dist":
            out[name] = _dist_pick(rng, vals[0])[0]
        elif kind in ("distlist", "distlistu"):
            out[name] = _dist_pick(rng, vals[0], int(vals[1]),
                                   distinct=(kind == "distlistu"))
        else:  # choice
            v = rng.choice(vals).strip()
            if v.startswith("'") and v.endswith("'"):
                v = v[1:-1]
            out[name] = v
    return out


def render_params(template_path: str, rngseed: str,
                  stream: int) -> Dict[str, object]:
    """The parameter draws for one (template, stream) pair; the audit
    tooling uses this to check every drawn value against the generated
    data domain (scripts/param_audit.py)."""
    params, _body = _parse_template(Path(template_path).read_text())
    return _draw_params(params, Path(template_path).name, rngseed, stream)


def render_template(template_path: str, rngseed: str, stream: int) -> str:
    params, body = _parse_template(Path(template_path).read_text())
    drawn = _draw_params(params, Path(template_path).name, rngseed, stream)
    for name, v in drawn.items():
        if isinstance(v, list):
            for i, p in enumerate(v, 1):
                body = body.replace(f"[{name}.{i}]", p)
        else:
            body = body.replace(f"[{name}]", v)
    leftover = re.findall(r"\[([A-Z][A-Z0-9_.]*)\]", body)
    if leftover:
        raise ValueError(
            f"{template_path}: unsubstituted parameters {sorted(set(leftover))}")
    return body


def render_template_parts(template_path: str, rngseed: str,
                          stream: int) -> List[Tuple[str, str]]:
    """Render a template and split multi-statement bodies into the
    reference's `_part1`/`_part2` naming (nds_gen_query_stream.py:91-103):
    single-statement -> [("queryN", sql)]; two-part -> two entries."""
    name = Path(template_path).name
    base = name[:-4] if name.endswith(".tpl") else name
    sql = render_template(template_path, rngseed, stream)
    # the SAME statement splitter the power runner parses streams with —
    # the two sides must agree on part naming
    from ndstpu.harness.power import _sql_statements
    stmts = [s.strip() for s in _sql_statements(sql)]
    if len(stmts) <= 1:
        return [(base, sql)]
    return [(f"{base}_part{k}", stmt + ";")
            for k, stmt in enumerate(stmts, 1)]


def _query_order(templates: List[str], rngseed: str,
                 stream: int) -> List[str]:
    """Stream 0 = canonical order (the Power Run); streams >= 1 get a
    deterministic permutation (TPC-DS per-stream ordering)."""
    if stream == 0:
        return list(templates)
    rng = random.Random(_stable_seed(rngseed, stream, "__order__"))
    out = list(templates)
    rng.shuffle(out)
    return out


def generate_query_streams(template_dir: Optional[str], rngseed: str,
                           output_dir: str, streams: int) -> List[str]:
    """Write query_{stream}.sql for streams 0..N-1; returns file paths."""
    os.makedirs(output_dir, exist_ok=True)
    d = Path(template_dir) if template_dir else TEMPLATE_DIR
    templates = list_templates(template_dir)
    if not templates:
        raise FileNotFoundError(f"no query*.tpl under {d}")
    paths = []
    for stream in range(streams):
        parts = []
        order = _query_order(templates, rngseed, stream)
        for i, tpl in enumerate(order):
            sql = render_template(str(d / tpl), rngseed, stream)
            if not sql.rstrip().endswith(";"):
                sql = sql.rstrip() + "\n;"
            parts.append(
                f"-- start query {i + 1} in stream {stream} "
                f"using template {tpl}\n{sql}\n"
                f"-- end query {i + 1} in stream {stream} "
                f"using template {tpl}\n")
        path = os.path.join(output_dir, f"query_{stream}.sql")
        with open(path, "w") as f:
            f.write("\n".join(parts))
        paths.append(path)
    return paths


def generate_single_template(template: str, template_dir: Optional[str],
                             rngseed: str, output_dir: str) -> List[str]:
    """Render one template (test mode) as a one-query stream file
    `query_0.sql` WITH start/end markers — dsqgen emits the spark.tpl
    markers in single-template mode too, and the power runner's parser
    requires them (reference nds_gen_query_stream.py:57-89,
    nds_power.py:49-76).  Multi-statement templates additionally produce
    split _part1/_part2 files (nds_gen_query_stream.py:91-103)."""
    os.makedirs(output_dir, exist_ok=True)
    d = Path(template_dir) if template_dir else TEMPLATE_DIR
    name = template if template.endswith(".tpl") else template + ".tpl"
    sql = render_template(str(d / name), rngseed, 0)
    if not sql.rstrip().endswith(";"):
        sql = sql.rstrip() + "\n;"
    stream_path = os.path.join(output_dir, "query_0.sql")
    with open(stream_path, "w") as f:
        f.write(f"-- start query 1 in stream 0 using template {name}\n"
                f"{sql}\n"
                f"-- end query 1 in stream 0 using template {name}\n")
    out_paths = [stream_path]
    parts = render_template_parts(str(d / name), rngseed, 0)
    if len(parts) > 1:
        for part_name, stmt in parts:
            p = os.path.join(output_dir, f"{part_name}.sql")
            with open(p, "w") as f:
                f.write(stmt.rstrip(";").rstrip() + ";\n")
            out_paths.append(p)
    return out_paths


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="NDS query stream generator")
    p.add_argument("--template_dir",
                   help="directory of query templates "
                        "(default: builtin corpus)")
    p.add_argument("--output_dir", required=True)
    p.add_argument("--rngseed", default="0",
                   help="RNG seed (chained from the load test end timestamp "
                        "per TPC-DS spec 4.3.1)")
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--template",
                   help="render one template (test mode)")
    g.add_argument("--streams", type=int,
                   help="generate N permuted full streams")
    return p


if __name__ == "__main__":
    args = build_parser().parse_args()
    if args.template:
        out = generate_single_template(args.template, args.template_dir,
                                       args.rngseed, args.output_dir)
    else:
        out = generate_query_streams(args.template_dir, args.rngseed,
                                     args.output_dir, args.streams)
    for p in out:
        print(p)
