"""Query corpus + reproducible stream generation (dsqgen analog)."""
