--@ define YEAR = uniform(1998, 2002)
--@ define MS = distlistu(marital_status, 3)
--@ define ES = distlistu(education, 3)
--@ define ST = distlistu(states, 3)
select avg(ss_quantity) aq,
       avg(ss_ext_sales_price) aesp,
       avg(ss_ext_wholesale_cost) aewc,
       sum(ss_ext_wholesale_cost) sewc
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = [YEAR]
  and ((ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS.1]'
        and cd_education_status = '[ES.1]'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
    or (ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS.2]'
        and cd_education_status = '[ES.2]'
        and ss_sales_price between 50.00 and 100.00
        and hd_dep_count = 1)
    or (ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS.3]'
        and cd_education_status = '[ES.3]'
        and ss_sales_price between 150.00 and 200.00
        and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[ST.1]', '[ST.2]', '[ST.3]')
        and ss_net_profit between 100 and 200)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[ST.1]', '[ST.2]', '[ST.3]')
        and ss_net_profit between 150 and 300)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[ST.1]', '[ST.2]', '[ST.3]')
        and ss_net_profit between 50 and 250))
