--@ define LP = uniform(0, 190)
--@ define CA = uniform(0, 18000)
--@ define WC = uniform(0, 80)
select *
from (select avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(distinct ss_list_price) b1_cntd
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between [LP] and [LP] + 10
             or ss_coupon_amt between [CA] and [CA] + 1000
             or ss_wholesale_cost between [WC] and [WC] + 20)) b1,
     (select avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(distinct ss_list_price) b2_cntd
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between [LP] and [LP] + 10
             or ss_coupon_amt between [CA] and [CA] + 1000
             or ss_wholesale_cost between [WC] and [WC] + 20)) b2,
     (select avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(distinct ss_list_price) b3_cntd
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between [LP] and [LP] + 10
             or ss_coupon_amt between [CA] and [CA] + 1000
             or ss_wholesale_cost between [WC] and [WC] + 20)) b3,
     (select avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(distinct ss_list_price) b4_cntd
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between [LP] and [LP] + 10
             or ss_coupon_amt between [CA] and [CA] + 1000
             or ss_wholesale_cost between [WC] and [WC] + 20)) b4,
     (select avg(ss_list_price) b5_lp, count(ss_list_price) b5_cnt,
             count(distinct ss_list_price) b5_cntd
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between [LP] and [LP] + 10
             or ss_coupon_amt between [CA] and [CA] + 1000
             or ss_wholesale_cost between [WC] and [WC] + 20)) b5,
     (select avg(ss_list_price) b6_lp, count(ss_list_price) b6_cnt,
             count(distinct ss_list_price) b6_cntd
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between [LP] and [LP] + 10
             or ss_coupon_amt between [CA] and [CA] + 1000
             or ss_wholesale_cost between [WC] and [WC] + 20)) b6
limit 100
