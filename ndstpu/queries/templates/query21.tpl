--@ define SDATE = choice('1998-03-10', '1999-03-10', '2000-03-10', '2001-03-10')
select w_warehouse_name, i_item_id,
       sum(case when d_date < cast('[SDATE]' as date)
                then inv_quantity_on_hand else 0 end) as inv_before,
       sum(case when d_date >= cast('[SDATE]' as date)
                then inv_quantity_on_hand else 0 end) as inv_after
from inventory, warehouse, item, date_dim
where i_item_sk = inv_item_sk
  and inv_warehouse_sk = w_warehouse_sk
  and inv_date_sk = d_date_sk
  and i_current_price between 0.99 and 49.99
  and d_date between (cast('[SDATE]' as date) - interval 30 days)
                 and (cast('[SDATE]' as date) + interval 30 days)
group by w_warehouse_name, i_item_id
having (case when sum(case when d_date < cast('[SDATE]' as date)
                           then inv_quantity_on_hand else 0 end) > 0
             then sum(case when d_date >= cast('[SDATE]' as date)
                           then inv_quantity_on_hand else 0 end) * 1.0 /
                  sum(case when d_date < cast('[SDATE]' as date)
                           then inv_quantity_on_hand else 0 end)
             else null end) between 0.666667 and 1.5
order by w_warehouse_name, i_item_id
limit 100
