--@ define YEAR = uniform(1998, 2002)
--@ define MONTH = uniform(1, 7)
select  i_item_id
       ,i_item_desc
       ,i_category
       ,i_class
       ,i_current_price
       ,sum(cs_ext_sales_price) as itemrevenue
       ,sum(cs_ext_sales_price)*100/sum(sum(cs_ext_sales_price)) over
           (partition by i_class) as revenueratio
 from	catalog_sales
     ,item
     ,date_dim
 where cs_item_sk = i_item_sk
   and i_category in ('Sports', 'Books', 'Home')
   and cs_sold_date_sk = d_date_sk
 and d_date between cast('[YEAR]-0[MONTH]-01' as date)
 				and (cast('[YEAR]-0[MONTH]-01' as date) + interval 30 days)
 group by i_item_id
         ,i_item_desc
         ,i_category
         ,i_class
         ,i_current_price
 order by i_category
         ,i_class
         ,i_item_id
         ,i_item_desc
         ,revenueratio
limit 100
