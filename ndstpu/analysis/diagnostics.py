"""Stable diagnostic codes for the static plan analyzer.

Severity model
--------------
``error``    the part cannot run on the device path — jaxexec WILL raise
             :class:`~ndstpu.engine.jaxexec.Unsupported` for this node and
             fall back to the numpy interpreter.
``warning``  the plan runs, but a typing hazard (lossy cast, mismatched
             join keys, SetOp drift) or an SPMD-spine limitation makes the
             result or the distributed placement fragile.
``info``     advisory only: data-dependent capacity guards, predicted
             exchange placement, nondeterministic-tie sorts.

Code ranges (docs/ARCHITECTURE.md "Static analysis"):

* ``NDS1xx`` — typing / schema inference (analysis/typecheck.py)
* ``NDS2xx`` — single-chip device lowering (analysis/lowering.py, mirrors
  jaxexec's raise sites)
* ``NDS3xx`` — SPMD / distributed spine (mirrors parallel/dplan.py)
* ``NDS4xx`` — plan canonicalization / parameter lifting
  (analysis/canon.py): which literal slots bind at runtime vs stay baked
  into the compiled program's shape
* ``NDS5xx`` — cross-query common-spine sharing (analysis/spines.py):
  which canonical subtrees recur across corpus parts and whether the
  runtime spine-materialization cache may splice them
* ``NDS6xx`` — static cost model (analysis/cost.py): calibrated
  cardinality/byte estimates, exchange-placement risk, and
  static-vs-observed misestimates (swept into COST_LINT.json)

The module is import-hygienic: no jax, no engine imports — it can run in
a process that never initializes a backend (CI lint, doc tooling).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warning", "info")

#: code -> (default severity, one-line summary).  The single source of
#: truth for the code space; emitters refuse unknown codes.
CODES: Dict[str, Tuple[str, str]] = {
    # -- NDS1xx typing ----------------------------------------------------
    "NDS101": ("warning", "join key dtype mismatch"),
    "NDS102": ("warning", "lossy implicit or explicit cast"),
    "NDS103": ("info", "int32 aggregate overflow risk at scale factor"),
    "NDS104": ("warning", "SetOp arity or column type mismatch"),
    "NDS105": ("info", "under-specified sort keys (nondeterministic ties)"),
    # -- NDS2xx device lowering ------------------------------------------
    "NDS201": ("error", "expression node not lowerable on device"),
    "NDS202": ("error", "binary operator not lowerable on device"),
    "NDS203": ("error", "unary operator not lowerable on device"),
    "NDS204": ("error", "cast not lowerable on device"),
    "NDS205": ("error", "function not lowerable on device"),
    "NDS206": ("error", "string operation on non-string operand"),
    "NDS207": ("error", "aggregate (or distinct aggregate) not lowerable"),
    "NDS208": ("error", "aggregate output expression not lowerable"),
    "NDS209": ("error", "window function not lowerable on device"),
    "NDS210": ("error", "join shape not lowerable on device"),
    "NDS211": ("error", "subquery kind not lowerable on device"),
    "NDS212": ("error", "IN-list incompatible with operand column"),
    "NDS213": ("info", "data-dependent device capacity guard"),
    "NDS214": ("info", "grouping sets need per-set passes (not combinable)"),
    # -- NDS3xx SPMD spine ------------------------------------------------
    "NDS301": ("info", "no distributable base-table scan"),
    "NDS302": ("warning", "aggregate not decomposable on the SPMD spine"),
    "NDS303": ("warning", "join kind unsupported on the SPMD spine"),
    "NDS304": ("warning", "non-equi join on the SPMD spine"),
    "NDS305": ("info", "predicted exchange placement (broadcast/shuffle)"),
    "NDS306": ("info", "row spine does no distributed work"),
    "NDS307": ("warning", "join key kind not shardable on the spine"),
    "NDS308": ("info", "existence-join build side reduced to distinct "
                       "key tuples distributed (no host build of the "
                       "sharded table)"),
    "NDS309": ("info", "aggregate distributes over a union-all of "
                       "sharded branches (per-branch spines, host "
                       "partial combine)"),
    "NDS310": ("info", "row-spine tail (sort/limit/window) finalizes "
                       "on-device; only the small result gathers"),
    "NDS311": ("warning", "configured chunked streaming fell back to the "
                          "single-chip whole-fact path (the fact must fit "
                          "HBM resident; spmd_chunk_rows is ignored there)"),
    "NDS312": ("info", "string join key shards on frozen global-dictionary "
                       "codes (no build-dictionary translation; "
                       "NDSTPU_GLOBAL_DICTS=0 restores the translate path)"),
    # -- NDS4xx canonicalization / parameter lifting ----------------------
    "NDS401": ("info", "shape-affecting literal: value feeds static shape "
                       "or capacity planning (LIMIT, interval width, "
                       "bounded CASE value, group key)"),
    "NDS402": ("info", "literal inside a pre-resolved subquery is baked "
                       "into the recorded size plan"),
    "NDS403": ("info", "literal in a host-static context cannot bind at "
                       "runtime (function argument, non-predicate string, "
                       "unclean IN-list)"),
    "NDS404": ("warning", "corpus part does not collapse to one canonical "
                          "fingerprint across probed streams/seeds"),
    # -- NDS5xx cross-query common-spine sharing --------------------------
    "NDS501": ("info", "shared-spine candidate: canonical subtree recurs "
                       "across corpus parts and is runtime-spliceable"),
    "NDS502": ("info", "param-divergent spine: subtrees share a canonical "
                       "shape but bind different literal values, so the "
                       "value-keyed materialization cache cannot serve "
                       "one result to all of them"),
    "NDS503": ("info", "nondeterministic/row-order-sensitive subtree "
                       "(sort/window/limit inside): excluded from spine "
                       "materialization"),
    "NDS504": ("info", "estimated spine bytes exceed the memory-planner "
                       "budget (memplan row-width model): materialization "
                       "would not be admitted"),
    # -- NDS6xx static cost model -----------------------------------------
    "NDS601": ("warning", "broadcast build side over the replication "
                          "byte budget (cost model demotes it to the "
                          "shuffle path)"),
    "NDS602": ("warning", "spill-risk working set: predicted per-device "
                          "bytes exceed the device budget (fact must "
                          "stream out-of-core)"),
    "NDS603": ("info", "exchange-heavy plan: predicted collective "
                       "(all_to_all) bytes over the heavy-traffic "
                       "threshold"),
    "NDS604": ("info", "misestimate: static cardinality estimate vs "
                       "ledger-observed output beyond the calibration "
                       "threshold"),
}

_SEV_ORDER = {"error": 0, "warning": 1, "info": 2}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One analyzer finding, anchored to a plan path.

    ``path`` is a ``/``-joined chain of plan node names from the root,
    each ``NodeName[i]`` where ``i`` is the child ordinal — stable across
    runs because plans are built deterministically from the template.
    """

    code: str
    message: str
    path: str
    query: str = ""
    severity: str = ""     # defaults to the code's registered severity

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unknown diagnostic code {self.code}")
        if not self.severity:
            object.__setattr__(self, "severity", CODES[self.code][0])
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity}")

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: query + code + plan path (message text may
        legitimately drift as inference sharpens)."""
        return (self.query, self.code, self.path)

    def as_dict(self) -> Dict[str, str]:
        return {"query": self.query, "code": self.code,
                "severity": self.severity, "path": self.path,
                "message": self.message}


def sort_diagnostics(diags: Iterable[Diagnostic]) -> List[Diagnostic]:
    return sorted(diags, key=lambda d: (d.query, _SEV_ORDER[d.severity],
                                        d.code, d.path, d.message))


# -- emitters ----------------------------------------------------------------

def to_json(diags: Iterable[Diagnostic], meta: Optional[dict] = None) -> str:
    """Deterministic JSON artifact (PLAN_LINT.json): no timestamps, sorted
    diagnostics, summary counts by severity and code."""
    diags = sort_diagnostics(diags)
    by_sev = {s: 0 for s in SEVERITIES}
    by_code: Dict[str, int] = {}
    for d in diags:
        by_sev[d.severity] += 1
        by_code[d.code] = by_code.get(d.code, 0) + 1
    doc = {
        "meta": dict(meta or {}),
        "summary": {"total": len(diags), "by_severity": by_sev,
                    "by_code": dict(sorted(by_code.items()))},
        "diagnostics": [d.as_dict() for d in diags],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def to_markdown(diags: Iterable[Diagnostic],
                meta: Optional[dict] = None) -> str:
    """Human-readable twin of :func:`to_json` (PLAN_LINT.md)."""
    diags = sort_diagnostics(diags)
    lines = ["# Plan lint report", ""]
    for k, v in sorted((meta or {}).items()):
        lines.append(f"- **{k}**: {v}")
    if meta:
        lines.append("")
    by_sev = {s: sum(1 for d in diags if d.severity == s)
              for s in SEVERITIES}
    lines.append(f"{len(diags)} diagnostics — "
                 + ", ".join(f"{by_sev[s]} {s}" for s in SEVERITIES))
    lines.append("")
    if diags:
        lines += ["| query | code | severity | path | message |",
                  "|---|---|---|---|---|"]
        for d in diags:
            msg = d.message.replace("|", "\\|")
            lines.append(f"| {d.query} | {d.code} | {d.severity} "
                         f"| `{d.path}` | {msg} |")
        lines.append("")
    lines += ["## Code reference", "",
              "| code | default severity | meaning |", "|---|---|---|"]
    for code, (sev, summary) in sorted(CODES.items()):
        lines.append(f"| {code} | {sev} | {summary} |")
    lines.append("")
    return "\n".join(lines)


# -- baseline / suppression --------------------------------------------------

def baseline_dump(diags: Iterable[Diagnostic]) -> str:
    """Serialize the accepted-diagnostic set (docs/plan_lint_baseline.json).
    Keys only — message drift does not invalidate a baseline entry."""
    keys = sorted({d.key() for d in diags})
    doc = {"accepted": [{"query": q, "code": c, "path": p}
                        for q, c, p in keys]}
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def baseline_load(text: str) -> set:
    doc = json.loads(text)
    return {(e["query"], e["code"], e["path"]) for e in doc["accepted"]}


def new_against_baseline(diags: Iterable[Diagnostic],
                         accepted: set) -> List[Diagnostic]:
    """Diagnostics not covered by the baseline — the CI failure set."""
    return [d for d in sort_diagnostics(diags) if d.key() not in accepted]
