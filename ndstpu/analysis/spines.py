"""Cross-query common-spine analysis (multi-query optimization, NDS5xx).

The scheduler already dedups *identical* canonical plans across streams;
this pass finds shared *sub*-plans.  Most corpus parts walk the same
fact-scan + dimension-join spines (date_dim filters, store_sales joins),
and a subtree-level canonical fingerprint (``canon.canonicalize_subtrees``
— slot numbering restarts per subtree, so a shared spine under different
enclosing plans still collapses) makes that overlap visible:

* :func:`subtree_sites` classifies one plan's candidate spines —
  scan+filter stacks, join build sides, pre-aggregation subtrees — as
  *shareable* (runtime-spliceable) or not, with a reason,
* :func:`build_index` sweeps many queries' sites into the global
  subtree→queries index and emits the NDS5xx diagnostics:

  ======= ==========================================================
  NDS501  shared-spine candidate (recurs across parts, spliceable)
  NDS502  param-divergent spine (same shape, different literal values)
  NDS503  nondeterministic/row-order-sensitive subtree (sort/window/
          limit inside) — excluded from materialization
  NDS504  estimated bytes exceed the memory-planner budget
  ======= ==========================================================

* :func:`index_to_doc` renders the deterministic MQO_AUDIT payload that
  ``scripts/mqo_audit.py`` writes and CI gates against
  ``docs/mqo_audit_baseline.json``.

The runtime consumer (``engine/spine.py`` + ``Session._splice_spines``)
imports the same :func:`subtree_sites` / :func:`eligible_sites` /
:func:`value_key` helpers, so what the analyzer flags and what the
spine-materialization cache splices cannot drift.

Import-hygienic like the rest of ``ndstpu.analysis``: numpy only, no
jax — :func:`spine_budget_bytes` deliberately reads env/defaults instead
of calling ``memplan.device_budget_bytes()`` (which probes a backend).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ndstpu.engine import columnar, memplan, plan as lp
from ndstpu.analysis.canon import SubtreeCanon, canonicalize_subtrees
from ndstpu.analysis.diagnostics import Diagnostic
from ndstpu.analysis.typecheck import infer_plan

__all__ = ["SpineSite", "subtree_sites", "eligible_sites", "value_key",
           "build_index", "index_to_doc", "spine_budget_bytes",
           "SF1_ROWS"]

#: TPC-DS per-table row counts at scale factor 1 (dsdgen table of
#: contents; date/time dims are SF-invariant).  Drives the NDS504
#: estimated-bytes check: est rows for a spine = the largest scanned
#: base table, scaled by the sweep's scale factor for the fact tables.
SF1_ROWS: Dict[str, int] = {
    "call_center": 6,
    "catalog_page": 11_718,
    "catalog_returns": 144_067,
    "catalog_sales": 1_441_548,
    "customer": 100_000,
    "customer_address": 50_000,
    "customer_demographics": 1_920_800,
    "date_dim": 73_049,
    "household_demographics": 7_200,
    "income_band": 20,
    "inventory": 11_745_000,
    "item": 18_000,
    "promotion": 300,
    "reason": 35,
    "ship_mode": 20,
    "store": 12,
    "store_returns": 287_514,
    "store_sales": 2_880_404,
    "time_dim": 86_400,
    "warehouse": 5,
    "web_page": 60,
    "web_returns": 71_763,
    "web_sales": 719_384,
    "web_site": 30,
}

#: tables whose row counts scale with the scale factor (facts + the
#: customer cluster); dimensions stay near-constant
_SCALED_TABLES = {
    "catalog_returns", "catalog_sales", "customer", "customer_address",
    "inventory", "store_returns", "store_sales", "web_returns",
    "web_sales",
}

#: subtree root types worth sharing (a bare Scan is already shared via
#: the warehouse; a bare Sort/Limit tail is per-query presentation)
_CANDIDATE_ROOTS = (lp.Filter, lp.Project, lp.Join, lp.Aggregate,
                    lp.Distinct)

#: nodes that make a subtree row-order-sensitive / tie-nondeterministic
_ORDER_SENSITIVE = (lp.Sort, lp.Window, lp.Limit)


def spine_budget_bytes() -> Tuple[int, str]:
    """Byte budget for materialized spines and where it came from.

    ``NDSTPU_SPINE_BUDGET_BYTES`` wins (tests / operator pin); then
    ``NDSTPU_HBM_BYTES`` x memplan.SAFETY; then the memplan default x
    SAFETY.  Never probes a device — this must run in the jax-free
    analysis context (CI lint, doc tooling)."""
    env = os.environ.get("NDSTPU_SPINE_BUDGET_BYTES")
    if env:
        return max(int(env), 1), "env"
    hbm = os.environ.get("NDSTPU_HBM_BYTES")
    if hbm:
        return max(int(int(hbm) * memplan.SAFETY), 1), "hbm"
    return int(memplan.DEFAULT_BUDGET_BYTES * memplan.SAFETY), "default"


# ---------------------------------------------------------------------------
# per-plan site classification
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SpineSite:
    """One candidate spine occurrence inside one plan."""

    path: str                  # canon-convention path from the plan root
    kind: str                  # subtree root node type name
    size: int                  # plan nodes in the subtree
    fingerprint: str           # subtree canonical fingerprint
    value_key: str             # fingerprint + hash over ALL slot values
    shareable: bool
    reason: str                # "" when shareable, else why not
    node: lp.Plan = dataclasses.field(compare=False, hash=False,
                                      default=None)
    scans: Tuple[str, ...] = ()          # base tables read, sorted
    est_rows: Optional[int] = None       # NDS504 row model (None=unknown)
    est_row_bytes: Optional[int] = None  # memplan row-width model

    @property
    def est_bytes(self) -> Optional[int]:
        if self.est_rows is None or self.est_row_bytes is None:
            return None
        return self.est_rows * self.est_row_bytes


def value_key(canon) -> str:
    """Runtime materialization-cache key: the subtree fingerprint plus a
    hash over ALL slot values (bind and shape alike — a spine serving a
    different literal is a different materialized table)."""
    vh = hashlib.sha256(repr(canon.values).encode()).hexdigest()[:16]
    return f"{canon.fingerprint}:{vh}"


def _has_work(node: lp.Plan) -> bool:
    for n in node.walk():
        if isinstance(n, (lp.Filter, lp.Join, lp.Aggregate, lp.Distinct)):
            return True
        if isinstance(n, lp.Scan) and n.predicate is not None:
            return True
    return False


def _estimate(node: lp.Plan, tables, query: str, scans: Tuple[str, ...],
              scale_factor: Optional[float]
              ) -> Tuple[Optional[int], Optional[int]]:
    """(est_rows, est_row_bytes) for the subtree's output, or Nones.

    Rows: the largest scanned base table bounds the spine's output for
    the shareable shapes (filters/joins/pre-aggregations never exceed
    the driving fact here).  Width: the inferred output schema through
    memplan's row-width model (strings count their int32 dict-code
    width, the form a cached device table holds)."""
    rows = None
    for t in scans:
        base = SF1_ROWS.get(t)
        if base is None:
            continue
        if scale_factor and t in _SCALED_TABLES:
            base = int(base * scale_factor)
        rows = base if rows is None else max(rows, base)
    if rows is None:
        return None, None
    try:
        schema, _ = infer_plan(node, tables, query)
    except Exception:
        return rows, None
    if not schema.known:
        return rows, None
    sizes = []
    for _, ct in schema.cols:
        if ct.ctype is None:
            return rows, None
        sizes.append(np.dtype(columnar.numpy_dtype(ct.ctype)).itemsize)
    return rows, memplan.row_bytes(sizes)


def subtree_sites(plan: lp.Plan, tables: Optional[Dict[str, object]] = None,
                  query: str = "",
                  scale_factor: Optional[float] = None,
                  subtrees: Optional[List[SubtreeCanon]] = None
                  ) -> List[SpineSite]:
    """Classify every candidate spine in one optimized plan, root-first.

    A subtree is a *candidate* when its root is a Filter/Project/Join/
    Aggregate/Distinct that is not the plan root, it reads at least one
    base table, and it does real work (a filter, join, aggregate, or
    distinct — a bare column projection shares nothing worth caching).
    A candidate is *shareable* unless it contains an order-sensitive
    node (NDS503) or failed to canonicalize."""
    if subtrees is None:
        subtrees = canonicalize_subtrees(plan, tables, query)
    sites: List[SpineSite] = []
    for sub in subtrees:
        if "/" not in sub.path:        # the plan root shares via the
            continue                   # whole-plan canonical cache
        if not isinstance(sub.node, _CANDIDATE_ROOTS):
            continue
        scans = tuple(sorted({n.table for n in sub.node.walk()
                              if isinstance(n, lp.Scan)}))
        if not scans or not _has_work(sub.node):
            continue
        if sub.canon is None:
            sites.append(SpineSite(
                path=sub.path, kind=sub.kind, size=sub.size,
                fingerprint="", value_key="", shareable=False,
                reason="canonicalization failed", node=sub.node,
                scans=scans))
            continue
        order = any(isinstance(n, _ORDER_SENSITIVE)
                    for n in sub.node.walk())
        est_rows, est_rb = _estimate(sub.node, tables, query, scans,
                                     scale_factor)
        sites.append(SpineSite(
            path=sub.path, kind=sub.kind, size=sub.size,
            fingerprint=sub.canon.fingerprint,
            value_key=value_key(sub.canon),
            shareable=not order,
            reason="order-sensitive (sort/window/limit inside)"
                   if order else "",
            node=sub.node, scans=scans,
            est_rows=est_rows, est_row_bytes=est_rb))
    return sites


def eligible_sites(sites: List[SpineSite]) -> List[SpineSite]:
    """Outermost non-overlapping shareable sites, in root-first order —
    the set the runtime splicer actually materializes (splicing a spine
    subsumes everything underneath it)."""
    kept: List[SpineSite] = []
    for s in sites:
        if not s.shareable:
            continue
        if any(s.path.startswith(k.path + "/") for k in kept):
            continue
        kept.append(s)
    return kept


# ---------------------------------------------------------------------------
# cross-corpus index
# ---------------------------------------------------------------------------


def build_index(per_query_sites: Dict[str, List[SpineSite]],
                budget_bytes: Optional[int] = None
                ) -> Tuple[Dict[str, dict], List[Diagnostic]]:
    """Fold per-query sites into the global fingerprint index and emit
    the NDS5xx diagnostics.

    Diagnostics are bounded to one per (query, fingerprint-class): the
    first site path in a query anchors the finding even when the spine
    recurs inside that one plan.  Only fingerprints seen in >= 2 distinct
    queries diagnose at all, so a subset sweep's diagnostic set is a
    subset of the full-corpus baseline (monotone gating)."""
    if budget_bytes is None:
        budget_bytes, _ = spine_budget_bytes()
    index: Dict[str, dict] = {}
    for q in sorted(per_query_sites):
        for s in per_query_sites[q]:
            if not s.fingerprint:
                continue
            rec = index.setdefault(s.fingerprint, {
                "fingerprint": s.fingerprint, "kind": s.kind,
                "size": s.size, "queries": {}, "value_keys": set(),
                "scans": set(), "shareable": s.shareable,
                "reason": s.reason, "est_bytes": None,
            })
            rec["queries"].setdefault(q, s.path)
            rec["value_keys"].add(s.value_key)
            rec["scans"].update(s.scans)
            rec["shareable"] = rec["shareable"] and s.shareable
            if s.reason and not rec["reason"]:
                rec["reason"] = s.reason
            if s.est_bytes is not None:
                rec["est_bytes"] = max(rec["est_bytes"] or 0, s.est_bytes)

    diags: List[Diagnostic] = []
    for fp in sorted(index):
        rec = index[fp]
        if len(rec["queries"]) < 2:
            continue
        qlist = ", ".join(sorted(rec["queries"])[:6])
        for q in sorted(rec["queries"]):
            path = rec["queries"][q]
            if not rec["shareable"]:
                diags.append(Diagnostic(
                    code="NDS503",
                    message=f"subtree {fp} ({rec['kind']}, "
                            f"{len(rec['queries'])} queries) is "
                            f"order-sensitive; excluded from spine "
                            f"materialization",
                    path=path, query=q))
                continue
            diags.append(Diagnostic(
                code="NDS501",
                message=f"spine {fp} ({rec['kind']} over "
                        f"{'/'.join(sorted(rec['scans']))}) shared by "
                        f"{len(rec['queries'])} queries: {qlist}",
                path=path, query=q))
            if len(rec["value_keys"]) > 1:
                diags.append(Diagnostic(
                    code="NDS502",
                    message=f"spine {fp} binds "
                            f"{len(rec['value_keys'])} distinct value "
                            f"sets across its occurrences",
                    path=path, query=q))
            if rec["est_bytes"] is not None and \
                    rec["est_bytes"] > budget_bytes:
                diags.append(Diagnostic(
                    code="NDS504",
                    message=f"spine {fp} estimated "
                            f"{rec['est_bytes']} B exceeds the "
                            f"{budget_bytes} B materialization budget",
                    path=path, query=q))
    return index, diags


def index_to_doc(index: Dict[str, dict],
                 budget_bytes: Optional[int] = None) -> dict:
    """Deterministic JSON payload for MQO_AUDIT.json: the shared-spine
    table (sorted by sharing degree then fingerprint) plus summary
    counts the CI gate asserts on."""
    if budget_bytes is None:
        budget_bytes, _ = spine_budget_bytes()
    shared = []
    for fp, rec in index.items():
        if len(rec["queries"]) < 2:
            continue
        shared.append({
            "fingerprint": fp,
            "kind": rec["kind"],
            "size": rec["size"],
            "queries": sorted(rec["queries"]),
            "n_queries": len(rec["queries"]),
            "n_value_sets": len(rec["value_keys"]),
            "scans": sorted(rec["scans"]),
            "shareable": rec["shareable"],
            "reason": rec["reason"],
            "est_bytes": rec["est_bytes"],
            "over_budget": (rec["est_bytes"] is not None and
                            rec["est_bytes"] > budget_bytes),
        })
    shared.sort(key=lambda r: (-r["n_queries"], r["fingerprint"]))
    candidates = [r for r in shared if r["shareable"]]
    return {
        "budget_bytes": budget_bytes,
        "subtrees_indexed": len(index),
        "shared_spines": shared,
        "summary": {
            "shared": len(shared),
            "shared_spine_candidates": len(candidates),
            "param_divergent": sum(1 for r in candidates
                                   if r["n_value_sets"] > 1),
            "order_sensitive": sum(1 for r in shared
                                   if not r["shareable"]),
            "over_budget": sum(1 for r in shared if r["over_budget"]),
        },
    }
