"""Supported-op registry and static lowerability audit.

The registry below is THE single source of truth for what the jax device
executor (``engine/jaxexec.py``) and the SPMD spine compiler
(``parallel/dplan.py``) can lower — extracted from their raise sites and
consumed back by both (jaxexec's membership checks and
``scripts/spmd_coverage.py`` import these sets), so the analyzer and the
runtime cannot drift apart silently.

On top of the registry, :func:`audit_plan` walks a logical plan and
predicts device-vs-fallback per query part *without executing anything*:

* NDS2xx (error): a node/expression jaxexec will refuse —
  ``_execute_node`` catches :class:`~ndstpu.engine.jaxexec.Unsupported`
  and interprets the node on host numpy, so any NDS2xx error outside a
  subquery sub-plan means verdict ``fallback``.
* NDS213/NDS214 (info): data-dependent capacity guards and per-set
  grouping-set passes — the plan still compiles for the device.
* NDS3xx (warning/info): SPMD spine restrictions mirrored from dplan.
  They never affect the device verdict: ``Session`` degrades
  ``DistUnsupported`` to single-chip execution gracefully.

Subquery sub-plans (``SubqueryExpr.plan``) are audited under a
``.../subquery[i]`` path segment and excluded from the verdict, exactly
like jaxexec's ``_resolve_subqueries`` isolates ``_used_fallback``.

Import-hygienic: no jax — safe for CI lint and doc tooling processes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ndstpu.engine import expr as ex
from ndstpu.engine import plan as lp
from ndstpu.analysis.diagnostics import Diagnostic, sort_diagnostics
from ndstpu.analysis.typecheck import Schema, TypeChecker, _child_path

# ---------------------------------------------------------------------------
# Registry (mirrors jaxexec raise sites; consumed by jaxexec + dplan tools)
# ---------------------------------------------------------------------------

#: JEval._binop — comparison/logic/arith/concat (jaxexec "binop {op}")
SUPPORTED_BINOPS = frozenset({
    "and", "or", "=", "<>", "<", "<=", ">", ">=",
    "+", "-", "*", "/", "%", "||",
})

#: JEval._unary (jaxexec "unary {op}")
SUPPORTED_UNARY_OPS = frozenset({"not", "neg", "isnull", "isnotnull"})

#: JEval.cast target kinds; string targets only parse FROM string
#: (jaxexec "cast {src} -> {target}" and "cast-to-string on device")
SUPPORTED_CAST_TARGET_KINDS = frozenset({
    "float64", "decimal", "int32", "int64", "date", "bool",
})

#: JEval._func (jaxexec "function {name}")
DEVICE_FUNCS = frozenset({
    "concat", "coalesce", "like", "substr", "substring", "upper",
    "lower", "trim", "length", "abs", "round", "floor", "ceil", "sqrt",
    "year", "month", "day", "nullif",
})

#: device funcs whose argument must already be a string column
#: (jaxexec _as_string: "cast-to-string on device")
STRING_ARG_FUNCS = frozenset({"upper", "lower", "trim", "length"})

#: literal python types JEval._lit accepts (None is always accepted)
SUPPORTED_LITERAL_TYPES = (bool, int, float, str)

#: _check_agg_supported (jaxexec "aggregate {func}")
SUPPORTED_AGG_FUNCS = frozenset({
    "sum", "count", "avg", "min", "max",
    "stddev_samp", "var_samp", "stddev", "variance",
})

#: _check_agg_supported (jaxexec "distinct aggregate {func} on device")
DISTINCT_AGG_FUNCS = frozenset({"sum", "count", "avg", "min", "max"})

#: aggregates whose grouping-set partials re-combine into coarser groups
#: in one pass (jaxexec._GS_COMBINABLE); others run one pass per set —
#: still on device, just more programs
GS_COMBINABLE_AGGS = frozenset({"count", "sum", "avg", "min", "max"})

#: _window_column ranking path (jaxexec "window {func}")
WINDOW_RANKING_FUNCS = frozenset({"rank", "dense_rank", "row_number"})

#: _window_column partition-aggregate path
WINDOW_AGG_FUNCS = frozenset({"count", "sum", "avg", "min", "max"})

#: _running_window (order_by present: "running window {func}")
RUNNING_WINDOW_FUNCS = frozenset({"count", "sum", "avg", "min", "max"})

#: keyless joins (jaxexec "non-equi {kind} join")
KEYLESS_JOIN_KINDS = frozenset({"cross", "inner"})

#: equi-join kinds (jaxexec _exec_join/_equi_join "join kind {kind}")
EQUI_JOIN_KINDS = frozenset({
    "inner", "left", "right", "full", "semi", "anti", "mark",
    "nullaware_anti",
})

#: subquery kinds _resolve_subqueries can inline (exists is host-only;
#: jaxexec "subquery kind {kind}")
DEVICE_SUBQUERY_KINDS = frozenset({"scalar", "in"})

# -- SPMD spine registry (mirrors parallel/dplan.py) -------------------------

#: join kinds allowed on the sharded spine (dplan "{kind} join on spine")
SPMD_SPINE_JOIN_KINDS = frozenset({
    "inner", "left", "semi", "anti", "nullaware_anti", "mark",
})

#: aggregate functions decomposable into per-device partials
#: (dplan._AGG_FUNCS, "agg {func} on spine")
SPMD_AGG_FUNCS = frozenset({
    "sum", "count", "avg", "min", "max",
    "stddev_samp", "var_samp", "stddev", "variance",
})

#: join-key dtype kinds shardable on the spine (dplan._KEY_KINDS; string
#: keys additionally need a dictionary — "{kind} join key on spine")
SPMD_KEY_KINDS = frozenset({"int32", "int64", "date"})

#: build sides larger than this broadcast limit take the shuffle-join
#: (all_to_all) path (dplan broadcast_limit_rows default)
SPMD_BROADCAST_LIMIT_ROWS = 8_000_000

#: sharded-size fact tables (SF-scaled): scans of these anchor a spine
SPMD_FACT_TABLES = frozenset({
    "store_sales", "store_returns", "catalog_sales", "catalog_returns",
    "web_sales", "web_returns", "inventory",
})

#: existence-join kinds whose sharded build side reduces to its distinct
#: (key, residual-column) tuples via a child distributed aggregate
#: before broadcasting (dplan._reduce_build — existence semantics are
#: insensitive to duplicate build rows, so the reduction is lossless)
SPMD_REDUCIBLE_BUILD_JOIN_KINDS = frozenset({
    "semi", "anti", "nullaware_anti", "mark",
})


def spmd_window_ok(node: lp.Window) -> bool:
    """True when a Window node runs sharded on the spine
    (dplan._exec_window_dist): every expr is a plain WindowExpr — no
    subqueries anywhere — computing a ranking or a whole-partition
    aggregate.  Running frames (agg func + ORDER BY) need the
    cross-row prefix scan and stay single-chip."""
    for _name, e in node.exprs:
        if not isinstance(e, ex.WindowExpr):
            return False
        if any(isinstance(x, ex.SubqueryExpr) for x in e.walk()):
            return False
        if e.func in WINDOW_RANKING_FUNCS:
            continue
        if e.func in WINDOW_AGG_FUNCS and not e.order_by:
            continue
        return False
    return True


def plan_path_to(root: lp.Plan, target: lp.Plan
                 ) -> Optional[List[lp.Plan]]:
    """Root-to-target node path, or None when target is not in the
    tree (shared by dplan's union splitter and this audit)."""
    if root is target:
        return [root]
    for c in root.children():
        p = plan_path_to(c, target)
        if p is not None:
            return [root] + p
    return None


def union_distributive_path(root: lp.Plan, target: lp.Plan) -> bool:
    """Aggregation over the union at `target` may be split per branch
    only when every node between them distributes over UNION ALL:
    row-wise ops, inner joins (either side), and probe-side-only for
    left/semi/anti/mark joins (a build-side union would change match
    semantics)."""
    path = plan_path_to(root, target)
    if path is None:
        return False
    for i, nd in enumerate(path[:-1]):
        nxt = path[i + 1]
        if isinstance(nd, (lp.Project, lp.Filter, lp.SubqueryAlias)):
            continue
        if isinstance(nd, lp.SetOp) and nd.kind == "union" and nd.all:
            continue
        if isinstance(nd, lp.Join):
            if nd.kind == "inner" or (nxt is nd.left and nd.kind in
                                      ("left", "semi", "anti",
                                       "nullaware_anti", "mark")):
                continue
            return False
        return False
    return True


# ---------------------------------------------------------------------------
# Audit
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AuditResult:
    """Static lowerability prediction for one query part."""

    verdict: str                     # "device" | "fallback"
    diagnostics: List[Diagnostic]

    @property
    def fallback_codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics
                       if d.severity == "error" and
                       "/subquery[" not in d.path})


def verdict_from(diags: List[Diagnostic]) -> str:
    """Device iff no error-severity lowering diagnostic on the main plan
    (subquery sub-plan fallbacks are isolated at runtime and don't make
    the outer plan non-compilable)."""
    for d in diags:
        if d.severity == "error" and "/subquery[" not in d.path:
            return "fallback"
    return "device"


class LoweringAuditor:
    """Plan walker emitting NDS2xx/NDS3xx diagnostics."""

    def __init__(self, tables: Dict[str, object], query: str = "",
                 scale_factor: Optional[float] = None, spmd: bool = True):
        self.tables = tables
        self.query = query
        self.spmd = spmd
        self.tc = TypeChecker(tables, query=query,
                              scale_factor=scale_factor)
        self.diags: List[Diagnostic] = []

    def _emit(self, code: str, message: str, path: str) -> None:
        self.diags.append(Diagnostic(code=code, message=message, path=path,
                                     query=self.query))

    # -- entry ---------------------------------------------------------------

    def audit(self, plan: lp.Plan) -> AuditResult:
        self._node(plan, type(plan).__name__)
        if self.spmd:
            self._audit_spine(plan)
        return AuditResult(verdict_from(self.diags),
                           sort_diagnostics(self.diags))

    # -- per-node checks -----------------------------------------------------

    def _node(self, p: lp.Plan, path: str) -> None:
        schemas = [self.tc.infer(c, _child_path(path, c, i))
                   for i, c in enumerate(p.children())]
        if isinstance(p, lp.Scan) and p.predicate is not None:
            self._expr(p.predicate, self.tc.infer(p), path)
        elif isinstance(p, lp.Filter):
            self._expr(p.condition, schemas[0], path)
        elif isinstance(p, lp.Project):
            for _, e in p.exprs:
                self._expr(e, schemas[0], path)
        elif isinstance(p, lp.Join):
            self._join(p, schemas[0], schemas[1], path)
        elif isinstance(p, lp.Aggregate):
            self._aggregate(p, schemas[0], path)
        elif isinstance(p, lp.Window):
            self._window(p, schemas[0], path)
        elif isinstance(p, lp.Sort):
            for entry in p.keys:
                self._expr(entry[0], schemas[0], path)
        for i, c in enumerate(p.children()):
            self._node(c, _child_path(path, c, i))

    def _join(self, p: lp.Join, left: Schema, right: Schema,
              path: str) -> None:
        if not p.keys:
            if p.kind not in KEYLESS_JOIN_KINDS:
                self._emit("NDS210", f"non-equi {p.kind} join without "
                           "keys is host-only", path)
        elif p.kind not in EQUI_JOIN_KINDS:
            self._emit("NDS210", f"join kind {p.kind} is host-only", path)
        for i, (le, re_) in enumerate(p.keys):
            self._expr(le, left, f"{path}/keys[{i}]")
            self._expr(re_, right, f"{path}/keys[{i}]")
        if p.extra is not None:
            merged = Schema(
                (left.cols or []) + (right.cols or [])
                if left.known and right.known else None)
            self._expr(p.extra, merged, path)

    def _aggregate(self, p: lp.Aggregate, child: Schema,
                   path: str) -> None:
        for _, e in p.group_by:
            self._expr(e, child, path, allow_agg=False)
        not_combinable = set()
        for name, e in p.aggs:
            self._agg_output(e, child, path)
            for sub in e.walk():
                if isinstance(sub, ex.AggExpr) and (
                        sub.func not in GS_COMBINABLE_AGGS or
                        sub.distinct):
                    not_combinable.add(
                        f"{sub.func}{' distinct' if sub.distinct else ''}")
        if p.grouping_sets is not None and not_combinable:
            self._emit(
                "NDS214",
                f"grouping sets with non-combinable aggregates "
                f"({', '.join(sorted(not_combinable))}): one device pass "
                f"per set ({len(p.grouping_sets)} sets) instead of one "
                "combinable pass", path)

    def _agg_output(self, e: ex.Expr, schema: Schema, path: str) -> None:
        """Mirror jaxexec._eval_agg: an aggregate output expression must
        be an AggExpr / grouping() / literal-cast-binop-case combination
        over those ("aggregate output {type}")."""
        if isinstance(e, ex.AggExpr):
            if e.func not in SUPPORTED_AGG_FUNCS:
                self._emit("NDS207", f"aggregate {e.func} is host-only",
                           path)
            elif e.distinct and e.func not in DISTINCT_AGG_FUNCS:
                self._emit("NDS207", f"distinct aggregate {e.func} is "
                           "host-only", path)
            if not isinstance(e.arg, ex.Star):
                self._expr(e.arg, schema, path, allow_agg=False)
            return
        if isinstance(e, ex.Func) and e.name == "grouping":
            return
        if isinstance(e, ex.Literal):
            self._check_literal(e, path)
            return
        if isinstance(e, ex.Cast):
            self._check_cast(e, schema, path)
            self._agg_output(e.operand, schema, path)
            return
        if isinstance(e, ex.BinOp):
            if e.op not in SUPPORTED_BINOPS:
                self._emit("NDS202", f"binop {e.op} is host-only", path)
            self._agg_output(e.left, schema, path)
            self._agg_output(e.right, schema, path)
            return
        if isinstance(e, ex.Case):
            for c, v in e.whens:
                self._agg_output(c, schema, path)
                self._agg_output(v, schema, path)
            if e.default is not None:
                self._agg_output(e.default, schema, path)
            return
        if isinstance(e, ex.Func):
            if e.name not in DEVICE_FUNCS:
                self._emit("NDS205", f"function {e.name} is host-only",
                           path)
            for a in e.args:
                self._agg_output(a, schema, path)
            return
        self._emit("NDS208", f"aggregate output {type(e).__name__} "
                   f"({e}) is host-only", path)

    def _window(self, p: lp.Window, child: Schema, path: str) -> None:
        for _, e in p.exprs:
            if not isinstance(e, ex.WindowExpr):
                self._emit("NDS209", f"non-window expr "
                           f"{type(e).__name__} in Window node", path)
                continue
            w: ex.WindowExpr = e
            if w.func in WINDOW_RANKING_FUNCS:
                pass
            elif w.func in WINDOW_AGG_FUNCS:
                if w.order_by and w.func not in RUNNING_WINDOW_FUNCS:
                    self._emit("NDS209", f"running window {w.func} is "
                               "host-only", path)
            else:
                self._emit("NDS209", f"window {w.func} is host-only",
                           path)
            for pe in w.partition_by:
                self._expr(pe, child, path, allow_agg=False)
            for oe, _ in w.order_by:
                self._expr(oe, child, path, allow_agg=False)
            if w.arg is not None and not isinstance(w.arg, ex.Star):
                self._expr(w.arg, child, path, allow_agg=False)

    # -- expression checks ---------------------------------------------------

    def _expr(self, e: ex.Expr, schema: Schema, path: str,
              allow_agg: bool = False) -> None:
        if isinstance(e, (ex.ColumnRef, ex.Star)):
            return
        if isinstance(e, ex.Literal):
            self._check_literal(e, path)
            return
        if isinstance(e, ex.Cast):
            self._check_cast(e, schema, path)
            self._expr(e.operand, schema, path, allow_agg)
            return
        if isinstance(e, ex.BinOp):
            if e.op not in SUPPORTED_BINOPS:
                self._emit("NDS202", f"binop {e.op} is host-only", path)
            elif e.op == "||":
                lt = self.tc.expr_type(e.left, schema)
                rt = self.tc.expr_type(e.right, schema)
                for side, t in (("left", lt), ("right", rt)):
                    if t.known and t.kind != "string":
                        self._emit("NDS206", f"|| {side} operand is "
                                   f"{t.kind}, not string", path)
                if lt.kind == rt.kind == "string":
                    self._emit("NDS213", "|| builds a dictionary "
                               "cross-product on device (guarded at 2^20 "
                               "entries)", path)
            self._expr(e.left, schema, path, allow_agg)
            self._expr(e.right, schema, path, allow_agg)
            return
        if isinstance(e, ex.UnaryOp):
            if e.op not in SUPPORTED_UNARY_OPS:
                self._emit("NDS203", f"unary {e.op} is host-only", path)
            self._expr(e.operand, schema, path, allow_agg)
            return
        if isinstance(e, ex.Case):
            for c, v in e.whens:
                self._expr(c, schema, path, allow_agg)
                self._expr(v, schema, path, allow_agg)
            if e.default is not None:
                self._expr(e.default, schema, path, allow_agg)
            return
        if isinstance(e, ex.Func):
            self._check_func(e, schema, path)
            for a in e.args:
                self._expr(a, schema, path, allow_agg)
            return
        if isinstance(e, ex.InList):
            self._check_in_list(e, schema, path)
            self._expr(e.operand, schema, path, allow_agg)
            return
        if isinstance(e, ex.Param):
            # lifted literal (analysis/canon.py): binds a supported-type
            # value at runtime, lowerable wherever a Literal is
            return
        if isinstance(e, ex.InParam):
            self._expr(e.operand, schema, path, allow_agg)
            return
        if isinstance(e, ex.SubqueryExpr):
            if e.kind not in DEVICE_SUBQUERY_KINDS:
                self._emit("NDS211", f"subquery kind {e.kind} is "
                           "host-only", path)
            if e.operand is not None:
                self._expr(e.operand, schema, path, allow_agg)
            if e.plan is not None:
                # audited in isolation, mirroring _resolve_subqueries'
                # _used_fallback save/restore: sub-plan fallbacks never
                # make the outer plan non-compilable
                counts = getattr(self, "_sub_counts", None)
                if counts is None:
                    counts = self._sub_counts = {}
                n = counts.get(path, 0)
                counts[path] = n + 1
                self._node(e.plan, f"{path}/subquery[{n}]")
            return
        if isinstance(e, (ex.AggExpr, ex.WindowExpr)) and not allow_agg:
            self._emit("NDS201", f"expr {type(e).__name__} outside its "
                       "node is host-only", path)
            return

    def _check_literal(self, e: ex.Literal, path: str) -> None:
        v = e.value
        if v is not None and not isinstance(v, SUPPORTED_LITERAL_TYPES):
            self._emit("NDS201", f"literal {v!r} "
                       f"({type(v).__name__}) is host-only", path)

    def _check_cast(self, e: ex.Cast, schema: Schema, path: str) -> None:
        tk = e.target.kind
        if tk in SUPPORTED_CAST_TARGET_KINDS:
            return
        src = self.tc.expr_type(e.operand, schema)
        if tk == "string" and (not src.known or src.kind == "string"):
            return  # identity string cast compiles
        self._emit("NDS204", f"cast {src.kind or '?'} -> {e.target} is "
                   "host-only", path)

    def _check_func(self, e: ex.Func, schema: Schema, path: str) -> None:
        if e.name not in DEVICE_FUNCS:
            self._emit("NDS205", f"function {e.name} is host-only", path)
            return
        if e.name in STRING_ARG_FUNCS and e.args:
            t = self.tc.expr_type(e.args[0], schema)
            if t.known and t.kind != "string":
                self._emit("NDS206", f"{e.name}() argument is {t.kind}; "
                           "device has no cast-to-string", path)

    def _check_in_list(self, e: ex.InList, schema: Schema,
                       path: str) -> None:
        t = self.tc.expr_type(e.operand, schema)
        if not t.known or t.kind == "string":
            return
        vals, _had_null = ex.coerce_in_values(t.ctype, list(e.values))
        if any(isinstance(v, str) for v in vals):
            self._emit("NDS212", f"IN-list string literals against "
                       f"{t.kind} column", path)

    # -- SPMD spine checks (mirror parallel/dplan.py) ------------------------

    def _audit_spine(self, plan: lp.Plan) -> None:
        scans = [n for n in plan.walk() if isinstance(n, lp.Scan)]
        facts = [n for n in scans if n.table in SPMD_FACT_TABLES]
        if not facts:
            self._emit("NDS301", "no sharded-size base-table scan: plan "
                       "runs single-chip", type(plan).__name__)
            return
        usite = self._union_agg_site(plan)
        if usite is not None:
            # dplan._try_union_agg runs before the spine split: each
            # union-all branch becomes its own sharded spine and the
            # decomposable partials combine on the host, so the spine
            # restrictions below never apply to this plan shape
            self._emit("NDS309", "aggregate distributes over a union-all "
                       "of sharded branches: per-branch spines, partials "
                       "combined on the host", usite)
            return
        target = facts[0]  # dplan tries largest-first; facts dominate
        chain = self._chain_to(plan, target)
        if chain is None:
            return
        spine_idx = len(chain) - 1
        for i in range(len(chain) - 1, -1, -1):
            if self._spine_ok(chain[i][0]):
                spine_idx = i
            else:
                break
        spine_path = chain[spine_idx][1]
        spine = chain[spine_idx][0]
        if spine_idx > 0 and isinstance(chain[spine_idx - 1][0],
                                        lp.Aggregate):
            self._spmd_check_agg(chain[spine_idx - 1][0],
                                 chain[spine_idx - 1][1])
            spine = chain[spine_idx - 1][0]
            spine_path = chain[spine_idx - 1][1]
        # exchange placement now comes from the cost model's estimated
        # build cardinality/bytes through the SAME choose_strategy the
        # runtime advisor uses (analysis/cost.py), not the old
        # fact-in-build structural proxy — NDS305 reports the predicted
        # strategy mix plus the estimated replicated build bytes
        from ndstpu.analysis import cost as costmod
        model = costmod.CostModel(self.tables,
                                  scale_factor=self.tc.scale_factor,
                                  query=self.query)
        budget, _src = costmod.cost_budget_bytes()
        broadcast = shuffle = reduced = 0
        bcast_bytes = 0
        for node, npath in self._walk_with_paths(spine, spine_path):
            if not isinstance(node, lp.Join):
                continue
            fact_left = any(n is target for n in node.left.walk())
            fact_right = any(n is target for n in node.right.walk())
            if not (fact_left or fact_right):
                continue
            if node.kind not in SPMD_SPINE_JOIN_KINDS:
                self._emit("NDS303", f"{node.kind} join on the spine "
                           "forces single-chip", npath)
                continue
            if not node.keys:
                self._emit("NDS304", "non-equi join on the spine forces "
                           "single-chip", npath)
                continue
            if fact_right and node.kind != "inner":
                if node.kind in SPMD_REDUCIBLE_BUILD_JOIN_KINDS and not (
                        node.kind == "nullaware_anti" and
                        node.extra is not None):
                    self._emit("NDS308", f"sharded build side of a "
                               f"{node.kind} join reduces to its "
                               "distinct key tuples distributed",
                               npath)
                else:
                    self._emit("NDS303", f"sharded table on the build "
                               f"side of a {node.kind} join forces "
                               "single-chip", npath)
            build = node.left if fact_right else node.right
            bschema = self.tc.infer(build)
            for i, (le, re_) in enumerate(node.keys):
                be = le if fact_right else re_
                t = self.tc.expr_type(be, bschema)
                if t.known and t.kind not in SPMD_KEY_KINDS and \
                        t.kind != "string":
                    self._emit("NDS307", f"{t.kind} join key is not "
                               "shardable on the spine",
                               f"{npath}/keys[{i}]")
                elif t.known and t.kind == "string":
                    from ndstpu.io import gdict
                    if gdict.enabled():
                        # static mirror of dplan._probe_keys' identity
                        # path: with warehouse-wide frozen dictionaries
                        # both sides share one code space and the key
                        # shards on raw codes
                        self._emit("NDS312", "string join key shards "
                                   "on frozen global-dictionary codes",
                                   f"{npath}/keys[{i}]")
            est = model.estimate(build)
            reducible = (
                node.kind in SPMD_REDUCIBLE_BUILD_JOIN_KINDS
                and not (node.kind == "nullaware_anti"
                         and node.extra is not None)
                and any(isinstance(n, lp.Scan)
                        and n.table in SPMD_FACT_TABLES
                        for n in build.walk()))
            d = costmod.choose_strategy(
                est.rows, est.bytes,
                broadcast_limit_rows=SPMD_BROADCAST_LIMIT_ROWS,
                budget_bytes=budget, reducible=reducible)
            if d.strategy == "shuffle":
                shuffle += 1
            elif d.strategy == "build-reduce":
                reduced += 1
            else:
                broadcast += 1
                if est.bytes is not None:
                    bcast_bytes += est.bytes
        if broadcast or shuffle or reduced:
            self._emit(
                "NDS305",
                f"predicted exchange placement over {target.table}: "
                f"{broadcast} broadcast join(s) (~{bcast_bytes} est "
                f"build B), {shuffle} shuffle (all_to_all) join(s), "
                f"{reduced} build-reduce join(s)", spine_path)
        if isinstance(spine, lp.Aggregate):
            return
        # mirror dplan._split's tail/window detection: a Sort+Limit (or
        # bare Limit) directly above the spine finalizes as a per-device
        # top-k, and absorbed Window nodes run sharded — either one is
        # distributed work, so NDS306 no longer applies
        has_win = any(isinstance(chain[j][0], lp.Window)
                      for j in range(spine_idx, len(chain)))
        has_tail = False
        i = spine_idx - 1
        if i >= 0 and isinstance(chain[i][0], lp.Sort):
            i -= 1
        if i >= 0 and isinstance(chain[i][0], lp.Limit) and \
                chain[i][0].n and int(chain[i][0].n) > 0:
            has_tail = True
        if has_tail or has_win:
            what = []
            if has_tail:
                what.append("per-device top-k sort/limit gathers only "
                            "the k-row result")
            if has_win:
                what.append("window runs sharded over the partition-"
                            "colocating exchange")
            self._emit("NDS310", "row spine finalizes on-device: "
                       + "; ".join(what), spine_path)
        elif not any(
                isinstance(nd, (lp.Join, lp.Filter)) or
                (isinstance(nd, lp.Scan) and nd.predicate is not None)
                for nd in spine.walk()):
            self._emit("NDS306", "row spine does no distributed work: "
                       "every sharded row ships back to the host",
                       spine_path)

    def _spmd_check_agg(self, node: lp.Aggregate, path: str) -> None:
        for _, e in node.aggs:
            for sub in e.walk():
                if isinstance(sub, ex.AggExpr):
                    if sub.func not in SPMD_AGG_FUNCS:
                        self._emit("NDS302", f"agg {sub.func} is not "
                                   "decomposable on the spine", path)
                    if sub.distinct and (isinstance(sub.arg, ex.Star) or
                                         sub.arg is None):
                        self._emit("NDS302", "distinct star agg is not "
                                   "decomposable on the spine", path)
                    if sub.distinct and node.grouping_sets is not None:
                        self._emit("NDS302", "distinct agg under "
                                   "grouping sets is not decomposable "
                                   "on the spine", path)
                if isinstance(sub, ex.WindowExpr):
                    self._emit("NDS302", "window inside aggregate is "
                               "not decomposable on the spine", path)

    @staticmethod
    def _spine_ok(node: lp.Plan) -> bool:
        if isinstance(node, lp.Join):
            return node.kind in SPMD_SPINE_JOIN_KINDS
        if isinstance(node, lp.Window):
            return spmd_window_ok(node)
        return isinstance(node, (lp.Scan, lp.Filter, lp.Project,
                                 lp.SubqueryAlias))

    def _union_agg_site(self, plan: lp.Plan) -> Optional[str]:
        """Path of the deepest Aggregate that dplan._try_union_agg will
        split over a distributive union-all of fact-bearing branches —
        the site must pass the runtime's gating: decomposable agg funcs,
        no DISTINCT leaves (cross-branch dedup unsupported), no window
        inside the aggregate.  None when the plan takes the spine path."""
        best: Optional[Tuple[int, str]] = None

        def agg_ok(p: lp.Aggregate) -> bool:
            for _, e in p.aggs:
                for sub in e.walk():
                    if isinstance(sub, ex.WindowExpr):
                        return False
                    if isinstance(sub, ex.AggExpr) and (
                            sub.func not in SPMD_AGG_FUNCS or
                            sub.distinct):
                        return False
            return True

        def walk(p: lp.Plan, path: str, depth: int) -> None:
            nonlocal best
            if isinstance(p, lp.Aggregate) and agg_ok(p):
                direct = [
                    s for s in p.child.walk()
                    if isinstance(s, lp.SetOp) and s.kind == "union"
                    and s.all and union_distributive_path(p.child, s)
                    and any(isinstance(n, lp.Scan) and
                            n.table in SPMD_FACT_TABLES
                            for n in s.walk())]
                if direct and (best is None or depth > best[0]):
                    best = (depth, path)
            for i, c in enumerate(p.children()):
                walk(c, _child_path(path, c, i), depth + 1)

        walk(plan, type(plan).__name__, 0)
        return best[1] if best is not None else None

    @staticmethod
    def _chain_to(plan: lp.Plan, target: lp.Plan
                  ) -> Optional[List[Tuple[lp.Plan, str]]]:
        chain: List[Tuple[lp.Plan, str]] = []

        def descend(node: lp.Plan, path: str) -> bool:
            chain.append((node, path))
            if node is target:
                return True
            for i, c in enumerate(node.children()):
                if descend(c, _child_path(path, c, i)):
                    return True
            chain.pop()
            return False

        return chain if descend(plan, type(plan).__name__) else None

    def _walk_with_paths(self, node: lp.Plan, path: str):
        yield node, path
        for i, c in enumerate(node.children()):
            yield from self._walk_with_paths(c, _child_path(path, c, i))


def audit_plan(plan: lp.Plan, tables: Dict[str, object], query: str = "",
               scale_factor: Optional[float] = None,
               spmd: bool = True) -> AuditResult:
    """Predict device-vs-fallback for ``plan`` and collect NDS2xx/NDS3xx
    diagnostics; see module docstring for verdict semantics."""
    return LoweringAuditor(tables, query=query, scale_factor=scale_factor,
                           spmd=spmd).audit(plan)
