"""Static plan analyzer: typecheck + lowering audit, no data, no jax.

The analyzer runs on plans alone.  :func:`schema_catalog` builds a
zero-row engine catalog straight from ``ndstpu.schema`` so the planner
and optimizer can produce exactly the plans the runtime would see —
``Session.plan()`` is jax-free by construction — while nothing is ever
loaded or executed.

Typical use (scripts/plan_lint.py, harness/power.py --static_check)::

    from ndstpu import analysis
    res = analysis.analyze_sql(sess, name, sql, scale_factor=1.0)
    res.verdict            # "device" | "fallback"
    res.diagnostics        # typing + lowering + SPMD findings
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ndstpu import schema as nds_schema
from ndstpu.engine import columnar
from ndstpu.analysis import (
    canon, cost, diagnostics, lowering, spines, typecheck)
from ndstpu.analysis.canon import (
    CanonResult, canonicalize, canonicalize_subtrees)
from ndstpu.analysis.diagnostics import Diagnostic
from ndstpu.analysis.lowering import audit_plan
from ndstpu.analysis.typecheck import infer_plan

__all__ = [
    "AnalysisResult", "CanonResult", "Diagnostic", "analyze_plan",
    "analyze_sql", "audit_plan", "canon", "canonicalize",
    "canonicalize_subtrees", "cost", "diagnostics", "infer_plan",
    "lowering", "schema_catalog", "schema_tables", "spines",
    "typecheck",
]


def schema_tables(use_decimal: bool = True) -> Dict[str, object]:
    """All table schemas (source + maintenance views' bases) by name."""
    tables = dict(nds_schema.get_schemas(use_decimal=use_decimal))
    tables.update(nds_schema.get_maintenance_schemas(
        use_decimal=use_decimal))
    return tables


def schema_catalog(use_decimal: bool = True):
    """Zero-row engine catalog over the full TPC-DS schema — enough for
    ``Session.plan()`` (parse → plan → optimize) without any warehouse."""
    from ndstpu.io import loader

    cat = loader.Catalog()
    for name, ts in schema_tables(use_decimal=use_decimal).items():
        cols = {}
        for spec in ts.columns:
            dt = columnar.numpy_dtype(spec.dtype)
            cols[spec.name] = columnar.Column(
                np.empty(0, dtype=dt), spec.dtype,
                valid=None,
                dictionary=(np.empty(0, dtype=object)
                            if spec.dtype.kind == "string" else None))
        cat.register(name, columnar.Table(cols))
    return cat


@dataclasses.dataclass
class AnalysisResult:
    """Combined static analysis of one query part."""

    query: str
    verdict: str                      # "device" | "fallback"
    diagnostics: List[Diagnostic]     # NDS1xx..NDS4xx, sorted
    schema: typecheck.Schema
    canon: Optional[CanonResult] = None   # plan-shape canonicalization
    spine_sites: Optional[List["spines.SpineSite"]] = None  # NDS5xx pass
    cost_report: Optional["cost.CostReport"] = None  # NDS6xx pass

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def fallback_codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics
                       if d.severity == "error" and
                       "/subquery[" not in d.path})


def analyze_plan(plan, tables: Optional[Dict[str, object]] = None,
                 query: str = "",
                 scale_factor: Optional[float] = None,
                 spmd: bool = True,
                 spine_pass: bool = False,
                 cost_pass: bool = False) -> AnalysisResult:
    """Run schema inference (NDS1xx) + lowerability audit (NDS2xx/3xx)
    over an optimized logical plan.  ``spine_pass=True`` also classifies
    the plan's candidate common spines (NDS5xx inputs — the per-query
    half of :func:`spines.build_index`); ``cost_pass=True`` runs the
    static cost model (NDS6xx — scripts/cost_lint.py) and attaches its
    :class:`cost.CostReport` with the NDS6xx findings merged into
    ``diagnostics``.  The default analysis stays cost-free so the
    PLAN_LINT baseline and the golden diagnostic sets are unchanged."""
    tables = tables if tables is not None else schema_tables()
    out_schema, type_diags = infer_plan(plan, tables, query=query,
                                        scale_factor=scale_factor)
    audit = audit_plan(plan, tables, query=query,
                       scale_factor=scale_factor, spmd=spmd)
    cres = canonicalize(plan, tables=tables, query=query)
    sites = None
    if spine_pass:
        sites = spines.subtree_sites(plan, tables, query=query,
                                     scale_factor=scale_factor)
    cost_report = None
    cost_diags: List[Diagnostic] = []
    if cost_pass:
        cost_report = cost.audit_cost(plan, tables, query=query,
                                      scale_factor=scale_factor)
        cost_diags = cost_report.diagnostics
    diags = diagnostics.sort_diagnostics(
        type_diags + audit.diagnostics + list(cres.diagnostics)
        + cost_diags)
    return AnalysisResult(query=query, verdict=audit.verdict,
                          diagnostics=diags, schema=out_schema,
                          canon=cres, spine_sites=sites,
                          cost_report=cost_report)


def analyze_sql(session, query: str, sql: str,
                tables: Optional[Dict[str, object]] = None,
                scale_factor: Optional[float] = None,
                spmd: bool = True,
                spine_pass: bool = False,
                cost_pass: bool = False) -> AnalysisResult:
    """Plan one SQL statement through ``session`` (jax-free path) and
    analyze it.  ``session`` is an ``engine.session.Session`` — usually
    over :func:`schema_catalog` so no data is touched."""
    plan, _cols = session.plan(sql)
    return analyze_plan(plan, tables=tables, query=query,
                        scale_factor=scale_factor, spmd=spmd,
                        spine_pass=spine_pass, cost_pass=cost_pass)
